"""ctypes loader for the native host library (hclust/cophenetic fast path).

Auto-builds ``libnmfx_native.so`` with the bundled Makefile on first import
when a C++ toolchain is present (the reference repo's equivalent move: a top
Makefile producing ``libnmf.so`` that the R layer dyn.loads, reference
``Makefile:1-7`` / ``nmf.r:4``). Everything degrades gracefully to the pure
numpy implementation in ``nmfx/cophenetic.py``; set ``NMFX_NATIVE=0`` to
force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import NamedTuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libnmfx_native.so")
_lib: ctypes.CDLL | None = None
_load_attempted = False


def _try_load() -> ctypes.CDLL | None:
    if os.environ.get("NMFX_NATIVE", "1") == "0":
        return None
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                           capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    c_double_p = ctypes.POINTER(ctypes.c_double)
    c_int32_p = ctypes.POINTER(ctypes.c_int32)
    lib.nmfx_average_linkage.restype = ctypes.c_int
    lib.nmfx_average_linkage.argtypes = [c_double_p, ctypes.c_int64,
                                         c_double_p, c_double_p, c_int32_p]
    lib.nmfx_cut_tree.restype = ctypes.c_int
    lib.nmfx_cut_tree.argtypes = [c_double_p, ctypes.c_int64,
                                  ctypes.c_int64, c_int32_p]
    return lib


def available() -> bool:
    global _lib, _load_attempted
    if not _load_attempted:
        _load_attempted = True  # cache failures too: never re-spawn make
        _lib = _try_load()
    return _lib is not None


class NativeHClust(NamedTuple):
    linkage: np.ndarray
    coph: np.ndarray
    order: np.ndarray


def average_linkage(dist: np.ndarray) -> NativeHClust:
    """Native UPGMA; same contract as nmfx.cophenetic.average_linkage."""
    assert available(), "native library not loaded"
    d = np.ascontiguousarray(dist, dtype=np.float64)
    n = d.shape[0]
    if d.shape != (n, n) or n < 2:
        raise ValueError("dist must be square with n >= 2")
    linkage = np.zeros((n - 1, 4), dtype=np.float64)
    coph = np.zeros((n, n), dtype=np.float64)
    order = np.zeros(n, dtype=np.int32)
    c_double_p = ctypes.POINTER(ctypes.c_double)
    c_int32_p = ctypes.POINTER(ctypes.c_int32)
    rc = _lib.nmfx_average_linkage(
        d.ctypes.data_as(c_double_p), n,
        linkage.ctypes.data_as(c_double_p),
        coph.ctypes.data_as(c_double_p),
        order.ctypes.data_as(c_int32_p))
    if rc != 0:
        raise RuntimeError(f"nmfx_average_linkage failed with code {rc}")
    return NativeHClust(linkage, coph, order.astype(np.int64))


def cut_tree(linkage: np.ndarray, n: int, k: int) -> np.ndarray:
    assert available(), "native library not loaded"
    lk = np.ascontiguousarray(linkage, dtype=np.float64)
    labels = np.zeros(n, dtype=np.int32)
    c_double_p = ctypes.POINTER(ctypes.c_double)
    c_int32_p = ctypes.POINTER(ctypes.c_int32)
    rc = _lib.nmfx_cut_tree(lk.ctypes.data_as(c_double_p), n, k,
                            labels.ctypes.data_as(c_int32_p))
    if rc != 0:
        raise RuntimeError(f"nmfx_cut_tree failed with code {rc}")
    return labels.astype(np.int64)
