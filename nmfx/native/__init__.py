"""ctypes loader for the native host library (hclust/cophenetic fast path).

Auto-builds ``libnmfx_native.so`` with the bundled Makefile on first import
when a C++ toolchain is present (the reference repo's equivalent move: a top
Makefile producing ``libnmf.so`` that the R layer dyn.loads, reference
``Makefile:1-7`` / ``nmf.r:4``). Everything degrades gracefully to the pure
numpy implementation in ``nmfx/cophenetic.py``; set ``NMFX_NATIVE=0`` to
force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import NamedTuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libnmfx_native.so")
_lib: ctypes.CDLL | None = None
_load_attempted = False


def _try_load() -> ctypes.CDLL | None:
    if os.environ.get("NMFX_NATIVE", "1") == "0":
        return None
    # ALWAYS invoke make (a ~10 ms no-op when the .so is fresh — the
    # Makefile declares the source dependencies): a stale prebuilt library
    # with an unchanged symbol set would otherwise be served forever, since
    # the AttributeError rebuild path below only fires on MISSING symbols.
    # Best-effort: with no toolchain, fall through to whatever .so exists.
    try:
        subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                       capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        pass
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        return _bind(lib)
    except OSError:
        return None
    except AttributeError:
        # stale prebuilt .so missing newer symbols: rebuild once, retry;
        # any further failure degrades to the numpy fallback as documented.
        # dlopen caches by pathname, so reloading the rebuilt file at the
        # same path would return the stale handle — load it through a
        # uniquely-named temporary copy instead
        import shutil
        import tempfile

        try:
            subprocess.run(["make", "-C", _DIR, "-s", "-B"], check=True,
                           capture_output=True, timeout=120)
            with tempfile.NamedTemporaryFile(
                    suffix=".so", delete=False) as tf:
                shutil.copyfile(_LIB_PATH, tf.name)
            try:
                lib = ctypes.CDLL(tf.name)
            finally:
                # dlopen holds the mapping (Linux); dropping the directory
                # entry immediately avoids leaking one temp file per
                # process that hits the stale-symbol path. Best-effort: an
                # unlink failure must not discard a successfully loaded
                # library (it would propagate to the outer except and
                # silently disable the native path)
                import contextlib

                with contextlib.suppress(OSError):
                    os.unlink(tf.name)
            return _bind(lib)
        except (OSError, AttributeError, subprocess.SubprocessError):
            return None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_double_p = ctypes.POINTER(ctypes.c_double)
    c_int32_p = ctypes.POINTER(ctypes.c_int32)
    lib.nmfx_average_linkage.restype = ctypes.c_int
    lib.nmfx_average_linkage.argtypes = [c_double_p, ctypes.c_int64,
                                         c_double_p, c_double_p, c_int32_p]
    lib.nmfx_cut_tree.restype = ctypes.c_int
    lib.nmfx_cut_tree.argtypes = [c_double_p, ctypes.c_int64,
                                  ctypes.c_int64, c_int32_p]
    lib.nmfx_parse_gct_rows.restype = ctypes.c_int64
    lib.nmfx_parse_gct_rows.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        c_double_p, ctypes.POINTER(ctypes.c_int64)]
    lib.nmfx_format_gct_body.restype = ctypes.c_int64
    lib.nmfx_format_gct_body.argtypes = [
        c_double_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p, ctypes.c_int64]
    return lib


def available() -> bool:
    global _lib, _load_attempted
    if not _load_attempted:
        _load_attempted = True  # cache failures too: never re-spawn make
        _lib = _try_load()
    return _lib is not None


class NativeHClust(NamedTuple):
    linkage: np.ndarray
    coph: np.ndarray
    order: np.ndarray


def average_linkage(dist: np.ndarray) -> NativeHClust:
    """Native UPGMA; same contract as nmfx.cophenetic.average_linkage."""
    assert available(), "native library not loaded"
    d = np.ascontiguousarray(dist, dtype=np.float64)
    n = d.shape[0]
    if d.shape != (n, n) or n < 2:
        raise ValueError("dist must be square with n >= 2")
    linkage = np.zeros((n - 1, 4), dtype=np.float64)
    coph = np.zeros((n, n), dtype=np.float64)
    order = np.zeros(n, dtype=np.int32)
    c_double_p = ctypes.POINTER(ctypes.c_double)
    c_int32_p = ctypes.POINTER(ctypes.c_int32)
    rc = _lib.nmfx_average_linkage(
        d.ctypes.data_as(c_double_p), n,
        linkage.ctypes.data_as(c_double_p),
        coph.ctypes.data_as(c_double_p),
        order.ctypes.data_as(c_int32_p))
    if rc != 0:
        raise RuntimeError(f"nmfx_average_linkage failed with code {rc}")
    return NativeHClust(linkage, coph, order.astype(np.int64))


def cut_tree(linkage: np.ndarray, n: int, k: int) -> np.ndarray:
    assert available(), "native library not loaded"
    lk = np.ascontiguousarray(linkage, dtype=np.float64)
    labels = np.zeros(n, dtype=np.int32)
    c_double_p = ctypes.POINTER(ctypes.c_double)
    c_int32_p = ctypes.POINTER(ctypes.c_int32)
    rc = _lib.nmfx_cut_tree(lk.ctypes.data_as(c_double_p), n, k,
                            labels.ctypes.data_as(c_int32_p))
    if rc != 0:
        raise RuntimeError(f"nmfx_cut_tree failed with code {rc}")
    return labels.astype(np.int64)


def parse_gct_rows(data: bytes, n_rows: int, n_cols: int):
    """Parse the numeric block of GCT data rows (bytes after the header
    lines) into an (n_rows, n_cols) float64 array. Returns (values, n_seen);
    raises ValueError naming the first malformed row."""
    assert available(), "native library not loaded"
    out = np.empty((n_rows, n_cols), dtype=np.float64)
    n_seen = ctypes.c_int64(0)
    rc = _lib.nmfx_parse_gct_rows(
        data, len(data), n_rows, n_cols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(n_seen))
    if rc != 0:
        raise ValueError(f"malformed GCT data row {rc}")
    return out, int(n_seen.value)


def format_gct_body(values: np.ndarray, prefixes: bytes,
                    prefix_ends: np.ndarray) -> memoryview:
    """The complete GCT data block: per row, its prefix bytes (caller joins
    "name\tdescription\t") followed by shortest-exact-repr tab-separated
    values and a newline — one C pass, one buffer, no Python-side copies."""
    assert available(), "native library not loaded"
    vals = np.ascontiguousarray(values, dtype=np.float64)
    n_rows, n_cols = vals.shape
    ends = np.ascontiguousarray(prefix_ends, dtype=np.int64)
    cap = n_rows * (n_cols * 32 + 1) + len(prefixes) + 64
    buf = np.empty(cap, dtype=np.uint8)
    written = _lib.nmfx_format_gct_body(
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_rows, n_cols, prefixes,
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        buf.ctypes.data_as(ctypes.c_char_p), cap)
    if written < 0:
        raise RuntimeError("nmfx_format_gct_body: buffer overflow")
    return memoryview(buf[:written])
