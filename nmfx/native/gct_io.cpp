// Native GCT data-block parser/formatter — the data-loader fast path.
//
// The reference's I/O lives in R (read.gct/write.gct, reference
// nmf.r:261-408) and is far from its bottleneck at its 1000x40 fixture
// sizes; at nmfx's target sizes (20000x1000 and up) text I/O in Python
// would dwarf the few-second on-TPU solve, so the hot numeric block is
// handled here: std::from_chars parsing and std::to_chars shortest-exact
// formatting (bit-roundtrip for float64), with names/headers staying in
// Python. Loaded via ctypes from nmfx/native/__init__.py with a pure-numpy
// fallback (same contract, cross-tested).
//
// Build: make -C nmfx/native   (g++ -O3 -std=c++17, no dependencies)

#include <charconv>
#include <cstdint>
#include <cstring>

extern "C" {

// Parse the numeric part of GCT data rows.
// buf[0..len): the file content after the three header lines; rows are
//   name \t description \t v1 \t ... \t v_{n_cols}, separated by '\n'
//   (blank lines skipped, final newline optional).
// out: n_rows * n_cols doubles (row-major).
// n_seen: receives the number of non-blank rows encountered.
// Returns 0 on success; r > 0 means data row r (1-based) was malformed.
// Stops after n_rows parsed rows (extra rows are counted in n_seen only).
int64_t nmfx_parse_gct_rows(const char* buf, int64_t len, int64_t n_rows,
                            int64_t n_cols, double* out, int64_t* n_seen) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t row = 0;
  *n_seen = 0;
  while (p < end) {
    if (*p == '\n' || *p == '\r') {  // blank line
      ++p;
      continue;
    }
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (line_end == nullptr) line_end = end;
    ++*n_seen;
    if (row < n_rows) {
      // skip the two leading text fields (name, description)
      for (int f = 0; f < 2; ++f) {
        const char* tab = static_cast<const char*>(
            memchr(p, '\t', static_cast<size_t>(line_end - p)));
        if (tab == nullptr) return row + 1;
        p = tab + 1;
      }
      double* dst = out + row * n_cols;
      for (int64_t c = 0; c < n_cols; ++c) {
        if (p < line_end && *p == '+') ++p;  // from_chars rejects '+1.5'
        auto res = std::from_chars(p, line_end, dst[c]);
        if (res.ec != std::errc()) return row + 1;
        p = res.ptr;
        if (c + 1 < n_cols) {
          if (p >= line_end || *p != '\t') return row + 1;
          ++p;
        }
      }
      // after the n_cols values: end of line (optionally '\r'), or extra
      // trailing fields, which are ignored as the reference reader does
      // (it takes fields[2 : 2+n_cols])
      if (p < line_end && *p != '\t' && !(*p == '\r' && p + 1 == line_end))
        return row + 1;
      ++row;
    }
    p = (line_end < end) ? line_end + 1 : end;
  }
  return 0;
}

// Format the complete GCT data block: for each row, copy its prefix bytes
// (the caller-prepared "name\tdescription\t"), then the n_cols values
// tab-separated in shortest exact representation (std::to_chars), then
// '\n'. prefixes is the concatenation of all row prefixes;
// prefix_ends[r] is the exclusive end offset of row r's prefix.
// Returns the number of bytes written, or -1 if out_cap could be exceeded.
int64_t nmfx_format_gct_body(const double* vals, int64_t n_rows,
                             int64_t n_cols, const char* prefixes,
                             const int64_t* prefix_ends, char* out,
                             int64_t out_cap) {
  char* p = out;
  char* cap = out + out_cap;
  int64_t pref_start = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    const int64_t pref_len = prefix_ends[r] - pref_start;
    if (pref_len < 0 || cap - p < pref_len) return -1;
    memcpy(p, prefixes + pref_start, static_cast<size_t>(pref_len));
    p += pref_len;
    pref_start = prefix_ends[r];
    const double* row = vals + r * n_cols;
    for (int64_t c = 0; c < n_cols; ++c) {
      if (cap - p < 32) return -1;
      auto res = std::to_chars(p, cap, row[c]);
      if (res.ec != std::errc()) return -1;
      p = res.ptr;
      *p++ = (c + 1 < n_cols) ? '\t' : '\n';
    }
  }
  return p - out;
}

}  // extern "C"
