// Native host path for the rank-selection step: average-linkage (UPGMA)
// hierarchical clustering, cophenetic distances, dendrogram leaf order, and
// cut-tree memberships.
//
// This is nmfx's analogue of the reference's native layer (libnmf.so loaded
// via dyn.load, reference nmf.r:4): the TPU handles the NMF compute, and this
// library handles the inherently-sequential host-side agglomeration the
// reference delegated to base R's hclust/cophenetic/cutree (nmf.r:165-177).
// Semantics match nmfx/cophenetic.py exactly (tested against it and scipy).
//
// Build: make -C nmfx/native   (g++ -O3, no dependencies)
// ABI: plain C, loaded with ctypes.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

namespace {
constexpr double kInf = std::numeric_limits<double>::infinity();
}

extern "C" {

// dist: n*n row-major symmetric, zero diagonal (not modified)
// linkage: out, (n-1)*4 rows [id_a, id_b, height, size], scipy id convention
// coph: out, n*n cophenetic distances
// order: out, n dendrogram leaf order
// returns 0 on success, nonzero on bad input
int nmfx_average_linkage(const double* dist, int64_t n, double* linkage,
                         double* coph, int32_t* order) {
  if (n < 2) return 1;
  std::vector<double> d(dist, dist + n * n);
  for (int64_t i = 0; i < n; ++i) d[i * n + i] = kInf;

  std::vector<uint8_t> active(n, 1);
  std::vector<double> size(n, 1.0);
  std::vector<int64_t> cid(n);
  std::vector<std::vector<int32_t>> members(n);
  // children[t] = ids merged at step t (cluster id n+t)
  std::vector<std::pair<int64_t, int64_t>> children(n - 1);
  for (int64_t i = 0; i < n; ++i) {
    cid[i] = i;
    members[i].push_back(static_cast<int32_t>(i));
  }
  std::memset(coph, 0, sizeof(double) * n * n);

  // Per-row nearest-neighbor cache over the upper triangle: nn_j[i] is the
  // FIRST j > i minimizing d[i][j] among active columns (strict <, so ties
  // keep the smallest j), nn_d[i] its distance. The globally closest pair is
  // then the first row attaining the minimum of nn_d — identical pair choice
  // (and tie-breaking) to the naive full row-major rescan, but each merge
  // costs O(n + r·n) with r = #rows whose cached neighbor was invalidated,
  // instead of O(n²): ~O(n²) total in practice vs the old O(n³) (28× slower
  // than scipy at n=2000; see benchmarks/RESULTS.md rank-selection rows).
  std::vector<double> nn_d(n, kInf);
  std::vector<int64_t> nn_j(n, -1);
  auto recompute_nn = [&](int64_t i) {
    double bd = kInf;
    int64_t bj2 = -1;
    const double* row = d.data() + i * n;
    for (int64_t j = i + 1; j < n; ++j) {
      if (active[j] && row[j] < bd) {
        bd = row[j];
        bj2 = j;
      }
    }
    nn_d[i] = bd;
    nn_j[i] = bj2;
  };
  for (int64_t i = 0; i < n - 1; ++i) recompute_nn(i);

  for (int64_t t = 0; t < n - 1; ++t) {
    // closest active pair from the caches (first row with the min distance)
    double best = kInf;
    int64_t bi = -1;
    for (int64_t i = 0; i < n; ++i) {
      if (active[i] && nn_d[i] < best) {
        best = nn_d[i];
        bi = i;
      }
    }
    if (bi < 0 || nn_j[bi] < 0) return 2;
    int64_t bj = nn_j[bi];

    int64_t a = std::min(cid[bi], cid[bj]);
    int64_t b = std::max(cid[bi], cid[bj]);
    double new_size = size[bi] + size[bj];
    linkage[t * 4 + 0] = static_cast<double>(a);
    linkage[t * 4 + 1] = static_cast<double>(b);
    linkage[t * 4 + 2] = best;
    linkage[t * 4 + 3] = new_size;

    for (int32_t mi : members[bi])
      for (int32_t mj : members[bj]) {
        coph[static_cast<int64_t>(mi) * n + mj] = best;
        coph[static_cast<int64_t>(mj) * n + mi] = best;
      }

    // UPGMA distance update into slot bi
    for (int64_t kcol = 0; kcol < n; ++kcol) {
      double merged =
          (size[bi] * d[bi * n + kcol] + size[bj] * d[bj * n + kcol]) /
          new_size;
      d[bi * n + kcol] = merged;
      d[kcol * n + bi] = merged;
    }
    d[bi * n + bi] = kInf;
    active[bj] = 0;
    // cache maintenance. Row bi's distances all changed: full recompute.
    // Any other active row whose cached neighbor was bi (distance changed —
    // the UPGMA average can move either way) or bj (deactivated) rescans;
    // otherwise only the refreshed d[i][bi] can displace the cached entry,
    // taking it on strict improvement OR an equal distance at smaller j
    // (the first-minimum convention the full rescan would apply)
    recompute_nn(bi);
    for (int64_t i = 0; i < n; ++i) {
      if (!active[i] || i == bi) continue;
      if (nn_j[i] == bi || nn_j[i] == bj) {
        recompute_nn(i);
      } else if (i < bi) {
        double di = d[i * n + bi];
        if (di < nn_d[i] || (di == nn_d[i] && bi < nn_j[i])) {
          nn_d[i] = di;
          nn_j[i] = bi;
        }
      }
    }
    children[t] = {a, b};
    auto& mj = members[bj];
    members[bi].insert(members[bi].end(), mj.begin(), mj.end());
    mj.clear();
    mj.shrink_to_fit();
    size[bi] = new_size;
    cid[bi] = n + t;
  }

  // depth-first leaf order, left child first
  std::vector<int64_t> stack;
  stack.push_back(2 * n - 2);
  int64_t pos = 0;
  while (!stack.empty()) {
    int64_t node = stack.back();
    stack.pop_back();
    if (node < n) {
      order[pos++] = static_cast<int32_t>(node);
    } else {
      auto [left, right] = children[node - n];
      stack.push_back(right);
      stack.push_back(left);
    }
  }
  return pos == n ? 0 : 3;
}

// linkage: (n-1)*4 as produced above; labels out: n entries in 1..k,
// numbered by first appearance in leaf index order (R cutree convention)
int nmfx_cut_tree(const double* linkage, int64_t n, int64_t k,
                  int32_t* labels) {
  if (k < 1 || k > n) return 1;
  std::vector<int64_t> parent(2 * n - 1);
  for (int64_t i = 0; i < 2 * n - 1; ++i) parent[i] = i;
  auto find = [&](int64_t x) {
    while (parent[x] != x) {
      parent[x] = parent[parent[x]];
      x = parent[x];
    }
    return x;
  };
  for (int64_t t = 0; t < n - k; ++t) {
    int64_t a = static_cast<int64_t>(linkage[t * 4 + 0]);
    int64_t b = static_cast<int64_t>(linkage[t * 4 + 1]);
    parent[find(a)] = n + t;
    parent[find(b)] = n + t;
  }
  std::vector<int64_t> seen(2 * n - 1, 0);
  int32_t next_label = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t root = find(i);
    if (seen[root] == 0) seen[root] = ++next_label;
    labels[i] = static_cast<int32_t>(seen[root]);
  }
  return 0;
}

}  // extern "C"
