"""Device-resident input cache: repeat sweeps over the same matrix
transfer zero bytes.

BENCH_r05 put the warm path's host→device transfer at 0.359 s against a
1.21 s device solve — and the serving scenarios this framework targets
(the exec-cache layer, per-rank executables, re-runs at new rank sets)
all re-submit the SAME matrix over and over. The reference has no such
cost (its workers read A from the filesystem once each, nmf.r:112); here
every `sweep()` re-placed A from host. PL-NMF (arxiv 1904.07935) gets
its throughput precisely from keeping operands device-resident across
updates; this module does the same across *requests*:

* **Content-fingerprint key.** A placed matrix is cached under a
  :class:`DataKey` — sha256 of the raw host bytes plus everything that
  changes the device buffer it maps to: shape, the placement dtype, the
  bucket pad shape (the exec-cache layer caches the PADDED array), and
  the mesh placement. Content hashing (not ``id()``) is the honesty
  discipline: a caller that mutates its array in place gets a new
  fingerprint and a fresh transfer, never a stale buffer. The key is a
  frozen dataclass whose coverage is NMFX001-checked
  (:func:`data_key_fields` — a field added with ``compare=False`` would
  alias two different placements onto one cached buffer and fails
  lint). The fingerprint costs one sha256 pass over the host bytes per
  ``place()`` call, hits included (~GB/s — cheap against the transfer
  it saves at the north-star sizes, but NOT free at multi-GB scale): a
  caller that can guarantee identity itself should place once and pass
  the resulting ``jax.Array`` thereafter — device inputs bypass the
  fingerprint entirely (they ARE the resident buffer).
* **Chunked, double-buffered first touch.** A cache miss on a
  single-device placement splits the transfer into row chunks and
  dispatches each ``device_put`` asynchronously — the chunks pipeline
  against each other and against whatever compile/dispatch work follows
  (the first rank's lane init), instead of one monolithic blocking
  copy. Mesh placements delegate to ``sweep.place_input`` (replication/
  tiling is the backend's job) but still cache the result.
* **Transfer counters.** :func:`transfer_count` / :func:`h2d_bytes`
  count actual host→device input transfers module-wide — the same
  honesty-counter discipline as ``exec_cache.compile_count()``: a
  second sweep over the same array must leave both unchanged
  (tests/test_data_cache.py gates it).

The cache holds LIVE device buffers, so it is LRU-bounded both by entry
count and by bytes (`max_entries`/`max_bytes`, default 8 entries /
2 GiB); oversized single arrays are transferred but never retained.
The process-wide default is re-boundable at runtime
(:meth:`DataCache.resize`, CLI ``--input-cache-bytes``; 0 disables
retention) for accelerators where resident inputs would compete with
solver working memory.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from nmfx.guards import guarded_by
from nmfx.obs import flight as _flight
from nmfx.obs import metrics as _metrics

__all__ = ["DataCache", "DataKey", "data_key_fields", "default_cache",
           "h2d_bytes", "place_resilient", "transfer_count"]

# registry instruments of ACTUAL input host->device transfers — the
# honesty counters behind the zero-transfer warm-path contract (a cached
# placement must not touch them), mirroring exec_cache.compile_count().
# transfer_count()/h2d_bytes() below are the back-compat read shims the
# counter-gated tests and bench gates keep using (ISSUE 10)
_h2d_transfers_total = _metrics.counter(
    "nmfx_data_h2d_transfers_total",
    "input-matrix host-to-device transfers actually paid (cache hits "
    "do not count)")
_h2d_bytes_total = _metrics.counter(
    "nmfx_data_h2d_bytes_total",
    "bytes of input-matrix host-to-device transfers actually paid")
_data_evictions_total = _metrics.counter(
    "nmfx_data_cache_evictions_total",
    "device-resident input-cache entries evicted (LRU bound)")

#: below this many bytes a chunked transfer costs more in dispatch
#: overhead than it overlaps; single device_put instead
_CHUNK_MIN_BYTES = 8 << 20
#: target bytes per chunk of the double-buffered first-touch transfer
_CHUNK_BYTES = 4 << 20


def transfer_count() -> int:
    """How many input matrices this process ACTUALLY transferred to
    device through the data cache (cache hits do not count). Reads the
    registry counter ``nmfx_data_h2d_transfers_total`` (back-compat
    shim — the gated contracts are unchanged)."""
    return int(_h2d_transfers_total.total())


def h2d_bytes() -> int:
    """Total bytes of input-matrix host→device transfers this process
    actually paid through the data cache (registry counter
    ``nmfx_data_h2d_bytes_total``)."""
    return int(_h2d_bytes_total.total())


def _note_transfer(nbytes: int) -> None:
    _h2d_transfers_total.inc()
    _h2d_bytes_total.inc(nbytes)


@dataclasses.dataclass(frozen=True)
class DataKey:
    """Everything that determines the device buffer a host matrix maps
    to. Every field participates in ``__eq__``/``__hash__`` (frozen
    dataclass, no ``compare=False``) — the NMFX001-style coverage
    :func:`data_key_fields` declares and ``nmfx-lint`` enforces: a field
    dropped from comparison would serve one resident buffer to two
    placements that must differ."""

    #: sha256 hex digest of the raw host bytes — content, not identity
    fingerprint: str
    #: the SOURCE array's dtype: the same raw bytes mean different
    #: values under a different interpretation (a float32 matrix and
    #: its int32 byte-view hash identically but cast differently)
    src_dtype: str
    #: the TRUE (m, n) of the matrix
    shape: tuple
    #: the placement dtype (SolverConfig.dtype string)
    dtype: str
    #: bucket (m_pad, n_pad) when the caller places a zero-padded copy
    #: (the exec-cache layer); None for exact-shape placement
    pad_shape: "tuple | None"
    #: the device mesh the array is placed for (replication vs
    #: feature/sample tiling); None = single-device default placement
    mesh: object
    #: the concrete target device for mesh-less placement (per-request
    #: ``jax.default_device`` routing must not share one resident
    #: buffer across devices); None when a mesh governs placement
    device: object


def data_key_fields() -> frozenset:
    """The :class:`DataKey` fields the cache key compares — the
    introspection hook lint rule NMFX001 cross-references. Reading
    ``field.compare`` keeps it honest: a field added with
    ``compare=False`` is invisible to the dataclass hash/eq the cache
    looks entries up by, and shows up here (and fails lint) as
    uncovered."""
    return frozenset(f.name for f in dataclasses.fields(DataKey)
                     if f.compare)


class _Entry:
    __slots__ = ("array", "nbytes")

    def __init__(self, array: jax.Array, nbytes: int):
        self.array = array
        self.nbytes = nbytes


@guarded_by("_lock", "_entries", "hits", "misses", "evictions")
class DataCache:
    """LRU of device-resident input matrices keyed by content
    fingerprint + placement (:class:`DataKey`).

    One instance (the module :func:`default_cache`) serves the whole
    process: ``sweep()`` and ``ExecCache.prefetch`` both place inputs
    through it, so a serving process pays each distinct (matrix,
    placement) exactly one transfer for as long as the entry stays
    resident. Thread-safe (the lookup/insert path is lock-guarded;
    transfers themselves run outside the lock).
    """

    def __init__(self, max_entries: int = 8,
                 max_bytes: int = 1 << 31):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        if max_bytes < 0:
            raise ValueError("max_bytes must be >= 0")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[DataKey, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- policy ------------------------------------------------------------
    def key_for(self, a, dtype: str,
                pad_shape: "tuple | None" = None,
                mesh=None) -> DataKey:
        from nmfx.sparse import SparseMatrix

        if isinstance(a, SparseMatrix):
            # content-hash the canonical triplets, not a densified copy
            # — densifying an atlas to fingerprint it defeats the sparse
            # path; the triplet digest is exactly as content-addressed
            # (SparseMatrix.fingerprint covers shape + value dtype too)
            digest = a.fingerprint()
            src_dtype = a.data.dtype.str
        else:
            arr = np.ascontiguousarray(a)
            digest = hashlib.sha256(
                arr.view(np.uint8).reshape(-1)).hexdigest()
            src_dtype = arr.dtype.str
        if mesh is None:
            # the device an un-meshed device_put would target RIGHT NOW
            device = (getattr(jax.config, "jax_default_device", None)
                      or jax.devices()[0])
        else:
            device = None  # the mesh names the devices
        return DataKey(fingerprint=digest, src_dtype=src_dtype,
                       shape=tuple(a.shape), dtype=str(dtype),
                       pad_shape=pad_shape, mesh=mesh, device=device)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def resize(self, max_entries: "int | None" = None,
               max_bytes: "int | None" = None) -> None:
        """Re-bound the live-buffer budget (the sizing surface for the
        process-wide :func:`default_cache`; CLI ``--input-cache-bytes``).
        ``max_bytes=0`` disables retention entirely — every placement
        still transfers correctly, nothing stays resident. Shrinking
        evicts LRU-first immediately."""
        with self._lock:
            if max_entries is not None:
                if max_entries < 1:
                    raise ValueError("max_entries must be >= 1")
                self.max_entries = max_entries
            if max_bytes is not None:
                if max_bytes < 0:
                    raise ValueError("max_bytes must be >= 0")
                self.max_bytes = max_bytes
            self._evict_locked()

    def _evict_locked(self) -> None:
        """LRU-evict until within bounds; caller holds ``_lock``. A
        just-inserted entry is MRU and pre-gated to fit ``max_bytes``,
        so it always survives its own insertion."""
        total = sum(e.nbytes for e in self._entries.values())
        while self._entries and (len(self._entries) > self.max_entries
                                 or total > self.max_bytes):
            key, dropped = self._entries.popitem(last=False)
            total -= dropped.nbytes
            self.evictions += 1
            _data_evictions_total.inc()
            _flight.record("cache.evict", cache="data",
                           nbytes=dropped.nbytes,
                           fingerprint=key.fingerprint[:12])

    @property
    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "bytes": sum(e.nbytes
                                 for e in self._entries.values()),
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}

    # -- placement ---------------------------------------------------------
    def place(self, a, solver_cfg, mesh=None, *,
              pad_shape: "tuple | None" = None,
              profiler=None) -> jax.Array:
        """Device-resident ``a`` in the solver dtype — from cache when
        this exact (content, placement) was placed before, else via a
        fresh (chunked, asynchronously dispatched) transfer that is
        cached for the next request.

        An input that is already a ``jax.Array`` passes through
        ``sweep.place_input``'s idempotent path untouched (it IS
        device-resident — caching it would only pin a second
        reference). ``pad_shape`` places a zero-padded ``(m_pad,
        n_pad)`` copy (the exec-cache bucket layout). Nothing here
        blocks: ``device_put`` dispatch is asynchronous, so the actual
        copy overlaps whatever compile/dispatch follows — callers time
        the dispatch under the ``xfer.h2d_overlap`` phase.
        """
        from nmfx.profiling import NullProfiler
        from nmfx.sweep import place_input

        prof = profiler if profiler is not None else NullProfiler()
        dtype = jnp.dtype(solver_cfg.dtype)
        if isinstance(a, jax.Array):
            # already device-resident: pad/cast on device — pulling it
            # back to host to fingerprint would pay the very transfer
            # this cache exists to avoid
            if pad_shape is None:
                return place_input(a, solver_cfg, mesh)
            m, n = a.shape
            m_pad, n_pad = pad_shape
            a_pad = jnp.pad(jnp.asarray(a, dtype),
                            ((0, m_pad - m), (0, n_pad - n)))
            return (place_input(a_pad, solver_cfg, mesh)
                    if mesh is not None else a_pad)
        a = np.asarray(a)
        key = self.key_for(a, solver_cfg.dtype, pad_shape, mesh)
        # Concurrency audit (the serve front-end's submit threads and
        # scheduler share this instance —
        # tests/test_data_cache.py::test_concurrent_place_access): the
        # lookup-or-miss decision and its counter land in ONE lock
        # acquisition, so hits+misses always equals host-path calls;
        # the transfer itself runs outside the lock by design (it must
        # overlap other threads' hits), which means two threads racing
        # the SAME cold key may both transfer — the second insert
        # overwrites the first (same key, same bytes), counters record
        # two honest misses, and no entry or byte total is corrupted.
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        if entry is not None:
            prof.mark("xfer.h2d_cache_hit")
            return entry.array
        from nmfx import faults

        # chaos site: the actual input transfer (cache hits never
        # transfer, so they sit above this); callers that can degrade
        # route through place_resilient, whose direct fallback does NOT
        # pass this site again
        faults.inject("h2d.transfer")
        t0 = time.perf_counter()
        host, placed = _pad_and_transfer(a, dtype, pad_shape,
                                         solver_cfg, mesh)
        prof.add_seconds("xfer.h2d_overlap", time.perf_counter() - t0)
        if host.nbytes <= self.max_bytes:
            with self._lock:
                self._entries[key] = _Entry(placed, host.nbytes)
                self._evict_locked()
        return placed

    @staticmethod
    def _chunked_put(host: np.ndarray) -> jax.Array:
        """Asynchronously dispatched host→device transfer; large arrays
        go up in row chunks so the copies double-buffer against each
        other (and against the first rank's compile/dispatch, which
        starts before any of them complete)."""
        if host.nbytes < _CHUNK_MIN_BYTES or host.shape[0] < 2:
            return jax.device_put(host)
        rows_per_chunk = max(
            1, int(host.shape[0] * _CHUNK_BYTES / host.nbytes))
        chunks = [jax.device_put(host[i:i + rows_per_chunk])
                  for i in range(0, host.shape[0], rows_per_chunk)]
        if len(chunks) == 1:
            return chunks[0]
        return jnp.concatenate(chunks, axis=0)


def _pad_and_transfer(a, dtype, pad_shape, solver_cfg, mesh
                      ) -> "tuple[np.ndarray, jax.Array]":
    """The ONE host-materialize → zero-pad → host→device transfer both
    :meth:`DataCache.place`'s miss path and :func:`place_resilient`'s
    direct fallback run — the degraded path transfers bit-identical
    device bytes by construction, not by parallel maintenance of two
    copies. Returns ``(host_array, placed)`` and books the transfer
    counters."""
    from nmfx.sweep import place_input

    host = np.asarray(a, dtype)
    if pad_shape is not None:
        m, n = a.shape
        padded = np.zeros(pad_shape, dtype)
        padded[:m, :n] = host
        host = padded
    if mesh is not None:
        placed = place_input(host, solver_cfg, mesh)
    else:
        placed = DataCache._chunked_put(host)
    _note_transfer(host.nbytes)
    return host, placed


_default = DataCache()


def default_cache() -> DataCache:
    """The process-wide cache ``sweep()``/``ExecCache.prefetch`` place
    inputs through."""
    return _default


def place_resilient(a, solver_cfg, mesh=None, *,
                    pad_shape: "tuple | None" = None,
                    profiler=None) -> jax.Array:
    """:meth:`DataCache.place` with graceful degradation: a placement
    failure inside the cache (an injected ``h2d.transfer`` fault, an
    allocator hiccup, a poisoned cache state) falls back to a DIRECT
    uncached host→device transfer of the same padded bytes — the device
    values, and therefore every downstream result, are bit-identical;
    only residency (and the zero-transfer warm-path win) is lost until
    the cache recovers. The fallback is warn-once per process
    (``nmfx.faults.warn_once``) and keeps the transfer counters honest.
    The serving stack places every input through this wrapper
    (``ExecCache.prefetch``, ``sweep.sweep``)."""
    try:
        return default_cache().place(a, solver_cfg, mesh,
                                     pad_shape=pad_shape,
                                     profiler=profiler)
    except Exception as e:
        from nmfx.faults import warn_once

        warn_once(
            "h2d-direct-fallback",
            f"input-cache placement failed ({e!r}); serving this (and "
            "only this) placement through a direct uncached transfer — "
            "results are unaffected, the resident-input optimization is "
            "bypassed")
        if isinstance(a, jax.Array):  # place() cannot fail before its
            raise  # device-input passthrough; don't re-place blindly
        _, placed = _pad_and_transfer(a, jnp.dtype(solver_cfg.dtype),
                                      pad_shape, solver_cfg, mesh)
        return placed
