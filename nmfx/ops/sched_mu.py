"""Slot-scheduled whole-grid MU: a work-conserving job scheduler inside one
``lax.while_loop``.

The reference's execution model is a job array: all |k|·R (k, restart) jobs
queued at once, chunk-shuffled over a fixed pool of workers that pick up new
jobs as they finish (reference ``nmf.r:64-68``, ``nmf.r:111-113``). This
module is that model made TPU-native, with the worker pool as a *static
batch dimension*:

* S **slots** (default 48 — ``ConsensusConfig.grid_slots`` is the
  authoritative knob; the sweep always passes it) form a dense zero-padded
  factor batch
  ``(S, m, k_max)`` / ``(S, k_max, n)`` — each slot hosts ONE job's
  factorization, iterated with the shared-GEMM step of ``grid_mu``.
* When a slot's job converges (the reference class-stability rule + TolX,
  via ``packed_mu.batch_convergence``), its factors scatter into per-job
  result buffers and the slot **reloads the next queued job's** W0/H0 in
  place — all static-shape gathers/scatters inside the loop carry.
* Jobs are fed **longest-expected-first** (rank-descending — iteration
  counts grow with k), the classic LPT schedule: stragglers start early and
  overlap the bulk, short jobs drain the tail.

Why this shape: a plain whole-grid batch (``grid_mu``) holds every lane
until the LAST lane converges, so the measured wall is
``global_max_iters × c(full width)`` — at the north-star sweep ~7200
straggler iterations × the 450-lane iteration cost, ~4× worse than the
sequential per-rank path. The slot pool keeps the running width at S
always-busy lanes instead: total wall ≈
``max(longest job, total lane-iters / S) × c(S)``, minimized near S = 48
at the north-star sweep (measured 1.41 s vs 1.63 s at 64, 8.35 s for the
fixed 450-lane batch) — while still being ONE compile
for the entire sweep (the per-k path pays one ~10 s compile per rank) and
keeping every GEMM at MXU-dense width. Per-job trajectories are
bit-identical to the fixed-batch path (each slot's updates read only its
own lane of the batched GEMMs); only scheduling changes.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from nmfx._compat import pcast
from nmfx.config import SolverConfig
from nmfx.ops.grid_mu import (BLOCKS, USES_TOLFUN, conv_cfg,
                              make_block, tolfun_update)
from nmfx.ops.packed_mu import batch_convergence, residual_norms_direct
from nmfx.solvers import base


#: TEST-ONLY stale-reload fault injection: fraction of pallas-path slot
#: reloads whose FACTOR writes are dropped while the scheduler's
#: bookkeeping proceeds as if the reload happened — the exact round-3
#: failure signature (VERDICT.md round 3; the fault class the
#: ``bench.py --verify`` hardware gate is proven against,
#: ``benchmarks/probe_fault_gate.py``). Since ISSUE 7 the armed state
#: lives on the ``nmfx.faults`` registry (site ``sched.stale_reload``,
#: rate-armed), which also keys the sweep builders' caches through
#: ``faults.trace_token()`` — arming after a trace can no longer
#: silently serve the previously cached clean executable, the staleness
#: class both this hook's env-var ancestor (ADVICE.md round 5; lint
#: rule NMFX002) and its explicit-call successor still carried. The
#: ``NMFX_FAULT_INJECT_STALE_RELOAD`` env var alone remains INERT in
#: library code; ``bench.py --verify`` — the one sanctioned harness —
#: translates it into the explicit call at process startup, which keeps
#: ``probe_fault_gate.py``'s subprocess protocol working unchanged.
_announced = {"done": False}


def enable_stale_reload_fault(fraction: float) -> None:
    """Deprecated shim: arm the stale-reload fault through the
    ``nmfx.faults`` registry (``faults.arm("sched.stale_reload",
    rate=fraction)`` is the canonical spelling). Kept because
    ``bench.py --verify``'s env→call subprocess protocol and external
    probe harnesses target this name; announces itself loudly on
    stderr + the nmfx logger exactly as before — results from an armed
    process are INVALID by design."""
    import warnings

    from nmfx import faults

    frac = float(fraction)
    if not 0.0 <= frac <= 1.0:
        raise ValueError(
            f"fault fraction must be in [0, 1], got {fraction!r}")
    warnings.warn(
        "enable_stale_reload_fault() is a deprecated shim; arm the "
        "registry directly: nmfx.faults.arm('sched.stale_reload', "
        "rate=...)", DeprecationWarning, stacklevel=2)
    if frac > 0:
        faults.arm("sched.stale_reload", rate=frac)
    else:
        faults.disarm("sched.stale_reload")
    if frac > 0 and not _announced["done"]:
        _announced["done"] = True
        import logging
        import sys

        banner = (
            "stale-reload fault injection ARMED at fraction %g: slot "
            "reloads are being deliberately corrupted (test-only, for "
            "the bench.py --verify gate). Results from this process "
            "are INVALID." % frac)
        print(f"nmfx: *** {banner} ***", file=sys.stderr)
        logging.getLogger("nmfx").warning(banner)


def _warn_inert_env_hook() -> None:
    """Import-time notice when the retired env var is set: it no longer
    does anything by itself (see ``_fault_state``), but a process that
    inherited it almost certainly expected the old behavior — say so
    loudly instead of silently diverging from that expectation."""
    import os

    if os.environ.get("NMFX_FAULT_INJECT_STALE_RELOAD", ""):
        import logging
        import sys

        notice = (
            "NMFX_FAULT_INJECT_STALE_RELOAD is set but IGNORED by "
            "library code: fault injection now requires the explicit "
            "nmfx.ops.sched_mu.enable_stale_reload_fault() opt-in "
            "(bench.py --verify makes that call itself). An inherited "
            "env var alone can no longer corrupt a run.")
        print(f"nmfx: *** {notice} ***", file=sys.stderr)
        logging.getLogger("nmfx").warning(notice)


_warn_inert_env_hook()


def _stale_reload_fraction() -> float:
    """The armed fault fraction (0.0 = off), from the ``nmfx.faults``
    registry — never env: trace-time environment reads are the NMFX002
    lint class, and the registry's ``trace_token`` keys the builder
    caches so this trace-time read can never go stale in a cached
    executable."""
    from nmfx import faults

    return faults.stale_reload_fraction()


def _stale_load_mask(load, gather):
    """Apply the stale-reload fault injection to a reload mask: drop the
    factor write for a deterministic per-job subset (Knuth-hash of the
    job id) while the caller's bookkeeping proceeds on the UNMASKED
    flags. Single source of the injected failure signature for BOTH
    reload paths (uniform ``reload`` and the ragged evict) — the
    hardware gate's fault-injection proof depends on the two injecting
    the identical fault class. Identity when the env hook is unset."""
    stale_frac = _stale_reload_fraction()
    if stale_frac <= 0:
        return load
    job_hash = (gather.astype(jnp.uint32) * jnp.uint32(2654435761)
                & jnp.uint32((1 << 16) - 1))
    return load & ~(job_hash < jnp.uint32(int(stale_frac * (1 << 16))))


def _streams_bf16_a(cfg: SolverConfig) -> bool:
    """Whether the loop streams A as one-time-truncated bf16 (the MXU
    would round the GEMM operands to bf16 either way under this
    precision, so results are unchanged and A's HBM traffic halves).
    kl is excluded by default: its block consumes A in an ELEMENTWISE
    division (the quotient A ⊘ WH), where truncation is a real ~0.4%
    per-element perturbation the vmapped engine does not have — not a
    free MXU rounding; ``cfg.experimental.kl_bf16_quotient`` opts in (see the
    measured accept/reject note on that field). Single source of truth
    for both the cast sites in ``mu_sched``/``mu_grid`` and the VMEM
    slot clamp's a_bytes — the sites must never disagree or the byte
    model is off by 2x on the A-tile term."""
    return (cfg.matmul_precision == "bfloat16"
            and (cfg.algorithm != "kl"
                 or cfg.experimental.kl_bf16_quotient)
            and jnp.dtype(cfg.dtype) == jnp.float32
            and jax.default_backend() == "tpu")


def _pallas_block_geometry(m: int, block_m: "int | None" = None):
    """Tile geometry shared by the clamp and the solver: ~512-row tiles,
    16-row-aligned so bf16 A streams on its native sublane tiling.
    ``block_m`` overrides the tile rows (``experimental.block_m`` — set
    by hand or by the autotuner); the override must be 16-aligned
    (validated by ExperimentalConfig) and m pads up to a multiple."""
    ceil_div = lambda x, d: -(-x // d)
    if block_m is not None:
        tiles = ceil_div(m, block_m)
        return tiles, block_m, tiles * block_m
    tiles = ceil_div(m, 512)
    block_m = ceil_div(ceil_div(m, tiles), 16) * 16
    return tiles, block_m, tiles * block_m


def _pallas_max_rk(m: int, n: int, cfg: SolverConfig,
                   factor_dtype: "str | None" = None,
                   check_block: int = 1, fused: bool = False,
                   algorithm: str = "mu",
                   block_m: "int | None" = None) -> int:
    """Largest packed column count the resident-W block kernel's VMEM
    envelope admits at this shape (the inequality documented in
    ``_pallas_slot_clamp``; shared by the uniform clamp and the ragged
    pool's column budget).

    ``factor_dtype="bfloat16"`` models the round-5 bf16-factor-storage
    experiment: the W/H windows halve while the f32 numer/gram scratch
    stays — modeled as ``2·rk·m_pad + 10·rk·n_pad + 4·rk²`` against a
    CONSERVATIVE 13.5 MiB budget. ``"bfloat16_w"`` (round 6) halves only
    the W window: ``2·rk·m_pad + 12·rk·n_pad + 4·rk²`` against the same
    conservative budget (neither bf16 variant is boundary-probed on
    hardware; Mosaic still rejects loudly if the model ever over-admits).
    ``check_block > 1`` adds the per-boundary stat windows (the H
    snapshots live in HBM and cost no VMEM): ``16·check_block·rk + 8·rk``
    bytes — ~64 KB at the north star, inside the fitted model's measured
    slack, but counted so the boundary stays honest.

    ``fused=True`` (round 7 join-the-updates kernel) adds the hgram
    scratch: ``4·rk²`` — one extra (rk, rk) f32 window. ``algorithm=
    "hals"`` adds the coordinate-sweep scratches of
    ``_hals_block_kernel``: the (rk, n) f32 sweep buffer, the
    (block_m, rk) f32 W work tile and ~3 transient (rk, rk) f32
    permutation temporaries — ``4·rk·n_pad + 4·block_m·rk + 12·rk²``,
    deliberately conservative (Mosaic still rejects loudly if the model
    ever over-admits). ``block_m`` forwards the experimental tile-shape
    override into the geometry so the envelope prices the tiles that
    will actually run."""
    _, block_m, m_pad = _pallas_block_geometry(m, block_m)
    n_pad = -(-n // 128) * 128
    a_bytes = 2 if _streams_bf16_a(cfg) else jnp.dtype(cfg.dtype).itemsize
    # per-boundary TolX stat outputs (wd/wm (N, rk) + hd/hm (N·rk, 1),
    # f32) plus the two (·, rk) budget-fence inputs
    def check_extra(rk):
        extra = 0
        if check_block > 1:
            extra += 16 * check_block * rk + 8 * rk
        if fused:
            extra += 4 * rk * rk
        if algorithm == "hals":
            extra += 4 * rk * n_pad + 4 * block_m * rk + 12 * rk * rk
        return extra

    if factor_dtype in ("bfloat16", "bfloat16_w"):
        # bf16 W window; the n-proportional term keeps f32 numer/extra
        # plus the H window at 2 ("bfloat16") or 4 ("bfloat16_w") bytes
        h_mult = 10 if factor_dtype == "bfloat16" else 12
        budget = int(13.5 * 2**20) - 2 * block_m * n_pad * a_bytes

        def need(rk):
            return (2 * rk * m_pad + h_mult * rk * n_pad + 4 * rk * rk
                    + check_extra(rk))
    else:
        budget = int(14.3 * 2**20) - 2 * block_m * n_pad * a_bytes

        def need(rk):
            return 4 * rk * (m_pad + 3 * n_pad + rk) + check_extra(rk)
    rk = 0
    while need(rk + 1) <= budget:
        rk += 1
    return rk


def _pallas_slot_clamp(s: int, k_max: int, m: int, n: int,
                       cfg: SolverConfig,
                       factor_dtype: "str | None" = None,
                       check_block: int = 1, fused: bool = False,
                       algorithm: str = "mu",
                       block_m: "int | None" = None) -> int:
    """Clamp the slot pool to the resident-W block kernel's VMEM envelope.

    Empirical v5e model (round 4, benchmarks/probe_vmem_envelope*.py —
    measured OK/OOM boundaries at m∈{5120,10240,20480}, n∈{512,1024,2048},
    both A dtypes): with rk = s·k_max packed columns,

        4·rk·(m_pad + 3·n_pad + rk) + 2·block_m·n_pad·a_bytes

    must stay ≤ 14.3 MiB (≈ the 16 MiB scoped-VMEM limit minus ~1.7 MiB
    fixed overhead; the 3·n_pad term — one slot beyond the h/numer
    windows — matches an extra n-proportional allocation visible in the
    measured OOM sizes). The fit separates every measured point with the
    accepts maxing at 14.14 MiB (rk=448 f32, boundary OK) and the
    rejects starting at 14.5 MiB (rk=512 f32 at block_m=128): accepts
    rk=480 at the north star (m=5120, n=512, bf16 — the 48-slot pool at
    k_max=10, model 14.07 MiB), rejects rk=512 there (model 15.0, OOM
    17.08 measured), accepts rk=320 (12.39) and rejects rk=384 (14.56)
    at n=1024. Boundary points are pinned by
    tests/test_slot_clamps.py. Shrinks the pool to the largest fitting slot count
    instead of letting Mosaic reject at compile time (the model is
    best-effort: if it ever admits an unfittable shape, Mosaic still
    fails loudly at compile time); the queue semantics are
    slot-count-free (test_sched_mu.py::test_schedule_free_results). The
    clamp is a real performance cliff (fewer resident lanes → narrower
    GEMMs), so any reduction below the requested pool is logged at
    WARNING.
    """
    def fits(slots: int) -> bool:
        return slots * k_max <= _pallas_max_rk(
            m, n, cfg, factor_dtype, check_block, fused=fused,
            algorithm=algorithm, block_m=block_m)

    if not fits(1):
        raise ValueError(
            f"one k={k_max} job at m={m}, n={n} already exceeds the pallas "
            "scheduler's resident-W VMEM envelope (see "
            "nmfx/ops/pallas_mu.py VMEM budget); use backend='packed'")
    clamped = s
    while not fits(clamped):
        clamped -= 1
    if clamped < s:
        import logging
        logging.getLogger("nmfx").warning(
            "pallas scheduler: slot pool clamped %d -> %d (VMEM envelope: "
            "k_max=%d, m=%d, n=%d, %d packed columns resident); fewer "
            "slots narrows the batched GEMMs — backend='packed' may be "
            "faster at this shape", s, clamped, k_max, m, n,
            clamped * k_max)
    return clamped


def _kl_slot_clamp(s: int, m: int, n: int, dtype) -> int:
    """Bound kl's quotient working set: each live lane materializes m×n
    intermediates (reconstruction, quotient, and the contraction operand
    — budgeted as 3 concurrently-live (B, m, n) buffers, conservative
    against XLA fusion), so the slot pool is the memory knob on this path
    (the role ``restart_chunk`` plays for the vmapped driver). Capped at
    ~4 GB of quotient traffic — no clamp at the north-star 5000×500
    (133-slot ceiling), 16 slots at 20000×1000 f32. Logged at WARNING
    when it shrinks the requested pool, like the pallas VMEM clamp."""
    bytes_per_lane = 3 * m * n * jnp.dtype(dtype).itemsize
    clamped = max(1, min(s, int(4e9 // bytes_per_lane)))
    if clamped < s:
        import logging
        logging.getLogger("nmfx").warning(
            "kl scheduler: slot pool clamped %d -> %d (each lane holds "
            "~3 m*n quotient intermediates; m=%d, n=%d)", s, clamped, m, n)
    return clamped


class _RaggedClass(NamedTuple):
    """Static description of one rank class in the ragged pool."""
    k: int  # true rank of this class's jobs
    jobs: tuple  # global job indices, dispatch order
    slots: int  # resident slots allocated to the class
    off: int  # first packed column of the class's span


def _ragged_iters_est(k: int) -> float:
    """Expected class-stability stop iteration by rank — the empirical
    north-star profile (BENCH_r04 mean_iters_per_k: flat ≈515 through
    k=4, then ≈ k^1.45 growth; a naive k^1.5-everywhere model
    mis-allocated the round-5 prototype 4× — see RESULTS.md round-5
    ragged section). Only schedule QUALITY depends on this; results
    never do. Workloads whose iteration profile departs the calibration
    should pass measured estimates instead
    (``ExperimentalConfig.ragged_iters_est``, derived from a previous
    run via :func:`ragged_estimates_from_iterations`) — ``_resolve_est``
    WARNs when the default model is extrapolating."""
    return 515.0 * max(1.0, k / 4.0) ** 1.45


def ragged_estimates_from_iterations(job_ks, iterations
                                     ) -> tuple[tuple[int, float], ...]:
    """Per-class mean stop iterations from a previous run's recorded
    ``SchedMUResult.iterations`` (or any per-job iteration array aligned
    with ``job_ks``) — the measured replacement for the built-in
    north-star model, in the hashable form
    ``ExperimentalConfig.ragged_iters_est`` takes. The scheduler's own
    ``pool_trips``/``pool_lanes`` counters bound the same quantity per
    stage; the per-job counts are strictly finer, so they are the
    calibration source."""
    its = np.asarray(iterations, dtype=np.float64)
    if len(job_ks) != its.shape[0]:
        raise ValueError(
            f"job_ks has {len(job_ks)} entries but iterations carries "
            f"{its.shape[0]} jobs")
    by_k: dict[int, list[float]] = {}
    for k, it in zip(job_ks, its):
        by_k.setdefault(int(k), []).append(float(it))
    return tuple(sorted((k, float(np.mean(v))) for k, v in by_k.items()))


def _resolve_est(iters_est, job_ks, max_iter: int):
    """The per-rank iteration-estimate function the ragged layout
    allocates slots with: caller-measured estimates when provided, else
    the built-in north-star model — WARNING when that model is
    extrapolating outside its calibrated profile (ranks beyond k=10, or
    an iteration cap below the class-stability stop range it was fitted
    on), since a bad estimate cost the round-5 prototype 4× (RESULTS.md
    round-5 ragged section)."""
    if iters_est is not None:
        table = {int(k): float(v) for k, v in iters_est}
        missing = sorted({int(k) for k in job_ks} - set(table))
        if missing:
            raise ValueError(
                "experimental.ragged_iters_est is missing estimates for "
                f"rank classes {missing}")
        return lambda k: table[int(k)]
    ks = {int(k) for k in job_ks}
    if max(ks) > 10 or max_iter < 1030:
        import logging
        logging.getLogger("nmfx").warning(
            "ragged slot allocation is using the built-in iteration "
            "model calibrated on the north-star profile (mu, k=2..10, "
            "class-stability stops ~515..2000 iterations; BENCH_r04) — "
            "this job mix (k in %s, max_iter=%d) departs it, so the "
            "greedy-minimax allocation may be poor (the round-5 "
            "prototype lost 4x to a mis-calibrated model). Pass "
            "measured per-class estimates via "
            "ExperimentalConfig.ragged_iters_est (see "
            "ragged_estimates_from_iterations)",
            sorted(ks), max_iter)
    return _ragged_iters_est


def _ragged_layout(job_ks: tuple, budget_cols: int,
                   iters_est=None, max_iter: int = 10000) -> list:
    """Partition a mixed-rank job list into rank classes and allocate
    slots by GREEDY MINIMAX: start at one slot per class and repeatedly
    give a slot to the class with the largest estimated remaining
    makespan (jobs × expected iterations / slots), while
    ``Σ slots_c·k_c ≤ budget_cols``.

    Zero-padding waste is the uniform pool's structural cost: at the
    north-star mix (k=2..10) only Σk/(|ks|·k_max) = 60% of its packed
    columns are true columns, and padded columns burn GEMM cycles like
    real ones. Class-blocked slots eliminate the padding entirely; the
    while_loop's trip count is ``max_c trips_c`` (every trip advances
    all classes), so the allocation target is equal per-class DRAIN
    TIME — the classic multiprocessor-makespan shape, solved greedily
    over integer slots (proportional-to-column-work allocation is the
    continuous optimum but integer rounding at 1-2-slot classes
    measured 4× worse; RESULTS.md round 5). The allocation only affects
    SCHEDULE quality, never results: trajectories are per-job (each
    job's columns see only its own lane of the batched GEMMs).
    """
    by_k: dict = {}
    for i, k in enumerate(job_ks):
        by_k.setdefault(int(k), []).append(i)
    ks_desc = sorted(by_k, reverse=True)  # LPT flavor: widest first
    if sum(k for k in ks_desc) > budget_cols:
        raise ValueError(
            f"ragged pool: one slot per rank class needs "
            f"{sum(k for k in ks_desc)} columns, budget is {budget_cols} "
            "(VMEM envelope); use backend='packed'")
    est = _resolve_est(iters_est, job_ks, max_iter)
    load = {k: len(by_k[k]) * est(k) for k in ks_desc}
    slots = {k: 1 for k in ks_desc}
    while True:
        spare = budget_cols - sum(slots[k] * k for k in ks_desc)
        grow = [k for k in ks_desc
                if slots[k] < len(by_k[k]) and k <= spare]
        if not grow:
            break
        best = max(grow, key=lambda k: load[k] / slots[k])
        slots[best] += 1
    layout, off = [], 0
    for k in ks_desc:
        layout.append(_RaggedClass(k=k, jobs=tuple(by_k[k]),
                                   slots=slots[k], off=off))
        off += slots[k] * k
    return layout


class _RaggedState(NamedTuple):
    """Per-class scheduler state for the ragged pool (tuples indexed by
    class, static length; every class runs its own queue inside the one
    while_loop — the shared kernel advances all classes together)."""
    wp: jax.Array  # (m_pad, RK) packed columns, class-major
    hp: jax.Array  # (RK, n)
    slot_iter: tuple  # per class (S_c,) i32
    classes: tuple  # per class (S_c, n) i32
    stable: tuple  # per class (S_c,) i32
    slot_job: tuple  # per class (S_c,) i32 — GLOBAL job ids
    active: tuple  # per class (S_c,) bool
    queue: tuple  # per class () i32 — next index into the class job list
    n_trips: jax.Array  # () i32
    n_lanes: jax.Array  # () i32 — live SLOTS summed over trips
    out_w: jax.Array  # (J+1, m, k_max)
    out_h: jax.Array
    out_iters: jax.Array
    out_stop: jax.Array


def _make_ragged_stage(layout, a_loop, w0, h0, cfg: SolverConfig,
                       kern_kw, vary, out0, *, m, m_pad, n, k_max, j,
                       tw, drain_tail, flip_floor=None) -> "_RaggedState":
    """Run the class-blocked main stage: one ``lax.while_loop`` whose
    body advances EVERY class's slots through one
    ``fused_block_iterations`` launch over the class-major packed
    columns (per-column segment ids give each job its own Gram block —
    no padding columns exist), then does per-class convergence
    bookkeeping and per-class queue evict/reload under one global
    ``lax.cond``. Runs until every queue drains and at most ``tw`` jobs
    survive (``drain_tail``) or to completion. ``w0`` is the
    (J, m_pad, k_max) zero-padded job store; per-class slices
    ``[:, :, :k_c]`` are exact because padding is trailing."""
    from nmfx.ops.pallas_mu import fused_block_iterations

    ce = cfg.check_every
    seg, slot_base = [], 0
    for c in layout:
        seg.append(np.repeat(np.arange(c.slots) + slot_base, c.k))
        slot_base += c.slots
    seg_ids = jnp.asarray(np.concatenate(seg).astype(np.int32))
    sqrteps = jnp.sqrt(jnp.finfo(jnp.float32).eps)

    def ratio(diff, ref):
        return diff / (sqrteps + ref)

    col_sl = {}
    off = 0
    for c in layout:
        col_sl[c] = slice(off, off + c.slots * c.k)
        off += c.slots * c.k

    def init_state():
        wseg, hseg = [], []
        per = {f: [] for f in ("slot_iter", "classes", "stable",
                               "slot_job", "active", "queue")}
        for c in layout:
            init_ids = jnp.asarray(c.jobs[:c.slots], jnp.int32)
            wseg.append(jnp.transpose(w0[init_ids][:, :, :c.k],
                                      (1, 0, 2)).reshape(m_pad, -1))
            hseg.append(h0[init_ids][:, :c.k, :].reshape(-1, n))
            per["slot_iter"].append(vary(jnp.zeros((c.slots,), jnp.int32)))
            per["classes"].append(vary(jnp.full((c.slots, n), -1,
                                                jnp.int32)))
            per["stable"].append(vary(jnp.zeros((c.slots,), jnp.int32)))
            per["slot_job"].append(vary(init_ids))
            per["active"].append(vary(jnp.ones((c.slots,), bool)))
            per["queue"].append(vary(jnp.asarray(c.slots, jnp.int32)))
        return _RaggedState(
            wp=jnp.concatenate(wseg, axis=1), hp=jnp.concatenate(hseg),
            slot_iter=tuple(per["slot_iter"]),
            classes=tuple(per["classes"]), stable=tuple(per["stable"]),
            slot_job=tuple(per["slot_job"]), active=tuple(per["active"]),
            queue=tuple(per["queue"]),
            n_trips=vary(jnp.asarray(0, jnp.int32)),
            n_lanes=vary(jnp.asarray(0, jnp.int32)), **out0)

    def body(st: _RaggedState) -> _RaggedState:
        fcol = jnp.concatenate([
            jnp.repeat(~st.active[ci] | (st.slot_iter[ci] >= cfg.max_iter),
                       c.k)
            for ci, c in enumerate(layout)]).astype(jnp.float32)[None, :]
        wp, hp, wd, wm, hd, hm = fused_block_iterations(
            a_loop, st.wp, st.hp, fcol, k=k_max, iters=ce,
            seg_ids=seg_ids, **kern_kw)

        it_new, classes, stable, finished, reason = [], [], [], [], []
        for ci, c in enumerate(layout):
            sl = col_sl[c]
            it_c = jnp.minimum(st.slot_iter[ci] + ce, cfg.max_iter)
            delta_c = None
            if cfg.use_tol_checks:
                wd_c = jnp.max(wd[0, sl].reshape(c.slots, c.k), axis=1)
                wm_c = jnp.max(wm[0, sl].reshape(c.slots, c.k), axis=1)
                hd_c = jnp.max(hd[sl, 0].reshape(c.slots, c.k), axis=1)
                hm_c = jnp.max(hm[sl, 0].reshape(c.slots, c.k), axis=1)
                delta_c = jnp.maximum(ratio(wd_c, wm_c),
                                      ratio(hd_c, hm_c))
            labels_c = jnp.argmax(hp[sl].reshape(c.slots, c.k, n),
                                  axis=1).astype(jnp.int32)
            nonfinite_c = None
            if cfg.nonfinite_guard:
                nonfinite_c = ~(jnp.all(jnp.isfinite(
                    wp[:, sl].reshape(m_pad, c.slots, c.k)), axis=(0, 2))
                    & jnp.all(jnp.isfinite(
                        hp[sl].reshape(c.slots, c.k, n)), axis=(1, 2)))
            cls_c, stb_c, conv_c, _, rsn_c = batch_convergence(
                cfg, it_c, new_classes=labels_c, delta=delta_c,
                n_glob=n, classes=st.classes[ci], stable=st.stable[ci],
                done=~st.active[ci],
                done_iter=jnp.zeros_like(it_c),
                stop_reason=jnp.full_like(it_c, base.StopReason.MAX_ITER),
                flip_floor=flip_floor, nonfinite=nonfinite_c)
            it_new.append(it_c)
            classes.append(cls_c)
            stable.append(stb_c)
            reason.append(rsn_c)
            finished.append(st.active[ci]
                            & (conv_c | (it_c >= cfg.max_iter)))

        def evict_reload(ops):
            wp, hp, out_w, out_h, out_iters, out_stop, slot_job, active, \
                queue = ops
            slot_job, active, queue = (list(slot_job), list(active),
                                       list(queue))
            for ci, c in enumerate(layout):
                sl = col_sl[c]
                fin = finished[ci]
                w3 = wp[:, sl].reshape(m_pad, c.slots, c.k)
                wdense = jnp.pad(jnp.transpose(w3, (1, 0, 2))[:, :m, :],
                                 ((0, 0), (0, 0), (0, k_max - c.k)))
                h3 = hp[sl].reshape(c.slots, c.k, n)
                hdense = jnp.pad(h3, ((0, 0), (0, k_max - c.k), (0, 0)))
                idx = jnp.where(fin, slot_job[ci], j)
                out_w = out_w.at[idx].set(wdense)
                out_h = out_h.at[idx].set(hdense)
                out_iters = out_iters.at[idx].set(it_new[ci])
                out_stop = out_stop.at[idx].set(reason[ci])
                # per-class prefix-sum claim of the class's queued jobs
                claim = jnp.cumsum(fin, dtype=jnp.int32)
                new_pos = queue[ci] + claim - 1
                load_book = fin & (new_pos < len(c.jobs))
                jobs_c = jnp.asarray(c.jobs, jnp.int32)
                gids = jobs_c[jnp.where(load_book, new_pos, 0)]
                # fault-injection hook shared with the uniform reload
                # (identity when unset) — the gate's boundary stage can
                # route through THIS path for mixed-rank jobs
                load = _stale_load_mask(load_book, gids)
                wg = jnp.transpose(w0[gids][:, :, :c.k], (1, 0, 2))
                w3 = jnp.where(load[None, :, None], wg, w3)
                wp = wp.at[:, sl].set(w3.reshape(m_pad, -1))
                hg = h0[gids][:, :c.k, :]
                h3 = jnp.where(load[:, None, None], hg, h3)
                hp = hp.at[sl].set(h3.reshape(-1, n))
                slot_job[ci] = jnp.where(load_book, jobs_c[
                    jnp.where(load_book, new_pos, 0)],
                    jnp.where(fin, j, slot_job[ci]))
                active[ci] = jnp.where(fin, load_book, active[ci])
                queue[ci] = queue[ci] + jnp.sum(load_book,
                                                dtype=jnp.int32)
            return (wp, hp, out_w, out_h, out_iters, out_stop,
                    tuple(slot_job), tuple(active), tuple(queue))

        any_fin = jnp.any(jnp.concatenate(finished))
        ops = (wp, hp, st.out_w, st.out_h, st.out_iters, st.out_stop,
               st.slot_job, st.active, st.queue)
        (wp, hp, out_w, out_h, out_iters, out_stop, slot_job, active,
         queue) = lax.cond(any_fin, evict_reload, lambda ops: ops, ops)
        return _RaggedState(
            wp=wp, hp=hp,
            slot_iter=tuple(jnp.where(finished[ci], 0, it_new[ci])
                            for ci in range(len(layout))),
            classes=tuple(jnp.where(finished[ci][:, None], -1,
                                    classes[ci])
                          for ci in range(len(layout))),
            stable=tuple(jnp.where(finished[ci], 0, stable[ci])
                         for ci in range(len(layout))),
            slot_job=slot_job, active=active, queue=queue,
            n_trips=st.n_trips + 1,
            n_lanes=st.n_lanes + sum(
                jnp.sum(a_c, dtype=jnp.int32) for a_c in st.active),
            out_w=out_w, out_h=out_h, out_iters=out_iters,
            out_stop=out_stop)

    def cond(st: _RaggedState):
        any_active = jnp.any(jnp.concatenate(st.active))
        if not drain_tail:
            return any_active
        live = sum(jnp.sum(a_c, dtype=jnp.int32) for a_c in st.active)
        pending = jnp.stack([
            st.queue[ci] < len(c.jobs)
            for ci, c in enumerate(layout)]).any()
        return any_active & (pending | (live > tw))

    return lax.while_loop(cond, body, init_state())


def _ragged_to_uniform(st_r: "_RaggedState", layout, tw, *, m_pad, n,
                       k_max, j, dtype) -> "SchedState":
    """Gather the ragged stage's survivors into a ``tw``-slot uniform
    k_max-padded pool positioned for the standard tail loop: per-class
    spans → dense (S_c, m_pad, k_c) views → zero-padded to k_max →
    global stable gather of the live slots. Queues are drained by the
    ragged stage's condition, so the uniform queue starts empty
    (``queue = j`` — no further loads)."""
    wdense, hdense = [], []
    off = 0
    for c in layout:
        sl = slice(off, off + c.slots * c.k)
        off += c.slots * c.k
        w3 = st_r.wp[:, sl].reshape(m_pad, c.slots, c.k)
        wdense.append(jnp.pad(jnp.transpose(w3, (1, 0, 2)),
                              ((0, 0), (0, 0), (0, k_max - c.k))))
        hdense.append(jnp.pad(st_r.hp[sl].reshape(c.slots, c.k, n),
                              ((0, 0), (0, k_max - c.k), (0, 0))))
    wdense = jnp.concatenate(wdense)  # (S_total, m_pad, k_max)
    hdense = jnp.concatenate(hdense)
    active = jnp.concatenate(st_r.active)
    order = jnp.argsort(~active, stable=True)[:tw]
    wp = jnp.transpose(wdense[order], (1, 0, 2)).reshape(m_pad, -1)
    hp = hdense[order].reshape(-1, n)
    return SchedState(
        wp=wp, hp=hp,
        slot_iter=jnp.concatenate(st_r.slot_iter)[order],
        classes=jnp.concatenate(st_r.classes)[order],
        stable=jnp.concatenate(st_r.stable)[order],
        dnorm=jnp.full((tw,), jnp.inf, dtype),
        slot_job=jnp.concatenate(st_r.slot_job)[order],
        active=active[order],
        pending=jnp.zeros((tw,), bool),
        queue=jnp.asarray(j, jnp.int32),
        n_trips=st_r.n_trips, n_lanes=st_r.n_lanes,
        out_w=st_r.out_w, out_h=st_r.out_h,
        out_iters=st_r.out_iters, out_stop=st_r.out_stop)


class SchedState(NamedTuple):
    # slot-resident solver state (no cross-block w_prev/h_prev: the TolX
    # delta is between the block's last two steps, both inside `body`)
    wp: jax.Array  # (S, m, k_max)
    hp: jax.Array  # (S, k_max, n)
    slot_iter: jax.Array  # (S,) i32 — iterations completed by the slot's job
    classes: jax.Array  # (S, n) i32
    stable: jax.Array  # (S,) i32
    dnorm: jax.Array  # (S,) residual at last check (TolFun family only)
    # scheduler state
    slot_job: jax.Array  # (S,) i32 — job index resident in each slot
    active: jax.Array  # (S,) bool — slot holds a live job
    pending: jax.Array  # (S,) bool — finished, factors not yet harvested
    queue: jax.Array  # () i32 — next job index to load
    # occupancy diagnostics (cumulative across stages; per-stage values
    # recovered by differencing at stage boundaries)
    n_trips: jax.Array  # () i32 — while-loop trips (check blocks) run
    n_lanes: jax.Array  # () i32 — Σ over trips of live slots at entry
    # per-job result buffers (scatter-once at eviction)
    out_w: jax.Array  # (J+1, m, k_max) — row J is the drop target
    out_h: jax.Array  # (J+1, k_max, n)
    out_iters: jax.Array  # (J+1,) i32
    out_stop: jax.Array  # (J+1,) i32


class SchedMUResult(NamedTuple):
    w: jax.Array  # (J, m, k_max) final factors per job, zero-padded
    h: jax.Array  # (J, k_max, n)
    iterations: jax.Array  # (J,) i32
    dnorm: jax.Array  # (J,) final RMS residual (direct form)
    stop_reason: jax.Array  # (J,) i32 StopReason
    # scheduler occupancy diagnostics, one row per cascade stage:
    # stage pool width, check-block trips run at that width, and the sum
    # of live slots over those trips. Occupancy = pool_lanes /
    # (pool_trips · pool_widths); the wall model is
    # Σ_stage trips(stage) · c(width(stage)) — what
    # benchmarks/probe_sched_occupancy.py decomposes
    pool_widths: jax.Array  # (n_stages,) i32
    pool_trips: jax.Array  # (n_stages,) i32
    pool_lanes: jax.Array  # (n_stages,) i32


def _resolve_tail(tail_slots, s: int) -> tuple[int, ...]:
    """Resolve the tail cascade: a strictly-decreasing tuple of pool
    widths the survivors compact through (() disables). Accepts None/0
    (off), "auto" (the measured default cascade), one int, or a
    sequence of ints — widths >= the current pool (or out of order) are
    dropped rather than erroring, so one cascade spec works across job
    counts."""
    if tail_slots in (None, 0):
        return ()
    if tail_slots == "auto":
        tail_slots = _AUTO_TAIL_SLOTS
    if isinstance(tail_slots, int):
        tail_slots = (tail_slots,)
    widths = []
    prev = s
    for t in tail_slots:
        t = int(t)
        if t < 1:
            raise ValueError(f"tail widths must be >= 1, got {t}")
        if t < prev:
            widths.append(t)
            prev = t
    return tuple(widths)


#: measured on the real chip (benchmarks/probe_tail_slots.py, round 4,
#: same-session interleaved min-of-N at the full north star): a single
#: 8-lane tail won over {off, 4, 16} for BOTH engines (XLA-dense 3.52 s
#: off → 3.12 s, pallas 3.31 → 3.02 s in its slow-tunnel session,
#: ~9–11% off the wall), and the 24→8 cascade measured at parity with
#: the single 8 (the drain window between 47 and 8 live jobs is short —
#: most post-drain iterations belong to the last few stragglers), so
#: the simpler single stage stays the default
_AUTO_TAIL_SLOTS = (8,)


@partial(jax.jit, static_argnames=("cfg", "slots", "varying_axes",
                                  "tail_slots", "job_ks"))
def mu_sched(a: jax.Array, w0: jax.Array, h0: jax.Array,
             cfg: SolverConfig = SolverConfig(),
             slots: int = 48,
             varying_axes: tuple[str, ...] = (),
             tail_slots: "int | None | str | tuple[int, ...]" = "auto",
             job_ks: "tuple[int, ...] | None" = None,
             flip_floor: "jax.Array | None" = None,
             ) -> SchedMUResult:
    """Solve J dense zero-padded jobs through an S-slot scheduler.

    ``w0``/``h0``: (J, m, k_max) / (J, k_max, n) initial factors, in the
    order jobs should be DISPATCHED (callers pass rank-descending for LPT;
    results come back indexed by the same job order). Semantically
    equivalent to solving each job independently (the per-k paths); only
    the schedule differs. ``cfg.max_iter`` should be a multiple of
    ``cfg.check_every`` (the CLI default 10000/2 is): a non-multiple cap
    lands on the next check boundary, where the cap is enforced by
    freezing, so at most check_every-1 trailing iterations are skipped
    relative to the generic driver's tail loop.

    ``varying_axes`` as in ``mu_packed``: inside ``shard_map`` over those
    mesh axes the constant-initialized carry components must be lifted to
    device-varying. The loop body has NO collectives, so each device runs
    its own queue at its own pace and exits independently — per-device
    work-conserving schedules over the device's job shard.

    ``tail_slots``: the straggler-tail cascade — an int or a
    decreasing tuple of pool widths. Once the queue drains and at most
    the next width's worth of jobs are live, the survivors compact into
    that narrower pool and finish there — straggler iterations then
    cost the narrow width's per-iteration price instead of the full
    pool's (see the cascade comment in the body). "auto" (default) uses
    the measured default; None/0 disables (single full-width loop).
    The knob targets wall-clock only: per-job stop decisions were
    identical on every tested workload, and factors drift only at the
    float-tolerance level any width change produces (a near-tie label or
    TolX delta could in principle flip a stop iteration on hardware).
    Must be hashable (tuple, not list) — it keys the jit cache.

    ``job_ks``: per-job true ranks (static tuple). Enables the exact
    snmf coupling mask (``grid_mu.pad_live_mask``) and unlocks the
    RAGGED class-blocked pool on the pallas block-kernel route.
    ``flip_floor``: precomputed class-stability flip budget (i32 scalar,
    may be traced) overriding ``floor(class_flip_tol · n)`` — the
    shape-bucketed executables pass the TRUE sample count's budget while
    n is the padded bucket width (``nmfx/exec_cache.py``; see
    ``packed_mu.batch_convergence``).

    ``cfg.check_block`` (round 6) batches N check blocks per while-loop
    trip: on the pallas block-kernel route ONE ``fused_block_iterations``
    launch runs all N blocks with the factors VMEM-resident and exports
    per-boundary label snapshots + TolX stats, against which the
    class-stability/TolX bookkeeping replays each check exactly; on the
    XLA-dense route (and the pallas per-iteration fallback) the N blocks
    run sequentially with the bookkeeping interleaved — exact semantics
    there. Either way the heavy per-trip machinery (while-carry copies,
    the evict/reload ``lax.cond``, harvest scatters) fires once per N
    checks. See ``SolverConfig.check_block`` for the drift contract and
    the "auto" resolution.

    The measured-rejected opt-ins — ragged class-blocked pool, evict
    hysteresis, slot-pool factor dtypes, kernel buffer donation — live
    in ``cfg.experimental`` (``nmfx.ExperimentalConfig``), not in this
    signature; see that class for each knob's measured verdict and the
    keep/remove policy.
    """
    if cfg.algorithm not in BLOCKS:
        raise ValueError(
            f"the slot scheduler implements {tuple(BLOCKS)}, got "
            f"algorithm={cfg.algorithm!r}")
    cfg = conv_cfg(cfg)
    exp = cfg.experimental
    evict_batch = exp.evict_batch
    factor_dtype = exp.factor_dtype
    alias_io = exp.alias_io
    use_pallas = cfg.backend == "pallas"
    if use_pallas and cfg.algorithm not in ("mu", "hals"):
        raise ValueError(
            "the pallas slot scheduler implements algorithm='mu' and "
            "'hals'; use backend='packed'/'auto' for the others")
    dtype = jnp.dtype(cfg.dtype)
    a = jnp.asarray(a, dtype)
    w0 = jnp.asarray(w0, dtype)
    h0 = jnp.asarray(h0, dtype)
    j, m, k_max = w0.shape
    n = h0.shape[2]
    if job_ks is not None and len(job_ks) != j:
        # fail loudly: JAX clamps out-of-bounds gathers/scatters, so a
        # wrong-length tuple would silently pair jobs with the wrong
        # ranks (phantom ids gather wrong W0/H0 rows; a short tuple
        # leaves jobs unsolved at zero factors) — ADVICE.md round 5
        raise ValueError(
            f"job_ks has {len(job_ks)} entries but w0/h0 carry {j} jobs "
            "— per-job true ranks must match the job batch exactly")
    s = min(slots, j)
    ce_ok = cfg.max_iter % cfg.check_every == 0
    if exp.ragged and not (use_pallas and ce_ok and job_ks is not None):
        raise ValueError(
            "experimental.ragged=True needs backend='pallas', job_ks, "
            "and max_iter a multiple of check_every (the block-kernel "
            "route)")
    # ragged default: OFF. Measured round 5 (benchmarks/probe_ragged_ab,
    # same-session min-of-5): the class-blocked pool cut main-stage trips
    # 4687 → 4129 as designed, but its straggler tail tripled (balanced
    # classes leave no deep straggler to keep the wide stage alive while
    # late-dispatched jobs catch up) and the 9-class unrolled
    # bookkeeping/evict body costs ~1.5× per trip — net 1.74 s vs the
    # uniform pool's 1.32 s at the north star. Kept as an opt-in for
    # mixes where padding waste is extreme (k_max >> typical k).
    use_ragged = bool(exp.ragged)
    if use_pallas and cfg.algorithm == "hals":
        # hals has no per-iteration pallas fallback (the coordinate
        # sweep only exists as the block kernel) and the ragged stage's
        # kernel is mu-hardwired
        if not ce_ok:
            raise ValueError(
                "backend='pallas' with algorithm='hals' requires "
                "max_iter to be a multiple of check_every (the block-"
                "kernel route; there is no per-iteration hals fallback)")
        if use_ragged:
            raise ValueError(
                "experimental.ragged=True is mu-only (the ragged "
                "class-blocked kernel); use the uniform pool for hals")
    # the block-kernel route: one fused launch per check block (and the
    # only route where check_block batches INSIDE the kernel)
    blk_route = use_pallas and ce_ok and not use_ragged
    # hals uses the TolFun residual test: its interior multi-check
    # boundaries would need a per-boundary residual the kernel cannot
    # export (the snapshots carry H, not ‖A−WH‖), so the multi-check
    # launch is only sound for hals when TolFun is off
    hals_multi_ok = (cfg.algorithm != "hals"
                     or not (USES_TOLFUN["hals"] and cfg.use_tol_checks))
    ncheck = cfg.check_block
    if ncheck == "auto":
        # resolved per engine: the round-5 trace decomposition puts the
        # per-trip non-kernel overhead (~47 µs of carry copies + cond +
        # bookkeeping against a 136 µs kernel) on the pallas scheduler;
        # the dense engine's bookkeeping measured within noise there, so
        # its default cadence stays 1 (the knob remains available)
        ncheck = 4 if (blk_route and hals_multi_ok) else 1
    ncheck = int(ncheck)
    if ncheck > 1 and blk_route and not hals_multi_ok:
        raise ValueError(
            "check_block > 1 on the pallas hals route needs "
            "use_tol_checks=False: TolFun's residual cannot be replayed "
            "from the kernel's boundary exports")
    if ncheck > 1 and use_ragged:
        raise ValueError(
            "check_block > 1 requires the uniform pool "
            "(experimental.ragged=False) — the ragged stage's per-class "
            "bookkeeping is check-per-trip")
    fdtype = jnp.bfloat16 if factor_dtype else None
    if fdtype is not None and not blk_route:
        raise ValueError(
            "experimental.factor_dtype='bfloat16'/'bfloat16_w' is the "
            "pallas block-kernel pool experiment: backend='pallas', "
            "max_iter a multiple of check_every, uniform (non-ragged) "
            "pool")
    if alias_io and not blk_route:
        # enforced, not silently ignored: the ragged stage and the
        # per-iteration fallback never thread the donation, so a user
        # "benchmarking alias_io" there would measure an unaliased build
        raise ValueError(
            "experimental.alias_io=True is the uniform pallas "
            "block-kernel route only: backend='pallas', max_iter a "
            "multiple of check_every, non-ragged")
    # join-the-updates kernel selection (round 7): "auto" resolves to
    # the phased kernel — the default numerics stay byte-for-byte the
    # round-6 build's; "fused" opts into the single-A-read variant
    # (bit-exact vs phased, pinned by tests/test_fused_kernel.py) and
    # is what the autotuner sets when it wins the timed search
    use_fused = exp.fused_updates == "fused"
    if use_fused and cfg.algorithm != "mu":
        raise ValueError(
            "experimental.fused_updates='fused' is the mu join-the-"
            "updates kernel; the hals block kernel has its own schedule")
    if use_fused and not blk_route:
        raise ValueError(
            "experimental.fused_updates='fused' is the uniform pallas "
            "block-kernel route only: backend='pallas', max_iter a "
            "multiple of check_every, non-ragged")
    if exp.block_m is not None and not use_pallas:
        raise ValueError(
            "experimental.block_m is a pallas tile-shape override; it "
            "has no meaning for backend="
            f"{cfg.backend!r}")
    if use_pallas and not use_ragged:
        s = _pallas_slot_clamp(s, k_max, m, n, cfg,
                               factor_dtype=factor_dtype,
                               check_block=ncheck, fused=use_fused,
                               algorithm=cfg.algorithm,
                               block_m=exp.block_m)
    if cfg.algorithm == "kl":
        s = _kl_slot_clamp(s, m, n, dtype)
    ce = cfg.check_every

    with base.matmul_precision_ctx(cfg.matmul_precision):
        a_loop = a
        if _streams_bf16_a(cfg):
            # one-time operand truncation as in grid_mu/packed_mu (see
            # _streams_bf16_a for why results are unchanged and why the
            # predicate is shared with the VMEM slot clamp)
            a_loop = a.astype(jnp.bfloat16)

        def vary(x):
            for ax in varying_axes:
                x = pcast(x, ax, to="varying")
            return x

        # --- layout hooks: dense (S, m, k) lanes under XLA, or packed
        # (m, S·k) columns feeding the fused pallas kernels --------------
        sqrteps = jnp.sqrt(jnp.finfo(jnp.dtype(dtype)).eps)

        def stepped_block(step_fn, delta_fn):
            """The generic check block: check_every single iterations with
            the per-step max_iter fence, prev snapshot before the last
            step, and the layout-specific TolX delta — shared by the dense
            path and the pallas per-iteration fallback so the fence/delta
            semantics cannot diverge. ``slot_job`` rides along for blocks
            with per-job auxiliaries (snmf's padding mask)."""
            def do_block(wp, hp, active, slot_iter, slot_job):
                for i in range(ce):
                    frozen = ~active | (slot_iter + i >= cfg.max_iter)
                    if i == ce - 1:
                        wprev, hprev = wp, hp
                    wp, hp = step_fn(wp, hp, frozen, slot_job)
                return wp, hp, delta_fn(wp, hp, wprev, hprev)

            return do_block

        def ratio(diff, ref):
            return diff / (sqrteps + ref)

        if use_pallas:
            from nmfx.ops.packed_mu import block_diag_mask
            from nmfx.ops.pallas_mu import (fused_block_iterations,
                                            fused_h_update, fused_w_update,
                                            hals_block_iterations)

            # m padded to the kernels' tile grid (zero rows are invariant
            # under the MU epilogue — same scheme as mu_packed, but
            # 16-row-aligned: A streams in bf16 under that precision, and
            # bf16's native sublane tiling is 16
            _, block_m, m_pad = _pallas_block_geometry(m, exp.block_m)
            if m_pad != m:
                a_loop = jnp.pad(a_loop, ((0, m_pad - m), (0, 0)))
                w0 = jnp.pad(w0, ((0, 0), (0, m_pad - m), (0, 0)))
            interp = jax.default_backend() != "tpu"
            kern_kw = dict(block_m=block_m, eps=cfg.div_eps,
                           zero_threshold=cfg.zero_threshold,
                           matmul_precision=cfg.matmul_precision,
                           interpret=interp)

            def block_launch(width, wp, hp, fcol, **kw):
                """The one block-kernel dispatch point: the mu kernel
                (phased or round-7 fused per ``use_fused``) or the hals
                coordinate-sweep kernel — identical operand/output
                signatures, so both check-block drivers below stay
                algorithm-agnostic."""
                if cfg.algorithm == "hals":
                    return hals_block_iterations(
                        a_loop, wp, hp, fcol, k=k_max, slots=width,
                        iters=ce, alias_io=alias_io, **kern_kw, **kw)
                return fused_block_iterations(
                    a_loop, wp, hp, fcol, k=k_max, iters=ce,
                    alias_io=alias_io, fused=use_fused, **kern_kw, **kw)

            # bf16-factor-storage experiments (experimental.factor_dtype):
            # "bfloat16" (round 5) stores BOTH pool factors bf16 — halves
            # the W round-trip per block and widens the VMEM envelope
            # ~1.6x, but the quantized H freezes labels at a bf16 fixed
            # point (measured-rejected, probe_bf16_pool.py).
            # "bfloat16_w" (round 6) stores only W bf16 and keeps H — the
            # label-bearing factor — at the solve dtype: the round-5
            # freeze cannot start from the labels, while W (10 of the
            # ~11 MB per-launch factor round-trip at the north star)
            # still moves at half the bytes. Both are REAL numerics
            # changes (per-iteration stores quantize the affected
            # factor), unlike the result-invariant bf16 A-streaming.
            w_pool = jnp.bfloat16 if factor_dtype else dtype
            h_pool = (jnp.bfloat16 if factor_dtype == "bfloat16"
                      else dtype)

            def to_pool_w(x):
                return x.astype(w_pool) if factor_dtype else x

            def to_pool_h(x):
                return (x.astype(h_pool) if factor_dtype == "bfloat16"
                        else x)

            def init_slots():
                # (s, m_pad, k) → packed (m_pad, s·k)
                return (to_pool_w(jnp.transpose(w0[:s],
                                                (1, 0, 2)).reshape(m_pad,
                                                                   -1)),
                        to_pool_h(h0[:s].reshape(s * k_max, n)))

            def make_do_block(width):
                """Width-specific check block (the tail pool re-derives it
                at its own packed width; the fused kernels themselves
                infer width from the operand shapes)."""
                if cfg.max_iter % ce == 0:
                    # the whole check block is ONE pallas_call: factors
                    # stay VMEM-resident across both half-updates of all
                    # check_every iterations, and the TolX ingredients
                    # come back as per-column stats
                    # (fused_block_iterations). The max_iter fence needs
                    # no per-step mask here: slot_iter is always a
                    # multiple of check_every, so a slot crosses the cap
                    # only at a block boundary.
                    def do_block(wp, hp, active, slot_iter, slot_job):
                        del slot_job  # no per-job auxiliaries on this path
                        frozen = ~active | (slot_iter >= cfg.max_iter)
                        fcol = jnp.repeat(frozen, k_max).astype(
                            jnp.float32)[None, :]
                        wp, hp, wd, wm, hd, hm = block_launch(
                            width, wp, hp, fcol)

                        def lane_max(x):  # (1, rk)/(rk, 1) → per-slot max
                            return jnp.max(x.reshape(-1, k_max), axis=1)

                        delta = jnp.maximum(
                            ratio(lane_max(wd), lane_max(wm)),
                            ratio(lane_max(hd), lane_max(hm)))
                        return wp, hp, delta

                    return do_block

                bd = block_diag_mask(width, k_max, dtype)

                def _one_step(wp, hp, frozen, slot_job):
                    del slot_job  # mu-only path: no per-job auxiliaries
                    frozen_col = jnp.repeat(frozen, k_max)
                    hn = fused_h_update(a_loop, wp, hp, k=k_max, **kern_kw)
                    hn = jnp.where(frozen_col[:, None], hp, hn)
                    from nmfx.ops.packed_mu import bd_select
                    gh = bd_select(hn @ hn.T, bd)  # tiny; stays in XLA
                    wn = fused_w_update(a_loop, wp, hn, gh, **kern_kw)
                    wn = jnp.where(frozen_col[None, :], wp, wn)
                    return wn, hn

                def packed_deltas(wp, hp, wprev, hprev):
                    def _d(cur, prev, shape, axes):
                        return ratio(
                            jnp.max(jnp.abs(cur - prev).reshape(shape),
                                    axis=axes),
                            jnp.max(jnp.abs(prev).reshape(shape),
                                    axis=axes))

                    return jnp.maximum(
                        _d(wp, wprev, (m_pad, width, k_max), (0, 2)),
                        _d(hp, hprev, (width, k_max, n), (1, 2)))

                return stepped_block(_one_step, packed_deltas)

            def make_do_multi(width):
                """The launch-resident multi-check block (check_block > 1,
                block-kernel route only): ONE fused launch runs ncheck
                check blocks with the factors VMEM-resident, the per-lane
                max_iter fence enforced in-kernel (budget columns), and
                each boundary's labels/TolX delta recovered from the
                kernel's exported snapshots/stats — so the while-loop
                body replays ncheck exact checks per trip."""
                rk = width * k_max

                def do_multi(wp, hp, active, slot_iter, slot_job):
                    del slot_job  # no per-job auxiliaries on this path
                    frozen = ~active | (slot_iter >= cfg.max_iter)
                    fcol = jnp.repeat(frozen, k_max).astype(
                        jnp.float32)[None, :]
                    budget = jnp.repeat(
                        jnp.maximum(cfg.max_iter - slot_iter, 0),
                        k_max).astype(jnp.float32)[None, :]
                    wp, hp, wd, wm, hd, hm, hck = block_launch(
                        width, wp, hp, fcol, check_block=ncheck,
                        budget_cols=budget)

                    def lane_max(x):  # (rk,) → per-slot max
                        return jnp.max(x.reshape(-1, k_max), axis=1)

                    deltas, labels = [], []
                    for b in range(ncheck):
                        deltas.append(jnp.maximum(
                            ratio(lane_max(wd[b]), lane_max(wm[b])),
                            ratio(lane_max(hd[b * rk:(b + 1) * rk, 0]),
                                  lane_max(hm[b * rk:(b + 1) * rk, 0]))))
                        labels.append(jnp.argmax(
                            hck[b].reshape(-1, k_max, n),
                            axis=1).astype(jnp.int32))
                    return wp, hp, deltas, labels

                return do_multi

            def slot_labels(hp):
                return jnp.argmax(hp.reshape(-1, k_max, n),
                                  axis=1).astype(jnp.int32)

            def slot_nonfinite(wp, hp):
                # packed-column layout: per-slot all-finite verdict over
                # the slot's k_max columns of W and rows of H
                return ~(jnp.all(jnp.isfinite(
                    wp.reshape(wp.shape[0], -1, k_max)), axis=(0, 2))
                    & jnp.all(jnp.isfinite(hp.reshape(-1, k_max, n)),
                              axis=(1, 2)))

            def dense_views(wp, hp):
                wd = jnp.transpose(wp.reshape(m_pad, -1, k_max),
                                   (1, 0, 2))[:, :m, :]
                # result buffers stay full precision
                return (wd.astype(dtype),
                        hp.reshape(-1, k_max, n).astype(dtype))

            def reload(wp, hp, load, gather):
                # fault-injection hook (identity when unset): drop the
                # factor write for a deterministic per-job subset of
                # reloads while the caller's bookkeeping still marks the
                # new job as loaded — factors go stale exactly as in the
                # round-3 aliasing bug (_stale_load_mask)
                load = _stale_load_mask(load, gather)
                w3 = wp.reshape(m_pad, -1, k_max)
                # gathers cast to the pool dtype so where() cannot
                # promote the bf16 carry back to f32
                wg = to_pool_w(jnp.transpose(w0[gather],
                                             (1, 0, 2)))  # (m_pad, s, k)
                w3 = jnp.where(load[None, :, None], wg, w3)
                h3 = jnp.where(load[:, None, None], to_pool_h(h0[gather]),
                               hp.reshape(-1, k_max, n))
                return w3.reshape(m_pad, -1), h3.reshape(-1, n)

            def gather_slots(wp, hp, order):
                """Packed-layout lane gather for the tail compaction."""
                w3 = wp.reshape(m_pad, -1, k_max)[:, order, :]
                h3 = hp.reshape(-1, k_max, n)[order]
                return w3.reshape(m_pad, -1), h3.reshape(-1, n)
        else:
            block = make_block(cfg, a)
            if cfg.algorithm == "snmf":
                # per-job true-k padding masks (snmf_block /
                # grid_mu.pad_live_mask — exact when the caller passes
                # job_ks); row j is the drop target for finished slots —
                # all-False, and its lane is frozen
                from nmfx.ops.grid_mu import pad_live_mask

                pad_jobs = jnp.concatenate(
                    [pad_live_mask(w0, h0, job_ks),
                     jnp.zeros((1, k_max), bool)])

                def step_fn(wp, hp, frozen, slot_job):
                    return block(a_loop, wp, hp, frozen, cfg,
                                 pad_live=pad_jobs[slot_job])
            else:
                def step_fn(wp, hp, frozen, slot_job):
                    del slot_job
                    return block(a_loop, wp, hp, frozen, cfg)

            def init_slots():
                return w0[:s], h0[:s]

            def dense_deltas(wp, hp, wprev, hprev):
                def _d(cur, prev):
                    return ratio(jnp.max(jnp.abs(cur - prev), axis=(1, 2)),
                                 jnp.max(jnp.abs(prev), axis=(1, 2)))

                return jnp.maximum(_d(wp, wprev), _d(hp, hprev))

            def make_do_block(width):
                del width  # the dense blocks are batch-width-free
                return stepped_block(step_fn, dense_deltas)

            make_do_multi = None  # XLA route: sub-blocks run sequentially

            def slot_labels(hp):
                return jnp.argmax(hp, axis=1).astype(jnp.int32)

            def slot_nonfinite(wp, hp):
                # dense layout: lanes are separate batch entries of every
                # block einsum, so a non-finite slot is contained by
                # construction; the guard stops it at the next check
                return ~(jnp.all(jnp.isfinite(wp), axis=(1, 2))
                         & jnp.all(jnp.isfinite(hp), axis=(1, 2)))

            def dense_views(wp, hp):
                return wp, hp

            def reload(wp, hp, load, gather):
                ld = load[:, None, None]
                return (jnp.where(ld, w0[gather], wp),
                        jnp.where(ld, h0[gather], hp))

            def gather_slots(wp, hp, order):
                return wp[order], hp[order]

        out0 = dict(
            out_w=vary(jnp.zeros((j + 1, m, k_max), dtype)),
            out_h=vary(jnp.zeros((j + 1, k_max, n), dtype)),
            out_iters=vary(jnp.zeros((j + 1,), jnp.int32)),
            out_stop=vary(jnp.full((j + 1,), base.StopReason.MAX_ITER,
                                   jnp.int32)),
        )

        def harvest(st: SchedState) -> SchedState:
            """Scatter every PENDING slot's converged factors into the
            result buffers and reload queued jobs into those slots — the
            heavy half of eviction (dense-view transpose, (J+1, m,
            k_max) scatters, W0/H0 gathers), batched behind the
            ``evict_batch`` hysteresis. Iteration counts/stop reasons
            were already recorded at finish time (cheap small scatters),
            so delaying the harvest never changes recorded results —
            only WHEN successor jobs start."""
            wdv, hdv = dense_views(st.wp, st.hp)
            idx = jnp.where(st.pending, st.slot_job, j)  # j = drop row
            out_w = st.out_w.at[idx].set(wdv)
            out_h = st.out_h.at[idx].set(hdv)
            # prefix-sum claim of the next queued jobs (dtypes pinned to
            # int32: under jax_enable_x64 jnp.sum/cumsum would otherwise
            # promote to int64 and break the lax.cond's
            # equal-output-types contract with the no-harvest branch)
            claim = jnp.cumsum(st.pending, dtype=jnp.int32)
            new_job = st.queue + claim - 1
            load = st.pending & (new_job < j)
            gather = jnp.where(load, new_job, st.slot_job)
            wp, hp = reload(st.wp, st.hp, load, gather)
            slot_job = jnp.where(load, new_job,
                                 jnp.where(st.pending, j, st.slot_job))
            active = st.active | load
            queue = st.queue + jnp.sum(load, dtype=jnp.int32)
            return st._replace(wp=wp, hp=hp, out_w=out_w, out_h=out_h,
                               slot_job=slot_job, active=active,
                               pending=jnp.zeros_like(st.pending),
                               queue=queue)

        def maybe_harvest(st: SchedState) -> SchedState:
            """Unconditional-call form for stage boundaries: a stage can
            exit with 0 < pending < evict_batch, and the compaction
            gather would drop un-harvested factors."""
            return lax.cond(jnp.any(st.pending), harvest, lambda s: s, st)

        def apply_check(st: SchedState, wp, hp, delta,
                        new_labels) -> SchedState:
            """ONE convergence check's bookkeeping — the class-stability
            snapshot rule, TolX, the TolFun residual test where the
            algorithm uses it, the max_iter fence, and the cheap per-job
            outcome scatters. ``wp``/``hp`` are the factors the check's
            results freeze with (on the multi-check launch the interior
            checks see the launch-final factors — the documented drift
            class; labels/deltas are the boundary-exact kernel exports)."""
            it_new = jnp.minimum(st.slot_iter + ce, cfg.max_iter)
            if not cfg.use_tol_checks:
                delta = None
            nonfinite = (slot_nonfinite(wp, hp) if cfg.nonfinite_guard
                         else None)
            classes, stable, conv, _, reason = batch_convergence(
                cfg, it_new, new_classes=new_labels, delta=delta,
                n_glob=n, classes=st.classes, stable=st.stable,
                done=~st.active,
                done_iter=jnp.zeros_like(st.slot_iter),
                stop_reason=jnp.full_like(st.slot_iter,
                                          base.StopReason.MAX_ITER),
                flip_floor=flip_floor, nonfinite=nonfinite)
            dnorm = st.dnorm
            if USES_TOLFUN[cfg.algorithm] and cfg.use_tol_checks:
                wd, hd = dense_views(wp, hp)
                dnorm, conv, reason = tolfun_update(
                    a, wd, hd, it_new, cfg, dnorm=dnorm, done=conv,
                    done_in=~st.active, stop_reason=reason)
            # conv folds in ~active (passed as `done`); isolate fresh
            # stops
            finished = st.active & (conv | (it_new >= cfg.max_iter))

            # record the CHEAP per-job outcomes immediately (tiny
            # (J+1,) integer scatters — iteration counts and stop
            # reasons are exact regardless of when the factors are
            # harvested); the slot freezes (inactive+pending) with
            # its converged factors in place
            idx_f = jnp.where(finished, st.slot_job, j)
            out_iters = st.out_iters.at[idx_f].set(it_new)
            out_stop = st.out_stop.at[idx_f].set(reason)
            return st._replace(
                wp=wp, hp=hp,
                # inactive slots hold their counter: a pending slot
                # waits frozen at 0 until harvest, so its successor
                # job starts at iteration 0 no matter how long the
                # evict_batch hysteresis delayed the reload
                slot_iter=jnp.where(
                    finished, 0,
                    jnp.where(st.active, it_new, st.slot_iter)),
                classes=jnp.where(finished[:, None], -1, classes),
                stable=jnp.where(finished, 0, stable),
                dnorm=jnp.where(finished, jnp.inf, dnorm),
                active=st.active & ~finished,
                pending=st.pending | finished,
                out_iters=out_iters, out_stop=out_stop)

        def make_body(width):
            """The while-loop body at this pool width: ncheck check
            blocks, then ONE harvest decision. On the pallas block-kernel
            route with check_block > 1 all ncheck blocks run inside one
            fused launch (do_multi) and the checks replay against its
            boundary exports; everywhere else the blocks run
            sequentially with the bookkeeping interleaved (exact
            semantics — converged lanes freeze before the next
            sub-block). Either way the per-trip machinery below the loop
            — carry copies, the evict/reload cond — fires once per
            ncheck checks."""
            multi = blk_route and ncheck > 1
            do_multi = make_do_multi(width) if multi else None
            do_block = None if multi else make_do_block(width)

            def body(st: SchedState) -> SchedState:
                entry_active = st.active
                if multi:
                    wp, hp, deltas, labels = do_multi(
                        st.wp, st.hp, st.active, st.slot_iter,
                        st.slot_job)
                    for b in range(ncheck):
                        st = apply_check(st, wp, hp, deltas[b], labels[b])
                else:
                    for _ in range(ncheck):
                        wp, hp, delta = do_block(st.wp, st.hp, st.active,
                                                 st.slot_iter,
                                                 st.slot_job)
                        st = apply_check(st, wp, hp, delta,
                                         slot_labels(hp))
                st = st._replace(
                    n_trips=st.n_trips + 1,
                    n_lanes=st.n_lanes + jnp.sum(entry_active,
                                                 dtype=jnp.int32))

                # --- harvest + reload, under lax.cond: the vast
                # majority of check blocks finish NO job, and inside a
                # (non-vmapped) while_loop body the cond is a real
                # branch. evict_batch > 1 additionally batches
                # completions: a finished slot idles frozen until
                # enough peers finish (or nothing else runs), cutting
                # the heavy branch's firing rate ~evict_batch× for a
                # few idle slot-trips of queue delay
                fire = (jnp.sum(st.pending, dtype=jnp.int32)
                        >= jnp.minimum(evict_batch,
                                       jnp.sum(st.pending | st.active,
                                               dtype=jnp.int32)))
                return lax.cond(fire & jnp.any(st.pending), harvest,
                                lambda s: s, st)

            return body

        # --- straggler-tail cascade ----------------------------------
        # The sweep's wall is dominated by its stragglers: once the
        # queue drains, a handful of long jobs keep iterating inside a
        # mostly-empty full-width pool, paying c(S) per iteration for a
        # few lanes of real work (measured: the north-star k=10
        # stragglers run thousands of iterations after the pool drains).
        # Each cascade stage runs its pool while the queue has jobs OR
        # more than the NEXT width's worth of slots are live; then the
        # surviving jobs compact (a stable lane gather) into the next,
        # narrower pool. Same bookkeeping, same result buffers; per-job
        # stop decisions matched the single-phase schedule on every
        # tested workload and factors agree to float tolerance
        # (XLA/Mosaic tile GEMMs differently per batch width — measured
        # ~1e-6 relative, the same drift any slot-count change produces,
        # so a near-tie check could in principle flip a stop iteration).
        def compact(st: SchedState, width: int) -> SchedState:
            order = jnp.argsort(~st.active, stable=True)[:width]
            wp_t, hp_t = gather_slots(st.wp, st.hp, order)
            return SchedState(
                wp=wp_t, hp=hp_t,
                slot_iter=st.slot_iter[order],
                classes=st.classes[order],
                stable=st.stable[order],
                dnorm=st.dnorm[order],
                slot_job=st.slot_job[order],
                active=st.active[order],
                pending=st.pending[order],
                queue=st.queue,
                n_trips=st.n_trips, n_lanes=st.n_lanes,
                out_w=st.out_w, out_h=st.out_h,
                out_iters=st.out_iters, out_stop=st.out_stop,
            )

        if use_ragged:
            # --- ragged main stage: class-blocked variable-width pool —
            # zero padding columns; the uniform machinery takes over for
            # the straggler tail (survivors gathered into a narrow
            # k_max-padded pool, where padding costs ~nothing at width 8)
            # column budget: the VMEM envelope, capped by the user's
            # slot knob in column units (grid_slots=48 × k_max=10 ≡ the
            # uniform pool's 480-column optimum at the north star)
            layout = _ragged_layout(
                job_ks, min(_pallas_max_rk(m, n, cfg), s * k_max),
                iters_est=exp.ragged_iters_est, max_iter=cfg.max_iter)
            s_total = sum(c.slots for c in layout)
            tail_w = _resolve_tail(tail_slots, s_total)
            tw = tail_w[-1] if tail_w else 1
            st_r = _make_ragged_stage(
                layout, a_loop, w0, h0, cfg, kern_kw, vary, out0,
                m=m, m_pad=m_pad, n=n, k_max=k_max, j=j, tw=tw,
                drain_tail=bool(tail_w), flip_floor=flip_floor)
            stage_widths = [s_total, tw]
            stage_marks = [(st_r.n_trips, st_r.n_lanes)]
            st = _ragged_to_uniform(st_r, layout, tw, m_pad=m_pad, n=n,
                                    k_max=k_max, j=j, dtype=dtype)
            final = lax.while_loop(lambda st: jnp.any(st.active),
                                   make_body(tw), st)
            stage_marks.append((final.n_trips, final.n_lanes))
        else:
            wp0, hp0 = init_slots()
            st = SchedState(
                wp=wp0, hp=hp0,
                slot_iter=vary(jnp.zeros((s,), jnp.int32)),
                classes=vary(jnp.full((s, n), -1, jnp.int32)),
                stable=vary(jnp.zeros((s,), jnp.int32)),
                dnorm=vary(jnp.full((s,), jnp.inf, dtype)),
                slot_job=vary(jnp.arange(s, dtype=jnp.int32)),
                active=vary(jnp.ones((s,), bool)),
                pending=vary(jnp.zeros((s,), bool)),
                queue=vary(jnp.asarray(s, jnp.int32)),
                n_trips=vary(jnp.asarray(0, jnp.int32)),
                n_lanes=vary(jnp.asarray(0, jnp.int32)),
                **out0,
            )
            body = make_body(s)
            stage_widths = [s]
            stage_marks = []  # cumulative (trips, lanes) at stage ends
            for width in _resolve_tail(tail_slots, s):
                def stage_cond(st, width=width):
                    live = jnp.sum(st.active | st.pending,
                                   dtype=jnp.int32)
                    return (jnp.any(st.active) | jnp.any(st.pending)) & (
                        (st.queue < j) | (live > width))

                st = maybe_harvest(lax.while_loop(stage_cond, body, st))
                stage_marks.append((st.n_trips, st.n_lanes))
                st = compact(st, width)
                stage_widths.append(width)
                body = make_body(width)
            final = maybe_harvest(
                lax.while_loop(lambda st: jnp.any(st.active), body, st))
            stage_marks.append((final.n_trips, final.n_lanes))
        # cumulative marks → per-stage trip/lane counts
        trips = jnp.stack([t for t, _ in stage_marks])
        lanes = jnp.stack([l for _, l in stage_marks])
        pool_trips = jnp.diff(trips, prepend=jnp.zeros((1,), trips.dtype))
        pool_lanes = jnp.diff(lanes, prepend=jnp.zeros((1,), lanes.dtype))
        out_w = final.out_w[:j]
        out_h = final.out_h[:j]
        # exact final residuals, once, from the retained per-job factors
        dnorm = residual_norms_direct(a, out_w, out_h)
    return SchedMUResult(w=out_w, h=out_h,
                         iterations=final.out_iters[:j],
                         dnorm=dnorm, stop_reason=final.out_stop[:j],
                         pool_widths=jnp.asarray(stage_widths, jnp.int32),
                         pool_trips=pool_trips, pool_lanes=pool_lanes)
