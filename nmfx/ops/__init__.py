"""TPU-shaped compute kernels.

Modules here restructure the framework's hot loops for the MXU/VMEM rather
than expressing them per-restart: ``packed_mu`` lays the whole restart batch
out as one set of large GEMMs; ``pallas_mu`` lowers the same iteration to a
hand-scheduled Pallas kernel.
"""
