"""Pallas TPU kernels for the restart-packed MU iteration.

The packed formulation (see ``nmfx.ops.packed_mu``) is a handful of large
GEMMs per iteration. XLA executes them as separate HLOs, so every
intermediate — numerators, Grams, denominators — makes an HBM round trip
between ops. These kernels fuse each half-update into one ``pallas_call``
that streams A and Wp through VMEM exactly once and keeps everything else
on-chip:

* ``fused_h_update`` — grid over m-tiles; accumulates both the numerator
  WpᵀA and the Gram WpᵀWp in VMEM scratch as tiles stream by, then applies
  the block-diagonal mask, the (Gram·Hp) denominator GEMM, and the
  multiplicative epilogue in the final grid step. Only the updated Hp ever
  returns to HBM.
* ``fused_w_update`` — grid over independent m-tiles; each computes its
  numerator tile A·Hpᵀ and denominator tile Wp·(HpHpᵀ∘B) and applies the
  epilogue in-register. The tiny masked H-Gram is precomputed by the caller
  (one small GEMM — not worth a kernel).

Measured on a single v5e chip (bf16, R=50): in round 2 the packed XLA
path won the north-star sweep by ~15–20% (see benchmarks/RESULTS.md
"Pallas backend: regime verdict" for that protocol and its variance
caveats); as of round 4 the FIXED fused-kernel scheduler wins it —
1.43 vs 1.59 s same-session minima, 1.74× cheaper marginal iteration —
and ``backend="pallas"`` is the documented fast path on TPU. The
library default remains the packed/dense family for stability (one
engine family across platforms and shapes; the pallas pool's VMEM
envelope is shape-dependent), not for speed. The whole-grid slot
scheduler (``nmfx.ops.sched_mu``)
also runs on these kernels under ``backend="pallas"`` (packed-column
slot state; one ``fused_block_iterations`` launch per check block).
History: round 3's block kernel used input/output-aliased VMEM windows
and was corrupted inside the scheduler's while_loop on real hardware
(BENCH_r03's headline was retracted — VERDICT.md round 3); round 4
replaced the aliasing with an explicit one-shot DMA and re-verified
on-chip (see below). For current performance numbers see
benchmarks/RESULTS.md round-4 section.

Numerical note (verified on hardware, round 4 —
``benchmarks/probe_block_kernel.py`` / ``probe_sched_pallas.py`` on a
real v5e): ``fused_block_iterations`` is bit-exact against the
per-iteration kernel pair over 60 iterations, including frozen-lane
invariance and the TolX stats, and the pallas slot scheduler's per-job
iteration counts are bit-identical between the block-kernel path and the
per-iteration fallback. Against the XLA dense path, Mosaic accumulation
order differs, so *factor trajectories* drift apart multiplicatively
over hundreds of iterations (~1e-2 relative after 60) and individual
stop iterations can drift with them; stop *reasons* and the converged
consensus pipeline agree (hardware gate: ``bench.py --verify``).

VMEM budget: the H kernel holds the (R·k, n) numerator and (R·k, R·k)
Gram accumulators plus three streamed blocks resident, ≈
(rk² + 2·rk·n + 2·block_m·(n + rk))·4 bytes — ~6 MB at the north-star
shapes (rk = n = 500, block_m = 512), comfortably inside a core's ~16 MB
VMEM. Much larger R·k or n overflows VMEM and Mosaic rejects the kernel
at compile time; use ``backend="packed"`` there (XLA tiles through HBM).

Reference math: the six dgemms + elementwise updates of
``libnmf/nmf_mu.c:174-216``, restructured for MXU/VMEM rather than
translated (SURVEY.md §7). Shapes must be pre-padded by the caller:
m ≡ 0 (mod block_m), n and R·k ≡ 0 (mod 128 lanes / 8 sublanes as dtype
requires) — ``nmfx.ops.packed_mu`` pads once per solve, and the MU
epilogue's exact-zero short-circuit keeps zero padding invariant across
iterations.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_CONTRACT_ROWS = (((0,), (0,)), ((), ()))  # AᵀB over leading (row) dim
_CONTRACT_COLS = (((1,), (1,)), ((), ()))  # ABᵀ over trailing (col) dim


def _maybe_cast(x, matmul_dtype):
    return x if matmul_dtype is None else x.astype(matmul_dtype)


def _epilogue(prev, numer, denom, eps, zero_threshold, out_dtype):
    """mu epilogue in f32: prev ∘ numer / (denom + eps), exact-zero
    short-circuit, zero-threshold clamp (nmf_mu.c:184-216)."""
    res = prev * (numer / (denom + eps))
    res = jnp.where((prev == 0.0) | (numer == 0.0), 0.0, res)
    res = jnp.where(res <= zero_threshold, 0.0, res)
    return res.astype(out_dtype)


def _h_kernel(a_ref, w_ref, h_ref, out_ref, numer_acc, gram_acc, *,
              k: int, eps: float, zero_threshold: float, matmul_dtype):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        numer_acc[:] = jnp.zeros_like(numer_acc)
        gram_acc[:] = jnp.zeros_like(gram_acc)

    w = _maybe_cast(w_ref[:], matmul_dtype)
    a = _maybe_cast(a_ref[:], matmul_dtype)
    numer_acc[:] += jax.lax.dot_general(
        w, a, _CONTRACT_ROWS, preferred_element_type=jnp.float32)
    gram_acc[:] += jax.lax.dot_general(
        w, w, _CONTRACT_ROWS, preferred_element_type=jnp.float32)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        rk = gram_acc.shape[0]
        rows = jax.lax.broadcasted_iota(jnp.int32, (rk, rk), 0) // k
        cols = jax.lax.broadcasted_iota(jnp.int32, (rk, rk), 1) // k
        gram = jnp.where(rows == cols, gram_acc[:], 0.0)
        hp0 = h_ref[:].astype(jnp.float32)
        denom = jax.lax.dot_general(
            _maybe_cast(gram, matmul_dtype), _maybe_cast(hp0, matmul_dtype),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        out_ref[:] = _epilogue(hp0, numer_acc[:], denom, eps,
                               zero_threshold, out_ref.dtype)


def _w_kernel(a_ref, w_ref, h_ref, gh_ref, out_ref, *,
              eps: float, zero_threshold: float, matmul_dtype):
    a = _maybe_cast(a_ref[:], matmul_dtype)
    h = _maybe_cast(h_ref[:], matmul_dtype)
    numer = jax.lax.dot_general(
        a, h, _CONTRACT_COLS, preferred_element_type=jnp.float32)
    wp0 = w_ref[:].astype(jnp.float32)
    denom = jax.lax.dot_general(
        _maybe_cast(wp0, matmul_dtype), _maybe_cast(gh_ref[:], matmul_dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    out_ref[:] = _epilogue(wp0, numer, denom, eps, zero_threshold,
                           out_ref.dtype)


def _matmul_dtype(matmul_precision: str):
    """Map SolverConfig.matmul_precision onto an explicit operand dtype
    (None = keep the storage dtype; 'bfloat16' = one-pass MXU, matching
    jax.default_matmul_precision('bfloat16') on the XLA path)."""
    return jnp.bfloat16 if matmul_precision == "bfloat16" else None


@functools.partial(jax.jit, static_argnames=(
    "k", "block_m", "eps", "zero_threshold", "matmul_precision",
    "interpret"))
def fused_h_update(a: jax.Array, wp: jax.Array, hp: jax.Array, *, k: int,
                   block_m: int = 512, eps: float = 1e-9,
                   zero_threshold: float = 0.0,
                   matmul_precision: str = "default",
                   interpret: bool = False) -> jax.Array:
    """Hp ← mu_epilogue(Hp, WpᵀA, (WpᵀWp ∘ B)·Hp) in one stream over A, Wp."""
    m, n = a.shape
    rk = wp.shape[1]
    if m % block_m:
        raise ValueError(f"m={m} must be a multiple of block_m={block_m}")
    kernel = functools.partial(
        _h_kernel, k=k, eps=eps, zero_threshold=zero_threshold,
        matmul_dtype=_matmul_dtype(matmul_precision))
    return pl.pallas_call(
        kernel,
        grid=(m // block_m,),
        in_specs=[
            pl.BlockSpec((block_m, n), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, rk), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rk, n), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rk, n), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rk, n), hp.dtype),
        scratch_shapes=[
            pltpu.VMEM((rk, n), jnp.float32),
            pltpu.VMEM((rk, rk), jnp.float32),
        ],
        interpret=interpret,
    )(a, wp, hp)


def _block_kernel(a_ref, frozen_ref, frozenr_ref, seg_row_ref, seg_col_ref,
                  *rest, block_m: int, k: int, eps: float,
                  zero_threshold: float, matmul_dtype,
                  check_every: int = 0, check_block: int = 1):
    """One grid step of the resident-W block kernel (see
    fused_block_iterations). Grid = (iters, 2 phases, nt m-tiles); w_ref /
    h_ref are FULL output blocks that stay VMEM-resident across every
    step (constant index maps) and are seeded from w_in/h_in by a
    one-shot DMA at the first step, so the factors never touch HBM
    inside a block; only A's tiles stream. Phase 0 accumulates the
    H-half numerator/Gram per tile and applies the H update at the last
    tile (also pre-masking HHᵀ into gram_acc for phase 1); phase 1 updates
    W tile-locally. The final iteration also accumulates per-column
    max|Δ| / max|prev| into the four small stat outputs — the TolX
    ingredients — so convergence checks need no extra factor snapshot.

    ``check_block > 1`` is the launch-resident multi-check mode (round
    6): the grid spans ``check_block`` check sub-blocks of
    ``check_every`` iterations each, the factors staying VMEM-resident
    throughout. At every sub-block BOUNDARY iteration the kernel (a)
    records the TolX stats into that boundary's row of the (now
    per-boundary) stat outputs and (b) DMAs the freshly-updated H out to
    that boundary's slice of the ``h_checks`` HBM output — the label
    snapshot the scheduler's per-check class-stability bookkeeping
    replays, one while-loop trip per ``check_block`` checks. Two extra
    per-lane inputs carry the iteration fence: ``budget``/``budgetr``
    hold each lane's remaining iteration allowance (``max_iter -
    slot_iter`` at launch entry), and a lane freezes in-kernel once the
    launch-local iteration index reaches it — so a lane crossing its cap
    mid-launch stops at exactly the right boundary without a host trip.
    """
    it = pl.program_id(0)
    ph = pl.program_id(1)
    t = pl.program_id(2)
    if check_block > 1:
        (budget_ref, budgetr_ref, w_in_ref, h_in_ref,
         w_ref, h_ref, wd_ref, wm_ref, hd_ref, hm_ref, hck_ref,
         numer_acc, gram_acc) = rest
        # boundary bookkeeping: which check sub-block this iteration
        # closes (valid only when is_boundary holds)
        is_boundary = (it + 1) % check_every == 0
        bidx = (it + 1) // check_every - 1
    else:
        (w_in_ref, h_in_ref,
         w_ref, h_ref, wd_ref, wm_ref, hd_ref, hm_ref,
         numer_acc, gram_acc) = rest

    # One-shot manual DMA of the initial factors (HBM, memory_space=ANY)
    # into the VMEM-resident output windows at the very first grid step.
    # Deliberately NOT input_output_aliases: round 3 shipped this kernel
    # with the inputs aliased onto the output windows, and on real
    # hardware, inside the scheduler's `lax.while_loop`/`lax.cond` body,
    # the aliased windows went stale — slot reloads written to the HBM
    # buffer between calls never reached VMEM, so reloaded jobs iterated
    # on the PREVIOUS job's converged factors (the BENCH_r03
    # mean_iters_per_k=2.0 corruption; VERDICT.md round 3, Weak #1).
    # Bisected on-chip in round 4: the kernel is bit-exact standalone
    # either way, and bit-exact in-scheduler only with the aliasing
    # removed (benchmarks/probe_block_kernel.py, probe_sched_pallas.py).
    @pl.when((it == 0) & (ph == 0) & (t == 0))
    def _():
        def init(sems):
            dma_w = pltpu.make_async_copy(w_in_ref, w_ref, sems.at[0])
            dma_h = pltpu.make_async_copy(h_in_ref, h_ref, sems.at[1])
            dma_w.start()
            dma_h.start()
            dma_w.wait()
            dma_h.wait()

        pl.run_scoped(init, pltpu.SemaphoreType.DMA((2,)))
    last_it = it == pl.num_programs(0) - 1
    # block-diagonal Gram mask from per-column segment (job) ids — the
    # (rk, 1)/(1, rk) pair broadcasts to the (rk, rk) same-job mask.
    # Uniform-k pools pass seg = iota // k; the ragged (class-blocked)
    # pool passes its variable-width job ids (see ragged_layout)
    bd = seg_row_ref[:] == seg_col_ref[:]
    # Mosaic note: masks and stats stay strictly 2-D (keepdims reductions,
    # pre-shaped (1, rk)/(rk, 1) frozen inputs) — inserting a minor dim on
    # a non-32-bit value (bool masks) is unsupported on TPU
    frozen_c = frozen_ref[:] > 0.0  # (1, rk) — W-phase column mask
    frozen_r = frozenr_ref[:] > 0.0  # (rk, 1) — H-phase row mask
    if check_block > 1:
        # per-lane iteration fence: budget holds the lane's remaining
        # allowance at launch entry (a multiple of check_every, like the
        # launch-local index) — the lane freezes for the rest of the
        # launch once `it` reaches it
        it_f = it.astype(jnp.float32)
        frozen_c = frozen_c | (budget_ref[:] <= it_f)
        frozen_r = frozen_r | (budgetr_ref[:] <= it_f)

    @pl.when((ph == 0) & (t == 0))
    def _():
        numer_acc[:] = jnp.zeros_like(numer_acc)
        gram_acc[:] = jnp.zeros_like(gram_acc)

    @pl.when(ph == 0)
    def _():
        wt = _maybe_cast(w_ref[pl.dslice(t * block_m, block_m), :],
                         matmul_dtype)
        at = _maybe_cast(a_ref[:], matmul_dtype)
        numer_acc[:] += jax.lax.dot_general(
            wt, at, _CONTRACT_ROWS, preferred_element_type=jnp.float32)
        gram_acc[:] += jax.lax.dot_general(
            wt, wt, _CONTRACT_ROWS, preferred_element_type=jnp.float32)

        @pl.when(t == pl.num_programs(2) - 1)
        def _():
            gram = jnp.where(bd, gram_acc[:], 0.0)
            h0 = h_ref[:].astype(jnp.float32)
            denom = jax.lax.dot_general(
                _maybe_cast(gram, matmul_dtype),
                _maybe_cast(h0, matmul_dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            hn = _epilogue(h0, numer_acc[:], denom, eps, zero_threshold,
                           jnp.float32)
            hn = jnp.where(frozen_r, h0, hn)
            h_ref[:] = hn.astype(h_ref.dtype)

            if check_block > 1:
                rk = h_ref.shape[0]

                @pl.when(is_boundary)
                def _():
                    # this boundary's H-side TolX stats + the label
                    # snapshot the scheduler replays the check against
                    sl = pl.dslice(bidx * rk, rk)
                    hd_ref[sl, :] = jnp.max(jnp.abs(hn - h0), axis=1,
                                            keepdims=True)
                    hm_ref[sl, :] = jnp.max(jnp.abs(h0), axis=1,
                                            keepdims=True)

                    def snap(sem):
                        dma = pltpu.make_async_copy(
                            h_ref, hck_ref.at[bidx], sem.at[0])
                        dma.start()
                        dma.wait()

                    pl.run_scoped(snap, pltpu.SemaphoreType.DMA((1,)))
            else:
                @pl.when(last_it)
                def _():
                    hd_ref[:] = jnp.max(jnp.abs(hn - h0), axis=1,
                                        keepdims=True)
                    hm_ref[:] = jnp.max(jnp.abs(h0), axis=1, keepdims=True)
            # pre-mask HHᵀ for phase 1 (gram_acc is free now)
            hc = _maybe_cast(hn, matmul_dtype)
            gram_acc[:] = jnp.where(bd, jax.lax.dot_general(
                hc, hc, _CONTRACT_COLS,
                preferred_element_type=jnp.float32), 0.0)

    @pl.when(ph == 1)
    def _():
        at = _maybe_cast(a_ref[:], matmul_dtype)
        h = h_ref[:].astype(jnp.float32)
        numer = jax.lax.dot_general(
            at, _maybe_cast(h, matmul_dtype), _CONTRACT_COLS,
            preferred_element_type=jnp.float32)
        wt0 = w_ref[pl.dslice(t * block_m, block_m), :].astype(jnp.float32)
        denom = jax.lax.dot_general(
            _maybe_cast(wt0, matmul_dtype),
            _maybe_cast(gram_acc[:], matmul_dtype),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        wn = _epilogue(wt0, numer, denom, eps, zero_threshold, jnp.float32)
        wn = jnp.where(frozen_c, wt0, wn)
        w_ref[pl.dslice(t * block_m, block_m), :] = wn.astype(w_ref.dtype)

        if check_block > 1:
            @pl.when(is_boundary)
            def _():
                wd_t = jnp.max(jnp.abs(wn - wt0), axis=0, keepdims=True)
                wm_t = jnp.max(jnp.abs(wt0), axis=0, keepdims=True)
                row = pl.dslice(bidx, 1)

                @pl.when(t == 0)
                def _():
                    wd_ref[row, :] = wd_t
                    wm_ref[row, :] = wm_t

                @pl.when(t > 0)
                def _():
                    wd_ref[row, :] = jnp.maximum(wd_ref[row, :], wd_t)
                    wm_ref[row, :] = jnp.maximum(wm_ref[row, :], wm_t)
        else:
            @pl.when(last_it)
            def _():
                wd_t = jnp.max(jnp.abs(wn - wt0), axis=0, keepdims=True)
                wm_t = jnp.max(jnp.abs(wt0), axis=0, keepdims=True)

                @pl.when(t == 0)
                def _():
                    wd_ref[:] = wd_t
                    wm_ref[:] = wm_t

                @pl.when(t > 0)
                def _():
                    wd_ref[:] = jnp.maximum(wd_ref[:], wd_t)
                    wm_ref[:] = jnp.maximum(wm_ref[:], wm_t)


@functools.partial(jax.jit, static_argnames=(
    "k", "iters", "block_m", "eps", "zero_threshold", "matmul_precision",
    "interpret", "alias_io", "check_block"))
def fused_block_iterations(a: jax.Array, wp: jax.Array, hp: jax.Array,
                           frozen_cols: jax.Array, *, k: int,
                           iters: int = 2, block_m: int = 512,
                           eps: float = 1e-9, zero_threshold: float = 0.0,
                           matmul_precision: str = "default",
                           interpret: bool = False,
                           seg_ids: "jax.Array | None" = None,
                           alias_io: bool = False,
                           check_block: int = 1,
                           budget_cols: "jax.Array | None" = None):
    """``iters`` full MU iterations (both half-updates) in ONE pallas_call
    with the packed factors VMEM-resident throughout — the whole-solve
    launch count drops from ~4 kernels per iteration-pair to 1.

    ``check_block > 1`` (round 6 — the launch-resident convergence
    engine): ONE pallas_call runs ``check_block`` check sub-blocks of
    ``iters`` iterations back-to-back, the factors staying VMEM-resident
    across ALL of them (the W/H HBM round-trip amortizes over
    ``check_block`` checks instead of one). The TolX stat outputs grow a
    per-boundary leading extent — ``wdiff``/``wmax`` become
    (check_block, R·k), ``hdiff``/``hmax`` (check_block·R·k, 1), row b
    measured across the LAST iteration of sub-block b — and a seventh
    output ``h_checks`` (check_block, R·k, n) carries the H snapshot at
    each boundary (DMA'd straight from the resident window: labels and
    class-stability flip counting replay per check against these, so the
    CHECK CADENCE is unchanged while the scheduler trip rate drops
    ``check_block``-fold). ``budget_cols`` (1, R·k) f32 is REQUIRED in
    this mode: each lane's remaining iteration allowance at launch entry
    (``max_iter − slot_iter``; a multiple of ``iters``) — the in-kernel
    fence freezes a lane that crosses its cap mid-launch at exactly the
    right boundary. Frozen-lane and numerical semantics per sub-block
    are identical to ``check_block`` separate launches EXCEPT that a
    lane whose stop condition fires at an interior boundary keeps
    iterating to the end of the launch (the caller records its stop
    iteration from the boundary data; its factors carry the extra
    in-launch iterations — the gate-checkable slot-drift class).

    ``frozen_cols``: (1, R·k) f32, >0 marks a frozen (converged/inactive)
    lane whose columns must not change — callers must keep it constant
    within the block (the slot scheduler's check/reload boundaries are
    block-aligned, so it is). Returns ``(wp, hp, wdiff, wmax, hdiff,
    hmax)`` — the last four are per-column TolX ingredients, (1, R·k) for
    the W pair and (R·k, 1) for the H pair, measured across the LAST
    iteration of the block (max|Δ| and max|prev| over the column/row,
    reduced per lane by the caller).

    The DATA path for the initial factors is never an alias: they arrive
    in HBM (``memory_space=ANY``) and the kernel DMAs them into the
    resident windows once at the first grid step. Round 3's design made
    the alias itself the data path (inputs aliased onto the VMEM output
    windows, no explicit copy) — bit-exact standalone but silently
    reading stale VMEM inside a ``lax.while_loop``/``lax.cond`` body on
    real hardware (see ``_block_kernel``'s comment and VERDICT.md round
    3); do not reintroduce THAT. ``alias_io=True`` is a different,
    gate-validated thing: pure XLA buffer DONATION of the w/h HBM
    buffers on top of the explicit step-0 DMA — the DMA still moves the
    data, the alias only lets the while-loop carry update in place
    instead of copying the packed factors every trip. It stays safe
    because the constant-index output windows write back only after the
    final grid step, long after the step-0 DMA has read the inputs (see
    the ``alias_io`` note at the ``pallas_call`` below and
    ``benchmarks/probe_alias_io.py`` for the bit-exactness bisect;
    measured ~8% slower than the carry copies on v5e, so it stays
    opt-in).

    VMEM budget (measured on v5e, round 4 —
    ``benchmarks/probe_vmem_envelope*.py``): W full-resident dominates;
    the empirical fit accepted by the scheduler
    (``sched_mu._pallas_slot_clamp``, the single source of truth for the
    formula) is ``4·rk·(m_pad + 3·n_pad + rk) + 2·block_m·n_pad·a_bytes
    ≤ 14.3 MiB`` with n_pad = n rounded up to 128 lanes (e.g. rk ≤ 480
    at m=5120, n=512, bf16 A; rk ≤ ~368 at n=1024). Beyond it Mosaic
    rejects at compile time — use the per-iteration kernels there.
    """
    m, n = a.shape
    rk = wp.shape[1]
    if m % block_m:
        raise ValueError(f"m={m} must be a multiple of block_m={block_m}")
    if check_block > 1 and budget_cols is None:
        raise ValueError("check_block > 1 needs budget_cols (each lane's "
                         "remaining iteration allowance at launch entry)")
    nt = m // block_m
    kernel = functools.partial(
        _block_kernel, block_m=block_m, k=k, eps=eps,
        zero_threshold=zero_threshold,
        matmul_dtype=_matmul_dtype(matmul_precision),
        check_every=iters, check_block=check_block)
    frozen_rows = frozen_cols.reshape(rk, 1)
    if seg_ids is None:
        # uniform pool: every job spans k consecutive columns
        seg_ids = jnp.arange(rk, dtype=jnp.int32) // k
    seg_ids = seg_ids.astype(jnp.int32)

    def const(shape):
        return pl.BlockSpec(shape, lambda i, p, t: (0, 0),
                            memory_space=pltpu.VMEM)

    # w0/h0 stay in HBM (ANY); the kernel DMAs them into the resident
    # output windows exactly once — same total traffic as the round-3
    # aliased design, without relying on custom-call aliasing semantics.
    # alias_io=True (round 5) ADDITIONALLY donates the w_in/h_in HBM
    # buffers as the output buffers — this is NOT the round-3 design:
    # the DATA path stays the explicit step-0 DMA (never the alias), the
    # alias only lets XLA update the while-carry in place instead of
    # copying the packed factors every trip (~30 µs/trip measured in the
    # round-5 trace). The read-before-write order holds because the
    # constant-index output windows write back after the final grid
    # step, long after the step-0 DMA read. Gate-validated: the
    # fault-injection-proven `bench.py --verify` (incl. the
    # reload-exercising boundary stage) must pass with this on — see
    # benchmarks/probe_alias_io.py for the bit-exactness bisect.
    in_specs = [
        pl.BlockSpec((block_m, n), lambda i, p, t: (t, 0),
                     memory_space=pltpu.VMEM),
        const((1, rk)), const((rk, 1)),
        const((rk, 1)), const((1, rk)),
    ]
    operands = [a, frozen_cols, frozen_rows, seg_ids.reshape(rk, 1),
                seg_ids.reshape(1, rk)]
    if check_block > 1:
        in_specs += [const((1, rk)), const((rk, 1))]
        budget_cols = budget_cols.astype(jnp.float32).reshape(1, rk)
        operands += [budget_cols, budget_cols.reshape(rk, 1)]
    w_in_idx = len(operands)
    in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                 pl.BlockSpec(memory_space=pl.ANY)]
    operands += [wp, hp]
    alias = {w_in_idx: 0, w_in_idx + 1: 1} if alias_io else {}
    nck = check_block
    out_specs = [const((m, rk)), const((rk, n)), const((nck, rk)),
                 const((nck, rk)), const((nck * rk, 1)),
                 const((nck * rk, 1))]
    out_shape = [
        jax.ShapeDtypeStruct((m, rk), wp.dtype),
        jax.ShapeDtypeStruct((rk, n), hp.dtype),
        jax.ShapeDtypeStruct((nck, rk), jnp.float32),
        jax.ShapeDtypeStruct((nck, rk), jnp.float32),
        jax.ShapeDtypeStruct((nck * rk, 1), jnp.float32),
        jax.ShapeDtypeStruct((nck * rk, 1), jnp.float32),
    ]
    if check_block > 1:
        # per-boundary H snapshots live in HBM (ANY) — written by one
        # small DMA per boundary straight from the resident H window, so
        # they cost no VMEM and ~rk·n bytes of traffic per check (the
        # same H read the separate-launch design's external labels paid)
        out_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        out_shape.append(
            jax.ShapeDtypeStruct((nck, rk, n), hp.dtype))
    return pl.pallas_call(
        kernel,
        grid=(iters * check_block, 2, nt),
        input_output_aliases=alias,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((rk, n), jnp.float32),
            pltpu.VMEM((rk, rk), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)


@functools.partial(jax.jit, static_argnames=(
    "block_m", "eps", "zero_threshold", "matmul_precision", "interpret"))
def fused_w_update(a: jax.Array, wp: jax.Array, hp: jax.Array,
                   gh_masked: jax.Array, *, block_m: int = 512,
                   eps: float = 1e-9, zero_threshold: float = 0.0,
                   matmul_precision: str = "default",
                   interpret: bool = False) -> jax.Array:
    """Wp ← mu_epilogue(Wp, A·Hpᵀ, Wp·(HpHpᵀ∘B)) tile-local per m-block."""
    m, n = a.shape
    rk = wp.shape[1]
    if m % block_m:
        raise ValueError(f"m={m} must be a multiple of block_m={block_m}")
    kernel = functools.partial(
        _w_kernel, eps=eps, zero_threshold=zero_threshold,
        matmul_dtype=_matmul_dtype(matmul_precision))
    return pl.pallas_call(
        kernel,
        grid=(m // block_m,),
        in_specs=[
            pl.BlockSpec((block_m, n), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, rk), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rk, n), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rk, rk), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_m, rk), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, rk), wp.dtype),
        interpret=interpret,
    )(a, wp, hp, gh_masked)
