"""Pallas TPU kernels for the restart-packed MU iteration.

The packed formulation (see ``nmfx.ops.packed_mu``) is a handful of large
GEMMs per iteration. XLA executes them as separate HLOs, so every
intermediate — numerators, Grams, denominators — makes an HBM round trip
between ops. These kernels fuse each half-update into one ``pallas_call``
that streams A and Wp through VMEM exactly once and keeps everything else
on-chip:

* ``fused_h_update`` — grid over m-tiles; accumulates both the numerator
  WpᵀA and the Gram WpᵀWp in VMEM scratch as tiles stream by, then applies
  the block-diagonal mask, the (Gram·Hp) denominator GEMM, and the
  multiplicative epilogue in the final grid step. Only the updated Hp ever
  returns to HBM.
* ``fused_w_update`` — grid over independent m-tiles; each computes its
  numerator tile A·Hpᵀ and denominator tile Wp·(HpHpᵀ∘B) and applies the
  epilogue in-register. The tiny masked H-Gram is precomputed by the caller
  (one small GEMM — not worth a kernel).

Measured on a single v5e chip (bf16, R=50): in round 2 the packed XLA
path won the north-star sweep by ~15–20% (see benchmarks/RESULTS.md
"Pallas backend: regime verdict" for that protocol and its variance
caveats); as of round 4 the FIXED fused-kernel scheduler wins it —
1.43 vs 1.59 s same-session minima, 1.74× cheaper marginal iteration —
and ``backend="pallas"`` is the documented fast path on TPU. The
library default remains the packed/dense family for stability (one
engine family across platforms and shapes; the pallas pool's VMEM
envelope is shape-dependent), not for speed. The whole-grid slot
scheduler (``nmfx.ops.sched_mu``)
also runs on these kernels under ``backend="pallas"`` (packed-column
slot state; one ``fused_block_iterations`` launch per check block).
History: round 3's block kernel used input/output-aliased VMEM windows
and was corrupted inside the scheduler's while_loop on real hardware
(BENCH_r03's headline was retracted — VERDICT.md round 3); round 4
replaced the aliasing with an explicit one-shot DMA and re-verified
on-chip (see below). For current performance numbers see
benchmarks/RESULTS.md round-4 section.

Numerical note (verified on hardware, round 4 —
``benchmarks/probe_block_kernel.py`` / ``probe_sched_pallas.py`` on a
real v5e): ``fused_block_iterations`` is bit-exact against the
per-iteration kernel pair over 60 iterations, including frozen-lane
invariance and the TolX stats, and the pallas slot scheduler's per-job
iteration counts are bit-identical between the block-kernel path and the
per-iteration fallback. Against the XLA dense path, Mosaic accumulation
order differs, so *factor trajectories* drift apart multiplicatively
over hundreds of iterations (~1e-2 relative after 60) and individual
stop iterations can drift with them; stop *reasons* and the converged
consensus pipeline agree (hardware gate: ``bench.py --verify``).

VMEM budget: the H kernel holds the (R·k, n) numerator and (R·k, R·k)
Gram accumulators plus three streamed blocks resident, ≈
(rk² + 2·rk·n + 2·block_m·(n + rk))·4 bytes — ~6 MB at the north-star
shapes (rk = n = 500, block_m = 512), comfortably inside a core's ~16 MB
VMEM. Much larger R·k or n overflows VMEM and Mosaic rejects the kernel
at compile time; use ``backend="packed"`` there (XLA tiles through HBM).

Reference math: the six dgemms + elementwise updates of
``libnmf/nmf_mu.c:174-216``, restructured for MXU/VMEM rather than
translated (SURVEY.md §7). Shapes must be pre-padded by the caller:
m ≡ 0 (mod block_m), n and R·k ≡ 0 (mod 128 lanes / 8 sublanes as dtype
requires) — ``nmfx.ops.packed_mu`` pads once per solve, and the MU
epilogue's exact-zero short-circuit keeps zero padding invariant across
iterations.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_CONTRACT_ROWS = (((0,), (0,)), ((), ()))  # AᵀB over leading (row) dim
_CONTRACT_COLS = (((1,), (1,)), ((), ()))  # ABᵀ over trailing (col) dim


def _maybe_cast(x, matmul_dtype):
    return x if matmul_dtype is None else x.astype(matmul_dtype)


def _epilogue(prev, numer, denom, eps, zero_threshold, out_dtype):
    """mu epilogue in f32: prev ∘ numer / (denom + eps), exact-zero
    short-circuit, zero-threshold clamp (nmf_mu.c:184-216)."""
    res = prev * (numer / (denom + eps))
    res = jnp.where((prev == 0.0) | (numer == 0.0), 0.0, res)
    res = jnp.where(res <= zero_threshold, 0.0, res)
    return res.astype(out_dtype)


def _h_kernel(a_ref, w_ref, h_ref, out_ref, numer_acc, gram_acc, *,
              k: int, eps: float, zero_threshold: float, matmul_dtype):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        numer_acc[:] = jnp.zeros_like(numer_acc)
        gram_acc[:] = jnp.zeros_like(gram_acc)

    w = _maybe_cast(w_ref[:], matmul_dtype)
    a = _maybe_cast(a_ref[:], matmul_dtype)
    numer_acc[:] += jax.lax.dot_general(
        w, a, _CONTRACT_ROWS, preferred_element_type=jnp.float32)
    gram_acc[:] += jax.lax.dot_general(
        w, w, _CONTRACT_ROWS, preferred_element_type=jnp.float32)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        rk = gram_acc.shape[0]
        rows = jax.lax.broadcasted_iota(jnp.int32, (rk, rk), 0) // k
        cols = jax.lax.broadcasted_iota(jnp.int32, (rk, rk), 1) // k
        gram = jnp.where(rows == cols, gram_acc[:], 0.0)
        hp0 = h_ref[:].astype(jnp.float32)
        denom = jax.lax.dot_general(
            _maybe_cast(gram, matmul_dtype), _maybe_cast(hp0, matmul_dtype),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        out_ref[:] = _epilogue(hp0, numer_acc[:], denom, eps,
                               zero_threshold, out_ref.dtype)


def _w_kernel(a_ref, w_ref, h_ref, gh_ref, out_ref, *,
              eps: float, zero_threshold: float, matmul_dtype):
    a = _maybe_cast(a_ref[:], matmul_dtype)
    h = _maybe_cast(h_ref[:], matmul_dtype)
    numer = jax.lax.dot_general(
        a, h, _CONTRACT_COLS, preferred_element_type=jnp.float32)
    wp0 = w_ref[:].astype(jnp.float32)
    denom = jax.lax.dot_general(
        _maybe_cast(wp0, matmul_dtype), _maybe_cast(gh_ref[:], matmul_dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    out_ref[:] = _epilogue(wp0, numer, denom, eps, zero_threshold,
                           out_ref.dtype)


def _matmul_dtype(matmul_precision: str):
    """Map SolverConfig.matmul_precision onto an explicit operand dtype
    (None = keep the storage dtype; 'bfloat16' = one-pass MXU, matching
    jax.default_matmul_precision('bfloat16') on the XLA path)."""
    return jnp.bfloat16 if matmul_precision == "bfloat16" else None


@functools.partial(jax.jit, static_argnames=(
    "k", "block_m", "eps", "zero_threshold", "matmul_precision",
    "interpret"))
def fused_h_update(a: jax.Array, wp: jax.Array, hp: jax.Array, *, k: int,
                   block_m: int = 512, eps: float = 1e-9,
                   zero_threshold: float = 0.0,
                   matmul_precision: str = "default",
                   interpret: bool = False) -> jax.Array:
    """Hp ← mu_epilogue(Hp, WpᵀA, (WpᵀWp ∘ B)·Hp) in one stream over A, Wp."""
    m, n = a.shape
    rk = wp.shape[1]
    if m % block_m:
        raise ValueError(f"m={m} must be a multiple of block_m={block_m}")
    kernel = functools.partial(
        _h_kernel, k=k, eps=eps, zero_threshold=zero_threshold,
        matmul_dtype=_matmul_dtype(matmul_precision))
    return pl.pallas_call(
        kernel,
        grid=(m // block_m,),
        in_specs=[
            pl.BlockSpec((block_m, n), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, rk), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rk, n), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rk, n), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rk, n), hp.dtype),
        scratch_shapes=[
            pltpu.VMEM((rk, n), jnp.float32),
            pltpu.VMEM((rk, rk), jnp.float32),
        ],
        interpret=interpret,
    )(a, wp, hp)


def _block_kernel(a_ref, frozen_ref, frozenr_ref, seg_row_ref, seg_col_ref,
                  *rest, block_m: int, k: int, eps: float,
                  zero_threshold: float, matmul_dtype,
                  check_every: int = 0, check_block: int = 1):
    """One grid step of the resident-W block kernel (see
    fused_block_iterations). Grid = (iters, 2 phases, nt m-tiles); w_ref /
    h_ref are FULL output blocks that stay VMEM-resident across every
    step (constant index maps) and are seeded from w_in/h_in by a
    one-shot DMA at the first step, so the factors never touch HBM
    inside a block; only A's tiles stream. Phase 0 accumulates the
    H-half numerator/Gram per tile and applies the H update at the last
    tile (also pre-masking HHᵀ into gram_acc for phase 1); phase 1 updates
    W tile-locally. The final iteration also accumulates per-column
    max|Δ| / max|prev| into the four small stat outputs — the TolX
    ingredients — so convergence checks need no extra factor snapshot.

    ``check_block > 1`` is the launch-resident multi-check mode (round
    6): the grid spans ``check_block`` check sub-blocks of
    ``check_every`` iterations each, the factors staying VMEM-resident
    throughout. At every sub-block BOUNDARY iteration the kernel (a)
    records the TolX stats into that boundary's row of the (now
    per-boundary) stat outputs and (b) DMAs the freshly-updated H out to
    that boundary's slice of the ``h_checks`` HBM output — the label
    snapshot the scheduler's per-check class-stability bookkeeping
    replays, one while-loop trip per ``check_block`` checks. Two extra
    per-lane inputs carry the iteration fence: ``budget``/``budgetr``
    hold each lane's remaining iteration allowance (``max_iter -
    slot_iter`` at launch entry), and a lane freezes in-kernel once the
    launch-local iteration index reaches it — so a lane crossing its cap
    mid-launch stops at exactly the right boundary without a host trip.
    """
    it = pl.program_id(0)
    ph = pl.program_id(1)
    t = pl.program_id(2)
    if check_block > 1:
        (budget_ref, budgetr_ref, w_in_ref, h_in_ref,
         w_ref, h_ref, wd_ref, wm_ref, hd_ref, hm_ref, hck_ref,
         numer_acc, gram_acc) = rest
        # boundary bookkeeping: which check sub-block this iteration
        # closes (valid only when is_boundary holds)
        is_boundary = (it + 1) % check_every == 0
        bidx = (it + 1) // check_every - 1
    else:
        (w_in_ref, h_in_ref,
         w_ref, h_ref, wd_ref, wm_ref, hd_ref, hm_ref,
         numer_acc, gram_acc) = rest

    # One-shot manual DMA of the initial factors (HBM, memory_space=ANY)
    # into the VMEM-resident output windows at the very first grid step.
    # Deliberately NOT input_output_aliases: round 3 shipped this kernel
    # with the inputs aliased onto the output windows, and on real
    # hardware, inside the scheduler's `lax.while_loop`/`lax.cond` body,
    # the aliased windows went stale — slot reloads written to the HBM
    # buffer between calls never reached VMEM, so reloaded jobs iterated
    # on the PREVIOUS job's converged factors (the BENCH_r03
    # mean_iters_per_k=2.0 corruption; VERDICT.md round 3, Weak #1).
    # Bisected on-chip in round 4: the kernel is bit-exact standalone
    # either way, and bit-exact in-scheduler only with the aliasing
    # removed (benchmarks/probe_block_kernel.py, probe_sched_pallas.py).
    @pl.when((it == 0) & (ph == 0) & (t == 0))
    def _():
        def init(sems):
            dma_w = pltpu.make_async_copy(w_in_ref, w_ref, sems.at[0])
            dma_h = pltpu.make_async_copy(h_in_ref, h_ref, sems.at[1])
            dma_w.start()
            dma_h.start()
            dma_w.wait()
            dma_h.wait()

        pl.run_scoped(init, pltpu.SemaphoreType.DMA((2,)))
    last_it = it == pl.num_programs(0) - 1
    # block-diagonal Gram mask from per-column segment (job) ids — the
    # (rk, 1)/(1, rk) pair broadcasts to the (rk, rk) same-job mask.
    # Uniform-k pools pass seg = iota // k; the ragged (class-blocked)
    # pool passes its variable-width job ids (see ragged_layout)
    bd = seg_row_ref[:] == seg_col_ref[:]
    # Mosaic note: masks and stats stay strictly 2-D (keepdims reductions,
    # pre-shaped (1, rk)/(rk, 1) frozen inputs) — inserting a minor dim on
    # a non-32-bit value (bool masks) is unsupported on TPU
    frozen_c = frozen_ref[:] > 0.0  # (1, rk) — W-phase column mask
    frozen_r = frozenr_ref[:] > 0.0  # (rk, 1) — H-phase row mask
    if check_block > 1:
        # per-lane iteration fence: budget holds the lane's remaining
        # allowance at launch entry (a multiple of check_every, like the
        # launch-local index) — the lane freezes for the rest of the
        # launch once `it` reaches it
        it_f = it.astype(jnp.float32)
        frozen_c = frozen_c | (budget_ref[:] <= it_f)
        frozen_r = frozen_r | (budgetr_ref[:] <= it_f)

    @pl.when((ph == 0) & (t == 0))
    def _():
        numer_acc[:] = jnp.zeros_like(numer_acc)
        gram_acc[:] = jnp.zeros_like(gram_acc)

    @pl.when(ph == 0)
    def _():
        wt = _maybe_cast(w_ref[pl.dslice(t * block_m, block_m), :],
                         matmul_dtype)
        at = _maybe_cast(a_ref[:], matmul_dtype)
        numer_acc[:] += jax.lax.dot_general(
            wt, at, _CONTRACT_ROWS, preferred_element_type=jnp.float32)
        gram_acc[:] += jax.lax.dot_general(
            wt, wt, _CONTRACT_ROWS, preferred_element_type=jnp.float32)

        @pl.when(t == pl.num_programs(2) - 1)
        def _():
            gram = jnp.where(bd, gram_acc[:], 0.0)
            h0 = h_ref[:].astype(jnp.float32)
            denom = jax.lax.dot_general(
                _maybe_cast(gram, matmul_dtype),
                _maybe_cast(h0, matmul_dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            hn = _epilogue(h0, numer_acc[:], denom, eps, zero_threshold,
                           jnp.float32)
            hn = jnp.where(frozen_r, h0, hn)
            h_ref[:] = hn.astype(h_ref.dtype)

            if check_block > 1:
                rk = h_ref.shape[0]

                @pl.when(is_boundary)
                def _():
                    # this boundary's H-side TolX stats + the label
                    # snapshot the scheduler replays the check against
                    sl = pl.dslice(bidx * rk, rk)
                    hd_ref[sl, :] = jnp.max(jnp.abs(hn - h0), axis=1,
                                            keepdims=True)
                    hm_ref[sl, :] = jnp.max(jnp.abs(h0), axis=1,
                                            keepdims=True)

                    def snap(sem):
                        dma = pltpu.make_async_copy(
                            h_ref, hck_ref.at[bidx], sem.at[0])
                        dma.start()
                        dma.wait()

                    pl.run_scoped(snap, pltpu.SemaphoreType.DMA((1,)))
            else:
                @pl.when(last_it)
                def _():
                    hd_ref[:] = jnp.max(jnp.abs(hn - h0), axis=1,
                                        keepdims=True)
                    hm_ref[:] = jnp.max(jnp.abs(h0), axis=1, keepdims=True)
            # pre-mask HHᵀ for phase 1 (gram_acc is free now)
            hc = _maybe_cast(hn, matmul_dtype)
            gram_acc[:] = jnp.where(bd, jax.lax.dot_general(
                hc, hc, _CONTRACT_COLS,
                preferred_element_type=jnp.float32), 0.0)

    @pl.when(ph == 1)
    def _():
        at = _maybe_cast(a_ref[:], matmul_dtype)
        h = h_ref[:].astype(jnp.float32)
        numer = jax.lax.dot_general(
            at, _maybe_cast(h, matmul_dtype), _CONTRACT_COLS,
            preferred_element_type=jnp.float32)
        wt0 = w_ref[pl.dslice(t * block_m, block_m), :].astype(jnp.float32)
        denom = jax.lax.dot_general(
            _maybe_cast(wt0, matmul_dtype),
            _maybe_cast(gram_acc[:], matmul_dtype),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        wn = _epilogue(wt0, numer, denom, eps, zero_threshold, jnp.float32)
        wn = jnp.where(frozen_c, wt0, wn)
        w_ref[pl.dslice(t * block_m, block_m), :] = wn.astype(w_ref.dtype)

        if check_block > 1:
            @pl.when(is_boundary)
            def _():
                wd_t = jnp.max(jnp.abs(wn - wt0), axis=0, keepdims=True)
                wm_t = jnp.max(jnp.abs(wt0), axis=0, keepdims=True)
                row = pl.dslice(bidx, 1)

                @pl.when(t == 0)
                def _():
                    wd_ref[row, :] = wd_t
                    wm_ref[row, :] = wm_t

                @pl.when(t > 0)
                def _():
                    wd_ref[row, :] = jnp.maximum(wd_ref[row, :], wd_t)
                    wm_ref[row, :] = jnp.maximum(wm_ref[row, :], wm_t)
        else:
            @pl.when(last_it)
            def _():
                wd_t = jnp.max(jnp.abs(wn - wt0), axis=0, keepdims=True)
                wm_t = jnp.max(jnp.abs(wt0), axis=0, keepdims=True)

                @pl.when(t == 0)
                def _():
                    wd_ref[:] = wd_t
                    wm_ref[:] = wm_t

                @pl.when(t > 0)
                def _():
                    wd_ref[:] = jnp.maximum(wd_ref[:], wd_t)
                    wm_ref[:] = jnp.maximum(wm_ref[:], wm_t)


def _fused_block_kernel(a_ref, frozen_ref, frozenr_ref, seg_row_ref,
                        seg_col_ref, *rest, block_m: int, k: int,
                        eps: float, zero_threshold: float, matmul_dtype,
                        check_every: int = 0, check_block: int = 1):
    """Join-the-updates variant of ``_block_kernel`` (PL-NMF blocking,
    arxiv 1904.07935): ONE grid axis of T+1 passes (T = check_every ·
    check_block iterations) replaces the (iteration, 2-phase) pair, and
    each pass touches each A tile ONCE for both half-updates — the
    W-half of iteration p−1 consumes the tile, then the H-half
    accumulation for iteration p re-reads it while it is still
    VMEM-resident. A's HBM traffic per launch drops from 2T reads to
    T+1 (pass 0 is H-accumulate-only, pass T W-only).

    Exactness: every dot_general fires in the same tile order with the
    same f32 accumulators as the phased kernel — pass p's W-half is
    phased iteration p−1's phase 1 (budget fence ``<= p−1``), its
    H-half is iteration p's phase 0 (fence ``<= p``), and the masked
    HHᵀ for the next W-half is refreshed into a third scratch
    (``hgram``) at each pass's last tile, after the W-half of this pass
    has consumed the previous one. Boundary stats/snapshots land on the
    same iterations: W stats when ``p % check_every == 0`` (p > 0, row
    p/check_every − 1), H stats + snapshot DMA when ``(p+1) %
    check_every == 0`` (p < T). The cost of the fusion is that third
    (rk, rk) scratch — ~0.9 MB at rk = 480 — accounted by the ``fused``
    term in ``sched_mu._pallas_max_rk``.
    """
    p = pl.program_id(0)
    t = pl.program_id(1)
    last_pass = pl.num_programs(0) - 1  # == T
    if check_block > 1:
        (budget_ref, budgetr_ref, w_in_ref, h_in_ref,
         w_ref, h_ref, wd_ref, wm_ref, hd_ref, hm_ref, hck_ref,
         numer_acc, gram_acc, hgram) = rest
    else:
        (w_in_ref, h_in_ref,
         w_ref, h_ref, wd_ref, wm_ref, hd_ref, hm_ref,
         numer_acc, gram_acc, hgram) = rest

    # same one-shot DMA data path as _block_kernel (NOT aliasing — see
    # the round-3 note there)
    @pl.when((p == 0) & (t == 0))
    def _():
        def init(sems):
            dma_w = pltpu.make_async_copy(w_in_ref, w_ref, sems.at[0])
            dma_h = pltpu.make_async_copy(h_in_ref, h_ref, sems.at[1])
            dma_w.start()
            dma_h.start()
            dma_w.wait()
            dma_h.wait()

        pl.run_scoped(init, pltpu.SemaphoreType.DMA((2,)))

    bd = seg_row_ref[:] == seg_col_ref[:]
    frozen_c = frozen_ref[:] > 0.0  # (1, rk) — W-half column mask
    frozen_r = frozenr_ref[:] > 0.0  # (rk, 1) — H-half row mask
    if check_block > 1:
        # pass p advances iteration p−1's W-half and iteration p's
        # H-half, so the two fences sit one pass apart
        p_f = p.astype(jnp.float32)
        frozen_c = frozen_c | (budget_ref[:] <= p_f - 1.0)
        frozen_r = frozen_r | (budgetr_ref[:] <= p_f)

    at = _maybe_cast(a_ref[:], matmul_dtype)
    rk = h_ref.shape[0]

    # --- W-half of iteration p−1: consumes hgram (masked H_p·H_pᵀ from
    # the previous pass) and the A tile the accumulation below re-reads
    @pl.when(p > 0)
    def _():
        h = h_ref[:].astype(jnp.float32)
        numer = jax.lax.dot_general(
            at, _maybe_cast(h, matmul_dtype), _CONTRACT_COLS,
            preferred_element_type=jnp.float32)
        wt0 = w_ref[pl.dslice(t * block_m, block_m), :].astype(jnp.float32)
        denom = jax.lax.dot_general(
            _maybe_cast(wt0, matmul_dtype),
            _maybe_cast(hgram[:], matmul_dtype),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        wn = _epilogue(wt0, numer, denom, eps, zero_threshold, jnp.float32)
        wn = jnp.where(frozen_c, wt0, wn)
        w_ref[pl.dslice(t * block_m, block_m), :] = wn.astype(w_ref.dtype)

        @pl.when(p % check_every == 0)
        def _():
            # iteration p−1 closes sub-block p/check_every − 1
            bidx = p // check_every - 1
            wd_t = jnp.max(jnp.abs(wn - wt0), axis=0, keepdims=True)
            wm_t = jnp.max(jnp.abs(wt0), axis=0, keepdims=True)
            row = pl.dslice(bidx, 1)

            @pl.when(t == 0)
            def _():
                wd_ref[row, :] = wd_t
                wm_ref[row, :] = wm_t

            @pl.when(t > 0)
            def _():
                wd_ref[row, :] = jnp.maximum(wd_ref[row, :], wd_t)
                wm_ref[row, :] = jnp.maximum(wm_ref[row, :], wm_t)

    # --- H-half accumulation for iteration p (skipped on the final,
    # W-only pass): the A tile is already VMEM-resident
    @pl.when(p < last_pass)
    def _():
        @pl.when(t == 0)
        def _():
            numer_acc[:] = jnp.zeros_like(numer_acc)
            gram_acc[:] = jnp.zeros_like(gram_acc)

        wt = _maybe_cast(w_ref[pl.dslice(t * block_m, block_m), :],
                         matmul_dtype)
        numer_acc[:] += jax.lax.dot_general(
            wt, at, _CONTRACT_ROWS, preferred_element_type=jnp.float32)
        gram_acc[:] += jax.lax.dot_general(
            wt, wt, _CONTRACT_ROWS, preferred_element_type=jnp.float32)

        @pl.when(t == pl.num_programs(1) - 1)
        def _():
            gram = jnp.where(bd, gram_acc[:], 0.0)
            h0 = h_ref[:].astype(jnp.float32)
            denom = jax.lax.dot_general(
                _maybe_cast(gram, matmul_dtype),
                _maybe_cast(h0, matmul_dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            hn = _epilogue(h0, numer_acc[:], denom, eps, zero_threshold,
                           jnp.float32)
            hn = jnp.where(frozen_r, h0, hn)
            h_ref[:] = hn.astype(h_ref.dtype)

            @pl.when((p + 1) % check_every == 0)
            def _():
                bidx = (p + 1) // check_every - 1
                sl = pl.dslice(bidx * rk, rk)
                hd_ref[sl, :] = jnp.max(jnp.abs(hn - h0), axis=1,
                                        keepdims=True)
                hm_ref[sl, :] = jnp.max(jnp.abs(h0), axis=1, keepdims=True)
                if check_block > 1:
                    def snap(sem):
                        dma = pltpu.make_async_copy(
                            h_ref, hck_ref.at[bidx], sem.at[0])
                        dma.start()
                        dma.wait()

                    pl.run_scoped(snap, pltpu.SemaphoreType.DMA((1,)))

            # masked HHᵀ for the NEXT pass's W-half — safe to overwrite
            # here: this pass's W-half already consumed the previous one
            hc = _maybe_cast(hn, matmul_dtype)
            hgram[:] = jnp.where(bd, jax.lax.dot_general(
                hc, hc, _CONTRACT_COLS,
                preferred_element_type=jnp.float32), 0.0)


@functools.partial(jax.jit, static_argnames=(
    "k", "iters", "block_m", "eps", "zero_threshold", "matmul_precision",
    "interpret", "alias_io", "check_block", "fused"))
def fused_block_iterations(a: jax.Array, wp: jax.Array, hp: jax.Array,
                           frozen_cols: jax.Array, *, k: int,
                           iters: int = 2, block_m: int = 512,
                           eps: float = 1e-9, zero_threshold: float = 0.0,
                           matmul_precision: str = "default",
                           interpret: bool = False,
                           seg_ids: "jax.Array | None" = None,
                           alias_io: bool = False,
                           check_block: int = 1,
                           budget_cols: "jax.Array | None" = None,
                           fused: bool = False):
    """``iters`` full MU iterations (both half-updates) in ONE pallas_call
    with the packed factors VMEM-resident throughout — the whole-solve
    launch count drops from ~4 kernels per iteration-pair to 1.

    ``check_block > 1`` (round 6 — the launch-resident convergence
    engine): ONE pallas_call runs ``check_block`` check sub-blocks of
    ``iters`` iterations back-to-back, the factors staying VMEM-resident
    across ALL of them (the W/H HBM round-trip amortizes over
    ``check_block`` checks instead of one). The TolX stat outputs grow a
    per-boundary leading extent — ``wdiff``/``wmax`` become
    (check_block, R·k), ``hdiff``/``hmax`` (check_block·R·k, 1), row b
    measured across the LAST iteration of sub-block b — and a seventh
    output ``h_checks`` (check_block, R·k, n) carries the H snapshot at
    each boundary (DMA'd straight from the resident window: labels and
    class-stability flip counting replay per check against these, so the
    CHECK CADENCE is unchanged while the scheduler trip rate drops
    ``check_block``-fold). ``budget_cols`` (1, R·k) f32 is REQUIRED in
    this mode: each lane's remaining iteration allowance at launch entry
    (``max_iter − slot_iter``; a multiple of ``iters``) — the in-kernel
    fence freezes a lane that crosses its cap mid-launch at exactly the
    right boundary. Frozen-lane and numerical semantics per sub-block
    are identical to ``check_block`` separate launches EXCEPT that a
    lane whose stop condition fires at an interior boundary keeps
    iterating to the end of the launch (the caller records its stop
    iteration from the boundary data; its factors carry the extra
    in-launch iterations — the gate-checkable slot-drift class).

    ``frozen_cols``: (1, R·k) f32, >0 marks a frozen (converged/inactive)
    lane whose columns must not change — callers must keep it constant
    within the block (the slot scheduler's check/reload boundaries are
    block-aligned, so it is). Returns ``(wp, hp, wdiff, wmax, hdiff,
    hmax)`` — the last four are per-column TolX ingredients, (1, R·k) for
    the W pair and (R·k, 1) for the H pair, measured across the LAST
    iteration of the block (max|Δ| and max|prev| over the column/row,
    reduced per lane by the caller).

    The DATA path for the initial factors is never an alias: they arrive
    in HBM (``memory_space=ANY``) and the kernel DMAs them into the
    resident windows once at the first grid step. Round 3's design made
    the alias itself the data path (inputs aliased onto the VMEM output
    windows, no explicit copy) — bit-exact standalone but silently
    reading stale VMEM inside a ``lax.while_loop``/``lax.cond`` body on
    real hardware (see ``_block_kernel``'s comment and VERDICT.md round
    3); do not reintroduce THAT. ``alias_io=True`` is a different,
    gate-validated thing: pure XLA buffer DONATION of the w/h HBM
    buffers on top of the explicit step-0 DMA — the DMA still moves the
    data, the alias only lets the while-loop carry update in place
    instead of copying the packed factors every trip. It stays safe
    because the constant-index output windows write back only after the
    final grid step, long after the step-0 DMA has read the inputs (see
    the ``alias_io`` note at the ``pallas_call`` below and
    ``benchmarks/probe_alias_io.py`` for the bit-exactness bisect;
    measured ~8% slower than the carry copies on v5e, so it stays
    opt-in).

    ``fused=True`` (round 7 — PL-NMF join-the-updates blocking) swaps in
    ``_fused_block_kernel``: grid (T+1, nt) with T = iters·check_block,
    both half-updates sharing each streamed A tile, cutting A's HBM
    reads per launch from 2T to T+1 at the price of one extra (rk, rk)
    f32 scratch. Operand list, output signature, boundary cadence,
    budget fences and frozen-lane semantics are IDENTICAL to the phased
    kernel — and so are the dot_generals, in the same tile order with
    the same f32 accumulators, so the two modes are bit-exact against
    each other (pinned by tests/test_fused_kernel.py in interpret mode;
    the hardware gate is the bench fused-vs-phased rung).

    VMEM budget (measured on v5e, round 4 —
    ``benchmarks/probe_vmem_envelope*.py``): W full-resident dominates;
    the empirical fit accepted by the scheduler
    (``sched_mu._pallas_slot_clamp``, the single source of truth for the
    formula) is ``4·rk·(m_pad + 3·n_pad + rk) + 2·block_m·n_pad·a_bytes
    ≤ 14.3 MiB`` with n_pad = n rounded up to 128 lanes (e.g. rk ≤ 480
    at m=5120, n=512, bf16 A; rk ≤ ~368 at n=1024); ``fused`` adds a
    ``4·rk²`` term for the hgram scratch. Beyond it Mosaic rejects at
    compile time — use the per-iteration kernels there.
    """
    m, n = a.shape
    rk = wp.shape[1]
    if m % block_m:
        raise ValueError(f"m={m} must be a multiple of block_m={block_m}")
    if check_block > 1 and budget_cols is None:
        raise ValueError("check_block > 1 needs budget_cols (each lane's "
                         "remaining iteration allowance at launch entry)")
    nt = m // block_m
    kern_fn = _fused_block_kernel if fused else _block_kernel
    kernel = functools.partial(
        kern_fn, block_m=block_m, k=k, eps=eps,
        zero_threshold=zero_threshold,
        matmul_dtype=_matmul_dtype(matmul_precision),
        check_every=iters, check_block=check_block)
    frozen_rows = frozen_cols.reshape(rk, 1)
    if seg_ids is None:
        # uniform pool: every job spans k consecutive columns
        seg_ids = jnp.arange(rk, dtype=jnp.int32) // k
    seg_ids = seg_ids.astype(jnp.int32)

    if fused:
        grid = (iters * check_block + 1, nt)
        a_map = lambda p, t: (t, 0)  # noqa: E731
        zero_map = lambda p, t: (0, 0)  # noqa: E731
    else:
        grid = (iters * check_block, 2, nt)
        a_map = lambda i, p, t: (t, 0)  # noqa: E731
        zero_map = lambda i, p, t: (0, 0)  # noqa: E731

    def const(shape):
        return pl.BlockSpec(shape, zero_map, memory_space=pltpu.VMEM)

    # w0/h0 stay in HBM (ANY); the kernel DMAs them into the resident
    # output windows exactly once — same total traffic as the round-3
    # aliased design, without relying on custom-call aliasing semantics.
    # alias_io=True (round 5) ADDITIONALLY donates the w_in/h_in HBM
    # buffers as the output buffers — this is NOT the round-3 design:
    # the DATA path stays the explicit step-0 DMA (never the alias), the
    # alias only lets XLA update the while-carry in place instead of
    # copying the packed factors every trip (~30 µs/trip measured in the
    # round-5 trace). The read-before-write order holds because the
    # constant-index output windows write back after the final grid
    # step, long after the step-0 DMA read. Gate-validated: the
    # fault-injection-proven `bench.py --verify` (incl. the
    # reload-exercising boundary stage) must pass with this on — see
    # benchmarks/probe_alias_io.py for the bit-exactness bisect.
    in_specs = [
        pl.BlockSpec((block_m, n), a_map, memory_space=pltpu.VMEM),
        const((1, rk)), const((rk, 1)),
        const((rk, 1)), const((1, rk)),
    ]
    operands = [a, frozen_cols, frozen_rows, seg_ids.reshape(rk, 1),
                seg_ids.reshape(1, rk)]
    if check_block > 1:
        in_specs += [const((1, rk)), const((rk, 1))]
        budget_cols = budget_cols.astype(jnp.float32).reshape(1, rk)
        operands += [budget_cols, budget_cols.reshape(rk, 1)]
    w_in_idx = len(operands)
    in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                 pl.BlockSpec(memory_space=pl.ANY)]
    operands += [wp, hp]
    alias = {w_in_idx: 0, w_in_idx + 1: 1} if alias_io else {}
    nck = check_block
    out_specs = [const((m, rk)), const((rk, n)), const((nck, rk)),
                 const((nck, rk)), const((nck * rk, 1)),
                 const((nck * rk, 1))]
    out_shape = [
        jax.ShapeDtypeStruct((m, rk), wp.dtype),
        jax.ShapeDtypeStruct((rk, n), hp.dtype),
        jax.ShapeDtypeStruct((nck, rk), jnp.float32),
        jax.ShapeDtypeStruct((nck, rk), jnp.float32),
        jax.ShapeDtypeStruct((nck * rk, 1), jnp.float32),
        jax.ShapeDtypeStruct((nck * rk, 1), jnp.float32),
    ]
    if check_block > 1:
        # per-boundary H snapshots live in HBM (ANY) — written by one
        # small DMA per boundary straight from the resident H window, so
        # they cost no VMEM and ~rk·n bytes of traffic per check (the
        # same H read the separate-launch design's external labels paid)
        out_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        out_shape.append(
            jax.ShapeDtypeStruct((nck, rk, n), hp.dtype))
    scratch_shapes = [
        pltpu.VMEM((rk, n), jnp.float32),
        pltpu.VMEM((rk, rk), jnp.float32),
    ]
    if fused:
        # hgram: the masked HHᵀ carried from each pass's H-half to the
        # next pass's W-half (the phased kernel reuses gram_acc, but the
        # fused pass needs both alive at once)
        scratch_shapes.append(pltpu.VMEM((rk, rk), jnp.float32))
    return pl.pallas_call(
        kernel,
        grid=grid,
        input_output_aliases=alias,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(*operands)


@functools.partial(jax.jit, static_argnames=(
    "block_m", "eps", "zero_threshold", "matmul_precision", "interpret"))
def fused_w_update(a: jax.Array, wp: jax.Array, hp: jax.Array,
                   gh_masked: jax.Array, *, block_m: int = 512,
                   eps: float = 1e-9, zero_threshold: float = 0.0,
                   matmul_precision: str = "default",
                   interpret: bool = False) -> jax.Array:
    """Wp ← mu_epilogue(Wp, A·Hpᵀ, Wp·(HpHpᵀ∘B)) tile-local per m-block."""
    m, n = a.shape
    rk = wp.shape[1]
    if m % block_m:
        raise ValueError(f"m={m} must be a multiple of block_m={block_m}")
    kernel = functools.partial(
        _w_kernel, eps=eps, zero_threshold=zero_threshold,
        matmul_dtype=_matmul_dtype(matmul_precision))
    return pl.pallas_call(
        kernel,
        grid=(m // block_m,),
        in_specs=[
            pl.BlockSpec((block_m, n), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, rk), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rk, n), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rk, rk), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_m, rk), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, rk), wp.dtype),
        interpret=interpret,
    )(a, wp, hp, gh_masked)


def _perm_matrix(rk: int, k: int, slots: int):
    """(rk, rk) f32 permutation grouping component jj of every slot into
    contiguous rows: row r = jj·slots + s selects packed column
    s·k + jj. Built from 2-D iotas in-kernel (Mosaic needs ≥2-D iota),
    applied as GEMMs so the HALS coordinate sweep below runs on
    contiguous (slots, ·) slices — MXU-dense instead of a strided
    gather, which Mosaic does not support on TPU."""
    r = jax.lax.broadcasted_iota(jnp.int32, (rk, rk), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (rk, rk), 1)
    return ((r % slots) * k + r // slots == c).astype(jnp.float32)


def _clamp(x, zero_threshold):
    """base.clamp inlined for the kernel (nmf_als.c:247-250)."""
    return jnp.where(x <= zero_threshold, jnp.zeros_like(x), x)


def _hals_block_kernel(a_ref, frozen_ref, frozenr_ref, seg_row_ref,
                       seg_col_ref, *rest, block_m: int, k: int,
                       slots: int, eps: float, zero_threshold: float,
                       matmul_dtype, check_every: int = 0,
                       check_block: int = 1):
    """HALS sibling of ``_block_kernel`` — same grid (iters, 2 phases,
    nt m-tiles), same VMEM-resident factor windows / step-0 DMA /
    budget fences / boundary stat+snapshot cadence, but the epilogues
    are the Cichocki–Phan coordinate sweeps of ``grid_mu.hals_block``
    instead of the mu ratio. The packed layout interleaves the pool's
    lanes (column s·k + jj is slot s, component jj), so the per-jj
    sweep is re-expressed through a permutation GEMM (``_perm_matrix``):
    conjugating the masked Gram with Q makes each component's rows/cols
    of ALL slots contiguous, each of the k sweep steps updates one
    (slots, ·) slice in scratch, and a final GEMM un-permutes. The
    block-diagonal mask zeroes every cross-slot Gram entry, so the
    sweep is exactly ``slots`` independent dense HALS sweeps run in
    lockstep — frozen-lane passthrough after the sweep is exact, and
    zero-padded components (k_j < k jobs) stay invariant (zero
    numerator, eps-guarded diagonal). Overhead vs mu: ~4 extra
    rk²-sized GEMM-equivalents per tile (the permutation conjugations
    and the k accumulated (·, slots) slice products) — subleading to
    the 2·block_m·n·rk streaming terms at north-star shapes, and
    priced honestly by the (hals, pallas) costmodel row."""
    it = pl.program_id(0)
    ph = pl.program_id(1)
    t = pl.program_id(2)
    if check_block > 1:
        (budget_ref, budgetr_ref, w_in_ref, h_in_ref,
         w_ref, h_ref, wd_ref, wm_ref, hd_ref, hm_ref, hck_ref,
         numer_acc, gram_acc, diag_ref, hwork, wwork) = rest
        is_boundary = (it + 1) % check_every == 0
        bidx = (it + 1) // check_every - 1
    else:
        (w_in_ref, h_in_ref,
         w_ref, h_ref, wd_ref, wm_ref, hd_ref, hm_ref,
         numer_acc, gram_acc, diag_ref, hwork, wwork) = rest

    @pl.when((it == 0) & (ph == 0) & (t == 0))
    def _():
        def init(sems):
            dma_w = pltpu.make_async_copy(w_in_ref, w_ref, sems.at[0])
            dma_h = pltpu.make_async_copy(h_in_ref, h_ref, sems.at[1])
            dma_w.start()
            dma_h.start()
            dma_w.wait()
            dma_h.wait()

        pl.run_scoped(init, pltpu.SemaphoreType.DMA((2,)))
    last_it = it == pl.num_programs(0) - 1
    bd = seg_row_ref[:] == seg_col_ref[:]
    frozen_c = frozen_ref[:] > 0.0
    frozen_r = frozenr_ref[:] > 0.0
    if check_block > 1:
        it_f = it.astype(jnp.float32)
        frozen_c = frozen_c | (budget_ref[:] <= it_f)
        frozen_r = frozen_r | (budgetr_ref[:] <= it_f)
    rk = h_ref.shape[0]
    eye = (jax.lax.broadcasted_iota(jnp.int32, (rk, rk), 0)
           == jax.lax.broadcasted_iota(jnp.int32, (rk, rk), 1))

    @pl.when((ph == 0) & (t == 0))
    def _():
        numer_acc[:] = jnp.zeros_like(numer_acc)
        gram_acc[:] = jnp.zeros_like(gram_acc)

    @pl.when(ph == 0)
    def _():
        wt = _maybe_cast(w_ref[pl.dslice(t * block_m, block_m), :],
                         matmul_dtype)
        at = _maybe_cast(a_ref[:], matmul_dtype)
        numer_acc[:] += jax.lax.dot_general(
            wt, at, _CONTRACT_ROWS, preferred_element_type=jnp.float32)
        gram_acc[:] += jax.lax.dot_general(
            wt, wt, _CONTRACT_ROWS, preferred_element_type=jnp.float32)

        @pl.when(t == pl.num_programs(2) - 1)
        def _():
            q = _perm_matrix(rk, k, slots)
            g = jnp.where(bd, gram_acc[:], 0.0)
            # conjugate: g_p[jj·S+s, ll·S+s'] = wtw[s][jj, ll]·[s==s']
            g_p = jax.lax.dot_general(
                jnp.dot(q, g, preferred_element_type=jnp.float32), q,
                _CONTRACT_COLS, preferred_element_type=jnp.float32)
            diag_g = jnp.sum(jnp.where(eye, g_p, 0.0), axis=1,
                             keepdims=True)  # (rk, 1)
            h0 = h_ref[:].astype(jnp.float32)
            hwork[:] = jnp.dot(q, h0, preferred_element_type=jnp.float32)
            wta_p = jnp.dot(q, numer_acc[:],
                            preferred_element_type=jnp.float32)
            for jj in range(k):
                lo = jj * slots
                sl = pl.dslice(lo, slots)
                # current hwork (prior components already updated) —
                # the dense sweep's Gauss–Seidel order, hals_block:157-160
                num = wta_p[lo:lo + slots, :] - jnp.dot(
                    g_p[lo:lo + slots, :], hwork[:],
                    preferred_element_type=jnp.float32)
                hj = hwork[sl, :] + num / (diag_g[lo:lo + slots, :] + eps)
                hwork[sl, :] = _clamp(hj, zero_threshold)
            hn = jax.lax.dot_general(
                q, hwork[:], _CONTRACT_ROWS,
                preferred_element_type=jnp.float32)  # un-permute: Qᵀ·
            hn = jnp.where(frozen_r, h0, hn)
            h_ref[:] = hn.astype(h_ref.dtype)

            if check_block > 1:
                @pl.when(is_boundary)
                def _():
                    sl = pl.dslice(bidx * rk, rk)
                    hd_ref[sl, :] = jnp.max(jnp.abs(hn - h0), axis=1,
                                            keepdims=True)
                    hm_ref[sl, :] = jnp.max(jnp.abs(h0), axis=1,
                                            keepdims=True)

                    def snap(sem):
                        dma = pltpu.make_async_copy(
                            h_ref, hck_ref.at[bidx], sem.at[0])
                        dma.start()
                        dma.wait()

                    pl.run_scoped(snap, pltpu.SemaphoreType.DMA((1,)))
            else:
                @pl.when(last_it)
                def _():
                    hd_ref[:] = jnp.max(jnp.abs(hn - h0), axis=1,
                                        keepdims=True)
                    hm_ref[:] = jnp.max(jnp.abs(h0), axis=1, keepdims=True)
            # pre-permute the masked HHᵀ + its diagonal for phase 1
            hc = _maybe_cast(hn, matmul_dtype)
            hht = jnp.where(bd, jax.lax.dot_general(
                hc, hc, _CONTRACT_COLS,
                preferred_element_type=jnp.float32), 0.0)
            gh_p = jax.lax.dot_general(
                jnp.dot(q, hht, preferred_element_type=jnp.float32), q,
                _CONTRACT_COLS, preferred_element_type=jnp.float32)
            gram_acc[:] = gh_p
            diag_ref[:] = jnp.sum(jnp.where(eye, gh_p, 0.0), axis=0,
                                  keepdims=True)  # (1, rk)

    @pl.when(ph == 1)
    def _():
        q = _perm_matrix(rk, k, slots)
        at = _maybe_cast(a_ref[:], matmul_dtype)
        h = _maybe_cast(h_ref[:], matmul_dtype)
        aht = jax.lax.dot_general(
            at, h, _CONTRACT_COLS, preferred_element_type=jnp.float32)
        wt0 = w_ref[pl.dslice(t * block_m, block_m), :].astype(jnp.float32)
        # permute columns: x_p = x·Qᵀ
        wwork[:] = jax.lax.dot_general(
            wt0, q, _CONTRACT_COLS, preferred_element_type=jnp.float32)
        aht_p = jax.lax.dot_general(
            aht, q, _CONTRACT_COLS, preferred_element_type=jnp.float32)
        g = gram_acc[:]  # permuted masked HHᵀ from phase 0
        diag = diag_ref[:]  # (1, rk), permuted
        for jj in range(k):
            lo = jj * slots
            csl = pl.dslice(lo, slots)
            num = aht_p[:, lo:lo + slots] - jnp.dot(
                wwork[:], g[:, lo:lo + slots],
                preferred_element_type=jnp.float32)
            wj = wwork[:, csl] + num / (diag[:, lo:lo + slots] + eps)
            wwork[:, csl] = _clamp(wj, zero_threshold)
        wn = jnp.dot(wwork[:], q, preferred_element_type=jnp.float32)
        wn = jnp.where(frozen_c, wt0, wn)
        w_ref[pl.dslice(t * block_m, block_m), :] = wn.astype(w_ref.dtype)

        if check_block > 1:
            @pl.when(is_boundary)
            def _():
                wd_t = jnp.max(jnp.abs(wn - wt0), axis=0, keepdims=True)
                wm_t = jnp.max(jnp.abs(wt0), axis=0, keepdims=True)
                row = pl.dslice(bidx, 1)

                @pl.when(t == 0)
                def _():
                    wd_ref[row, :] = wd_t
                    wm_ref[row, :] = wm_t

                @pl.when(t > 0)
                def _():
                    wd_ref[row, :] = jnp.maximum(wd_ref[row, :], wd_t)
                    wm_ref[row, :] = jnp.maximum(wm_ref[row, :], wm_t)
        else:
            @pl.when(last_it)
            def _():
                wd_t = jnp.max(jnp.abs(wn - wt0), axis=0, keepdims=True)
                wm_t = jnp.max(jnp.abs(wt0), axis=0, keepdims=True)

                @pl.when(t == 0)
                def _():
                    wd_ref[:] = wd_t
                    wm_ref[:] = wm_t

                @pl.when(t > 0)
                def _():
                    wd_ref[:] = jnp.maximum(wd_ref[:], wd_t)
                    wm_ref[:] = jnp.maximum(wm_ref[:], wm_t)


@functools.partial(jax.jit, static_argnames=(
    "k", "slots", "iters", "block_m", "eps", "zero_threshold",
    "matmul_precision", "interpret", "alias_io", "check_block"))
def hals_block_iterations(a: jax.Array, wp: jax.Array, hp: jax.Array,
                          frozen_cols: jax.Array, *, k: int, slots: int,
                          iters: int = 2, block_m: int = 512,
                          eps: float = 1e-9, zero_threshold: float = 0.0,
                          matmul_precision: str = "default",
                          interpret: bool = False,
                          alias_io: bool = False,
                          check_block: int = 1,
                          budget_cols: "jax.Array | None" = None):
    """``iters`` full HALS iterations for the UNIFORM packed pool in one
    ``pallas_call`` — the hals sibling of ``fused_block_iterations``,
    with the identical operand list (minus seg overrides: hals is
    uniform-pool only, seg = iota // k), identical outputs, identical
    check_block/budget semantics, so the slot scheduler routes both
    through the same ``make_do_block``/``make_do_multi`` plumbing. The
    update math is ``grid_mu.hals_block`` re-expressed for the packed
    layout via a permutation conjugation (see ``_hals_block_kernel``);
    agreement with the vmapped dense engine is consensus-level (Mosaic
    accumulation order differs), gated by
    tests/test_fused_kernel.py::test_hals_pallas_agreement.

    VMEM: on top of the mu block kernel's envelope this holds one extra
    (rk, n) f32 sweep scratch, a (block_m, rk) f32 W work tile and the
    (rk, rk) permutation temporaries — ``sched_mu._pallas_max_rk``
    prices it via its ``algorithm="hals"`` term.
    """
    m, n = a.shape
    rk = wp.shape[1]
    if m % block_m:
        raise ValueError(f"m={m} must be a multiple of block_m={block_m}")
    if rk != k * slots:
        raise ValueError(f"packed width {rk} != k*slots = {k}*{slots}")
    if check_block > 1 and budget_cols is None:
        raise ValueError("check_block > 1 needs budget_cols (each lane's "
                         "remaining iteration allowance at launch entry)")
    nt = m // block_m
    kernel = functools.partial(
        _hals_block_kernel, block_m=block_m, k=k, slots=slots, eps=eps,
        zero_threshold=zero_threshold,
        matmul_dtype=_matmul_dtype(matmul_precision),
        check_every=iters, check_block=check_block)
    frozen_rows = frozen_cols.reshape(rk, 1)
    seg_ids = jnp.arange(rk, dtype=jnp.int32) // k

    def const(shape):
        return pl.BlockSpec(shape, lambda i, p, t: (0, 0),
                            memory_space=pltpu.VMEM)

    in_specs = [
        pl.BlockSpec((block_m, n), lambda i, p, t: (t, 0),
                     memory_space=pltpu.VMEM),
        const((1, rk)), const((rk, 1)),
        const((rk, 1)), const((1, rk)),
    ]
    operands = [a, frozen_cols, frozen_rows, seg_ids.reshape(rk, 1),
                seg_ids.reshape(1, rk)]
    if check_block > 1:
        in_specs += [const((1, rk)), const((rk, 1))]
        budget_cols = budget_cols.astype(jnp.float32).reshape(1, rk)
        operands += [budget_cols, budget_cols.reshape(rk, 1)]
    w_in_idx = len(operands)
    in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                 pl.BlockSpec(memory_space=pl.ANY)]
    operands += [wp, hp]
    alias = {w_in_idx: 0, w_in_idx + 1: 1} if alias_io else {}
    nck = check_block
    out_specs = [const((m, rk)), const((rk, n)), const((nck, rk)),
                 const((nck, rk)), const((nck * rk, 1)),
                 const((nck * rk, 1))]
    out_shape = [
        jax.ShapeDtypeStruct((m, rk), wp.dtype),
        jax.ShapeDtypeStruct((rk, n), hp.dtype),
        jax.ShapeDtypeStruct((nck, rk), jnp.float32),
        jax.ShapeDtypeStruct((nck, rk), jnp.float32),
        jax.ShapeDtypeStruct((nck * rk, 1), jnp.float32),
        jax.ShapeDtypeStruct((nck * rk, 1), jnp.float32),
    ]
    if check_block > 1:
        out_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        out_shape.append(jax.ShapeDtypeStruct((nck, rk, n), hp.dtype))
    return pl.pallas_call(
        kernel,
        grid=(iters * check_block, 2, nt),
        input_output_aliases=alias,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((rk, n), jnp.float32),
            pltpu.VMEM((rk, rk), jnp.float32),
            pltpu.VMEM((1, rk), jnp.float32),
            pltpu.VMEM((rk, n), jnp.float32),
            pltpu.VMEM((block_m, rk), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
