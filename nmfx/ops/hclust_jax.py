"""On-device hierarchical clustering (average/complete/single linkage),
cophenetic distances, and cut-tree.

The reference delegates rank selection to base R on the host —
``hclust(as.dist(1-C), "average")`` → ``cophenetic`` → ``cor`` → ``cutree``
(reference ``nmf.r:165-177``); nmfx's default does the same small-n work in
host numpy / native C++ (``nmfx/cophenetic.py``). This module is the fully
TPU-resident alternative (SURVEY.md §7 build step 3): the n−1 inherently
sequential merge steps run as a ``lax.fori_loop`` over a masked distance
matrix, so an entire per-rank pipeline — solve → consensus → ρ/membership —
can execute under one jit with nothing but scalars returning to the host.

Algorithmic conventions match ``nmfx/cophenetic.py`` exactly (scipy-style
cluster ids, first-minimum tie-breaking in row-major order, R ``cutree``
label numbering by first appearance, left-child-first dendrogram leaf
order), and the two implementations are cross-tested.

O(n³) total work on the VPU — for consensus matrices (n = #samples ≤ a few
thousand) this is negligible next to the NMF iterations.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, static_argnames=("k", "method"))
def linkage_jax(dist: jax.Array, k: int | None = None,
                method: str = "average"):
    """Agglomerative clustering of an (n, n) distance matrix, on device,
    under the "average", "complete", or "single" Lance-Williams update.

    Returns ``(linkage, coph, order, membership)``:

    * ``linkage`` — (n−1, 4) scipy-style merge table
    * ``coph`` — (n, n) cophenetic distances
    * ``order`` — (n,) dendrogram leaf order (DFS, left child first)
    * ``membership`` — (n,) labels 1..k from cutting at k clusters
      (1s if ``k`` is None)
    """
    n = dist.shape[0]
    if dist.shape != (n, n):
        raise ValueError("dist must be square")
    from nmfx.config import LINKAGE_METHODS

    if method not in LINKAGE_METHODS:
        raise ValueError(
            f"linkage must be one of {LINKAGE_METHODS}, got {method!r}")
    kcut = 1 if k is None else k
    if not 1 <= kcut <= n:
        raise ValueError(f"k must be in [1, {n}]")
    f = jnp.promote_types(dist.dtype, jnp.float32)
    d = jnp.asarray(dist, f)
    d = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, d)

    # carry: working distances, active mask, sizes, slot cluster-ids,
    # per-slot member masks, cophenetic accumulator, linkage rows, and the
    # per-sample slot snapshot taken when exactly `kcut` clusters remain
    mem0 = jnp.eye(n, dtype=bool)
    init = (d, jnp.ones(n, bool), jnp.ones(n, f), jnp.arange(n),
            mem0, jnp.zeros((n, n), f), jnp.zeros((n - 1, 4), f),
            jnp.arange(n))

    def merge(t, carry):
        d, active, size, cid, mem, coph, linkage, cut_slot = carry
        pair_ok = active[:, None] & active[None, :]
        masked = jnp.where(pair_ok, d, jnp.inf)
        idx = jnp.argmin(masked.ravel())  # first minimum, row-major
        i, j = jnp.minimum(idx // n, idx % n), jnp.maximum(idx // n, idx % n)
        height = masked.ravel()[idx]
        ci, cj = cid[i], cid[j]
        a, b = jnp.minimum(ci, cj), jnp.maximum(ci, cj)
        new_size = size[i] + size[j]
        linkage = linkage.at[t].set(
            jnp.stack([a.astype(f), b.astype(f), height, new_size]))
        cross = mem[i][:, None] & mem[j][None, :]
        coph = coph + height * (cross | cross.T).astype(f)
        if method == "average":
            merged = (size[i] * d[i] + size[j] * d[j]) / new_size
        elif method == "complete":
            merged = jnp.maximum(d[i], d[j])
        else:  # single
            merged = jnp.minimum(d[i], d[j])
        d = d.at[i, :].set(merged).at[:, i].set(merged).at[i, i].set(jnp.inf)
        active = active.at[j].set(False)
        mem = mem.at[i].set(mem[i] | mem[j])
        size = size.at[i].set(new_size)
        cid = cid.at[i].set(n + t)
        # snapshot sample→slot when kcut clusters remain (after this merge
        # there are n-(t+1) clusters)
        slot_of_sample = jnp.argmax(mem.T, axis=1)  # each sample: one slot
        take = (n - (t + 1)) == kcut
        cut_slot = jnp.where(take, slot_of_sample, cut_slot)
        return d, active, size, cid, mem, coph, linkage, cut_slot

    (_, _, _, _, _, coph, linkage,
     cut_slot) = lax.fori_loop(0, n - 1, merge, init)

    order = _leaf_order(linkage, n)
    membership = _first_appearance_labels(cut_slot)
    return linkage, coph, order, membership


def _leaf_order(linkage: jax.Array, n: int) -> jax.Array:
    """Dendrogram leaf order via an explicit-stack DFS (left child first),
    as a fori_loop — every node is popped exactly once (2n−1 pops)."""
    if n == 1:
        return jnp.zeros((1,), jnp.int32)
    stack = jnp.zeros((2 * n,), jnp.int32).at[0].set(2 * n - 2)
    order = jnp.zeros((n,), jnp.int32)

    def pop(_, carry):
        stack, sp, order, no = carry
        node = stack[sp - 1]
        sp = sp - 1
        is_leaf = node < n
        # leaf: append to order
        order = jnp.where(is_leaf, order.at[no].set(node), order)
        no = no + is_leaf.astype(jnp.int32)
        # internal: push right then left (left is popped first)
        t = jnp.maximum(node - n, 0)
        left = linkage[t, 0].astype(jnp.int32)
        right = linkage[t, 1].astype(jnp.int32)
        stack = jnp.where(is_leaf, stack,
                          stack.at[sp].set(right).at[sp + 1].set(left))
        sp = jnp.where(is_leaf, sp, sp + 2)
        return stack, sp, order, no

    _, _, order, _ = lax.fori_loop(
        0, 2 * n - 1, pop,
        (stack, jnp.int32(1), order, jnp.int32(0)))
    return order


def _first_appearance_labels(raw: jax.Array) -> jax.Array:
    """Renumber arbitrary integer labels 1..k by first appearance in index
    order (R cutree convention, reference nmf.r:177)."""
    n = raw.shape[0]
    idx = jnp.arange(n)
    # first occurrence position of each sample's label
    same = raw[:, None] == raw[None, :]
    first_pos = jnp.min(jnp.where(same, idx[None, :], n), axis=1)
    # label = 1 + number of distinct first-positions strictly before ours
    distinct_before = jnp.sum(
        (jnp.unique(first_pos, size=n, fill_value=n)[None, :]
         < first_pos[:, None]), axis=1)
    return (distinct_before + 1).astype(jnp.int32)


def average_linkage_jax(dist: jax.Array, k: int | None = None):
    """UPGMA clustering on device (kept as the named average-linkage
    entry; see ``linkage_jax`` for the general method)."""
    return linkage_jax(dist, k, "average")


@partial(jax.jit, static_argnames=("k", "method"))
def rank_selection_jax(consensus: jax.Array, k: int,
                       method: str = "average"):
    """Fully on-device analogue of ``nmfx.cophenetic.rank_selection``:
    (ρ, membership 1..k, dendrogram leaf order) from one consensus matrix."""
    n = consensus.shape[0]
    f = jnp.promote_types(consensus.dtype, jnp.float32)
    dist = (1.0 - jnp.asarray(consensus, f))
    dist = jnp.where(jnp.eye(n, dtype=bool), 0.0, dist)
    _, coph, order, membership = linkage_jax(dist, k, method)
    iu = jnp.triu_indices(n, k=1)
    x = dist[iu]
    y = coph[iu]
    xc = x - x.mean()
    yc = y - y.mean()
    denom = jnp.sqrt((xc @ xc) * (yc @ yc))
    rho = jnp.where(denom == 0, 1.0, (xc @ yc) / denom)
    return rho, membership, order
