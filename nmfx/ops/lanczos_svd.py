"""On-device truncated SVD via Lanczos on the Gram operator.

TPU-native equivalent of the reference's ARPACK path (reference
``libnmf/calculatesvd.c:38-267``): dsaupd reverse-communication Lanczos on
the smaller Gram operator — the caller supplies y = Aᵀ(Ax) per iteration —
followed by Ritz extraction, σ = √λ, and the other-side vectors via
u = Av/‖Av‖. Here the reverse-communication loop becomes a ``lax.scan`` of
matvec pairs with full reorthogonalization (numerically stronger than
ARPACK's selective scheme at the small subspace sizes NNDSVD needs), and
the tridiagonal eigenproblem is solved with ``jnp.linalg.eigh``.

Used by NNDSVD initialization (``nmfx/init.py``) when requested
(``InitConfig.svd_method="lanczos"``): at consensus-NMF sizes the dense
``jnp.linalg.svd`` is fine, but it factors the full min(m,n)-dimensional
spectrum — for tall-and-wide matrices where only k ≪ min(m,n) pairs are
needed, the Lanczos path does O(ncv) matvec pairs instead.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, static_argnames=("k", "ncv"))
def truncated_svd(a: jax.Array, k: int, ncv: int | None = None):
    """Leading-k SVD of A (m×n): returns (U m×k, S k, Vt k×n).

    ``ncv``: Lanczos subspace size. Default: 2k+1 with a floor of 20 (full
    reorthogonalization converges in one restart with a modest cushion;
    ARPACK instead iterates with restarts), capped to the operator
    dimension — cf. the reference's ncv defaulting at
    ``libnmf/generatematrix.c:107-120``.
    """
    m, n = a.shape
    big_m = m >= n  # iterate on the smaller Gram, as the reference does
    dim = n if big_m else m
    if not 1 <= k <= dim:
        raise ValueError(f"k must be in [1, {dim}]")
    if ncv is None:
        ncv = min(max(2 * k + 1, 20), dim)
    ncv = min(max(ncv, k + 1), dim)
    f = jnp.promote_types(a.dtype, jnp.float32)
    a = jnp.asarray(a, f)

    def gram_matvec(x):
        # y = Aᵀ(Ax) or A(Aᵀx) — two dense matvecs (calculatesvd.c:141-164)
        return a.T @ (a @ x) if big_m else a @ (a.T @ x)

    # Lanczos with full reorthogonalization, fixed ncv steps.
    # basis Q (ncv, dim), tridiagonal (alpha, beta).
    key = jax.random.key(0)  # deterministic start vector (reference uses
    # ARPACK's internal default start; any non-degenerate vector works)
    q0 = jax.random.normal(key, (dim,), f)
    q0 = q0 / jnp.linalg.norm(q0)

    # Breakdown handling: when β falls below a relative tolerance the
    # Krylov space is (numerically) invariant — ARPACK would stop; a scan
    # has a fixed trip count, so a latched `dead` flag zeroes the rest of
    # the recurrence instead. Without it the post-breakdown noise vectors
    # reintroduce ghost copies of the top eigenvalues into T.
    tol_rel = 25 * jnp.finfo(f).eps

    def step(carry, _):
        q_prev, q, beta_prev, basis, i, dead, scale = carry
        w = gram_matvec(q) - beta_prev * q_prev
        alpha = w @ q
        w = w - alpha * q
        # full reorthogonalization, two passes (f32 cancellation at large
        # spectral range leaves O(eps·λmax) residue after one)
        w = w - basis.T @ (basis @ w)
        w = w - basis.T @ (basis @ w)
        beta = jnp.linalg.norm(w)
        scale = jnp.maximum(scale, jnp.maximum(jnp.abs(alpha), beta))
        dead_next = dead | (beta <= tol_rel * scale)
        alpha = jnp.where(dead, 0.0, alpha)
        beta = jnp.where(dead_next, 0.0, beta)
        q_next = jnp.where(dead_next, jnp.zeros_like(w),
                           w / jnp.where(beta > 0, beta, 1.0))
        basis = basis.at[i].set(q)
        return (q, q_next, beta, basis, i + 1, dead_next,
                scale), (alpha, beta)

    basis0 = jnp.zeros((ncv, dim), f)
    (_, _, _, basis, _, _, _), (alphas, betas) = lax.scan(
        step, (jnp.zeros((dim,), f), q0, jnp.zeros((), f), basis0,
               jnp.int32(0), jnp.zeros((), bool), jnp.zeros((), f)),
        None, length=ncv)

    # tridiagonal T = diag(alphas) + offdiag(betas[:-1])
    t = (jnp.diag(alphas) + jnp.diag(betas[:-1], 1) + jnp.diag(betas[:-1],
                                                               -1))
    evals, evecs = jnp.linalg.eigh(t)  # ascending
    # top-k Ritz pairs, descending (reference reorders with dswap,
    # calculatesvd.c:229-246)
    sel = jnp.argsort(evals)[::-1][:k]
    lam = jnp.maximum(evals[sel], 0.0)
    ritz = basis.T @ evecs[:, sel]  # (dim, k) eigenvectors of the Gram
    s = jnp.sqrt(lam)

    safe = jnp.where(s > 0, s, 1.0)
    if big_m:
        v = ritz  # (n, k)
        u = (a @ v) / safe[None, :]  # u = Av/σ (calculatesvd.c:198-224)
        u = jnp.where(s[None, :] > 0, u, 0.0)
    else:
        u = ritz  # (m, k)
        v = (a.T @ u) / safe[None, :]
        v = jnp.where(s[None, :] > 0, v, 0.0)

    # Degenerate-multiplet guard: a single-start-vector Krylov space holds
    # only ONE Ritz copy per distinct eigenvalue, so for a repeated σ the
    # top-k list is missing the second copy — and every *returned* pair is
    # still a genuine singular pair, so per-pair residuals can't tell.
    # What can: the deflated operator A − U S Vᵀ must have spectral norm
    # ≤ σ_k if the returned set really is the top k. Estimate it with a
    # few power iterations (operator form, nothing materialized) and fall
    # back to the dense factorization when it exceeds the smallest
    # returned σ.
    vt = v.T

    def deflated_matvec(x):
        return a @ x - u @ (s * (vt @ x))

    def deflated_rmatvec(y):
        return a.T @ y - vt.T @ (s * (u.T @ y))

    x0 = jax.random.normal(jax.random.fold_in(key, 1), (n,), f)
    x0 = x0 / jnp.linalg.norm(x0)

    def power(i, x):
        z = deflated_rmatvec(deflated_matvec(x))
        nz = jnp.linalg.norm(z)
        return z / jnp.where(nz > 0, nz, 1.0)

    x = lax.fori_loop(0, 12, power, x0)
    est = jnp.linalg.norm(deflated_matvec(x))
    ok = est <= s[k - 1] * 1.01 + 1e-3 * jnp.maximum(s[0],
                                                     jnp.finfo(f).tiny)

    def dense():
        ud, sd, vtd = jnp.linalg.svd(a, full_matrices=False)
        return ud[:, :k], sd[:k], vtd[:k, :]

    return lax.cond(ok, lambda: (u, s, vt), dense)
