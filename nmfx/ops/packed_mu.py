"""Restart-packed multiplicative-update iteration: the MXU-shaped MU solver.

The generic driver (``nmfx.solvers.base``) runs one restart per vmap lane, so
a rank-k update becomes a *batched* GEMM with tiny per-lane shapes (k rows of
output per restart) — the MXU pads k up to a full tile and most of the
systolic array idles. This module instead lays the whole restart batch out as
one pair of packed factor matrices

    Wp = (m, R·k)   — restart-major column blocks
    Hp = (R·k, n)

so every per-iteration contraction is a single large GEMM over the shared
data matrix A (reference math: the six dgemms of ``libnmf/nmf_mu.c:174-216``,
batched over the reference's BatchJobs restart grid, ``nmf.r:64-68``):

    numerh = Wpᵀ · A        Gw = Wpᵀ · Wp
    denomh = (Gw ∘ B) · Hp                  B = block-diagonal mask
    Hp            ← mu_epilogue(Hp, numerh, denomh)
    numerw        = A · Hpᵀ
    denomw        = Wp · (Hp·Hpᵀ ∘ B)
    Wp            ← mu_epilogue(Wp, numerw, denomw)

The full Grams Gw = WpᵀWp and Hp·Hpᵀ contain cross-restart blocks the math
never uses; masking them costs ~R× redundant FLOPs on an (R·k)² term but
keeps every matmul MXU-dense — a win whenever R·k per device is small
relative to n (always true on a multi-chip mesh, and measured faster on a
single chip for the target sizes). Off-restart blocks never influence
results: the block-diagonal mask zeroes them before they touch Hp/Wp.

Convergence bookkeeping (class-stability + TolX) is vectorized over the
restart axis with per-restart freeze masks, reproducing exactly the
semantics the vmapped ``lax.while_loop`` gives the generic driver: a
converged restart's factors, labels, and counters stop updating while the
batch runs on (SURVEY.md §7 hard part #1).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from nmfx._compat import pcast
from nmfx.config import SolverConfig
from nmfx.solvers import base
from nmfx.solvers.mu import _mu_update


class PackedState(NamedTuple):
    wp: jax.Array  # (m, R*k)
    hp: jax.Array  # (R*k, n)
    wp_prev: jax.Array
    hp_prev: jax.Array
    iteration: jax.Array  # () i32 — shared batch clock
    classes: jax.Array  # (R, n) i32
    stable: jax.Array  # (R,) i32
    done: jax.Array  # (R,) bool
    done_iter: jax.Array  # (R,) i32 — iteration at which each restart stopped
    stop_reason: jax.Array  # (R,) i32
    #: (R,) bool — sticky numeric-quarantine flag (nonfinite_guard): the
    #: lane's factors went non-finite; it is frozen at its last finite
    #: iterate and stops with NUMERIC_FAULT at the next check
    nonfinite: jax.Array = None


class PackedMUResult(NamedTuple):
    wp: jax.Array  # (m, R*k) final packed factors
    hp: jax.Array  # (R*k, n)
    iterations: jax.Array  # (R,) i32
    dnorm: jax.Array  # (R,) final RMS residual per restart
    stop_reason: jax.Array  # (R,) i32 StopReason


def block_diag_mask(r: int, k: int, dtype) -> jax.Array:
    """(R·k, R·k) 0/1 mask keeping only within-restart k×k blocks."""
    rk = jnp.arange(r * k) // k
    return (rk[:, None] == rk[None, :]).astype(dtype)


def bd_select(g: jax.Array, bd: jax.Array) -> jax.Array:
    """Apply the block-diagonal Gram mask as a SELECT, not a multiply.
    Identical values for finite Grams (g·1 = g, masked entries exactly
    zero), but a non-finite CROSS-lane Gram entry becomes a true zero
    instead of ``NaN·0 = NaN`` — the numeric quarantine's containment
    fence: one diverged lane's inf/NaN cannot leak through the masked
    Gram into its dispatch-mates' denominators."""
    return jnp.where(bd != 0, g, jnp.zeros((), g.dtype))


def _lanes_finite(x: jax.Array, axes, mesh_axis: "str | None" = None
                  ) -> jax.Array:
    """Per-lane all-finite verdict of a lane-stacked factor array; with
    ``mesh_axis`` (the factor's shard axis inside ``shard_map``) the
    verdict reduces globally, so every device of a lane's group agrees."""
    ok = jnp.all(jnp.isfinite(x), axis=axes)
    if mesh_axis is not None:
        ok = lax.psum((~ok).astype(jnp.int32), mesh_axis) == 0
    return ok


def pack(w0s: jax.Array, h0s: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(R,m,k),(R,k,n) → packed (m, R·k), (R·k, n)."""
    r, m, k = w0s.shape
    n = h0s.shape[2]
    return (jnp.transpose(w0s, (1, 0, 2)).reshape(m, r * k),
            h0s.reshape(r * k, n))


def unpack_w(wp: jax.Array, r: int) -> jax.Array:
    """Packed (m, R·k) → (R, m, k)."""
    m = wp.shape[0]
    k = wp.shape[1] // r
    return jnp.transpose(wp.reshape(m, r, k), (1, 0, 2))


def _block_sums(x: jax.Array, r: int) -> jax.Array:
    """Sum an (R·k, n)-shaped elementwise product per restart block → (R,)."""
    n = x.shape[1]
    return jnp.sum(x.reshape(r, -1, n), axis=(1, 2))


def _diag_blocks(g: jax.Array, r: int) -> jax.Array:
    """(R·k, R·k) full Gram → (R, k, k) diagonal blocks."""
    k = g.shape[0] // r
    return jnp.einsum("rkrl->rkl", g.reshape(r, k, r, k))


def residual_norms(a: jax.Array, wp: jax.Array, hp: jax.Array, r: int,
                   feature_axis: str | None = None,
                   m_total: int | None = None,
                   sample_axis: str | None = None,
                   n_total: int | None = None) -> jax.Array:
    """Per-restart RMS residual ‖A − WᵣHᵣ‖_F/√(mn) without materializing any
    m×n reconstruction: ‖A−WH‖² = ‖A‖² − 2⟨WᵀA, H⟩ + ⟨WᵀW, HHᵀ⟩, with every
    term read off packed Grams (reference calculateNorm materializes the full
    m×n difference per restart, ``libnmf/calculatenorm.c:44-78``).

    With ``feature_axis``/``sample_axis`` (inside ``shard_map``, A row- and/or
    column-sharded, Wp row-sharded, Hp column-sharded accordingly) the m- and
    n-contracted terms are partial sums reduced with psums;
    ``m_total``/``n_total`` are the unsharded (unpadded) dims for the RMS
    normalizer."""
    m, n = a.shape
    numerh = wp.T @ a  # (R·k, n_local)
    gw_full = wp.T @ wp
    a2 = jnp.sum(a * a)
    if feature_axis is not None:
        if m_total is None:
            raise ValueError(
                "residual_norms with feature_axis needs m_total (the "
                "unsharded row count); the local shard's row count would "
                "silently inflate the RMS by sqrt(#shards)")
        numerh = lax.psum(numerh, feature_axis)
        gw_full = lax.psum(gw_full, feature_axis)
        a2 = lax.psum(a2, feature_axis)
        m = m_total
    gh_full = hp @ hp.T
    cross = _block_sums(numerh * hp, r)
    if sample_axis is not None:
        if n_total is None:
            raise ValueError(
                "residual_norms with sample_axis needs n_total (the "
                "unsharded column count)")
        gh_full = lax.psum(gh_full, sample_axis)
        cross = lax.psum(cross, sample_axis)
        a2 = lax.psum(a2, sample_axis)
        n = n_total
    gw = _diag_blocks(gw_full, r)  # (R, k, k)
    gh = _diag_blocks(gh_full, r)
    quad = jnp.sum(gw * gh, axis=(1, 2))
    sq = jnp.maximum(a2 - 2.0 * cross + quad, 0.0)
    return jnp.sqrt(sq / (m * n))


def residual_norms_direct(a: jax.Array, w: jax.Array, h: jax.Array,
                          chunk: int | None = None,
                          feature_axis: str | None = None,
                          m_total: int | None = None,
                          sample_axis: str | None = None,
                          n_total: int | None = None) -> jax.Array:
    """Per-lane RMS residual ‖A − WᵦHᵦ‖_F/√(mn) from dense (B, m, k) /
    (B, k, n) factor stacks, computed the DIRECT way — fused subtract-square
    reduction over chunks of lanes, never more than ``chunk`` m×n
    reconstructions live at once.

    This is the end-of-solve form: the in-loop Gram-trace identity
    (:func:`residual_norms`) subtracts numbers ~‖A‖²/‖A−WH‖² larger than the
    result, so its relative error grows without bound as convergence
    tightens (at dnorm/‖A‖ ~ √eps the identity returns pure cancellation
    noise, hidden by its clamp). The direct form costs one reconstruction
    per lane — half a mu iteration — and runs once per solve, as the
    reference does in f64 (``libnmf/calculatenorm.c:44-78``). Zero-padded
    trailing k-columns/rows contribute exact zeros. Under
    ``feature_axis``/``sample_axis`` the local square-sums psum over the
    grid axes and the RMS normalizer uses the unsharded dims.

    ``chunk=None`` (the default used by every solver entry point) caps
    the transient at ~80 MB of reconstructions: chunk = 8 at the
    north-star 5000×500 (measured optimal there: 8/16/32/64 →
    73/112/113/112 ms) and proportionally fewer as m·n grows — at
    20000×1000 a fixed chunk of 8 would materialize a ~640 MB (8, m, n)
    scratch per scan step."""
    b, m, _ = w.shape
    n = h.shape[2]
    if chunk is None:
        budget = 80 * 2**20  # bytes of live (chunk, m, n) reconstruction
        chunk = max(1, min(8, budget // (m * n * a.dtype.itemsize)))
    nb = -(-b // chunk)
    pad = nb * chunk - b
    if pad:
        w = jnp.pad(w, ((0, pad), (0, 0), (0, 0)))
        h = jnp.pad(h, ((0, pad), (0, 0), (0, 0)))

    def body(_, wh):
        wc, hc = wh
        d = a[None] - jnp.einsum("cmk,ckn->cmn", wc, hc)
        return _, jnp.sum(d * d, axis=(1, 2))

    _, sq = lax.scan(body, None,
                     (w.reshape(nb, chunk, *w.shape[1:]),
                      h.reshape(nb, chunk, *h.shape[1:])))
    sq = sq.reshape(-1)[:b]
    if feature_axis is not None:
        if m_total is None:
            raise ValueError("residual_norms_direct with feature_axis "
                             "needs m_total (the unsharded row count)")
        sq = lax.psum(sq, feature_axis)
        m = m_total
    if sample_axis is not None:
        if n_total is None:
            raise ValueError("residual_norms_direct with sample_axis "
                             "needs n_total (the unsharded column count)")
        sq = lax.psum(sq, sample_axis)
        n = n_total
    return jnp.sqrt(jnp.maximum(sq, 0.0) / (m * n))


def _labels(hp: jax.Array, r: int) -> jax.Array:
    """(R·k, n) → per-restart argmax labels (R, n)."""
    n = hp.shape[1]
    return jnp.argmax(hp.reshape(r, -1, n), axis=1).astype(jnp.int32)


def flip_budget(class_flip_tol: float, n: int) -> int:
    """The class-stability flip budget ``floor(class_flip_tol · n)``, in
    exact double math. The +eps before flooring matters: 0.3 · 10 is
    2.999... in binary float and a bare ``int()`` would land one flip
    below the documented floor. Single source for the in-executable rule
    below AND the serving layer's host-side computation
    (``exec_cache.run_sweep``), whose cached/uncached stop-decision
    parity depends on the two being identical."""
    return int(class_flip_tol * n + 1e-9)


def batch_convergence(cfg: SolverConfig, it, *, new_classes, delta, n_glob,
                      classes, stable, done, done_iter, stop_reason,
                      mism_reduce=None, flip_floor=None, nonfinite=None):
    """(B,)-batched convergence bookkeeping shared by the packed and
    whole-grid formulations: the noise-tolerant class-stability snapshot
    rule plus the TolX test, with per-lane freeze flags — mirroring
    ``base.check_convergence``'s scalar semantics exactly (see
    ``SolverConfig.class_flip_tol``; reference rule ``nmf_mu.c:253-282``).

    ``new_classes`` (B, n_local) are this check's labels; ``delta`` the
    caller's per-lane maxchange ratio, precomputed because its reductions
    are layout- and sharding-specific (or None when ``use_tol_checks`` is
    off); ``mism_reduce`` psums label mismatches when labels are
    column-sharded. ``flip_floor`` overrides the ``floor(class_flip_tol ·
    n_glob)`` flip budget with a precomputed (possibly traced) i32 scalar
    — the shape-bucketed executables compute it host-side from the TRUE
    sample count in exact double math, since their static n is the padded
    bucket width and a traced f32 ``floor`` would round differently.
    ``nonfinite`` (or None): the caller's per-lane numeric-quarantine
    verdict — a flagged lane stops FIRST with ``NUMERIC_FAULT``, before
    the class/TolX tests can read its NaN-derived labels or deltas.
    Returns the five updated bookkeeping arrays."""
    is_check = (it > 1) & (it % cfg.check_every == 0)
    active = is_check & (~done)
    done_in = done
    reason = stop_reason

    if nonfinite is not None:
        bad = active & nonfinite
        done = done | bad
        active = active & ~bad
        reason = jnp.where(bad, jnp.int32(base.StopReason.NUMERIC_FAULT),
                           reason)

    if cfg.use_class_stop:
        flip_tol = (flip_budget(cfg.class_flip_tol, n_glob)
                    if flip_floor is None else flip_floor)
        mism = jnp.sum((new_classes != classes).astype(jnp.int32), axis=1)
        if mism_reduce is not None:
            mism = mism_reduce(mism)
        same = mism <= flip_tol
        stable = jnp.where(active, jnp.where(same, stable + 1, 0), stable)
        reset = active & ~same
        classes = jnp.where(reset[:, None], new_classes, classes)
        hit = active & (stable >= cfg.stable_checks)
        done = done | hit
        reason = jnp.where(hit, jnp.int32(base.StopReason.CLASS_STABLE),
                           reason)

    if cfg.use_tol_checks:
        hit = active & (delta < cfg.tol_x) & ~done
        done = done | hit
        reason = jnp.where(hit, jnp.int32(base.StopReason.TOL_X), reason)

    newly = done & ~done_in
    done_iter = jnp.where(newly, it, done_iter)
    return classes, stable, done, done_iter, reason


def _step(a, bd, state: PackedState, cfg: SolverConfig, r: int,
          check: bool, use_pallas: bool = False, block_m: int = 512,
          interpret: bool = False,
          feature_axis: str | None = None,
          sample_axis: str | None = None,
          n_total: int | None = None) -> PackedState:
    m, n = a.shape
    k = state.hp.shape[0] // r
    wp0, hp0 = state.wp, state.hp
    it = state.iteration + 1

    if use_pallas:
        # fused kernels (nmfx.ops.pallas_mu): numerators, Grams, and
        # epilogues never leave VMEM; only the updated factors hit HBM
        from nmfx.ops.pallas_mu import fused_h_update, fused_w_update

        hp = fused_h_update(
            a, wp0, hp0, k=k, block_m=block_m, eps=cfg.div_eps,
            zero_threshold=cfg.zero_threshold,
            matmul_precision=cfg.matmul_precision, interpret=interpret)
        gh = bd_select(hp @ hp.T, bd)  # tiny; stays in XLA
        wp = fused_w_update(
            a, wp0, hp, gh, block_m=block_m, eps=cfg.div_eps,
            zero_threshold=cfg.zero_threshold,
            matmul_precision=cfg.matmul_precision, interpret=interpret)
    elif a.dtype == jnp.bfloat16:
        # bandwidth-lean bf16 path (mu_packed pre-truncated A): under
        # matmul_precision="bfloat16" the MXU rounds every GEMM operand to
        # bf16 anyway, so feeding explicitly-truncated operands with f32
        # accumulation is bit-identical to the f32-operand GEMMs below while
        # halving the HBM bytes read for A (the largest array, reread twice
        # per iteration) and the factor matrices
        f32 = hp0.dtype
        wb = wp0.astype(jnp.bfloat16)
        numerh = jnp.matmul(wb.T, a, preferred_element_type=f32)
        gw = jnp.matmul(wb.T, wb, preferred_element_type=f32)
        if feature_axis is not None:
            # A/Wp are row shards: the m-contracted terms are partial sums
            numerh = lax.psum(numerh, feature_axis)
            gw = lax.psum(gw, feature_axis)
        denomh = bd_select(gw, bd) @ hp0
        hp = _mu_update(hp0, numerh, denomh, cfg)

        hb = hp.astype(jnp.bfloat16)
        gh = jnp.matmul(hb, hb.T, preferred_element_type=f32)
        numerw = jnp.matmul(a, hb.T, preferred_element_type=f32)
        if sample_axis is not None:
            # A/Hp are column shards: the n-contracted terms are partials
            gh = lax.psum(gh, sample_axis)
            numerw = lax.psum(numerw, sample_axis)
        denomw = wp0 @ bd_select(gh, bd)
        wp = _mu_update(wp0, numerw, denomw, cfg)
    else:
        # H update — numerator GEMM plus the full W-Gram (cross-restart
        # blocks masked off; see module docstring for the FLOP trade)
        numerh = wp0.T @ a  # (R·k, n)
        gw = wp0.T @ wp0  # (R·k, R·k)
        if feature_axis is not None:
            numerh = lax.psum(numerh, feature_axis)
            gw = lax.psum(gw, feature_axis)
        denomh = bd_select(gw, bd) @ hp0
        hp = _mu_update(hp0, numerh, denomh, cfg)

        # W update with the fresh H (reference order, nmf_mu.c:198-216)
        gh = hp @ hp.T
        numerw = a @ hp.T
        if sample_axis is not None:
            gh = lax.psum(gh, sample_axis)
            numerw = lax.psum(numerw, sample_axis)
        denomw = wp0 @ bd_select(gh, bd)
        wp = _mu_update(wp0, numerw, denomw, cfg)

    # numeric quarantine containment (nonfinite_guard): the packed
    # layout shares Grams across lanes, so a lane that diverges must be
    # ROLLED BACK to its last finite iterate the same iteration it goes
    # non-finite — by induction the carry (and hence every shared-GEMM
    # operand) stays finite, and bd_select keeps the one remaining
    # cross-lane term (the masked Gram) NaN-proof. The sticky flag
    # stops the lane with NUMERIC_FAULT at its next check.
    bad = state.nonfinite
    if cfg.nonfinite_guard:
        new_bad = ~(_lanes_finite(wp.reshape(-1, r, k), (0, 2),
                                  feature_axis)
                    & _lanes_finite(hp.reshape(r, k, -1), (1, 2),
                                    sample_axis))
        bad = new_bad if bad is None else bad | new_bad

    # freeze converged restarts (the vmapped while_loop does this masking
    # implicitly; here the restart axis lives inside one GEMM, so
    # explicitly); quarantined lanes freeze the same way
    frozen = state.done if bad is None else state.done | bad
    frozen_col = jnp.repeat(frozen, k)  # (R·k,)
    hp = jnp.where(frozen_col[:, None], hp0, hp)
    wp = jnp.where(frozen_col[None, :], wp0, wp)

    state = state._replace(wp=wp, hp=hp, wp_prev=wp0, hp_prev=hp0,
                           iteration=it, nonfinite=bad)
    if not check:
        return state
    return _check(state, cfg, r, feature_axis, sample_axis, n_total)


def _check(state: PackedState, cfg: SolverConfig, r: int,
           feature_axis: str | None = None,
           sample_axis: str | None = None,
           n_total: int | None = None) -> PackedState:
    """Per-restart convergence tests, mirroring base.check_convergence for
    the mu solver (class stability first, then TolX) with (R,)-shaped
    bookkeeping instead of vmapped scalars."""
    it = state.iteration
    k = state.hp.shape[0] // r

    # noise-tolerant snapshot rule (see base.check_convergence and
    # SolverConfig.class_flip_tol): mismatches are counted against a held
    # reference labeling that only updates on reset, so bounded label
    # oscillation passes while genuine drift accumulates and resets.
    # flip_tol=0 is bit-identical to the reference's consecutive-check
    # rule (nmf_mu.c:253-282). Bookkeeping shared with the whole-grid
    # formulation via batch_convergence; only the labels and the maxchange
    # reductions are packed-layout-specific.
    new_classes = _labels(state.hp, r)
    if sample_axis is not None:
        if n_total is None:
            raise ValueError(
                "class-stability check with sample_axis needs n_total "
                "(the unsharded column count); the local shard width "
                "would make the flip tolerance ~#shards too strict")
        n_glob = n_total
        # labels are column shards: the mismatch count is a global sum
        mism_reduce = partial(lax.psum, axis_name=sample_axis)
    else:
        n_glob = state.hp.shape[1]
        mism_reduce = None

    delta = None
    if cfg.use_tol_checks:
        sqrteps = jnp.sqrt(jnp.finfo(state.wp.dtype).eps)

        def _delta(cur, prev, axes, shape):
            diff = jnp.max(jnp.abs(cur - prev).reshape(shape), axis=axes)
            ref = jnp.max(jnp.abs(prev).reshape(shape), axis=axes)
            return diff / (sqrteps + ref)

        def _delta_sharded(cur, prev, axes, shape, mesh_axis):
            # sharded maxchange is a ratio of *global* maxima: pmax the
            # ratio's ingredients before dividing
            diff = lax.pmax(jnp.max(jnp.abs(cur - prev).reshape(shape),
                                    axis=axes), mesh_axis)
            ref = lax.pmax(jnp.max(jnp.abs(prev).reshape(shape), axis=axes),
                           mesh_axis)
            return diff / (sqrteps + ref)

        m = state.wp.shape[0]
        n = state.hp.shape[1]
        if feature_axis is None:
            dw = _delta(state.wp, state.wp_prev, (0, 2), (m, r, k))
        else:
            dw = _delta_sharded(state.wp, state.wp_prev, (0, 2), (m, r, k),
                                feature_axis)
        if sample_axis is None:
            dh = _delta(state.hp, state.hp_prev, (1, 2), (r, k, n))
        else:
            dh = _delta_sharded(state.hp, state.hp_prev, (1, 2), (r, k, n),
                                sample_axis)
        delta = jnp.maximum(dw, dh)  # (R,)

    classes, stable, done, done_iter, reason = batch_convergence(
        cfg, it, new_classes=new_classes, delta=delta, n_glob=n_glob,
        classes=state.classes, stable=state.stable, done=state.done,
        done_iter=state.done_iter, stop_reason=state.stop_reason,
        mism_reduce=mism_reduce, nonfinite=state.nonfinite)
    return state._replace(classes=classes, stable=stable, done=done,
                          done_iter=done_iter, stop_reason=reason)


@partial(jax.jit, static_argnames=("cfg", "varying_axes", "feature_axis",
                                   "m_total", "sample_axis", "n_total"))
def mu_packed(a: jax.Array, w0s: jax.Array, h0s: jax.Array,
              cfg: SolverConfig = SolverConfig(),
              varying_axes: tuple[str, ...] = (),
              feature_axis: str | None = None,
              m_total: int | None = None,
              sample_axis: str | None = None,
              n_total: int | None = None) -> PackedMUResult:
    """Solve the whole restart batch with packed GEMM iterations.

    Semantically equivalent to ``vmap(solve)`` with ``algorithm='mu'``
    (same update rule, same convergence tests, same freeze-on-convergence
    behavior); restructured for the MXU. Jittable; used by the sweep layer
    for mu batches (``SolverConfig.backend``).

    ``varying_axes``: when called inside ``shard_map`` over those mesh axes,
    the constant-initialized carry components (counters, done masks) must be
    lifted to device-varying so the while_loop carry types match the body's
    outputs, which inherit the varying tag from the sharded factors.

    ``feature_axis``: name of a mesh axis over which A and Wp are
    *row*-sharded (this workload's tensor-parallel dimension — SURVEY.md §5
    "feature-dimension sharding"). The two m-contracted terms of the H
    update (WpᵀA and WpᵀWp) become one fused ``psum`` pair per iteration
    over that axis; the entire W half-step stays device-local. ``m_total``
    is the unsharded row count (for RMS normalization).

    ``sample_axis``: the mirror image for A's columns and Hp (this
    workload's sequence/context-parallel dimension): the two n-contracted
    terms of the W update (AHpᵀ and HpHpᵀ) psum over it while the H
    half-step stays local. Both axes compose — a 2-D (feature × sample)
    shard of A is SUMMA-style parallelism for a single huge factorization,
    and either composes with the restart (data-parallel) axis.
    """
    if cfg.algorithm != "mu":
        raise ValueError("mu_packed only implements the mu algorithm")
    if (feature_axis is not None or sample_axis is not None) \
            and cfg.backend == "pallas":
        raise ValueError("feature/sample-axis sharding is not supported "
                         "with the pallas backend (the fused kernels have "
                         "no collective stage); use backend='packed'")
    dtype = jnp.dtype(cfg.dtype)
    a = jnp.asarray(a, dtype)
    w0s = jnp.asarray(w0s, dtype)
    h0s = jnp.asarray(h0s, dtype)
    r, m, k = w0s.shape
    n = h0s.shape[2]
    a_true = a  # unpadded, for the final residuals
    use_pallas = cfg.backend == "pallas"
    block_m = 512
    interpret = False
    if use_pallas:
        # the fused kernels stream A/Wp in m-tiles; pad m up to the tile
        # size (zero rows are invariant under the MU epilogue's exact-zero
        # short-circuit and contribute nothing to numerators or Grams).
        # Mosaic masks the unaligned n and R·k dims itself. The tile count
        # is fixed first so block_m shrinks to fit m (padding stays < one
        # sublane row per tile instead of up to a whole 512-row tile).
        ceil_div = lambda x, d: -(-x // d)
        tiles = ceil_div(m, 512)
        block_m = ceil_div(ceil_div(m, tiles), 8) * 8
        m_pad = tiles * block_m
        if m_pad != m:
            a = jnp.pad(a, ((0, m_pad - m), (0, 0)))
        interpret = jax.default_backend() != "tpu"
    with base.matmul_precision_ctx(cfg.matmul_precision):
        wp, hp = pack(w0s, h0s)
        if use_pallas and a.shape[0] != m:
            wp = jnp.pad(wp, ((0, a.shape[0] - m), (0, 0)))
        bd = block_diag_mask(r, k, dtype)
        def vary(x):
            for ax in varying_axes:
                x = pcast(x, ax, to="varying")
            return x

        nonfinite0 = None
        if cfg.nonfinite_guard:
            # quarantine induction base: a lane whose INITIAL factors are
            # already non-finite (an injected fault, a corrupt warm
            # start) is zeroed at pack time — zero factors are inert
            # under MU and contribute exact zeros to the shared Grams,
            # the pad-lane invariant — and flagged sticky, so the next
            # check stops it with NUMERIC_FAULT
            bad0 = ~(_lanes_finite(wp.reshape(-1, r, k), (0, 2),
                                   feature_axis)
                     & _lanes_finite(hp.reshape(r, k, n), (1, 2),
                                     sample_axis))
            zero_col = jnp.repeat(bad0, k)
            wp = jnp.where(zero_col[None, :], 0.0, wp)
            hp = jnp.where(zero_col[:, None], 0.0, hp)
            nonfinite0 = vary(bad0)

        state0 = PackedState(
            wp=wp, hp=hp, wp_prev=wp, hp_prev=hp,
            iteration=jnp.zeros((), jnp.int32),
            classes=vary(jnp.full((r, n), -1, jnp.int32)),
            stable=vary(jnp.zeros((r,), jnp.int32)),
            done=vary(jnp.zeros((r,), bool)),
            done_iter=vary(jnp.zeros((r,), jnp.int32)),
            stop_reason=vary(jnp.full((r,), base.StopReason.MAX_ITER,
                                      jnp.int32)),
            nonfinite=nonfinite0,
        )
        a_loop = a
        if (not use_pallas and cfg.matmul_precision == "bfloat16"
                and dtype == jnp.float32 and jax.default_backend() == "tpu"):
            # one-time truncation: every loop GEMM reads A in the exact bf16
            # form the MXU would round it to anyway (see _step's bf16 branch);
            # the full-precision a_true still feeds the final residuals.
            # TPU-only: other backends ignore the bfloat16 precision hint and
            # run full-f32 GEMMs, so truncating there would change results
            a_loop = a.astype(jnp.bfloat16)
        step = partial(_step, a_loop, bd, use_pallas=use_pallas,
                       block_m=block_m, interpret=interpret,
                       feature_axis=feature_axis, sample_axis=sample_axis,
                       n_total=n_total)

        # check_block batches N check blocks per while-loop trip ("auto"
        # resolves to 1 here: the fixed-batch driver's per-trip overhead
        # is one cond evaluation against a full-width batch iteration).
        # Checks still run at every check_every boundary — between the
        # unrolled sub-blocks — so stop decisions are EXACT; converged
        # restarts freeze before the next sub-block as always, and the
        # loop merely evaluates its condition (and any residual
        # done-lane masking work) once per N blocks.
        ncheck = 1 if cfg.check_block == "auto" else int(cfg.check_block)

        def cond(s: PackedState):
            return jnp.any(~s.done) & (
                s.iteration + cfg.check_every * ncheck <= cfg.max_iter)

        def body(s: PackedState):
            for _ in range(ncheck):
                for i in range(cfg.check_every):
                    s = step(s, cfg, r, check=(i == cfg.check_every - 1))
            return s

        final = lax.while_loop(cond, body, state0)

        def tail_cond(s: PackedState):
            return jnp.any(~s.done) & (s.iteration < cfg.max_iter)

        final = lax.while_loop(tail_cond,
                               lambda s: step(s, cfg, r, check=True), final)

        iterations = jnp.where(final.done, final.done_iter, final.iteration)
        wp_final = final.wp[:m]  # drop pallas m-padding rows, if any
        # final residuals the DIRECT way (reference f64 calculateNorm,
        # libnmf/calculatenorm.c:44-78): the Gram-trace identity loses all
        # precision to cancellation at tight convergence, and this number
        # picks the best restart and lands in rank_metrics.txt. One
        # reconstruction per restart, chunked — half an iteration's FLOPs,
        # once per solve.
        dnorm = residual_norms_direct(
            a_true, unpack_w(wp_final, r), final.hp.reshape(r, k, n),
            feature_axis=feature_axis, m_total=m_total,
            sample_axis=sample_axis, n_total=n_total)
    return PackedMUResult(wp=wp_final, hp=final.hp,
                          iterations=iterations.astype(jnp.int32),
                          dnorm=dnorm, stop_reason=final.stop_reason)
