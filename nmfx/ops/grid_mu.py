"""Whole-grid MU: every (k × restart) cell of the sweep in ONE solve.

The reference expands the FULL (k × restart) grid into one job array and
runs all |k|·R jobs concurrently with shuffled chunking (reference
``nmf.r:64-68``, ``nmf.r:111``). The per-rank packed path
(``nmfx.ops.packed_mu``) restored within-rank concurrency but still looped
ranks sequentially: each rank was its own jit compile (~10 s × |k| ranks of
cold start against a ~2 s execute) and at small k the chip ran 100-column
GEMMs while the grid as a whole holds Σ R·k columns. This module lays the
ENTIRE grid out as one dense zero-padded lane batch

    W = (B, m, k_max)      B = |ks|·R lanes, rank-major
    H = (B, k_max, n)

so each iteration's two data contractions run over every grid cell at once:

    numerh = einsum("bmk,mn->bkn", W, A)    — ONE (B·k_max, m)@(m, n) GEMM
    numerw = einsum("mn,bkn->bmk", A, H)    — ONE (m, n)@(n, B·k_max) GEMM

(A carries no batch dimension, so XLA folds the lane axis into the GEMM's
free dimension — B·k_max MXU-dense columns where the sequential path had
R·k.) The k×k Grams and their products stay exact batched (B, k, k) ops.

Why dense padding instead of generalizing ``packed_mu``'s block-diagonal
mask to variable-k blocks: the masked-Gram trick costs two (P, P) products
per iteration, affordable while P = R·k stays small against m and n — but
the whole grid's P = R·Σk (2700 at the north-star sweep) EXCEEDS n = 500,
so the masked products would dominate the useful work ~5×. Dense batching
computes only the true per-lane Grams and pays instead a
|ks|·k_max/Σk FLOP overhead (≈1.67× at k=2..10) on the two data GEMMs —
strictly cheaper at grid scale, and the padding is exactly invariant: a
zero column of W / zero row of H has zero numerator, and the MU epilogue's
exact-zero short-circuit (``solvers/mu.py``) keeps it zero forever, the
same invariant the feature/sample grid sharding already relies on
(``sweep.py``). Labels, Grams, residuals, and maxchange all ignore padding
by construction (argmax never picks an all-zero row over a positive one;
zero entries contribute nothing to products, sums, or |diffs|).

Convergence bookkeeping is per-lane with freeze masks, shared with the
packed path (``packed_mu.batch_convergence``) — identical semantics to the
reference's class-stability rule with the documented TolX addition. The
whole sweep is ONE jit compile and ONE ``lax.while_loop``; a converged
lane's factors freeze while the batch runs on.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from nmfx._compat import pcast
from nmfx.config import SolverConfig
from nmfx.ops.packed_mu import batch_convergence, residual_norms_direct
from nmfx.solvers import base
from nmfx.solvers.mu import _mu_update


class GridState(NamedTuple):
    w: jax.Array  # (B, m, k_max)
    h: jax.Array  # (B, k_max, n)
    w_prev: jax.Array
    h_prev: jax.Array
    iteration: jax.Array  # () i32 — shared batch clock
    classes: jax.Array  # (B, n) i32
    stable: jax.Array  # (B,) i32
    done: jax.Array  # (B,) bool
    done_iter: jax.Array  # (B,) i32
    stop_reason: jax.Array  # (B,) i32
    dnorm: jax.Array  # (B,) residual at last check (TolFun family only)


class GridMUResult(NamedTuple):
    w: jax.Array  # (B, m, k_max) final factors, zero-padded past each k
    h: jax.Array  # (B, k_max, n)
    iterations: jax.Array  # (B,) i32
    dnorm: jax.Array  # (B,) final RMS residual per lane (direct form)
    stop_reason: jax.Array  # (B,) i32 StopReason


def _labels(h: jax.Array) -> jax.Array:
    """(B, k_max, n) → per-lane argmax labels (B, n). Padded rows are exact
    zeros and loadings are non-negative, so they never beat a positive true
    loading; an all-zero column labels 0 under any k."""
    return jnp.argmax(h, axis=1).astype(jnp.int32)


def mu_block(a, wp, hp, done_mask, cfg: SolverConfig):
    """ONE dense-batched MU iteration: the six reference dgemms
    (nmf_mu.c:174-216) as batched einsums whose lane axis folds into the
    data GEMMs' free dimension; lanes under ``done_mask`` freeze (the
    vmapped while_loop masks implicitly; here the lane axis lives inside
    shared GEMMs, so explicitly). Shared by the fixed-batch (mu_grid) and
    slot-scheduled (sched_mu) whole-grid drivers."""
    if a.dtype == jnp.bfloat16:
        # bandwidth-lean bf16 operand path (A pre-truncated by the caller):
        # bit-identical to the f32-operand GEMMs under
        # matmul_precision="bfloat16" (the MXU rounds operands to bf16
        # either way) while halving the HBM bytes of the big reads — see
        # packed_mu._step's identical branch for the measurement
        f32 = hp.dtype
        wb = wp.astype(jnp.bfloat16)
        numerh = jnp.einsum("bmk,mn->bkn", wb, a,
                            preferred_element_type=f32)
        gw = jnp.einsum("bmk,bml->bkl", wb, wb,
                        preferred_element_type=f32)
        denomh = jnp.einsum("bkl,bln->bkn", gw, hp)
        h = _mu_update(hp, numerh, denomh, cfg)

        hb = h.astype(jnp.bfloat16)
        gh = jnp.einsum("bkn,bln->bkl", hb, hb,
                        preferred_element_type=f32)
        numerw = jnp.einsum("mn,bkn->bmk", a, hb,
                            preferred_element_type=f32)
        denomw = jnp.einsum("bmk,bkl->bml", wp, gh)
        w = _mu_update(wp, numerw, denomw, cfg)
    else:
        # H update (reference nmf_mu.c:174-191, batched over the whole grid)
        numerh = jnp.einsum("bmk,mn->bkn", wp, a)
        gw = jnp.einsum("bmk,bml->bkl", wp, wp)
        denomh = jnp.einsum("bkl,bln->bkn", gw, hp)
        h = _mu_update(hp, numerh, denomh, cfg)

        # W update with the fresh H (reference order, nmf_mu.c:198-216)
        gh = jnp.einsum("bkn,bln->bkl", h, h)
        numerw = jnp.einsum("mn,bkn->bmk", a, h)
        denomw = jnp.einsum("bmk,bkl->bml", wp, gh)
        w = _mu_update(wp, numerw, denomw, cfg)

    frozen = done_mask[:, None, None]
    return jnp.where(frozen, wp, w), jnp.where(frozen, hp, h)


def hals_block(a, wp, hp, done_mask, cfg: SolverConfig):
    """ONE dense-batched HALS iteration (Cichocki & Phan 2009 — see
    solvers/hals.py for the per-restart form and reference relationship):
    the two shared GEMMs batch over every lane exactly like mu_block; the
    k coordinate minimizations unroll at trace time as (B, n)/(B, m) VPU
    AXPYs. Zero-padded components are invariant: their numerators are zero
    (zero W column / H row), the eps-guarded diagonal keeps the division
    finite, and real components never see them (their Gram cross-terms are
    zero)."""
    eps = cfg.div_eps
    k_max = wp.shape[2]
    if a.dtype == jnp.bfloat16:
        f32 = hp.dtype
        wb = wp.astype(jnp.bfloat16)
        wta = jnp.einsum("bmk,mn->bkn", wb, a, preferred_element_type=f32)
        wtw = jnp.einsum("bmk,bml->bkl", wb, wb, preferred_element_type=f32)
    else:
        wta = jnp.einsum("bmk,mn->bkn", wp, a)
        wtw = jnp.einsum("bmk,bml->bkl", wp, wp)
    h = hp
    for jj in range(k_max):
        num = wta[:, jj, :] - jnp.einsum("bl,bln->bn", wtw[:, jj, :], h)
        hj = h[:, jj, :] + num / (wtw[:, jj, jj, None] + eps)
        h = h.at[:, jj, :].set(base.clamp(hj, cfg.zero_threshold))
    if a.dtype == jnp.bfloat16:
        hb = h.astype(jnp.bfloat16)
        aht = jnp.einsum("mn,bkn->bmk", a, hb, preferred_element_type=f32)
        hht = jnp.einsum("bkn,bln->bkl", hb, hb, preferred_element_type=f32)
    else:
        aht = jnp.einsum("mn,bkn->bmk", a, h)
        hht = jnp.einsum("bkn,bln->bkl", h, h)
    w = wp
    for jj in range(k_max):
        num = aht[:, :, jj] - jnp.einsum("bmk,bk->bm", w, hht[:, :, jj])
        wj = w[:, :, jj] + num / (hht[:, jj, jj, None] + eps)
        w = w.at[:, :, jj].set(base.clamp(wj, cfg.zero_threshold))
    frozen = done_mask[:, None, None]
    return jnp.where(frozen, wp, w), jnp.where(frozen, hp, h)


def _batched_gram_solve(gram, rhs):
    """(B, k, k) @ x = (B, k, rhs_cols) via the same trace-scaled
    Tikhonov Cholesky as the per-restart form (base.solve_gram_reg),
    vmapped. Zero-padded components solve to exact zeros: their Gram
    rows/cols are zero, the jitter puts λ on their diagonal, and their
    right-hand-side rows are zero — x_pad = 0/λ = 0. (λ's trace/k uses
    k_max here vs the lane's true k per-restart: a ~10·eps-scale
    difference, within the float tolerance any engine change carries.)"""
    return jax.vmap(base.solve_gram_reg)(gram, rhs)


def neals_block(a, wp, hp, done_mask, cfg: SolverConfig):
    """ONE dense-batched normal-equation ALS iteration (see solvers/
    neals.py for the per-restart form; reference nmf_neals.c:200-306):
    H = max(G_w \\ WᵀA, 0), W = max((G_h \\ HAᵀ)ᵀ, 0) with the shared
    jittered-Cholesky Gram solve (hp feeds only the frozen-lane
    passthrough: ALS re-derives H from W alone). Both Grams batch over
    lanes; the k×k solves are tiny and vmap cleanly. Zero padding is
    invariant (see _batched_gram_solve)."""
    f32 = wp.dtype
    if a.dtype == jnp.bfloat16:
        wb = wp.astype(jnp.bfloat16)
        gw = jnp.einsum("bmk,bml->bkl", wb, wb, preferred_element_type=f32)
        wta = jnp.einsum("bmk,mn->bkn", wb, a, preferred_element_type=f32)
    else:
        gw = jnp.einsum("bmk,bml->bkl", wp, wp)
        wta = jnp.einsum("bmk,mn->bkn", wp, a)
    h = base.clamp(_batched_gram_solve(gw, wta), cfg.zero_threshold)
    if a.dtype == jnp.bfloat16:
        hb = h.astype(jnp.bfloat16)
        gh = jnp.einsum("bkn,bln->bkl", hb, hb, preferred_element_type=f32)
        hat = jnp.einsum("bkn,mn->bkm", hb, a, preferred_element_type=f32)
    else:
        gh = jnp.einsum("bkn,bln->bkl", h, h)
        hat = jnp.einsum("bkn,mn->bkm", h, a)
    w = base.clamp(jnp.transpose(_batched_gram_solve(gh, hat), (0, 2, 1)),
                   cfg.zero_threshold)
    frozen = done_mask[:, None, None]
    return jnp.where(frozen, wp, w), jnp.where(frozen, hp, h)


def als_block(a, wp, hp, done_mask, cfg: SolverConfig):
    """ONE dense-batched QR-free ALS iteration (see solvers/als.py for
    the per-restart form; reference nmf_als.c:209-360): each half-step
    is the minimum-norm least-squares solve of the OTHER factor, batched
    over lanes with ``a`` broadcast, then clamped — the same
    ``jnp.linalg.lstsq`` the per-restart engine uses, so trajectories
    match it to float tolerance (hp feeds only the frozen-lane
    passthrough: ALS re-derives H from W alone). Zero padding is
    EXACTLY invariant under the min-norm solution: a zero W column
    contributes a zero singular direction, and minimum-norm puts zero
    coefficient on it, so padded H rows stay zero (and symmetrically for
    W's half-step) — rank-deficiency is the lstsq pseudo-inverse's
    well-defined case, not a fallback path (the reason the per-restart
    form chose SVD lstsq over the reference's pivoted QR). bf16
    A-streaming is sound here for the same reason as the Gram blocks:
    every consumption of A inside lstsq is a GEMM against the SVD bases
    (x = V·S⁻¹·Uᵀ·A), which the MXU rounds to bf16 under that precision
    anyway — the SVD itself factors only the batched factor matrices,
    never A."""
    from nmfx.solvers.als import lstsq_min_norm

    h = base.clamp(jax.vmap(lambda w: lstsq_min_norm(w, a))(wp),
                   cfg.zero_threshold)
    wt = jax.vmap(lambda hh: lstsq_min_norm(hh.T, a.T))(h)
    w = base.clamp(jnp.transpose(wt, (0, 2, 1)), cfg.zero_threshold)
    frozen = done_mask[:, None, None]
    return jnp.where(frozen, wp, w), jnp.where(frozen, hp, h)


def snmf_block(a, wp, hp, done_mask, cfg: SolverConfig, eta=None,
               pad_live=None):
    """ONE dense-batched sparse-NMF iteration (Kim & Park 2007; see
    solvers/snmf.py): the H-solve's L1 surrogate ``beta·ones`` couples
    components, so it is masked to each lane's TRUE-k components
    (``pad_live``) — zero-padded lanes of the mixed-rank grid would
    otherwise leak the coupling into real components. The mask is BY
    PADDING, not by nonzero-W: a component whose W column genuinely dies
    mid-solve stays in the coupling exactly as the per-restart form
    keeps its zero row in the k×k ones matrix — sparse NMF actively
    kills components at k above the data's structure, and dropping them
    changes the LIVE components' solve (round-5 measurement: a
    nonzero-W mask diverged to max|ΔC|=1.0 / mean|ΔC|≈0.3 from the
    vmapped engine once deaths began; the padding mask restores exact
    stop/label parity — tests/test_grid_exec.py dead-component test).
    The W-solve's ridge is diagonal and needs no mask.

    ``eta``: the Kim & Park ``max(A)²`` ridge, precomputed ONCE by the
    drivers from the FULL-PRECISION A (``make_block``) — computing it
    here from ``a`` would use the bf16-truncated loop matrix under that
    precision and re-reduce O(mn) every iteration. ``pad_live``:
    (B, k_max) bool, True on each lane's true-k columns, resolved by the
    DRIVERS from the initial factors (every true column of W0|H0 is
    nonzero at init; death keeps pad_live True, padding never does)."""
    f32 = wp.dtype
    if eta is None or pad_live is None:
        # a direct BLOCKS["snmf"] call would be tempted to derive eta
        # from `a` (under bf16 streaming: the TRUNCATED loop operand)
        # and pad_live from the CURRENT factors (where death is
        # indistinguishable from padding) — the exact hazards the
        # docstring describes. Fail fast instead of silently drifting
        # from the per-restart form.
        raise ValueError("snmf_block requires eta and pad_live resolved "
                         "by the driver (make_block(cfg, a_full) + the "
                         "initial-factor padding mask)")
    beta = jnp.asarray(cfg.sparsity_beta, f32)
    k_max = wp.shape[2]
    live = pad_live  # (B, k_max)
    ones_mask = (live[:, :, None] & live[:, None, :]).astype(f32)
    if a.dtype == jnp.bfloat16:
        wb = wp.astype(jnp.bfloat16)
        gw = jnp.einsum("bmk,bml->bkl", wb, wb, preferred_element_type=f32)
        wta = jnp.einsum("bmk,mn->bkn", wb, a, preferred_element_type=f32)
    else:
        gw = jnp.einsum("bmk,bml->bkl", wp, wp)
        wta = jnp.einsum("bmk,mn->bkn", wp, a)
    h = base.clamp(_batched_gram_solve(gw + beta * ones_mask, wta),
                   cfg.zero_threshold)
    if a.dtype == jnp.bfloat16:
        hb = h.astype(jnp.bfloat16)
        gh = jnp.einsum("bkn,bln->bkl", hb, hb, preferred_element_type=f32)
        hat = jnp.einsum("bkn,mn->bkm", hb, a, preferred_element_type=f32)
    else:
        gh = jnp.einsum("bkn,bln->bkl", h, h)
        hat = jnp.einsum("bkn,mn->bkm", h, a)
    eye = jnp.eye(k_max, dtype=f32)
    w = base.clamp(
        jnp.transpose(_batched_gram_solve(gh + eta * eye, hat), (0, 2, 1)),
        cfg.zero_threshold)
    frozen = done_mask[:, None, None]
    return jnp.where(frozen, wp, w), jnp.where(frozen, hp, h)


def kl_block(a, wp, hp, done_mask, cfg: SolverConfig):
    """ONE dense-batched KL-divergence iteration (Brunet rule; see
    solvers/kl.py): each lane materializes its m×n quotient
    A ⊘ (WH) — the whole block holds a (B, m, n) intermediate, so under
    the slot scheduler ``grid_slots`` directly bounds kl's working set
    (B = slots), playing the role ``restart_chunk`` plays for the
    vmapped driver. Zero padding is invariant: a padded component's
    numerator contraction and column/row sum are both zero, so its
    update is 0·x/(0+eps) = 0."""
    eps = cfg.div_eps
    # NOTE: unlike the other blocks, kl receives FULL-PRECISION A by
    # default even under matmul_precision="bfloat16"
    # (sched_mu._streams_bf16_a excludes kl unless
    # cfg.experimental.kl_bf16_quotient opts in): A feeds the
    # elementwise quotient,
    # where bf16 truncation is a real input perturbation, not the MXU's
    # own operand rounding (the division below promotes a bf16 A to f32
    # arithmetic either way). The GEMMs still run at bf16 MXU precision
    # via the surrounding matmul_precision_ctx, matching the vmapped
    # engine.
    wh = jnp.einsum("bmk,bkn->bmn", wp, hp)
    q = a[None] / (wh + eps)
    numer = jnp.einsum("bmk,bmn->bkn", wp, q)
    h = hp * numer / (jnp.sum(wp, axis=1)[:, :, None] + eps)
    h = base.clamp(h, cfg.zero_threshold)
    wh = jnp.einsum("bmk,bkn->bmn", wp, h)
    q = a[None] / (wh + eps)
    numer = jnp.einsum("bmn,bkn->bmk", q, h)
    w = wp * numer / (jnp.sum(h, axis=2)[:, None, :] + eps)
    w = base.clamp(w, cfg.zero_threshold)
    frozen = done_mask[:, None, None]
    return jnp.where(frozen, wp, w), jnp.where(frozen, hp, h)


#: dense-batched iteration blocks by algorithm; whether the algorithm's
#: convergence uses the TolFun residual-decrease test; and whether it
#: uses the class-stability stop — matching each solver's per-restart
#: check_convergence flags (mu/kl = class+TolX; hals/snmf =
#: class+TolX+TolFun; neals = TolX+TolFun only, solvers/*.py)
BLOCKS = {"mu": mu_block, "hals": hals_block, "neals": neals_block,
          "als": als_block, "snmf": snmf_block, "kl": kl_block}
USES_TOLFUN = {"mu": False, "hals": True, "neals": True, "als": True,
               "snmf": True, "kl": False}
USES_CLASS = {"mu": True, "hals": True, "neals": False, "als": False,
              "snmf": True, "kl": True}


def conv_cfg(cfg: SolverConfig) -> SolverConfig:
    """Normalize the config for the batched drivers' convergence path:
    algorithms whose per-restart form never uses the class-stability stop
    (neals) must not gain it from the shared batch_convergence, which
    keys only on cfg.use_class_stop."""
    if cfg.use_class_stop and not USES_CLASS[cfg.algorithm]:
        import dataclasses
        return dataclasses.replace(cfg, use_class_stop=False)
    return cfg


def pad_live_mask(w0, h0, job_ks=None):
    """(B, k_max) bool — True on each lane's TRUE-k components, the
    single source of the snmf beta-coupling mask (see ``snmf_block``).

    With ``job_ks`` (the per-lane true ranks, known to the sweep
    builders, which construct the lanes) the mask is exact:
    ``col < k_lane``. Without it (direct driver calls) the mask is
    inferred from the INITIAL factors — correct for uniform-random init
    (every true entry is nonzero a.s.), but NNDSVD can produce an
    exact-zero trailing component (sigma_j = 0 at k above rank(A)),
    which the inference would misclassify as padding and drop from the
    coupling where the per-restart engine keeps it. Callers that know
    the lane composition must pass ``job_ks``."""
    if job_ks is not None:
        if len(job_ks) != w0.shape[0]:
            # clamped gathers would otherwise pair lanes with the wrong
            # ranks silently (ADVICE.md round 5)
            raise ValueError(
                f"job_ks has {len(job_ks)} entries but the lane batch "
                f"carries {w0.shape[0]} jobs")
        k_max = w0.shape[2]
        return jnp.asarray(
            [[c < k for c in range(k_max)] for k in job_ks], bool)
    return jnp.any(w0 != 0, axis=1) | jnp.any(h0 != 0, axis=2)


def make_block(cfg: SolverConfig, a_full):
    """The per-iteration block for ``cfg.algorithm``, with any
    data-dependent auxiliaries resolved ONCE from the FULL-PRECISION A
    (snmf's default ``eta = max(A)²`` — matching the per-restart
    ``snmf.init_aux``, which also sees the untruncated matrix; under
    bf16 streaming the loop operand is truncated and must not feed
    eta). Shared by both batched drivers (mu_grid, mu_sched)."""
    block = BLOCKS[cfg.algorithm]
    if cfg.algorithm == "snmf":
        dtype = jnp.dtype(cfg.dtype)
        eta = (jnp.max(jnp.asarray(a_full, dtype)) ** 2
               if cfg.ridge_eta is None
               else jnp.asarray(cfg.ridge_eta, dtype))
        return partial(snmf_block, eta=eta)
    return block


def tolfun_update(a, state_w, state_h, it, cfg: SolverConfig, *,
                  dnorm, done, done_in, stop_reason):
    """The TolFun test for the batched drivers, mirroring
    ``base.check_convergence``'s rule (relative residual decrease vs the
    previous check, after the class/TolX tests of the same check): the
    residual is the DIRECT chunked form — the Gram-trace identity's
    cancellation noise would fire the decrease test spuriously near
    convergence. Returns (dnorm, done, stop_reason)."""
    is_check = (it > 1) & (it % cfg.check_every == 0)
    active = is_check & (~done_in)
    new_dnorm = residual_norms_direct(a, state_w, state_h)
    hit = (active & jnp.isfinite(dnorm)
           & (dnorm - new_dnorm <= cfg.tol_fun * dnorm) & ~done)
    dnorm = jnp.where(is_check & ~done_in, new_dnorm, dnorm)
    done = done | hit
    stop_reason = jnp.where(hit, jnp.int32(base.StopReason.TOL_FUN),
                            stop_reason)
    return dnorm, done, stop_reason


def _step(block, a, a_res, state: GridState, cfg: SolverConfig,
          check: bool) -> GridState:
    """``block`` from make_block; ``a`` feeds the iteration (possibly
    bf16-truncated); ``a_res`` the TolFun residual (full precision,
    matching the generic driver)."""
    w0, h0 = state.w, state.h
    it = state.iteration + 1
    w, h = block(a, state.w, state.h, state.done, cfg)
    state = state._replace(w=w, h=h, w_prev=w0, h_prev=h0, iteration=it)
    if not check:
        return state
    return _check(a_res, state, cfg)


def _check(a_res, state: GridState, cfg: SolverConfig) -> GridState:
    """Per-lane convergence tests on the dense layout; the bookkeeping
    semantics live in packed_mu.batch_convergence (shared with the packed
    per-rank path), plus the TolFun residual test for the algorithms whose
    per-restart form uses it."""
    delta = None
    if cfg.use_tol_checks:
        sqrteps = jnp.sqrt(jnp.finfo(state.w.dtype).eps)

        def _delta(cur, prev):
            diff = jnp.max(jnp.abs(cur - prev), axis=(1, 2))
            ref = jnp.max(jnp.abs(prev), axis=(1, 2))
            return diff / (sqrteps + ref)

        delta = jnp.maximum(_delta(state.w, state.w_prev),
                            _delta(state.h, state.h_prev))  # (B,)

    nonfinite = None
    if cfg.nonfinite_guard:
        # numeric quarantine, dense layout: each lane is its own batch
        # entry of every einsum, so a non-finite lane is contained by
        # construction — the guard only has to STOP it (NUMERIC_FAULT)
        # before its NaN labels can masquerade as a stable class
        nonfinite = ~(jnp.all(jnp.isfinite(state.w), axis=(1, 2))
                      & jnp.all(jnp.isfinite(state.h), axis=(1, 2)))
    done_in = state.done
    classes, stable, done, done_iter, reason = batch_convergence(
        cfg, state.iteration, new_classes=_labels(state.h), delta=delta,
        n_glob=state.h.shape[2], classes=state.classes, stable=state.stable,
        done=state.done, done_iter=state.done_iter,
        stop_reason=state.stop_reason, nonfinite=nonfinite)
    dnorm = state.dnorm
    if USES_TOLFUN[cfg.algorithm] and cfg.use_tol_checks:
        dnorm, done, reason = tolfun_update(
            a_res, state.w, state.h, state.iteration, cfg, dnorm=dnorm,
            done=done, done_in=done_in, stop_reason=reason)
        newly = done & ~done_in
        done_iter = jnp.where(newly, state.iteration, done_iter)
    return state._replace(classes=classes, stable=stable, done=done,
                          done_iter=done_iter, stop_reason=reason,
                          dnorm=dnorm)


@partial(jax.jit, static_argnames=("cfg", "varying_axes", "job_ks"))
def mu_grid(a: jax.Array, w0: jax.Array, h0: jax.Array,
            cfg: SolverConfig = SolverConfig(),
            varying_axes: tuple[str, ...] = (),
            job_ks: "tuple[int, ...] | None" = None) -> GridMUResult:
    """Solve a dense zero-padded lane batch (every grid cell, any mix of
    ranks) with shared-GEMM iterations. ``job_ks``: optional per-lane
    true ranks (see ``pad_live_mask`` — exact snmf coupling masks).

    Semantically equivalent to running ``mu_packed`` per rank on the same
    initial factors (same update rule, same convergence tests, same
    freeze-on-convergence), restructured so the whole (k × restart) grid is
    one compile and one while_loop. ``varying_axes`` as in ``mu_packed``:
    inside ``shard_map`` over those mesh axes the constant-initialized
    carry components must be lifted to device-varying.
    """
    if cfg.algorithm not in BLOCKS:
        raise ValueError(
            f"the dense-batched grid drivers implement {tuple(BLOCKS)}, "
            f"got algorithm={cfg.algorithm!r}")
    if job_ks is not None and len(job_ks) != h0.shape[0]:
        raise ValueError(
            f"job_ks has {len(job_ks)} entries but w0/h0 carry "
            f"{h0.shape[0]} lanes — per-lane true ranks must match the "
            "batch exactly")
    if cfg.algorithm == "snmf" and job_ks is None:
        # the inferred mask is exact for uniform-random init but NNDSVD
        # can yield an exact-zero trailing component that it would
        # misclassify as padding, dropping it from the beta coupling
        # where the per-restart engine keeps it (see pad_live_mask)
        import logging

        logging.getLogger("nmfx").warning(
            "mu_grid: snmf without job_ks infers the padding mask from "
            "the initial factors; an NNDSVD init whose trailing "
            "component is exactly zero would be misclassified as "
            "padding — pass job_ks (the per-lane true ranks) when the "
            "lane composition is known")
    cfg = conv_cfg(cfg)
    dtype = jnp.dtype(cfg.dtype)
    a = jnp.asarray(a, dtype)
    w0 = jnp.asarray(w0, dtype)
    h0 = jnp.asarray(h0, dtype)
    b, _, n = h0.shape
    a_true = a  # full precision, for the final residuals
    with base.matmul_precision_ctx(cfg.matmul_precision):

        def vary(x):
            for ax in varying_axes:
                x = pcast(x, ax, to="varying")
            return x

        state0 = GridState(
            w=w0, h=h0, w_prev=w0, h_prev=h0,
            iteration=jnp.zeros((), jnp.int32),
            classes=vary(jnp.full((b, n), -1, jnp.int32)),
            stable=vary(jnp.zeros((b,), jnp.int32)),
            done=vary(jnp.zeros((b,), bool)),
            done_iter=vary(jnp.zeros((b,), jnp.int32)),
            stop_reason=vary(jnp.full((b,), base.StopReason.MAX_ITER,
                                      jnp.int32)),
            dnorm=vary(jnp.full((b,), jnp.inf, dtype)),
        )
        from nmfx.ops.sched_mu import _streams_bf16_a
        a_loop = a
        if _streams_bf16_a(cfg):
            # one-time truncation: every loop GEMM reads A in the exact
            # bf16 form the MXU would round it to anyway (TPU-only; kl
            # excluded — see _streams_bf16_a; other backends ignore the
            # precision hint and run full-f32 GEMMs, so truncating there
            # would change results)
            a_loop = a.astype(jnp.bfloat16)
        block = make_block(cfg, a_true)
        if cfg.algorithm == "snmf":
            # each lane's true-k padding mask (mid-solve death must NOT
            # drop a component from the beta coupling — see snmf_block /
            # pad_live_mask)
            block = partial(block, pad_live=pad_live_mask(w0, h0, job_ks))
        step = partial(_step, block, a_loop, a_true)

        # check_block: N check blocks per while-loop trip ("auto" = 1
        # here), checks interleaved between sub-blocks — stop decisions
        # exact, the loop cond amortized N-fold (see packed_mu's
        # identical resolution)
        ncheck = 1 if cfg.check_block == "auto" else int(cfg.check_block)

        def cond(s: GridState):
            return jnp.any(~s.done) & (
                s.iteration + cfg.check_every * ncheck <= cfg.max_iter)

        def body(s: GridState):
            for _ in range(ncheck):
                for i in range(cfg.check_every):
                    s = step(s, cfg, check=(i == cfg.check_every - 1))
            return s

        final = lax.while_loop(cond, body, state0)

        def tail_cond(s: GridState):
            return jnp.any(~s.done) & (s.iteration < cfg.max_iter)

        final = lax.while_loop(tail_cond, lambda s: step(s, cfg, True),
                               final)

        iterations = jnp.where(final.done, final.done_iter, final.iteration)
        dnorm = residual_norms_direct(a_true, final.w, final.h)
    return GridMUResult(w=final.w, h=final.h,
                        iterations=iterations.astype(jnp.int32),
                        dnorm=dnorm, stop_reason=final.stop_reason)
