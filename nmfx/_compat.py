"""Version tolerance for the narrow slice of jax API that moved between
releases.

nmfx targets the current jax API (``jax.shard_map`` with ``check_vma``,
``lax.pcast``, the ``jax_num_cpu_devices`` config) but must also run on
the LTS-ish jaxlibs baked into accelerator images (observed: 0.4.x,
where shard_map still lives in ``jax.experimental.shard_map`` with the
``check_rep`` spelling, ``pcast`` does not exist, and virtual CPU
devices are forced through ``XLA_FLAGS``). Every call site imports the
symbol from here instead of feature-testing locally, so the supported
surface — and the fallbacks' semantics — live in one place:

* ``shard_map``: ``check_vma`` maps onto ``check_rep`` on old jax. All
  nmfx call sites pass ``check_vma=False`` (the replication checker
  cannot see through the argmin-over-gathered-candidates epilogues), so
  the semantic gap between the two checkers is never exercised.
* ``pcast``: identity on old jax. Its only job is lifting
  constant-initialized carries to device-varying for the NEW
  varying-manual-axes checker; with ``check_rep=False`` there is no
  checker to satisfy and the values are already correct.
* ``force_cpu_devices``: the ``jax_num_cpu_devices`` config when it
  exists, else ``--xla_force_host_platform_device_count`` via
  ``XLA_FLAGS`` — both only effective before backend initialization,
  exactly like the config they stand in for.
"""

from __future__ import annotations

import os

import jax
from jax import lax

__all__ = ["shard_map", "pcast", "force_cpu_devices",
           "serialize_compiled", "deserialize_compiled",
           "compiled_cost_analysis"]


# The sweep's key-chain contracts — restart r's key is independent of mesh
# shape and padding (api.restart_factors), and a padded restart batch is a
# prefix-extension of the unpadded one (sweep._pad_count) — hold only under
# the partitionable threefry PRNG, where split(key, n) is prefix-stable.
# Current jax has no other mode; 0.4.x defaults the flag OFF, which
# silently breaks every mesh-vs-unmeshed parity guarantee. Flip it at
# import, before any key is made (keys themselves are mode-independent;
# only derived streams change, uniformly for the whole process).
try:
    if not jax.config.jax_threefry_partitionable:
        jax.config.update("jax_threefry_partitionable", True)
except AttributeError:  # newer jax: partitionable is the only behavior
    pass


if hasattr(jax, "shard_map"):  # jax >= 0.6: the top-level API

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:  # jax 0.4.x/0.5.x: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)


if hasattr(lax, "pcast"):
    pcast = lax.pcast
else:

    def pcast(x, axis_name, *, to):  # noqa: ARG001 - mirror lax.pcast
        return x


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` where it exists (newer jax);
    the runtime's client handle on 0.4.x, which predates the accessor."""
    try:
        return bool(jax.distributed.is_initialized())
    except AttributeError:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None


def serialize_compiled(compiled) -> bytes:
    """One opaque blob for a ``jax.stages.Compiled`` — the PJRT-serialized
    executable plus the pickled arg/result pytree structure
    (``jax.experimental.serialize_executable`` returns the trees separately
    because pytrees aren't self-serializing; bundling them here keeps the
    on-disk format a single atomic artifact). Raises ``RuntimeError`` when
    this jax/backend cannot serialize executables — callers degrade to
    plain recompilation."""
    import pickle

    try:
        from jax.experimental.serialize_executable import serialize
    except ImportError as e:  # pragma: no cover - every supported jax has it
        raise RuntimeError(
            "this jax has no jax.experimental.serialize_executable") from e
    try:
        payload, in_tree, out_tree = serialize(compiled)
    except (ValueError, RuntimeError) as e:
        # e.g. "Compilation does not support serialization" on backends
        # whose PJRT client lacks executable serialization
        raise RuntimeError(
            f"executable serialization unsupported here: {e}") from e
    return pickle.dumps((payload, in_tree, out_tree),
                        protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_compiled(blob: bytes):
    """Inverse of :func:`serialize_compiled` — a loaded, callable
    ``jax.stages.Compiled`` on the current default backend."""
    import pickle

    from jax.experimental.serialize_executable import deserialize_and_load

    payload, in_tree, out_tree = pickle.loads(blob)
    return deserialize_and_load(payload, in_tree, out_tree)


def compiled_cost_analysis(compiled) -> "dict | None":
    """``jax.stages.Compiled.cost_analysis()`` normalized across the
    releases this repo spans: 0.4.x returns a one-element LIST of
    per-device-program dicts, newer jax returns the dict itself, and
    backends without a cost model return None/empty or raise. Returns
    one flat ``{"flops": ..., "bytes accessed": ..., ...}`` dict, or
    None when no analysis is available — callers (the
    ``nmfx.obs.costmodel`` cross-check) degrade to analytic-only."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # nmfx: ignore[NMFX006] -- capability probe: None = unavailable
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    return dict(ca)


def force_cpu_devices(n: int) -> None:
    """Force an ``n``-device virtual CPU platform for tests/dry runs.

    Must run before the XLA backend initializes (same constraint as the
    config it wraps); on old jax the XLA_FLAGS route is additionally
    inherited by subprocesses, which the multi-process tests rely on.
    """
    # replace (not just append) any inherited count: a pytest parent's
    # XLA_FLAGS propagates into worker subprocesses that want their own
    kept = [f for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(kept)
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # old jax: XLA_FLAGS above already did it
        pass
