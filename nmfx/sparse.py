"""Sparse ingestion for atlas-scale inputs (ISSUE 17).

The atlases real users submit — million-sample scRNA-seq count matrices
— are >90% zeros, and MPI-FAUN (arxiv 1609.09154) shows why sparsity
pays for NMF specifically: the alternating updates consume A only
through the Gram-style contractions WᵀA and AHᵀ, so contracting against
the stored nonzeros alone cuts the data-sized FLOPs and bytes by the
density factor while every k-sized term stays dense. This module is the
HOST-SIDE half of that story:

* :class:`SparseMatrix` — a minimal CSR container (``indptr``/
  ``indices``/``data`` + ``shape``) with deterministic canonical form
  (row-major, column-sorted, explicit zeros dropped), cheap row-block
  slicing (the exact operation the tile pipeline in ``nmfx/tiles.py``
  streams by), and a content fingerprint over the canonical triplets —
  the same honesty discipline as ``data_cache.DataKey``: a mutated
  matrix gets a new fingerprint, never a stale resume or cache hit.
* Tile → BCOO conversion (:meth:`SparseMatrix.tile_coo`): each streamed
  row block becomes the ``(indices, data)`` pair a device-side
  ``jax.experimental.sparse.BCOO`` wraps, so the per-tile Gram updates
  contract against stored nonzeros only (the stacked-GEMM formulation in
  ``nmfx/tiles.py`` — one sparse×dense GEMM over lane-stacked factors,
  never a vmap over BCOO ops).

Exactness contract: a sparse solve is the SAME mathematical program as
the densified solve — the agreement gates (``nmfx/agreement.py``)
pin sparse≡densified consensus/label equivalence at test shapes
(tests/test_sparse.py); bit-level identity is not promised (sparse
contractions order their reductions by stored-nonzero layout).

File loaders (MatrixMarket ``.mtx``, the simple CSR ``.csr.npz``
bundle) live in ``nmfx/io.py`` next to the dense GCT/RES readers.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from nmfx.obs import metrics as _metrics

__all__ = ["SparseMatrix"]

#: nonzeros streamed through sparse tile contractions (every tile of
#: every pass counts its stored nnz once) — the honesty counter behind
#: the contract that the sparse path's data work scales with nnz, not
#: m·n; docs/observability.md documents it (NMFX010)
_sparse_nnz_total = _metrics.counter(
    "nmfx_sparse_nnz_total",
    "stored nonzeros streamed through sparse tile contractions")
_sparse_nnz_bytes_total = _metrics.counter(
    "nmfx_sparse_nnz_bytes_total",
    "bytes of sparse tile payloads (values + indices) transferred "
    "host-to-device")


def note_sparse_tile(nnz: int, nbytes: int) -> None:
    """Book one sparse tile's streamed nonzeros/bytes (called by the
    tile stream, ``nmfx/tiles.py``)."""
    _sparse_nnz_total.inc(nnz)
    _sparse_nnz_bytes_total.inc(nbytes)


@dataclasses.dataclass(frozen=True)
class SparseMatrix:
    """Host CSR matrix in canonical form.

    Canonical means: ``indptr`` is a monotone ``int64`` array of length
    ``m + 1``; within each row ``indices`` is strictly increasing
    ``int32`` (no duplicates); ``data`` holds no explicit zeros. Both
    constructors (:meth:`from_dense`, :meth:`from_coo`) canonicalize, so
    two representations of the same matrix always fingerprint
    identically — the content-addressing the checkpoint manifest and
    ``DataKey`` rely on."""

    indptr: np.ndarray  # (m + 1,) int64
    indices: np.ndarray  # (nnz,) int32 column indices
    data: np.ndarray  # (nnz,) values
    shape: tuple

    def __post_init__(self):
        m, n = self.shape
        object.__setattr__(self, "shape", (int(m), int(n)))
        indptr = np.ascontiguousarray(self.indptr, np.int64)
        indices = np.ascontiguousarray(self.indices, np.int32)
        data = np.ascontiguousarray(self.data)
        if indptr.shape != (self.shape[0] + 1,):
            raise ValueError(
                f"indptr must have shape ({self.shape[0] + 1},), got "
                f"{indptr.shape}")
        if indptr[0] != 0 or indptr[-1] != len(data):
            raise ValueError("indptr must run [0, ..., nnz]")
        if np.any(np.diff(indptr) < 0):
            raise ValueError("indptr must be monotone non-decreasing")
        if len(indices) != len(data):
            raise ValueError("indices and data must have equal length")
        if len(indices) and (indices.min() < 0
                             or indices.max() >= self.shape[1]):
            raise ValueError("column indices out of range")
        object.__setattr__(self, "indptr", indptr)
        object.__setattr__(self, "indices", indices)
        object.__setattr__(self, "data", data)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_dense(cls, a) -> "SparseMatrix":
        """CSR of a dense host array (row-major scan — canonical by
        construction)."""
        a = np.asarray(a)
        if a.ndim != 2:
            raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
        rows, cols = np.nonzero(a)
        indptr = np.zeros(a.shape[0] + 1, np.int64)
        np.add.at(indptr, rows + 1, 1)
        np.cumsum(indptr, out=indptr)
        return cls(indptr=indptr, indices=cols.astype(np.int32),
                   data=a[rows, cols], shape=a.shape)

    @classmethod
    def from_coo(cls, rows, cols, vals, shape) -> "SparseMatrix":
        """CSR from COO triplets: sorts row-major then by column,
        SUMS duplicate entries (the MatrixMarket convention), and drops
        entries that cancel to exact zero — canonical form."""
        rows = np.asarray(rows, np.int64)
        cols = np.asarray(cols, np.int64)
        vals = np.asarray(vals)
        m, n = int(shape[0]), int(shape[1])
        if len(rows) and (rows.min() < 0 or rows.max() >= m
                          or cols.min() < 0 or cols.max() >= n):
            raise ValueError("COO indices out of range for shape "
                             f"({m}, {n})")
        order = np.lexsort((cols, rows))
        rows, cols, vals = rows[order], cols[order], vals[order]
        if len(rows):
            # sum duplicates: group boundaries where (row, col) changes
            new = np.empty(len(rows), bool)
            new[0] = True
            new[1:] = (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1])
            group = np.cumsum(new) - 1
            vals = np.bincount(group, weights=vals.astype(np.float64),
                               minlength=group[-1] + 1).astype(vals.dtype)
            rows, cols = rows[new], cols[new]
        keep = vals != 0
        rows, cols, vals = rows[keep], cols[keep], vals[keep]
        indptr = np.zeros(m + 1, np.int64)
        np.add.at(indptr, rows + 1, 1)
        np.cumsum(indptr, out=indptr)
        return cls(indptr=indptr, indices=cols.astype(np.int32),
                   data=vals, shape=(m, n))

    # -- basic queries ------------------------------------------------------
    @property
    def nnz(self) -> int:
        return int(len(self.data))

    @property
    def density(self) -> float:
        m, n = self.shape
        return self.nnz / float(m * n) if m and n else 0.0

    @property
    def nbytes(self) -> int:
        return (self.indptr.nbytes + self.indices.nbytes
                + self.data.nbytes)

    def toarray(self, dtype=None) -> np.ndarray:
        """Densify (test shapes / the sparse≡densified agreement gates
        only — densifying an atlas defeats the module)."""
        m, n = self.shape
        out = np.zeros((m, n), dtype if dtype is not None
                       else self.data.dtype)
        rows = np.repeat(np.arange(m), np.diff(self.indptr))
        out[rows, self.indices] = self.data
        return out

    # -- tiling -------------------------------------------------------------
    def row_block(self, r0: int, r1: int) -> "SparseMatrix":
        """Rows ``[r0, r1)`` as their own canonical CSR (shares the
        value/index buffers — a view, not a copy)."""
        p0, p1 = int(self.indptr[r0]), int(self.indptr[r1])
        return SparseMatrix(indptr=self.indptr[r0:r1 + 1] - p0,
                            indices=self.indices[p0:p1],
                            data=self.data[p0:p1],
                            shape=(r1 - r0, self.shape[1]))

    def tile_coo(self, r0: int, r1: int, dtype
                 ) -> "tuple[np.ndarray, np.ndarray]":
        """Rows ``[r0, r1)`` as the ``(indices, data)`` pair of a
        row-local COO block — exactly what a device-side
        ``jax.experimental.sparse.BCOO`` of shape ``(r1 - r0, n)``
        wraps. ``indices`` is ``(nnz_t, 2) int32`` ``[row - r0, col]``
        in canonical (row-major, column-sorted) order; ``data`` is cast
        to the solve dtype host-side so the transfer carries the bytes
        the device consumes."""
        p0, p1 = int(self.indptr[r0]), int(self.indptr[r1])
        counts = np.diff(self.indptr[r0:r1 + 1]).astype(np.int64)
        local_rows = np.repeat(np.arange(r1 - r0, dtype=np.int32), counts)
        idx = np.stack([local_rows, self.indices[p0:p1]], axis=1)
        return idx, np.asarray(self.data[p0:p1], dtype)

    def block_sq_norms(self, boundaries) -> np.ndarray:
        """``sum(data**2)`` per ``(r0, r1)`` row block, accumulated in
        float64 — the per-tile ‖A_t‖² constants the tiled residual's
        Gram form needs (``nmfx/tiles.py``)."""
        sq = (self.data.astype(np.float64) ** 2)
        csum = np.concatenate([[0.0], np.cumsum(sq)])
        return np.asarray([csum[self.indptr[r1]] - csum[self.indptr[r0]]
                           for r0, r1 in boundaries])

    # -- content addressing --------------------------------------------------
    def fingerprint(self) -> str:
        """sha256 over the canonical triplets + shape + value dtype —
        the sparse analogue of ``DataKey.fingerprint`` (content, not
        identity: in-place mutation yields a new digest)."""
        h = hashlib.sha256()
        h.update(repr((self.shape, self.data.dtype.str)).encode())
        h.update(np.ascontiguousarray(self.indptr).view(np.uint8))
        h.update(np.ascontiguousarray(self.indices).view(np.uint8))
        h.update(np.ascontiguousarray(self.data).view(np.uint8))
        return h.hexdigest()
