"""On-first-run block-shape autotuner for the pallas slot scheduler.

The fused/phased choice, the tile rows (``experimental.block_m``) and the
launch-resident check cadence (``check_block``) trade VMEM residency
against HBM round-trips differently at different (m, n, k, slots)
shapes — the round-4 envelope probes showed the best tile geometry
moving with both m and the packed width, and no closed-form model
survived contact with Mosaic's layout choices. So, PL-NMF style, we
*measure*: the first solve at a shape bucket times a small candidate
grid of (block_m, check_block, fused-vs-phased) with RAW kernel
launches on the real device, picks the fastest per-iteration candidate,
and persists the verdict content-addressed next to the exec cache — the
second process at the same bucket pays ZERO search (the warm path is
gated in the bench by the ``nmfx_autotune_{searches,hits}_total``
counter pair, and in tests/test_autotune.py).

Opt-in and strictly resolution-time: ``experimental.autotune="on"``
makes :func:`resolve` rewrite the config ONCE, host-side, before any
tracing — the solver itself never consults the store, so jit keys,
registry fingerprints and exec-cache keys all see the RESOLVED numerics
(``autotune="off"`` plus explicit ``check_block``/``block_m``/
``fused_updates``). A warm run resolves to the identical config, so a
checkpoint written by a cold run resumes cleanly under a warm one.
Explicit user values always win: the search still times the FULL
candidate grid (so the persisted entry's content is independent of
which fields happened to be explicit in the requesting config), but
tuned values only fill ``"auto"``/``None`` gaps at apply time.

Key discipline (the NMFX001 family): a tuned shape must never be
served across anything that changes what "fastest" means — data shape
(bucketed on the exec cache's lattice), every config field that reaches
the kernels, device kind, jax/jaxlib/PJRT versions. The key is the
repr of ``(normalized cfg, shape bucket, env fingerprint)`` where the
normalized config pins exactly the TUNABLE fields to sentinels (they
are what the entry decides, so they must not split the key) — the
exempt sets below are the authoritative declaration the static
analyzer cross-references against :func:`autotune_key_fields`, so a
new config field joins the key automatically and can only leave it via
a reviewed exemption.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import tempfile
import threading
import time
import warnings

from nmfx.obs import metrics

#: Disk-entry format; bump on any record-layout change (a mismatched
#: format re-searches, never mis-reads).
_FORMAT = 1

#: Iterations per timed launch (one check sub-block); per-iteration
#: normalization divides by ``_TIME_ITERS * check_block``.
_TIME_ITERS = 4
_TIME_REPS = 3

#: Cold-path searches performed (one per unseen key) / warm-path store
#: hits (memo or disk). A warm process at a tuned bucket must show
#: hits > 0 and searches == 0 — the bench autotune rung and
#: tests/test_autotune.py gate on exactly these.
searches_total = metrics.counter(
    "nmfx_autotune_searches_total",
    help="block-shape autotune candidate searches performed (cold path)")
hits_total = metrics.counter(
    "nmfx_autotune_hits_total",
    help="block-shape autotune store hits served without search")

#: AUTHORITATIVE tunable declarations — the ONLY fields the key may
#: normalize away, because they are what the stored entry decides.
#: Everything else in the config tree reaches the key via its repr;
#: the static analyzer (NMFX001's autotune clause) cross-references
#: these against the live dataclasses so the lists cannot go stale and
#: a new field cannot silently skip the key.
AUTOTUNE_EXEMPT_SOLVER = frozenset({"check_block"})
AUTOTUNE_EXEMPT_EXPERIMENTAL = frozenset({
    "autotune", "block_m", "fused_updates"})

_lock = threading.Lock()
_memo: "dict[str, dict]" = {}
_warned: "set[str]" = set()


def autotune_key_fields() -> "tuple[frozenset, frozenset]":
    """The (SolverConfig, ExperimentalConfig) fields the autotune key
    covers — the introspection hook the NMFX001-family lint clause
    reads. Total by construction: the key is the repr of the config
    with ONLY the declared tunables pinned to sentinels, so every
    repr-visible field outside the exempt sets participates (and
    NMFX001's repr=False clause independently forbids repr-invisible
    fields anywhere in the config tree)."""
    from nmfx.config import ExperimentalConfig, SolverConfig

    solver = frozenset(f.name for f in dataclasses.fields(SolverConfig)
                       if f.repr) - AUTOTUNE_EXEMPT_SOLVER
    exp = frozenset(f.name for f in dataclasses.fields(ExperimentalConfig)
                    if f.repr) - AUTOTUNE_EXEMPT_EXPERIMENTAL
    return solver, exp


def shape_bucket(m: int, n: int, k_max: int, slots: int) -> tuple:
    """The (m, n, k_max, slots) lattice point a tuned entry is keyed
    (and timed) at — the exec cache's bucket quanta, so the two caches
    agree on which shapes share a compiled/tuned artifact."""
    from nmfx import exec_cache

    return (exec_cache.bucket_dim(int(m), 256),
            exec_cache.bucket_dim(int(n), 64),
            int(k_max), int(slots))


def _normalized(cfg):
    """``cfg`` with exactly the tunable fields pinned to sentinels —
    the config part of the key. ``dataclasses.replace`` round-trips
    through ``__post_init__``, so the sentinels stay valid values."""
    exp = dataclasses.replace(cfg.experimental, autotune="off",
                              block_m=None, fused_updates="auto")
    return dataclasses.replace(cfg, check_block="auto", experimental=exp)


def _key_repr(cfg, m: int, n: int, k_max: int, slots: int) -> str:
    from nmfx import exec_cache

    return repr((_normalized(cfg), shape_bucket(m, n, k_max, slots),
                 exec_cache._env_fingerprint()))


def _warn_once(category: str, msg: str) -> None:
    with _lock:
        if category in _warned:
            return
        _warned.add(category)
    warnings.warn(f"nmfx autotune: {msg}", RuntimeWarning, stacklevel=3)


def _disk_path(cache_dir: str, key_repr: str) -> str:
    h = hashlib.sha256(key_repr.encode()).hexdigest()[:40]
    return os.path.join(cache_dir, h + ".json")


def _disk_load(cache_dir: str, key_repr: str) -> "dict | None":
    """A verified entry's ``best`` dict, or None. Anything short of a
    full match — unreadable JSON, wrong format, a key that differs
    despite the matching hash (collision or hand-moved file) — warns
    once, removes the entry and falls back to a fresh search: the
    degradation is always a re-measure, never a mis-applied shape."""
    path = _disk_path(cache_dir, key_repr)
    try:
        with open(path) as f:
            rec = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        rec = None
    best = rec.get("best") if isinstance(rec, dict) else None
    if (not isinstance(rec, dict) or rec.get("format") != _FORMAT
            or rec.get("key") != key_repr
            or not isinstance(best, dict)
            or not {"block_m", "check_block",
                    "fused_updates"} <= set(best)):
        _warn_once(path, f"entry at {path!r} is corrupt or was written "
                         "under a different key/format; re-searching")
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    return best


def _disk_store(cache_dir: str, key_repr: str, best: dict,
                timings: dict) -> None:
    """Atomic tmp+rename publish (the exec cache's discipline): a
    concurrent reader sees either nothing or a complete entry."""
    rec = {"format": _FORMAT, "key": key_repr, "best": best,
           "timings": timings}
    try:
        os.makedirs(cache_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=cache_dir, prefix="write-",
                                   suffix=".part")
    except OSError as e:
        _warn_once(cache_dir, f"cannot write under {cache_dir!r} ({e}); "
                              "tuning stays in-process only")
        return
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, _disk_path(cache_dir, key_repr))
    except OSError as e:
        _warn_once(cache_dir, f"cannot publish under {cache_dir!r} "
                              f"({e}); tuning stays in-process only")
        try:
            os.remove(tmp)
        except OSError:
            pass


def _candidates(cfg, m: int, n: int, k_max: int,
                slots: int) -> "list[dict]":
    """The full candidate grid at this (bucketed) shape, validity-pruned
    by the scheduler's VMEM envelope. Always the FULL grid — entry
    content must not depend on which fields the requesting config had
    explicit (explicit values win at apply time instead)."""
    from nmfx.ops import sched_mu
    from nmfx.ops.grid_mu import USES_TOLFUN

    default_bm = sched_mu._pallas_block_geometry(m)[1]
    bms = sorted({int(default_bm), 256, 512})
    cbs = [1, 4]
    if (cfg.algorithm == "hals" and USES_TOLFUN["hals"]
            and cfg.use_tol_checks):
        # interior boundaries cannot replay TolFun from the kernel's
        # boundary exports — mirror the scheduler's hals restriction
        cbs = [1]
    fuseds = (["phased", "fused"] if cfg.algorithm == "mu"
              else ["phased"])
    rk = slots * k_max
    out = []
    for bm in bms:
        for cb in cbs:
            for fu in fuseds:
                if rk > sched_mu._pallas_max_rk(
                        m, n, cfg, cfg.experimental.factor_dtype,
                        check_block=cb, fused=(fu == "fused"),
                        algorithm=cfg.algorithm, block_m=bm):
                    continue
                out.append({"block_m": int(bm), "check_block": int(cb),
                            "fused_updates": fu})
    return out


def _cand_label(cand: dict) -> str:
    return (f"bm{cand['block_m']}_cb{cand['check_block']}"
            f"_{cand['fused_updates']}")


def _time_candidate(cfg, cand: dict, m: int, n: int, k_max: int,
                    slots: int) -> float:
    """Per-iteration wall seconds of one raw block-kernel launch at the
    bucket shape on synthetic data (fixed PRNG key — determinism keeps
    reruns comparable). Raw launches, not a full ``mu_sched`` solve:
    the candidates differ only inside the kernel, and a full solve per
    candidate would pay scheduler compile time ~10x the signal."""
    import jax
    import jax.numpy as jnp

    from nmfx.ops import sched_mu
    from nmfx.ops.pallas_mu import (fused_block_iterations,
                                    hals_block_iterations)

    bm, cb = cand["block_m"], cand["check_block"]
    m_pad = -(-m // bm) * bm
    rk = slots * k_max
    exp = cfg.experimental
    a_dt = (jnp.bfloat16 if sched_mu._streams_bf16_a(cfg)
            else jnp.float32)
    w_dt = (jnp.bfloat16 if exp.factor_dtype in ("bfloat16", "bfloat16_w")
            else jnp.float32)
    h_dt = jnp.bfloat16 if exp.factor_dtype == "bfloat16" else jnp.float32
    ka, kw, kh = jax.random.split(jax.random.PRNGKey(0), 3)
    a = jax.random.uniform(ka, (m_pad, n), a_dt)
    wp = jax.random.uniform(kw, (m_pad, rk), w_dt)
    hp = jax.random.uniform(kh, (rk, n), h_dt)
    frozen = jnp.zeros((1, rk), jnp.float32)
    kw_common = dict(k=k_max, iters=_TIME_ITERS, block_m=bm,
                     eps=cfg.div_eps,
                     zero_threshold=cfg.zero_threshold,
                     matmul_precision=cfg.matmul_precision,
                     interpret=jax.default_backend() != "tpu",
                     check_block=cb)
    if cb > 1:
        # no lane hits its budget during a timing launch
        kw_common["budget_cols"] = jnp.full((1, rk), 1e9, jnp.float32)
    if cfg.algorithm == "hals":
        def launch():
            return hals_block_iterations(a, wp, hp, frozen, slots=slots,
                                         **kw_common)
    else:
        def launch():
            return fused_block_iterations(
                a, wp, hp, frozen,
                fused=cand["fused_updates"] == "fused", **kw_common)
    jax.block_until_ready(launch())  # compile + warm
    best = math.inf
    for _ in range(_TIME_REPS):
        t0 = time.perf_counter()
        jax.block_until_ready(launch())
        best = min(best, time.perf_counter() - t0)
    return best / (_TIME_ITERS * cb)


def _lookup_or_search(cfg, m: int, n: int, k_max: int, slots: int,
                      cache_dir: "str | None") -> "dict | None":
    key = _key_repr(cfg, m, n, k_max, slots)
    with _lock:
        if key in _memo:
            hits_total.inc()
            return dict(_memo[key])
    if cache_dir is not None:
        best = _disk_load(cache_dir, key)
        if best is not None:
            hits_total.inc()
            with _lock:
                _memo[key] = dict(best)
            return dict(best)
    m_b, n_b, _, _ = shape_bucket(m, n, k_max, slots)
    cands = _candidates(cfg, m_b, n_b, k_max, slots)
    if not cands:
        # the shape overflows the VMEM envelope at every candidate —
        # the scheduler's own clamp will route it; nothing to tune
        return None
    searches_total.inc()
    timings, best, best_t = {}, None, math.inf
    for cand in cands:
        t = _time_candidate(cfg, cand, m_b, n_b, k_max, slots)
        timings[_cand_label(cand)] = t
        if t < best_t:
            best, best_t = cand, t
    with _lock:
        _memo[key] = dict(best)
    if cache_dir is not None:
        _disk_store(cache_dir, key, best, timings)
    return dict(best)


def resolve(cfg, m: int, n: int, k_max: int, slots: int,
            cache_dir: "str | None" = None):
    """The one entry point: rewrite ``cfg`` with tuned kernel-schedule
    values for this problem shape, or return it unchanged (minus the
    ``autotune`` flag itself) when there is nothing to tune.

    Host-side and idempotent: the returned config always has
    ``autotune="off"`` and fully explicit tuned fields, so every
    downstream key (jit static args, registry fingerprint, exec-cache
    bucket) sees the resolved numerics, and a warm process resolves to
    the IDENTICAL config. Tuned values fill only ``"auto"``/``None``
    gaps — explicit user choices always win. ``cache_dir`` (normally
    ``<exec cache dir>/autotune``) enables the cross-process warm path;
    ``None`` keeps tuning in-process (the memo)."""
    exp = cfg.experimental
    if exp.autotune != "on":
        return cfg
    off = dataclasses.replace(exp, autotune="off")
    if cfg.backend != "pallas" or exp.ragged:
        # nothing to tune: the block-kernel route is pallas-only, and
        # the ragged pool runs the per-iteration kernels (no block_m /
        # check_block / fused choice to make)
        return dataclasses.replace(cfg, experimental=off)
    best = _lookup_or_search(cfg, m, n, k_max, slots, cache_dir)
    if best is None:
        return dataclasses.replace(cfg, experimental=off)
    tuned_exp = dataclasses.replace(
        off,
        block_m=(exp.block_m if exp.block_m is not None
                 else int(best["block_m"])),
        fused_updates=(exp.fused_updates if exp.fused_updates != "auto"
                       else str(best["fused_updates"])))
    tuned_cb = (cfg.check_block if cfg.check_block != "auto"
                else int(best["check_block"]))
    return dataclasses.replace(cfg, check_block=tuned_cb,
                               experimental=tuned_exp)
