"""Replica pool: N serving replicas behind one router front door.

One ``NMFXServer`` owns one device — the ROADMAP's "a server must
become a service" gap. This module supplies the POOL half of the
service tier (ISSUE 15): a :class:`ReplicaPool` runs N replicas, each a
full ``NMFXServer`` with its own spill directory, publishing heartbeats
(:class:`nmfx.obs.export.HeartbeatLedger`, ``replica_<id>.json`` in the
pool root) and queue-depth/inflight levels (telemetry snapshot
``status``) the router's health checker and ``nmfx-top`` read. The
router half lives in ``nmfx/router.py``.

Two replica kinds, one contract:

* :class:`ThreadReplica` — an in-process ``NMFXServer`` on its own
  scheduler thread. Zero spawn cost, shares the process's exec/data
  caches, and is fully deterministic to drive (pause/resume, fake
  engines) — the kind tests and the bench scaling rung use, and the
  honest option when one process owns several devices.
* :class:`ProcessReplica` — a subprocess worker (``python -m
  nmfx.replica``) with its own interpreter, device, and registry — the
  production shape. The transport is the SPILL RECORD format + claim
  protocol from ``nmfx/serve.py``: the router forwards a request by
  atomically writing its full submission payload into the replica's
  ``inbox/``; the worker claims it, serves it through a normal
  ``NMFXServer.submit``, and writes the result (or a typed error) into
  ``outbox/``. The inbox record is removed only AFTER the result
  lands, so it doubles as the write-ahead copy: a replica SIGKILLed
  mid-queue leaves its unfinished records (some under a dead pid's
  claim) for the router to claim back and readmit on survivors —
  bit-identical to the original submission, because re-admission goes
  through the one ``spill_submit_kwargs`` funnel every consumer
  shares.

Spawn cost is what makes scale-up a real elasticity primitive: a
worker started against the warm persistent executable cache
(``--cache-dir``, ISSUE 4) cold-starts in ~1 s (deserialize-and-
dispatch, zero compiles) instead of ~22 s.

Directory layout of one pool root::

    <root>/replica_<id>.json     heartbeats (HeartbeatLedger)
    <root>/<id>/inbox/           spill-format requests (+ .claim)
    <root>/<id>/outbox/          result_<rid>.npz | error_<rid>.json
    <root>/<id>/spill/           the replica server's own spill_dir
                                 (thread replicas: drain spills land
                                 here for the router to claim)

See docs/serving.md "Service tier".
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import numpy as np

from nmfx.guards import guarded_by
from nmfx.obs import flight as _flight
from nmfx.obs import metrics as _metrics

__all__ = ["ProcessReplica", "ReplicaError", "ReplicaPool",
           "SpawnFailed", "ThreadReplica", "worker_main"]

#: heartbeat filenames in the pool root (HeartbeatLedger prefix)
HEARTBEAT_PREFIX = "replica_"

#: outbox filenames
RESULT_PREFIX = "result_"
ERROR_PREFIX = "error_"

_replicas_gauge = _metrics.gauge(
    "nmfx_replica_pool_size",
    "replicas in this process's pool, by lifecycle state",
    labelnames=("state",))


class ReplicaError(RuntimeError):
    """Base class of replica-tier failures."""


class SpawnFailed(ReplicaError):
    """Replica scale-up failed (the ``replica.spawn`` chaos site, an
    exec failure, ...). The pool keeps serving at its current size —
    a failed spawn is a degradation, never an outage."""


def _rid_of(path: str) -> str:
    """The request id a spill/result/error filename embeds."""
    name = os.path.basename(path)
    for prefix in ("spill_", RESULT_PREFIX, ERROR_PREFIX):
        if name.startswith(prefix):
            name = name[len(prefix):]
            break
    for suffix in (".npz", ".json"):
        if name.endswith(suffix):
            name = name[:-len(suffix)]
    return name


class _Beater:
    """Daemon thread writing one instance's heartbeats into the pool
    ledger every ``interval_s``. The ``replica.heartbeat`` chaos site
    fires HERE: an armed site skips the write (the frozen-publisher
    rehearsal — the instance keeps serving but its heartbeat ages, and
    the router's health checker drains it)."""

    def __init__(self, ledger, instance: str, status_fn,
                 interval_s: float):
        self.ledger = ledger
        self.instance = instance
        self.status_fn = status_fn
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def beat_once(self) -> "str | None":
        from nmfx import faults

        try:
            faults.inject("replica.heartbeat")
        except faults.FaultInjected:
            # the frozen publisher: the fire is on the flight recorder
            # (FAULT_EVENTS), the heartbeat file simply does not
            # advance — exactly what a wedged writer looks like from
            # the outside
            return None
        return self.ledger.beat(self.instance, **self.status_fn())

    def _run(self) -> None:
        while not self._stop.is_set():
            self.beat_once()
            self._stop.wait(self.interval_s)

    def launch(self) -> "_Beater":
        # named "launch", not "start": nmfx-lint's name-graph call
        # resolution links any traced kernel's `start(...)` call to a
        # method of that name, which would drag beat_once -> beat ->
        # open into the traced set and false-positive NMFX005
        if self._thread is None:
            self.beat_once()  # a replica is visible the moment it exists
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"nmfx-replica-hb-{self.instance}")
            self._thread.start()
        return self

    def close(self, final_status: "dict | None" = None) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if final_status is not None:
            # final beat OUTSIDE the chaos site: a clean shutdown
            # always leaves its terminal state in the ledger
            self.ledger.beat(self.instance, **final_status)


class ThreadReplica:
    """One in-process replica: a full ``NMFXServer`` (role="replica")
    plus a heartbeat beater. The router forwards by direct
    ``submit()`` — the thinnest possible hop, which is what keeps the
    1-replica router within the bench overhead gate."""

    kind = "thread"

    def __init__(self, replica_id: str, root: str, ledger, *,
                 serve_cfg=None, engine=None, exec_cache=None,
                 mesh_spec: "str | None" = None, devices=None,
                 profiler=None, telemetry_dir: "str | None" = None,
                 heartbeat_interval_s: float = 0.5):
        import dataclasses

        from nmfx.serve import NMFXServer, ServeConfig

        self.replica_id = replica_id
        self.root = root
        self.spawned_at = time.monotonic()
        self.spill_dir = os.path.join(root, "spill")
        os.makedirs(self.spill_dir, exist_ok=True)
        self.state = "routable"
        cfg = serve_cfg if serve_cfg is not None else ServeConfig()
        if mesh_spec is None:
            mesh_spec = cfg.mesh_spec
        self.mesh_spec = mesh_spec
        if mesh_spec is not None:
            from nmfx.distributed import parse_mesh_spec

            r, f, s = parse_mesh_spec(mesh_spec)
            self.n_devices = r * f * s
        else:
            self.n_devices = 1
        cfg = dataclasses.replace(
            cfg, role="replica", instance=replica_id,
            spill_dir=self.spill_dir, mesh_spec=mesh_spec,
            telemetry_dir=(telemetry_dir if cfg.telemetry_dir is None
                           else cfg.telemetry_dir))
        if engine is None and mesh_spec is not None \
                and devices is not None:
            # the pool carved this replica an explicit device block —
            # build the mesh engine over exactly those devices (the
            # server's own mesh_spec path would grab the head of
            # jax.devices() and alias siblings onto the same chips)
            from nmfx.serve import MeshEngine

            engine = MeshEngine(mesh_spec, devices=devices,
                                profiler=profiler)
        self.server = NMFXServer(
            cfg, engine=engine,
            exec_cache=None if engine is not None else exec_cache,
            profiler=profiler)
        self._beater = _Beater(ledger, replica_id, self._status,
                               heartbeat_interval_s).launch()

    def _status(self) -> dict:
        s = self.server.stats()
        return {"role": "replica", "kind": self.kind,
                "state": self.state, "queue_depth": s["queued"],
                "inflight": s["inflight"],
                "mesh": self.mesh_spec, "devices": self.n_devices}

    def forward(self, rid: str, a: np.ndarray, meta: dict) -> Future:
        """Submit one spill-format payload to this replica's server;
        the returned future is the server's own (the router chains
        it)."""
        from nmfx.serve import spill_dataset, spill_submit_kwargs

        return self.server.submit(spill_dataset(a, meta),
                                  **spill_submit_kwargs(meta))

    def alive(self) -> bool:
        return self.server._down is None and not self.server._closed

    def drain(self) -> None:
        """Stop serving: fail queued requests through the spill path
        (each ``ServerClosed`` carries its ``spill_path``; the router
        claims the records and readmits on survivors), let in-flight
        work finish, then stop — beater included, so the drained
        replica's heartbeat AGES into staleness instead of a leaked
        thread publishing a phantom live instance forever. Idempotent."""
        self.state = "draining"
        self.server.close(cancel_pending=True)
        self.state = "dead"
        self._beater.close(final_status=self._status())

    def retire(self) -> None:
        """Stop this replica's side threads without a drain — the
        router's recovery path for a crashed replica (the server is
        already down; only the beater must not outlive the pool
        membership)."""
        self._beater.close(final_status=self._status())

    def close(self) -> None:
        if self.state == "routable":
            self.state = "draining"
            self.server.close()
            self.state = "dead"
        self._beater.close(final_status=self._status())

    def poll(self) -> None:
        """Nothing to poll — thread replicas resolve their futures
        directly (uniform surface with :class:`ProcessReplica`)."""


@guarded_by("_lock", "_pending", "_read_failures")
class ProcessReplica:
    """One subprocess replica: the worker (``python -m nmfx.replica``)
    serves spill-format requests from its ``inbox/`` and writes
    results into ``outbox/``; this handle writes forwards, polls the
    outbox, and owns the child's lifecycle."""

    kind = "process"

    def __init__(self, replica_id: str, root: str, ledger, *,
                 cache_dir: "str | None" = None,
                 telemetry_dir: "str | None" = None,
                 mesh_spec: "str | None" = None,
                 heartbeat_interval_s: float = 0.5,
                 poll_interval_s: float = 0.05,
                 worker_args: "tuple[str, ...]" = (),
                 env: "dict | None" = None):
        self.replica_id = replica_id
        self.root = root
        self.spawned_at = time.monotonic()
        self.mesh_spec = mesh_spec
        if mesh_spec is not None:
            from nmfx.distributed import parse_mesh_spec

            r, f, s = parse_mesh_spec(mesh_spec)
            self.n_devices = r * f * s
        else:
            self.n_devices = 1
        self.inbox = os.path.join(root, "inbox")
        self.outbox = os.path.join(root, "outbox")
        #: for a process replica the INBOX is the spill dir the router
        #: recovers from — unfinished records simply stay there
        self.spill_dir = self.inbox
        os.makedirs(self.inbox, exist_ok=True)
        os.makedirs(self.outbox, exist_ok=True)
        self.state = "routable"
        self.ledger = ledger
        #: router-side pending: rid -> (future, inbox record path)
        self._pending: "dict[str, tuple[Future, str]]" = {}
        #: transient outbox read failures per rid (retried next poll)
        self._read_failures: "dict[str, int]" = {}
        self._lock = threading.Lock()
        cmd = [sys.executable, "-m", "nmfx.replica",
               "--dir", root, "--id", replica_id,
               "--pool-dir", ledger.directory,
               "--heartbeat-interval", str(heartbeat_interval_s),
               "--poll-interval", str(poll_interval_s)]
        if cache_dir is not None:
            cmd += ["--cache-dir", cache_dir]
        if telemetry_dir is not None:
            cmd += ["--telemetry-dir", telemetry_dir]
        if mesh_spec is not None:
            cmd += ["--mesh-spec", mesh_spec]
        cmd += list(worker_args)
        self.process = subprocess.Popen(
            cmd, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL
            if os.environ.get("NMFX_REPLICA_WORKER_STDERR") is None
            else None)

    @property
    def pid(self) -> int:
        return self.process.pid

    def forward(self, rid: str, a: np.ndarray, meta: dict) -> Future:
        """Atomically write the request into the worker's inbox (the
        write IS the forward — and the write-ahead copy recovery
        claims back if the worker dies); returns the future the outbox
        poller resolves."""
        from nmfx.serve import write_spill_record

        fut: Future = Future()
        path = os.path.join(self.inbox, f"spill_{rid}.npz")
        with self._lock:
            self._pending[rid] = (fut, path)
        try:
            write_spill_record(path, a, meta)
        except Exception:
            with self._lock:
                self._pending.pop(rid, None)
            raise
        return fut

    def alive(self) -> bool:
        return self.process.poll() is None

    def poll(self) -> None:
        """Resolve pending futures from the worker's outbox (results
        load bit-identical through ``ConsensusResult.load``; errors
        come back typed by name). Removes consumed outbox files."""
        try:
            names = os.listdir(self.outbox)
        except OSError:
            return
        for name in sorted(names):
            if name.startswith(RESULT_PREFIX) and name.endswith(".npz"):
                self._finish(name, error=False)
            elif name.startswith(ERROR_PREFIX) and name.endswith(".json"):
                self._finish(name, error=True)

    def _finish(self, name: str, error: bool) -> None:
        from nmfx.faults import warn_once

        rid = _rid_of(name)
        with self._lock:
            entry = self._pending.pop(rid, None)
        path = os.path.join(self.outbox, name)
        if entry is None:
            # a result for a request this router no longer owns (a
            # duplicate after failover, or another router's) — the
            # dedup half of at-most-once delivery: consume and drop
            try:
                os.unlink(path)
            except OSError:  # nmfx: ignore[NMFX006] -- raced consumer
                pass
            return
        fut, record = entry
        try:
            if error:
                with open(path) as f:
                    payload = json.load(f)
                exc = _typed_error(payload)
                if not fut.done():
                    fut.set_exception(exc)
            else:
                from nmfx.api import ConsensusResult

                result = ConsensusResult.load(path)
                if not fut.done():
                    fut.set_result(result)
        except Exception as e:
            # a transiently unreadable outbox file (fd pressure, a
            # flaky network filesystem): put the request BACK in
            # pending and leave both files in place — the next poll
            # tick retries the read. Only a PERSISTENTLY unreadable
            # file (several consecutive polls) fails the future typed;
            # destroying an intact result over one transient read
            # error would lose completed work
            with self._lock:
                n = self._read_failures.get(rid, 0) + 1
                self._read_failures[rid] = n
                if n < 5:
                    self._pending[rid] = (fut, record)
            if n < 5:
                return
            warn_once("replica-outbox-torn",
                      f"outbox file {path!r} unreadable on {n} "
                      f"consecutive polls ({e!r}); failing the "
                      "request typed rather than hanging")
            if not fut.done():
                fut.set_exception(ReplicaError(
                    f"replica {self.replica_id}: unreadable result "
                    f"for request {rid} ({e!r})"))
        with self._lock:
            self._read_failures.pop(rid, None)
        for p in (path, record):
            try:
                os.unlink(p)
            except OSError:  # nmfx: ignore[NMFX006] -- already gone
                pass         # (worker removed the record first)

    def pending(self) -> "dict[str, tuple[Future, str]]":
        with self._lock:
            return dict(self._pending)

    def forget(self, rid: str) -> None:
        with self._lock:
            self._pending.pop(rid, None)

    def drain(self) -> None:
        """Graceful scale-down: SIGTERM — the worker stops claiming,
        lets in-flight work finish (results still land in the outbox),
        and releases the claims of queued records so the router (or a
        survivor) reclaims them."""
        self.state = "draining"
        if self.alive():
            self.process.terminate()

    def retire(self) -> None:
        """Nothing to stop router-side — the worker owns its beater
        and it died (or will die) with the process (uniform surface
        with :class:`ThreadReplica`)."""

    def kill(self) -> None:
        """SIGKILL — the chaos path. The state is left untouched on
        purpose: an externally killed worker looks exactly like this,
        and the router's health checker must DISCOVER the death
        (``alive()`` goes false) and recover — unfinished inbox
        records survive under the dead pid's claims for recovery to
        break."""
        self.process.kill()

    def close(self, timeout: float = 30.0) -> None:
        if self.alive():
            self.process.terminate()
        try:
            self.process.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait()
        self.state = "dead"


@guarded_by("_lock", "replicas", "_device_cursor")
class ReplicaPool:
    """N replicas sharing one pool root + heartbeat ledger.

    ``mode="thread"`` builds :class:`ThreadReplica` members (tests,
    bench scaling, multi-device single-process); ``mode="process"``
    spawns subprocess workers (the production shape — pass
    ``cache_dir`` so spawns land on the warm executable cache).
    ``engine_factory`` (thread mode) builds each replica's
    ``nmfx.serve.Engine`` — the hook the router test-suite uses to run
    the whole tier against scriptable fakes.

    ``mesh_specs`` (ISSUE 19) makes the fleet HETEROGENEOUS: one spec
    per replica (None = a plain 1-device replica), so one pool holds
    1-chip and 8-chip members behind one router. In thread mode each
    meshed member is carved a CONTIGUOUS block of ``jax.devices()``
    (no two meshed replicas alias a chip); in process mode the spec
    travels to the worker as ``--mesh-spec`` (each subprocess owns its
    own runtime, so carving is the deployment's concern)."""

    def __init__(self, replicas: int = 2, *, root: str,
                 mode: str = "thread", serve_cfg=None,
                 exec_cache=None, engine_factory=None,
                 cache_dir: "str | None" = None,
                 telemetry_dir: "str | None" = None,
                 mesh_specs=None,
                 heartbeat_interval_s: float = 0.5,
                 worker_args: "tuple[str, ...]" = (),
                 env: "dict | None" = None):
        from nmfx.obs.export import HeartbeatLedger

        if mode not in ("thread", "process"):
            raise ValueError(f"unknown replica mode {mode!r}")
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        if mode == "process" and engine_factory is not None:
            raise ValueError("engine_factory is a thread-mode hook")
        if mesh_specs is not None:
            mesh_specs = tuple(mesh_specs)
            if len(mesh_specs) != replicas:
                raise ValueError(
                    f"mesh_specs has {len(mesh_specs)} entries for "
                    f"{replicas} replicas — pass one spec (or None) "
                    "per replica")
            from nmfx.distributed import parse_mesh_spec

            for spec in mesh_specs:
                if spec is not None:
                    parse_mesh_spec(spec)  # raises MeshSpecError
        os.makedirs(root, exist_ok=True)
        self.root = root
        self.mode = mode
        self.serve_cfg = serve_cfg
        self.exec_cache = exec_cache
        self.engine_factory = engine_factory
        self.cache_dir = cache_dir
        self.telemetry_dir = telemetry_dir
        self.heartbeat_interval_s = heartbeat_interval_s
        self.worker_args = tuple(worker_args)
        self.env = env
        self.ledger = HeartbeatLedger(root, prefix=HEARTBEAT_PREFIX)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        #: next unclaimed jax.devices() index for thread-mode mesh
        #: carving (plain replicas never advance it — they share the
        #: default device, today's behavior)
        self._device_cursor = 0
        self.replicas: "dict[str, object]" = {}
        for i in range(replicas):
            self.spawn(mesh_spec=None if mesh_specs is None
                       else mesh_specs[i])

    def _sync_gauge(self) -> None:
        states: "dict[str, int]" = {}
        for rep in self.replicas.values():
            states[rep.state] = states.get(rep.state, 0) + 1
        for state in ("routable", "draining", "dead"):
            _replicas_gauge.set(states.get(state, 0), state=state)

    def _carve_devices(self, mesh_spec: str) -> list:
        """Claim the next contiguous ``jax.devices()`` block for one
        meshed thread replica (the HPC-NMF processor-grid discipline:
        a replica's sub-mesh is a fixed partition of the fleet, never
        an overlapping view)."""
        import jax

        from nmfx.distributed import parse_mesh_spec

        r, f, s = parse_mesh_spec(mesh_spec)
        need = r * f * s
        devs = jax.devices()
        with self._lock:
            lo = self._device_cursor
            if lo + need > len(devs):
                raise SpawnFailed(
                    f"mesh_spec {mesh_spec!r} needs {need} devices but "
                    f"only {len(devs) - lo} of {len(devs)} remain "
                    "unclaimed by earlier meshed replicas")
            self._device_cursor = lo + need
        return devs[lo:lo + need]

    def spawn(self, mesh_spec: "str | None" = None):
        """Scale-up: one new replica against the (warm) cache. Passes
        the ``replica.spawn`` chaos site; a failure raises
        :class:`SpawnFailed` — the caller (the router's autoscaler)
        degrades warn-once and keeps the current fleet. A
        ``mesh_spec`` spawns a MESH member (see the class docstring);
        the autoscaler's bare ``spawn()`` keeps adding 1-device
        replicas."""
        from nmfx import faults

        rid = f"replica-{os.getpid()}-{next(self._seq)}"
        root = os.path.join(self.root, rid)
        try:
            faults.inject("replica.spawn")
            if self.mode == "thread":
                engine = (self.engine_factory()
                          if self.engine_factory is not None else None)
                devices = None
                if mesh_spec is not None and engine is None:
                    devices = self._carve_devices(mesh_spec)
                rep = ThreadReplica(
                    rid, root, self.ledger, serve_cfg=self.serve_cfg,
                    engine=engine, exec_cache=self.exec_cache,
                    mesh_spec=mesh_spec, devices=devices,
                    telemetry_dir=self.telemetry_dir,
                    heartbeat_interval_s=self.heartbeat_interval_s)
            else:
                rep = ProcessReplica(
                    rid, root, self.ledger, cache_dir=self.cache_dir,
                    telemetry_dir=self.telemetry_dir,
                    mesh_spec=mesh_spec,
                    heartbeat_interval_s=self.heartbeat_interval_s,
                    worker_args=self.worker_args, env=self.env)
        except faults.FaultInjected as e:
            raise SpawnFailed(f"replica spawn failed: {e}") from e
        except OSError as e:
            raise SpawnFailed(f"replica spawn failed: {e!r}") from e
        with self._lock:
            self.replicas[rid] = rep
            self._sync_gauge()
        _flight.record("replica.spawned", replica=rid, mode=self.mode)
        return rep

    def routable(self) -> list:
        """Replicas the router may place on, in a stable order."""
        with self._lock:
            return [rep for _, rep in sorted(self.replicas.items())
                    if rep.state == "routable"]

    def all(self) -> list:
        """Every pool member, snapshotted under the pool lock — the
        iteration surface for threads racing spawn()/remove() (a bare
        ``replicas.values()`` walk can see the dict resize)."""
        with self._lock:
            return [rep for _, rep in sorted(self.replicas.items())]

    def get(self, replica_id: str):
        with self._lock:
            return self.replicas.get(replica_id)

    def remove(self, replica_id: str) -> None:
        """Forget a dead/drained replica (its heartbeat file remains,
        aging into staleness — history, like a dead instance's
        counters in the fleet view)."""
        with self._lock:
            self.replicas.pop(replica_id, None)
            self._sync_gauge()

    def heartbeats(self, stale_after_s: "float | None" = None) -> dict:
        """``{replica_id: payload}`` from the shared ledger (with
        ``stale``/``age_s`` when ``stale_after_s`` is given) — what
        the router's health checker reads."""
        return self.ledger.status(stale_after_s)

    def poll(self) -> None:
        # snapshot under the pool lock: a bare replicas.values() walk
        # races spawn()/remove() resizing the dict mid-iteration
        for rep in self.all():
            rep.poll()

    def close(self) -> None:
        with self._lock:
            reps = list(self.replicas.values())
        for rep in reps:
            rep.close()
        with self._lock:
            self._sync_gauge()


def _typed_error(payload: dict):
    """Reconstruct a typed exception from a worker's error file —
    known serving/fault types come back as themselves so a caller's
    ``except DeadlineExceeded`` works across the process boundary;
    unknown types wrap in :class:`ReplicaError`."""
    from nmfx import faults as faults_mod
    from nmfx import serve as serve_mod

    name = str(payload.get("type", ""))
    msg = str(payload.get("message", ""))
    for mod in (serve_mod, faults_mod):
        cls = getattr(mod, name, None)
        if isinstance(cls, type) and issubclass(cls, BaseException):
            try:
                return cls(msg)
            except Exception:  # nmfx: ignore[NMFX006] -- falls through
                break          # to the generic wrapper below
    return ReplicaError(f"{name or 'error'}: {msg}")


# --------------------------------------------------------------------------
# the subprocess worker (python -m nmfx.replica)
# --------------------------------------------------------------------------

def _write_error(outbox: str, rid: str, exc: BaseException) -> None:
    path = os.path.join(outbox, f"{ERROR_PREFIX}{rid}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump({"rid": rid, "type": exc.__class__.__name__,
                       "message": str(exc)}, f)
        os.replace(tmp, path)
    except OSError:  # nmfx: ignore[NMFX006] -- the router's forward
        pass         # timeout turns a lost error file into a typed
        #              failure; never crash the worker loop over it


def _write_result(outbox: str, rid: str, result) -> None:
    path = os.path.join(outbox, f"{RESULT_PREFIX}{rid}.npz")
    tmp = os.path.join(outbox, f".tmp_{os.getpid()}_{rid}.npz")
    result.save(tmp)
    os.replace(tmp, path)


def worker_main(argv: "list[str] | None" = None) -> int:
    """The subprocess replica body: claim spill-format requests from
    ``<dir>/inbox``, serve each through a normal ``NMFXServer.submit``
    (results bit-identical to any other admission path — the
    ``spill_submit_kwargs`` funnel), write results/typed errors into
    ``<dir>/outbox``, heartbeat into the pool ledger, and on SIGTERM
    drain gracefully: stop claiming, finish in-flight work, release
    the claims of queued records so survivors reclaim them
    (spill-migration)."""
    import argparse
    import signal

    p = argparse.ArgumentParser(prog="nmfx.replica")
    p.add_argument("--dir", required=True)
    p.add_argument("--id", required=True)
    p.add_argument("--pool-dir", required=True)
    p.add_argument("--heartbeat-interval", type=float, default=0.5)
    p.add_argument("--poll-interval", type=float, default=0.05)
    p.add_argument("--cache-dir", default=None)
    p.add_argument("--telemetry-dir", default=None)
    p.add_argument("--max-queue-depth", type=int, default=64)
    p.add_argument("--mesh-spec", default=None)
    args = p.parse_args(argv)

    from nmfx.faults import warn_once
    from nmfx.obs.export import HeartbeatLedger
    from nmfx.serve import (NMFXServer, QueueFull, ServeConfig,
                            ServerClosed, claim_spill, list_spills,
                            load_spill_record, release_spill_claim,
                            spill_claimant, spill_dataset,
                            spill_submit_kwargs)

    inbox = os.path.join(args.dir, "inbox")
    outbox = os.path.join(args.dir, "outbox")
    os.makedirs(inbox, exist_ok=True)
    os.makedirs(outbox, exist_ok=True)
    exec_cache = None
    if args.cache_dir is not None and args.mesh_spec is None:
        from nmfx.config import ExecCacheConfig
        from nmfx.exec_cache import ExecCache

        exec_cache = ExecCache(ExecCacheConfig(cache_dir=args.cache_dir))
    n_devices = 1
    if args.mesh_spec is not None:
        from nmfx.distributed import parse_mesh_spec

        r_sh, f_sh, s_sh = parse_mesh_spec(args.mesh_spec)
        n_devices = r_sh * f_sh * s_sh
    server = NMFXServer(
        ServeConfig(role="replica", instance=args.id,
                    max_queue_depth=args.max_queue_depth,
                    telemetry_dir=args.telemetry_dir,
                    mesh_spec=args.mesh_spec),
        exec_cache=exec_cache)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    inflight_lock = threading.Lock()
    inflight: "set[str]" = set()

    def status() -> dict:
        s = server.stats()
        return {"role": "replica", "kind": "process",
                "state": "draining" if stop.is_set() else "routable",
                "queue_depth": s["queued"], "inflight": s["inflight"],
                "mesh": args.mesh_spec, "devices": n_devices}

    ledger = HeartbeatLedger(args.pool_dir, prefix=HEARTBEAT_PREFIX)
    beater = _Beater(ledger, args.id, status,
                     args.heartbeat_interval).launch()

    def finish(path: str, rid: str, fut) -> None:
        exc = fut.exception()
        if isinstance(exc, ServerClosed):
            # drained before dispatch: hand the record back for a
            # survivor (or the router) to reclaim — spill-migration
            release_spill_claim(path)
        else:
            if exc is not None:
                _write_error(outbox, rid, exc)
            else:
                _write_result(outbox, rid, fut.result())
            # result first, record second: a crash between the two
            # leaves BOTH, and recovery dedups on the result file
            try:
                os.unlink(path)
            except OSError:  # nmfx: ignore[NMFX006] -- already gone
                pass
            release_spill_claim(path)
        with inflight_lock:
            inflight.discard(rid)

    while not stop.is_set():
        for path in list_spills(inbox):
            if stop.is_set():
                break
            rid = _rid_of(path)
            with inflight_lock:
                if rid in inflight:
                    continue
            if os.path.exists(os.path.join(
                    outbox, f"{RESULT_PREFIX}{rid}.npz")):
                # crash-leftover: the result already landed — consume
                # the record instead of recomputing it
                try:
                    os.unlink(path)
                except OSError:  # nmfx: ignore[NMFX006] -- raced
                    pass
                release_spill_claim(path)
                continue
            if spill_claimant(path) is not None:
                continue
            if not claim_spill(path, args.id):
                continue
            try:
                a, meta = load_spill_record(path)
                fut = server.submit(spill_dataset(a, meta),
                                    **spill_submit_kwargs(meta))
            except QueueFull:
                release_spill_claim(path)  # admission reopens later
                break
            except Exception as e:
                # a torn record cannot be served by ANYONE — answer
                # typed instead of leaving the router to time out
                warn_once("replica-inbox-torn",
                          f"inbox record {path!r} unreadable ({e!r}); "
                          "answering with a typed error")
                _write_error(outbox, rid, e)
                try:
                    os.unlink(path)
                except OSError:  # nmfx: ignore[NMFX006] -- raced
                    pass
                release_spill_claim(path)
                continue
            with inflight_lock:
                inflight.add(rid)
            fut.add_done_callback(
                lambda f, p=path, r=rid: finish(p, r, f))
        stop.wait(args.poll_interval)
    # graceful drain: queued requests fail ServerClosed (their claims
    # are released by finish()), in-flight requests complete and land
    # in the outbox before the server joins its workers
    server.close(cancel_pending=True)
    beater.close(final_status=dict(status(), state="dead"))
    return 0


if __name__ == "__main__":
    sys.exit(worker_main())
