"""nmfx — TPU-native consensus NMF.

A brand-new JAX/XLA framework with the capabilities of mschubert/NMFconsensus
(reference layer map in /root/repo/SURVEY.md): randomly-restarted non-negative
matrix factorization (mu / als / neals / pg / alspg solvers plus the BROAD
original's Brunet kl rule, random or NNDSVD init), connectivity/consensus
aggregation across restarts, and rank selection by cophenetic correlation —
with the restart axis packed into MXU-dense GEMM batches, the sweep sharded
over a TPU device mesh (up to restarts × features × samples), and consensus
accumulation kept on-device.
"""

from nmfx.config import (
    ConsensusConfig,
    InitConfig,
    OutputConfig,
    SolverConfig,
)
from nmfx.io import read_dataset, read_gct, read_res, write_gct
from nmfx.api import ConsensusResult, nmf, nmfconsensus, run_example
from nmfx.sweep import default_mesh, feature_mesh, grid_mesh

from nmfx.config import VERSION as __version__

__all__ = [
    "ConsensusConfig",
    "ConsensusResult",
    "InitConfig",
    "OutputConfig",
    "SolverConfig",
    "default_mesh",
    "feature_mesh",
    "grid_mesh",
    "nmf",
    "nmfconsensus",
    "read_dataset",
    "run_example",
    "read_gct",
    "read_res",
    "write_gct",
]
