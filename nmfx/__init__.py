"""nmfx — TPU-native consensus NMF.

A brand-new JAX/XLA framework with the capabilities of mschubert/NMFconsensus
(reference layer map in /root/repo/SURVEY.md): randomly-restarted non-negative
matrix factorization (mu / als / neals / pg / alspg solvers plus the BROAD
original's Brunet kl rule, random or NNDSVD init), connectivity/consensus
aggregation across restarts, and rank selection by cophenetic correlation —
with the restart axis packed into MXU-dense GEMM batches, the sweep sharded
over a TPU device mesh (up to restarts × features × samples), and consensus
accumulation kept on-device.
"""

from nmfx.config import (
    CheckpointConfig,
    ConsensusConfig,
    ExecCacheConfig,
    ExperimentalConfig,
    InitConfig,
    OutputConfig,
    SketchConfig,
    SolverConfig,
)
from nmfx.agreement import (
    adjusted_rand_index,
    consensus_agreement,
    cophenetic_gap,
    membership_agreement,
)
from nmfx.exec_cache import ExecCache
from nmfx.io import read_dataset, read_gct, read_res, write_gct
from nmfx.api import (
    ConsensusResult,
    nmf,
    nmfconsensus,
    restart_factors,
    run_example,
)
from nmfx.sweep import (
    RestartResult,
    consensus_from_cells,
    default_mesh,
    feature_mesh,
    grid_cells,
    grid_mesh,
    reduce_grid,
)

from nmfx.config import VERSION as __version__

__all__ = [
    "CheckpointConfig",
    "ConsensusConfig",
    "ExperimentalConfig",
    "ConsensusResult",
    "ExecCache",
    "ExecCacheConfig",
    "InitConfig",
    "OutputConfig",
    "RestartResult",
    "SketchConfig",
    "SolverConfig",
    "adjusted_rand_index",
    "consensus_agreement",
    "consensus_from_cells",
    "cophenetic_gap",
    "membership_agreement",
    "default_mesh",
    "feature_mesh",
    "grid_cells",
    "grid_mesh",
    "nmf",
    "nmfconsensus",
    "read_dataset",
    "reduce_grid",
    "restart_factors",
    "run_example",
    "read_gct",
    "read_res",
    "write_gct",
]
