"""Phase timing and device tracing.

The reference instruments by recompiling: ``PROFILE_*`` macros (all shipped
commented out, reference ``libnmf/include/common.h:27-45``) bracket each C
routine with ``gettimeofday`` and print µs via ``outputTiming`` (reference
``libnmf/outputtiming.c:27-35``); the R layer has only ``system.time``
(reference ``test_nmf.r:27``). Here profiling is a runtime flag:

* ``Profiler.phase(name)`` — wall-clock per pipeline phase, with
  ``jax.block_until_ready`` on whatever the phase returns so async dispatch
  can't hide device time in a later phase.
* ``Profiler(trace_dir=...)`` — additionally captures a ``jax.profiler``
  device trace (XLA op-level, viewable in TensorBoard/Perfetto) for the
  wrapped region.

Enabled from the CLI with ``--profile [--trace-dir D]``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any

import jax


class PhaseRecord:
    __slots__ = ("name", "seconds", "count")

    def __init__(self, name: str):
        self.name = name
        self.seconds = 0.0
        self.count = 0


class Profiler:
    """Accumulates per-phase wall-clock; optionally wraps a device trace."""

    def __init__(self, trace_dir: str | None = None):
        self.trace_dir = trace_dir
        self.phases: dict[str, PhaseRecord] = {}
        self._t0: float | None = None
        self._t_total: float | None = None

    # -- region ------------------------------------------------------------
    def __enter__(self) -> "Profiler":
        self._t0 = time.perf_counter()
        if self.trace_dir is not None:
            jax.profiler.start_trace(self.trace_dir)
        return self

    def __exit__(self, *exc) -> None:
        if self.trace_dir is not None:
            jax.profiler.stop_trace()
        self._t_total = time.perf_counter() - self._t0

    # -- phases ------------------------------------------------------------
    @contextlib.contextmanager
    def phase(self, name: str):
        """Time one phase; call the yielded function on the phase's result
        (or any array pytree) to block on device completion before the
        timer stops — otherwise JAX's async dispatch attributes device time
        to whichever later phase first touches the values."""
        rec = self.phases.setdefault(name, PhaseRecord(name))
        sync_target: list[Any] = []

        def sync(x):
            sync_target.append(x)
            return x

        t0 = time.perf_counter()
        try:
            yield sync
        finally:
            for x in sync_target:
                jax.block_until_ready(x)
            rec.seconds += time.perf_counter() - t0
            rec.count += 1

    def mark(self, name: str) -> None:
        """Record an instantaneous event as a zero-duration phase
        occurrence — the count column is the payload (e.g. the serving
        layer's ``compile.cache_hit``/``compile.persist_hit``/
        ``compile.persist_miss`` marks, where the whole point is that no
        — or only deserialization — time was spent)."""
        self.phases.setdefault(name, PhaseRecord(name)).count += 1

    def add_seconds(self, name: str, seconds: float, count: int = 1) -> None:
        """Credit externally-measured wall time to a phase. For work timed
        off-thread — the serving layer's per-rank compile spans
        (``compile.k=<k>``) run inside pool threads, where this
        profiler's single-threaded ``phase`` bookkeeping must not be
        touched — the coordinating thread records the measured seconds
        here after the fact."""
        rec = self.phases.setdefault(name, PhaseRecord(name))
        rec.seconds += seconds
        rec.count += count

    # -- reporting ---------------------------------------------------------
    def total_seconds(self) -> float:
        if self._t_total is not None:
            return self._t_total
        return sum(r.seconds for r in self.phases.values())

    def report(self) -> str:
        total = self.total_seconds()
        lines = [f"{'phase':<28}{'calls':>6}{'seconds':>10}{'share':>8}"]
        for rec in sorted(self.phases.values(), key=lambda r: -r.seconds):
            share = rec.seconds / total if total > 0 else 0.0
            lines.append(f"{rec.name:<28}{rec.count:>6}{rec.seconds:>10.3f}"
                         f"{share:>7.1%}")
        lines.append(f"{'total':<28}{'':>6}{total:>10.3f}{'':>8}")
        if self.trace_dir is not None:
            lines.append(f"device trace written to {self.trace_dir} "
                         "(tensorboard --logdir, or load in Perfetto)")
        return "\n".join(lines)


class NullProfiler(Profiler):
    """No-op drop-in so call sites need no ``if profiler`` branching."""

    def __enter__(self) -> "NullProfiler":
        return self

    def __exit__(self, *exc) -> None:
        pass

    @contextlib.contextmanager
    def phase(self, name: str):
        yield lambda x: x

    def mark(self, name: str) -> None:
        pass

    def add_seconds(self, name: str, seconds: float, count: int = 1) -> None:
        pass

    def report(self) -> str:
        return "profiling disabled"
