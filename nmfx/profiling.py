"""Phase timing and device tracing.

The reference instruments by recompiling: ``PROFILE_*`` macros (all shipped
commented out, reference ``libnmf/include/common.h:27-45``) bracket each C
routine with ``gettimeofday`` and print µs via ``outputTiming`` (reference
``libnmf/outputtiming.c:27-35``); the R layer has only ``system.time``
(reference ``test_nmf.r:27``). Here profiling is a runtime flag:

* ``Profiler.phase(name)`` — wall-clock per pipeline phase, with
  ``jax.block_until_ready`` on whatever the phase returns so async dispatch
  can't hide device time in a later phase.
* ``Profiler(trace_dir=...)`` — additionally captures a ``jax.profiler``
  device trace (XLA op-level, viewable in TensorBoard/Perfetto) for the
  wrapped region.

Enabled from the CLI with ``--profile [--trace-dir D]``.

Thread-safety: ``phase``/``mark``/``add_seconds`` may be called
concurrently — the streaming harvest workers (``nmfx/harvest.py``)
record their device→host and rank-selection walls from worker threads
while the main thread times solve phases. All ``phases`` mutation is
lock-guarded, so concurrent recording neither drops nor double-counts
time (tests/test_profiling.py pins this).

Tracer integration (ISSUE 10): the profiler is the AGGREGATING view
over the structured tracer (``nmfx.obs.trace``) — every recording
funnels through :meth:`Profiler.add_seconds`, which both accumulates
the per-phase books kept here (``report``/``audit`` semantics
unchanged) and, while the process-wide tracer is enabled, books the
same interval as a timestamped span on the recording THREAD's
timeline (retroactive ``Tracer.complete`` — start back-computed from
the measured duration, so worker-thread phases nest correctly in the
exported Chrome trace). ``NullProfiler`` stays a no-op for the books
but keeps the tracer emission, so a served request traces fully even
where no profiler was passed. While the tracer is disabled the extra
cost is one attribute read per recording.

Overlap accounting: phases whose names start with an
``OVERLAP_PREFIXES`` prefix (``xfer.``, ``post.``) record work that runs
CONCURRENTLY with the main-thread pipeline — async transfer dispatch,
worker-thread harvests. :meth:`Profiler.audit` therefore splits the
books in two: the sequential phase sum (which must track the wall — the
phase-sum-vs-wall audit that keeps hidden async time from silently
migrating between phases) and the overlapped seconds, reported as an
overlap ratio against the wall.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any

import jax

from nmfx.obs import trace as _trace

#: phase-name prefixes recorded as OVERLAPPED work: async-transfer
#: bookkeeping (``xfer.``) and post-solve host work streamed through
#: harvest worker threads (``post.``). These run concurrently with the
#: sequential pipeline phases, so the audit keeps them out of the
#: phase-sum-vs-wall reconciliation and reports them as overlap instead
OVERLAP_PREFIXES = ("xfer.", "post.")


class PhaseRecord:
    __slots__ = ("name", "seconds", "count")

    def __init__(self, name: str):
        self.name = name
        self.seconds = 0.0
        self.count = 0

    @property
    def overlapped(self) -> bool:
        return self.name.startswith(OVERLAP_PREFIXES)


class Profiler:
    """Accumulates per-phase wall-clock; optionally wraps a device trace."""

    def __init__(self, trace_dir: str | None = None):
        self.trace_dir = trace_dir
        self.phases: dict[str, PhaseRecord] = {}
        self._lock = threading.Lock()
        self._t0: float | None = None
        self._t_total: float | None = None

    # -- region ------------------------------------------------------------
    def __enter__(self) -> "Profiler":
        self._t0 = time.perf_counter()
        if self.trace_dir is not None:
            jax.profiler.start_trace(self.trace_dir)
        return self

    def __exit__(self, *exc) -> None:
        if self.trace_dir is not None:
            jax.profiler.stop_trace()
        self._t_total = time.perf_counter() - self._t0

    # -- phases ------------------------------------------------------------
    @contextlib.contextmanager
    def phase(self, name: str):
        """Time one phase; call the yielded function on the phase's result
        (or any array pytree) to block on device completion before the
        timer stops — otherwise JAX's async dispatch attributes device time
        to whichever later phase first touches the values."""
        sync_target: list[Any] = []

        def sync(x):
            sync_target.append(x)
            return x

        t0 = time.perf_counter()
        try:
            yield sync
        finally:
            for x in sync_target:
                jax.block_until_ready(x)
            self.add_seconds(name, time.perf_counter() - t0)

    def mark(self, name: str) -> None:
        """Record an instantaneous event as a zero-duration phase
        occurrence — the count column is the payload (e.g. the serving
        layer's ``compile.cache_hit``/``compile.persist_hit``/
        ``compile.persist_miss`` marks, where the whole point is that no
        — or only deserialization — time was spent)."""
        self.add_seconds(name, 0.0)

    def add_seconds(self, name: str, seconds: float, count: int = 1) -> None:
        """Credit measured wall time to a phase — the one mutation point
        every recording entry (``phase``/``mark``/this) funnels through,
        and it is lock-guarded: harvest workers and compile pools record
        from their own threads concurrently with the main thread's
        phases, and the accumulation must neither drop nor double-count
        a contribution. Also books the interval on the structured
        tracer's timeline when tracing is enabled (see the module
        docstring)."""
        with self._lock:
            rec = self.phases.setdefault(name, PhaseRecord(name))
            rec.seconds += seconds
            rec.count += count
        _emit_span(name, seconds)

    # -- reporting ---------------------------------------------------------
    def total_seconds(self) -> float:
        if self._t_total is not None:
            return self._t_total
        with self._lock:  # workers may be inserting phases concurrently
            return sum(r.seconds for r in self.phases.values()
                       if not r.overlapped)

    def audit(self, wall_s: "float | None" = None) -> dict:
        """Phase-sum-vs-wall reconciliation + overlap summary.

        ``phase_sum_s`` is the SEQUENTIAL phases only (overlap-classed
        phases run concurrently with them, so including them would make
        the sum exceed the wall by design); ``coverage`` is how much of
        the wall those phases explain — the audit that keeps hidden
        async time from migrating between phases unaccounted (the
        round-5/r05 failure mode: host rank selection ran entirely
        outside the phase books). ``overlap_s``/``overlap_ratio`` report
        the work that ran behind the sequential pipeline — transfer
        dispatch and streamed harvests; a ratio near the non-solve share
        of the wall means the pipelining is actually hiding that work.

        Meaningful when the sequential phases are flat (non-nested) —
        true of the sweep/serving pipeline; compile-miss paths nest
        spans and are not audited.
        """
        if wall_s is None:
            wall_s = (self._t_total if self._t_total is not None
                      else self.total_seconds())
        with self._lock:
            seq = sum(r.seconds for r in self.phases.values()
                      if not r.overlapped)
            over = sum(r.seconds for r in self.phases.values()
                       if r.overlapped)
        cov = seq / wall_s if wall_s > 0 else 0.0
        return {"wall_s": round(wall_s, 3),
                "phase_sum_s": round(seq, 3),
                "unattributed_s": round(max(wall_s - seq, 0.0), 3),
                "coverage": round(cov, 3),
                "overlap_s": round(over, 3),
                "overlap_ratio": round(over / wall_s, 3)
                if wall_s > 0 else 0.0}

    def report(self) -> str:
        total = self.total_seconds()
        lines = [f"{'phase':<28}{'calls':>6}{'seconds':>10}{'share':>8}"]
        with self._lock:  # snapshot: workers may still insert phases
            recs = list(self.phases.values())
        for rec in sorted(recs, key=lambda r: -r.seconds):
            if rec.overlapped:
                # the denominator is the SEQUENTIAL sum: a share here
                # would be against a total this row is not part of
                # (and could exceed 100% with several workers)
                tag, share_txt = "~", f"{'-':>7}"
            else:
                share = rec.seconds / total if total > 0 else 0.0
                tag, share_txt = "", f"{share:>7.1%}"
            lines.append(f"{tag + rec.name:<28}{rec.count:>6}"
                         f"{rec.seconds:>10.3f}{share_txt}")
        lines.append(f"{'total':<28}{'':>6}{total:>10.3f}{'':>8}")
        a = self.audit()
        lines.append(f"(~ = overlapped with the phases above; "
                     f"{a['overlap_s']:.3f}s overlapped, ratio "
                     f"{a['overlap_ratio']:.0%} of wall)")
        # per-dispatch roofline attribution (ISSUE 13): the profiled
        # solve dispatches annotated themselves with model FLOPs/bytes
        # (sweep/exec_cache → nmfx.obs.costmodel); surface the verdict
        # table whenever any dispatch was attributed this process
        from nmfx.obs import costmodel as _costmodel

        if _costmodel.perf_summary()["kinds"]:
            lines.append(_costmodel.perf_report())
        if self.trace_dir is not None:
            lines.append(f"device trace written to {self.trace_dir} "
                         "(tensorboard --logdir, or load in Perfetto)")
        return "\n".join(lines)


def _emit_span(name: str, seconds: float) -> None:
    """Mirror one phase recording onto the structured tracer: a
    retroactive span for a measured interval, an instant event for a
    zero-duration mark. One enabled check while tracing is off."""
    tracer = _trace.default_tracer()
    if not tracer.enabled:
        return
    if seconds > 0.0:
        tracer.complete(name, seconds, cat="phase")
    else:
        tracer.instant(name, cat="phase")


class NullProfiler(Profiler):
    """No-op drop-in so call sites need no ``if profiler`` branching.

    No-op for the per-phase BOOKS only: the structured-tracer emission
    is kept (enabled-gated, see ``_emit_span``), so the serving stack —
    which defaults to a NullProfiler per server/engine — still traces
    every phase of a request once ``nmfx.obs.trace`` is enabled. The
    phase() region is timed only while tracing is on; the sync callable
    stays a passthrough either way (a NullProfiler must never add
    device blocking the unprofiled path didn't have)."""

    def __enter__(self) -> "NullProfiler":
        return self

    def __exit__(self, *exc) -> None:
        pass

    @contextlib.contextmanager
    def phase(self, name: str):
        tracer = _trace.default_tracer()
        if not tracer.enabled:
            yield lambda x: x
            return
        with tracer.span(name, cat="phase"):
            yield lambda x: x

    def mark(self, name: str) -> None:
        _emit_span(name, 0.0)

    def add_seconds(self, name: str, seconds: float, count: int = 1) -> None:
        _emit_span(name, seconds)

    def report(self) -> str:
        return "profiling disabled"
