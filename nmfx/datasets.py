"""Synthetic dataset generators for tests and benchmarks.

Stands in for the reference's fixture factory (OCplus ``MAsim.smyth`` shifted
positive, reference ``test_nmf.r:1-3`` / ``nmf.r:7-9``) and its bundled
two-group GCT (``20+20x1000.gct``: 1000 genes x 40 samples, 20+20 design).
"""

from __future__ import annotations

import numpy as np


def two_group_matrix(
    n_genes: int = 1000,
    n_per_group: int = 20,
    frac_de: float = 0.2,
    effect: float = 2.0,
    noise: float = 0.5,
    seed: int = 0,
) -> np.ndarray:
    """Non-negative (genes x samples) matrix with two sample groups.

    A fraction ``frac_de`` of genes is differentially expressed between the
    groups; everything is shifted positive the way the reference preprocesses
    its simulated data (``A = (A - min(A) + runif(1,0,1))/10``, nmf.r:9).
    """
    rng = np.random.default_rng(seed)
    n = 2 * n_per_group
    base = rng.normal(5.0, 1.0, size=(n_genes, 1))
    a = base + rng.normal(0.0, noise, size=(n_genes, n))
    n_de = int(frac_de * n_genes)
    de_idx = rng.choice(n_genes, size=n_de, replace=False)
    signs = rng.choice([-1.0, 1.0], size=n_de)
    a[de_idx, n_per_group:] += signs[:, None] * effect
    a = (a - a.min() + rng.uniform(0, 1)) / 10.0
    return np.ascontiguousarray(a)


def grouped_matrix(
    n_genes: int,
    group_sizes: tuple[int, ...],
    effect: float = 2.0,
    noise: float = 0.5,
    seed: int = 0,
) -> np.ndarray:
    """Non-negative matrix with an arbitrary number of sample groups, each
    marked by its own block of upregulated genes. Used for rank-selection
    tests (cophenetic rho should peak at len(group_sizes))."""
    rng = np.random.default_rng(seed)
    n = sum(group_sizes)
    g = len(group_sizes)
    a = rng.normal(5.0, noise, size=(n_genes, n))
    block = n_genes // g
    col = 0
    for gi, size in enumerate(group_sizes):
        rows = slice(gi * block, (gi + 1) * block)
        a[rows, col : col + size] += effect
        col += size
    a = (a - a.min() + rng.uniform(0, 1)) / 10.0
    return np.ascontiguousarray(a)


def make_sparse_design(
    m: int,
    n: int,
    k: int,
    density: float = 0.05,
    seed: int = 0,
):
    """Planted sparse factorizable matrix (ISSUE 17): a non-negative
    rank-``k`` product W·H with block-structured factors, thinned by an
    independent Bernoulli(``density``) mask — the scRNA-count shape the
    sparse ingestion path exists for (>90% exact zeros, yet a planted
    k-group structure a consensus solve should recover). Returns a
    :class:`nmfx.sparse.SparseMatrix`; densify with ``.toarray()`` for
    the sparse≡densified agreement gates.

    The realized nnz is Binomial(m·n, density), so ``.density`` tracks
    the requested density up to sampling noise rather than matching it
    exactly."""
    from nmfx.sparse import SparseMatrix

    if not 0.0 <= density <= 1.0:
        raise ValueError(f"density must be in [0, 1], got {density!r}")
    rng = np.random.default_rng(seed)
    # block-structured planted factors: each of the k components owns a
    # row block (features) and a column block (samples), plus a dense
    # low-level background so every row/column has support to plant in
    w = rng.uniform(0.05, 0.3, size=(m, k))
    h = rng.uniform(0.05, 0.3, size=(k, n))
    for j in range(k):
        w[(m * j) // k:(m * (j + 1)) // k, j] += rng.uniform(
            2.0, 4.0, size=(m * (j + 1)) // k - (m * j) // k)
        h[j, (n * j) // k:(n * (j + 1)) // k] += rng.uniform(
            2.0, 4.0, size=(n * (j + 1)) // k - (n * j) // k)
    mask = rng.random((m, n)) < density
    return SparseMatrix.from_dense((w @ h) * mask)
