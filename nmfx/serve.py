"""Multi-tenant serving engine: async request queue + continuous
cross-request restart batching.

Everything below the request level was already built — the persistent
AOT executable cache (``nmfx/exec_cache.py``), the device-resident
input cache (``nmfx/data_cache.py``), the streamed per-rank harvest
(``nmfx/harvest.py``), and lane-batched grid solvers with per-lane
masks and in-kernel budgets (``nmfx/ops/sched_mu.py``) — yet the repo
still served one sweep per process at a time. This module is the
missing front-end: many concurrent consensus jobs share one device
through an async request queue and a single scheduler thread that owns
dispatch.

The scheduler does **continuous restart batching** — the
token-level-batching analogue for consensus NMF: restarts from
*different* requests are packed into the same padded executable lanes
of one slot-scheduled dispatch (``sweep._build_packed_serve_fn``).
Each request's rank-k restart block becomes one lane group; the slot
scheduler solves every lane independently (per-lane masks, per-lane
in-kernel budgets), so a request's results are **bit-identical to its
solo run** — pinned by tests/test_serve.py the same way
streamed-vs-sequential harvest parity already is. Requests that cannot
share lanes (different matrices, NNDSVD init, non-cacheable configs,
deadline-budget-clamped solves) degrade gracefully to solo dispatch
through the same engine.

Layering::

    submit(A, ks, ...) ──► admission control ──► priority queue
                                                     │  scheduler thread
                                                     ▼
                                  compatibility grouping + lane packing
                                                     │
                     ┌───────────────────────────────┴─────────────┐
                     ▼ (≥2 compatible requests)                    ▼ (solo)
          _build_packed_serve_fn dispatch            ExecCache.run_sweep /
          (one executable, lanes from                sweep.sweep
           several requests)                                       │
                     └───────────────────────────────┬─────────────┘
                                                     ▼
                            completion workers: per-rank harvest
                            (``harvest.harvest_rank`` — the SAME body
                            the streamed pipeline runs) ──► Future

Admission control bounds the queue by depth AND by pending input bytes
(the matrices waiting to be placed); the priority queue orders by
(priority desc, deadline asc, arrival); a request whose deadline
expires while queued resolves to a typed :class:`DeadlineExceeded`
without ever dispatching, and one that would expire mid-solve is
dispatched solo with its per-lane iteration budget clamped from the
remaining deadline (``ServeConfig.iter_rate_estimate``) — eviction via
the in-kernel per-lane budget mechanism the grid solvers already
enforce, since a launched XLA dispatch cannot be interrupted.

Exactness contract: a packed request's lanes draw the canonical
per-(seed, k, restart) key chain and traverse the slot scheduler
independently of their dispatch-mates (batched GEMMs evaluate each lane
independently; zero-padding to a larger ``k_max`` adds exact-zero terms
only — the ``grid_mu`` invariant), so per-request results equal the
solo path bit-for-bit on the XLA engines. A deadline-clamped request is
exact against a solo run of the same clamped ``max_iter`` (recorded in
its :class:`RequestStats`). See docs/serving.md "Serving front-end".
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from typing import TYPE_CHECKING, Mapping, Protocol, Sequence

import numpy as np

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.guards import guarded_by
from nmfx.obs import costmodel as _costmodel
from nmfx.obs import flight as _flight
from nmfx.obs import metrics as _metrics
from nmfx.obs import trace as _trace

if TYPE_CHECKING:
    from nmfx.api import ConsensusResult
    from nmfx.sweep import KSweepOutput

__all__ = ["DeadlineExceeded", "Engine", "ExecCacheEngine", "NMFXServer",
           "QueueFull", "RequestFailed", "RequestStats", "ServeConfig",
           "ServeError", "ServerClosed", "ServerCrashed",
           "break_spill_claim", "claim_spill", "dispatch_count",
           "list_spills", "load_spill_record", "packed_dispatch_count",
           "packing_efficiency", "release_spill_claim",
           "serve_key_fields", "spill_claimant", "spill_dataset",
           "spill_meta", "spill_submit_kwargs", "verify_spill_claim",
           "write_spill_record"]


# --------------------------------------------------------------------------
# module counters — the honesty-counter discipline of
# exec_cache.compile_count() / data_cache.transfer_count(): the
# cross-request-packing contract is gated on these, not on log lines
# (tests/test_serve.py, bench.py traffic stage). Since ISSUE 10 the
# numbers live as labeled series on the process-wide metrics registry
# (nmfx.obs.metrics); dispatch_count()/packed_dispatch_count()/
# packing_efficiency() are the back-compat read shims the gated
# contracts keep using
_dispatch_total = _metrics.counter(
    "nmfx_serve_dispatches_total",
    "executable dispatches issued by serve schedulers",
    labelnames=("packed",))
_lanes_total = _metrics.counter(
    "nmfx_serve_lanes_total",
    "restart lanes dispatched by serve schedulers",
    labelnames=("packed",))
#: serve latency surfaces (docs/observability.md): streaming-quantile
#: histograms per request — queue residency, the dispatch step, the
#: device-blocked fetch, and submit→resolved end-to-end
_queue_wait_hist = _metrics.histogram(
    "nmfx_serve_queue_wait_seconds", "submit-to-dispatch queue residency")
_pack_hist = _metrics.histogram(
    "nmfx_serve_pack_seconds",
    "placement + lane packing + executable lookup + async dispatch")
_solve_hist = _metrics.histogram(
    "nmfx_serve_solve_seconds",
    "per-request device-blocked fetch wall (solve + queueing behind "
    "dispatch-mates)")
_e2e_hist = _metrics.histogram(
    "nmfx_serve_e2e_seconds",
    "submit-to-resolution request latency", labelnames=("outcome",))
#: quality-elastic degradations (ISSUE 12): requests the scheduler
#: served through the sketched engine instead of expiring (cause=
#: "deadline") or rejecting (cause="overload") — every increment has a
#: matching tagged result (ConsensusResult.quality == "sketched") and
#: a serve.quality_degraded flight event; never a silent downgrade
_quality_degraded_total = _metrics.counter(
    "nmfx_serve_quality_degraded_total",
    "requests degraded to the sketched engine by quality-elastic "
    "scheduling", labelnames=("cause",))
#: request-economics counter (ISSUE 16): also declared in
#: nmfx.result_cache — the registry's idempotent get-or-create hands
#: both sites one shared series
_coalesced_total = _metrics.counter(
    "nmfx_result_cache_coalesced_total",
    "requests attached as followers to an identical in-flight solve "
    "instead of dispatching their own", labelnames=("layer",))
#: level gauges for the fleet view (ISSUE 14): a router/autoscaler
#: reads per-replica queue depth and inflight load from the merged
#: telemetry, where gauges stay keyed by instance (nmfx.obs.aggregate)
_queue_depth_gauge = _metrics.gauge(
    "nmfx_serve_queue_depth",
    "requests queued but not yet dispatched (admission-bounded)")
_inflight_gauge = _metrics.gauge(
    "nmfx_serve_inflight",
    "requests dispatched but not yet resolved")
#: process-wide spill-record counter: per-SERVER request seqs restart
#: at 0, so a restarted server in the same process would overwrite an
#: earlier server's spill_{pid}_{seq}.npz — this counter keeps every
#: spill filename unique within the process (pid keeps it unique
#: across processes)
_spill_seq = itertools.count()


def dispatch_count() -> int:
    """Executable dispatches issued by serve schedulers in this process
    (packed and solo). Reads the registry counter
    ``nmfx_serve_dispatches_total`` summed over its ``packed`` label
    (back-compat shim)."""
    return int(_dispatch_total.total())


def packed_dispatch_count() -> int:
    """Dispatches that ACTUALLY contained lanes from >= 2 distinct
    requests — the counter the cross-request packing contract is gated
    on (a test asserting packing must watch this, not wall clocks)."""
    return int(_dispatch_total.value(packed="true"))


def packing_efficiency() -> "float | None":
    """Fraction of all dispatched lanes that rode a packed (multi-
    request) dispatch; None before the first dispatch."""
    series = _lanes_total.series()  # one atomic cut of both labels
    total = sum(series.values())
    if total == 0:
        return None
    return series.get(("true",), 0.0) / total


def _note_dispatch(n_requests: int, lanes: int) -> None:
    packed = "true" if n_requests >= 2 else "false"
    _dispatch_total.inc(packed=packed)
    _lanes_total.inc(lanes, packed=packed)


# --------------------------------------------------------------------------
# spill records + the claim protocol (ISSUE 15)
#
# A spill record is ONE request's full submission payload as an atomic
# npz (``spill_*.npz``: the matrix + a JSON meta blob) — written by a
# server spilling its queue on shutdown (``ServeConfig.spill_dir``), by
# a router forwarding to a subprocess replica (the record IS the
# forward), or by anything else that needs a request to survive a
# process. Re-admitting one through :func:`spill_submit_kwargs` +
# ``NMFXServer.submit`` reproduces the original submission
# field-for-field, so results are bit-identical by the serving
# exactness contract.
#
# The CLAIM protocol makes spill directories safe for MULTIPLE
# consumers (two routers recovering one dead replica, N survivor
# replicas draining one spill dir): a consumer must own
# ``<record>.claim`` before readmitting, created with O_CREAT|O_EXCL —
# the one atomic-exclusive primitive POSIX gives us (tmp+rename
# REPLACES silently, so it cannot express mutual exclusion). Exclusion
# is by existence; the claim's JSON payload (claimant, pid, time) is
# advisory context for breaking the claim of a consumer that died
# between claiming and readmitting (:func:`break_spill_claim`). The
# record and its claim are removed only after the re-admission
# SUCCEEDED, so a consumer crash at any point leaves either an
# unclaimed record (anyone readmits) or a stale claim (broken by pid
# or age), never a lost or double-readmitted request —
# tests/test_multiprocess.py races two OS processes over one spill dir
# to pin exactly-once re-admission.
# --------------------------------------------------------------------------

#: spill record filenames: spill_<unique>.npz (+ .claim while owned)
SPILL_PREFIX = "spill_"
_CLAIM_SUFFIX = ".claim"


def spill_meta(*, request_id, ks, restarts, seed, scfg, icfg,
               label_rule="argmax", linkage="average", grid_slots=48,
               grid_tail_slots="auto", min_restarts=1, priority=0,
               col_names=(), **extra) -> dict:
    """The JSON-serializable meta half of a spill record. ``extra``
    keys (e.g. a router's own request id) ride along verbatim and come
    back from :func:`load_spill_record`."""
    import os

    meta = {
        "request_id": request_id, "spill_pid": os.getpid(),
        "ks": [int(k) for k in ks], "restarts": int(restarts),
        "seed": int(seed), "label_rule": label_rule, "linkage": linkage,
        "grid_slots": int(grid_slots),
        "grid_tail_slots": (list(grid_tail_slots)
                            if isinstance(grid_tail_slots, (list, tuple))
                            else grid_tail_slots),
        "min_restarts": int(min_restarts), "priority": int(priority),
        "col_names": [str(c) for c in col_names],
        "solver_cfg": dataclasses.asdict(scfg),
        "init_cfg": dataclasses.asdict(icfg),
    }
    meta.update(extra)
    return meta


def write_spill_record(path: str, a: np.ndarray, meta: dict) -> str:
    """Atomically persist one spill record (tmp+rename via the
    checkpoint ledger's writer, which also passes the ``ckpt.write``
    chaos site)."""
    import json
    import os

    from nmfx.checkpoint import atomic_save_npz

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_save_npz(path, {"a": np.asarray(a),
                           "meta": np.asarray(json.dumps(meta))})
    return path


def load_spill_record(path: str) -> "tuple[np.ndarray, dict]":
    """Read one spill record back (raises on torn/corrupt — callers
    apply the ledger's skip-warn-once discipline). Passes the
    ``ckpt.load`` chaos site."""
    import json

    from nmfx import faults

    faults.inject("ckpt.load")
    with np.load(path, allow_pickle=False) as z:
        a = z["a"]
        meta = json.loads(str(z["meta"]))
    return a, meta


def spill_submit_kwargs(meta: dict) -> dict:
    """Reconstruct ``NMFXServer.submit`` keyword arguments from a spill
    record's meta — the ONE re-admission funnel ``readmit``, the
    router's failover path, and the subprocess replica worker all
    share, so a readmitted request is field-for-field the original
    submission no matter who readmits it."""
    from nmfx.config import ExperimentalConfig, SketchConfig

    solver = dict(meta["solver_cfg"])
    exp = solver.pop("experimental")
    # nested configs were asdict()-flattened at spill time; sketch may
    # be absent in pre-ISSUE-12 spill records
    sk = solver.pop("sketch", None)
    scfg = SolverConfig(**solver,
                        experimental=ExperimentalConfig(**exp),
                        sketch=(SketchConfig(**sk) if sk is not None
                                else SketchConfig()))
    icfg = InitConfig(**meta["init_cfg"])
    tail = meta["grid_tail_slots"]
    if isinstance(tail, list):
        tail = tuple(tail)
    return dict(ks=tuple(meta["ks"]), restarts=meta["restarts"],
                seed=meta["seed"], solver_cfg=scfg, init_cfg=icfg,
                label_rule=meta["label_rule"], linkage=meta["linkage"],
                grid_slots=meta["grid_slots"], grid_tail_slots=tail,
                min_restarts=meta["min_restarts"],
                priority=meta["priority"])


def spill_dataset(a: np.ndarray, meta: dict):
    """A Dataset carrying the spilled col_names back through submit's
    ``_as_matrix``, so the re-admitted result is field-for-field what
    the original submission would have delivered (row names were never
    retained by the request)."""
    from nmfx.io import Dataset

    names = [str(c) for c in meta["col_names"]]
    return Dataset(values=a,
                   row_names=[str(i + 1) for i in range(a.shape[0])],
                   col_names=names)


def list_spills(spill_dir: str) -> "list[str]":
    """The spill record paths in a directory, sorted (stable
    re-admission order across consumers)."""
    import os

    if not os.path.isdir(spill_dir):
        return []
    return [os.path.join(spill_dir, name)
            for name in sorted(os.listdir(spill_dir))
            if name.startswith(SPILL_PREFIX) and name.endswith(".npz")]


def claim_spill(path: str, claimant: str) -> bool:
    """Atomically claim one spill record for re-admission. True when
    THIS caller now owns it; False when another consumer already does.
    O_CREAT|O_EXCL on ``<path>.claim`` is the exclusion; the payload
    (claimant/pid/time) is advisory context for
    :func:`break_spill_claim`."""
    import json
    import os
    import time as _time

    try:
        fd = os.open(path + _CLAIM_SUFFIX,
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    try:
        os.write(fd, json.dumps({"claimant": claimant,
                                 "pid": os.getpid(),
                                 "time": _time.time()}).encode())
    finally:
        os.close(fd)
    return True


def spill_claimant(path: str) -> "dict | None":
    """The advisory claim payload of a spill record, or None when
    unclaimed (a torn claim payload reads as ``{}`` — the claim still
    excludes; only its context is gone)."""
    import json
    import os

    try:
        with open(path + _CLAIM_SUFFIX) as f:
            body = f.read()
    except OSError:
        return None
    try:
        payload = json.loads(body)
        return payload if isinstance(payload, dict) else {}
    except ValueError:
        return {}


def release_spill_claim(path: str) -> None:
    """Drop a claim (after re-admission, or to hand the record back —
    e.g. a draining replica releasing what it never started)."""
    import os

    try:
        os.unlink(path + _CLAIM_SUFFIX)
    except OSError:  # nmfx: ignore[NMFX006] -- already released/raced;
        pass         # exclusion is by existence, absence needs no cleanup


#: how long a ``.break`` marker may exist before it reads as a crashed
#: breaker (the marker is held for microseconds on the happy path)
_BREAK_MARKER_STALE_S = 60.0


def break_spill_claim(path: str, *, owner_pid: "int | None" = None,
                      older_than_s: "float | None" = None) -> bool:
    """Break another consumer's claim when its owner is known dead
    (``owner_pid`` matches the claim's pid — a router breaking a
    SIGKILLed replica's claims) or provably stale (``older_than_s``).
    Returns True when the record is claimable again.

    Breaking is serialized through an O_EXCL ``.break`` marker, and
    the staleness judgment happens UNDER the marker: a bare
    read-then-unlink would let breaker B (acting on a stale read of
    the OLD claim) delete breaker A's fresh re-claim, leaving both
    believing they own the record — the double-readmission the claim
    protocol exists to prevent. With the marker, exactly one breaker
    unlinks per claim generation, and a fresh re-claim is never
    judged by a stale read. A marker left by a crashed breaker is
    removed once it ages past ``_BREAK_MARKER_STALE_S`` (the caller
    retries on its next pass)."""
    import json
    import os
    import time as _time

    if spill_claimant(path) is None:
        return True  # never claimed
    marker = path + ".break"
    try:
        fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        # another breaker holds the marker; clean a crashed breaker's
        # leftover so a later pass can retry
        try:
            if _time.time() - os.stat(marker).st_mtime \
                    > _BREAK_MARKER_STALE_S:
                os.unlink(marker)
        except OSError:  # nmfx: ignore[NMFX006] -- marker already
            pass         # released by its (live) owner
        return False
    try:
        os.write(fd, json.dumps({"pid": os.getpid(),
                                 "time": _time.time()}).encode())
    finally:
        os.close(fd)
    try:
        # judged under the marker: re-read the CURRENT claim
        payload = spill_claimant(path)
        if payload is None:
            return True
        ok = False
        if owner_pid is not None and payload.get("pid") == owner_pid:
            ok = True
        if older_than_s is not None:
            t = payload.get("time")
            if not isinstance(t, (int, float)) \
                    or _time.time() - t > older_than_s:
                ok = True
        if not ok:
            return False
        try:
            os.unlink(path + _CLAIM_SUFFIX)
        except OSError:  # nmfx: ignore[NMFX006] -- claim released by
            pass         # its owner while we held the marker
        return True
    finally:
        try:
            os.unlink(marker)
        except OSError:  # nmfx: ignore[NMFX006] -- a cleaner judged
            pass         # our marker crashed-stale; harmless


def verify_spill_claim(path: str, claimant: str) -> bool:
    """Whether ``claimant`` currently holds the record's claim (a
    belt-and-braces re-check after winning a contested break)."""
    payload = spill_claimant(path)
    return payload is not None and payload.get("claimant") == claimant


# --------------------------------------------------------------------------
class ServeError(RuntimeError):
    """Base class of the serving engine's typed failures."""


class QueueFull(ServeError):
    """Admission control rejected the request (queue depth or pending
    input bytes over bound) — back off and resubmit."""


class ServerClosed(ServeError):
    """The server no longer accepts (or will not complete) requests."""


class DeadlineExceeded(ServeError, TimeoutError):
    """The request's deadline expired — while queued (never dispatched)
    or mid-solve (its lanes were stopped by the per-lane iteration
    budget; the computed results are discarded)."""


class RequestFailed(ServeError):
    """Every dispatch attempt for the request failed — the packed
    attempt (if any) and ``ServeConfig.dispatch_retries`` solo retries
    with exponential backoff. ``__cause__`` chains the last underlying
    failure; other requests in the same batch are unaffected (failure
    isolation is per-request)."""


class ServerCrashed(ServeError):
    """The scheduler thread died with this request pending — the
    watchdog resolved the future instead of leaving it hanging forever
    (``__cause__`` chains the exception that killed the scheduler).
    With ``ServeConfig.restart_scheduler`` the server keeps accepting
    NEW requests on a fresh scheduler; work pending at crash time is
    failed loudly, never replayed silently (at-most-once dispatch)."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-engine policy (``nmfx/serve.py``).

    Every field participates in ``__eq__``/``__hash__`` (frozen
    dataclass, no ``compare=False``) — the coverage
    :func:`serve_key_fields` declares and lint rule NMFX001 enforces,
    exactly like ``DataKey``/``SolverConfig``: the server's behavior
    contract is keyed by this config (tests and the bench traffic stage
    construct comparable servers from equal configs), so a field
    invisible to comparison would alias two different serving policies.
    """

    #: admission bound on requests queued but not yet dispatched;
    #: submit raises :class:`QueueFull` beyond it
    max_queue_depth: int = 64
    #: admission bound on the total host bytes of queued input matrices
    #: (they become device-resident at dispatch through the input
    #: cache); protects the placement path from unbounded buffering
    max_pending_bytes: int = 1 << 30
    #: pack lanes from at most this many requests into one dispatch
    max_batch_requests: int = 4
    #: cap on total lanes (Σ |ks|·restarts over the batch) per dispatch
    #: — bounds the packed executable's job batch the way grid_slots
    #: bounds its concurrent lanes
    max_batch_lanes: int = 1024
    #: enable cross-request lane packing (False = every request solo —
    #: the A/B baseline the packing-efficiency counter is read against)
    pack: bool = True
    #: after popping a packable request, linger this long for more
    #: compatible arrivals before dispatching — the classic continuous-
    #: batching knob (0 = dispatch immediately with whatever is queued)
    batch_linger_s: float = 0.0
    #: deadline applied to requests submitted without one (None = no
    #: implicit deadline)
    default_timeout_s: "float | None" = None
    #: estimated per-lane solver iterations per second, used to clamp a
    #: deadline request's per-lane iteration budget
    #: (``max_iter' = remaining_s * rate``, rounded up to a power-of-two
    #: multiple of check_every to bound executable churn). None = no
    #: mid-solve budget clamping; deadlines are then enforced at queue
    #: and completion boundaries only
    iter_rate_estimate: "float | None" = None
    #: completion worker threads (device→host fetch + host rank
    #: selection per finished request)
    harvest_workers: int = 2
    #: solo dispatch retries after a failed attempt (a failed PACKED
    #: dispatch always falls back to per-request solo first; these are
    #: the additional attempts each solo dispatch gets). Exhausting them
    #: resolves the future with a typed :class:`RequestFailed` whose
    #: cause chains the last failure
    dispatch_retries: int = 1
    #: base seconds of the exponential backoff between dispatch retries
    #: (attempt i sleeps ``retry_backoff_s * 2**i``)
    retry_backoff_s: float = 0.05
    #: scheduler-death policy: True (default) = the watchdog fails every
    #: request pending at crash time with :class:`ServerCrashed` and
    #: starts a fresh scheduler thread for subsequent submits; False =
    #: the server stays down (submits raise :class:`ServerCrashed`)
    restart_scheduler: bool = True
    #: watchdog poll interval: how often the monitor thread checks the
    #: scheduler's liveness/heartbeat (bounds crash-to-resolution
    #: latency)
    watchdog_interval_s: float = 0.25
    #: quality-elastic scheduling (ISSUE 12, docs/serving.md "Quality
    #: elasticity"): let the scheduler DEGRADE a request to the
    #: sketched engine (``backend="sketched"`` — the random-projection
    #: compressed solver, statistical accuracy contract) instead of
    #: failing it, in two situations: (a) a deadline that would clamp
    #: the exact solve's iteration budget (``iter_rate_estimate``)
    #: dispatches sketched at the full budget instead — cause
    #: "deadline"; (b) a submit that admission control would reject on
    #: queue DEPTH admits degraded while the depth stays under
    #: 2×``max_queue_depth`` — cause "overload" (the pending-bytes
    #: bound stays hard: it protects host memory, not latency). Only
    #: requests whose algorithm has a sketched form
    #: (``config.SKETCHED_ALGORITHMS``) and that did not opt into
    #: screening are eligible; everything else keeps today's
    #: expiry/rejection. A degraded result is ALWAYS typed and tagged:
    #: ``ConsensusResult.quality = "sketched"``,
    #: ``RequestStats.quality``/``degraded_cause``, the
    #: ``nmfx_serve_quality_degraded_total{cause=…}`` counter, and a
    #: ``serve.quality_degraded`` flight event.
    quality_elastic: bool = False
    #: request coalescing (ISSUE 16, docs/serving.md "Request
    #: economics"): concurrent IDENTICAL submissions — same
    #: content-addressed result key: input bytes, every
    #: result-affecting config field, seed, quality — attach as
    #: FOLLOWERS to the one in-flight leader solve instead of
    #: dispatching their own; followers share the leader's outcome
    #: (result, typed error, or degraded-and-tagged result) and are
    #: never left hanging (a cancelled leader promotes its first live
    #: follower into the queue). Only requests WITHOUT a deadline
    #: coalesce — attaching a deadline'd request to a solve that may
    #: outlive its budget would conflate two expiry semantics. Opt-in:
    #: deduplication changes dispatch-count observables that existing
    #: packing tests and A/B baselines key on.
    coalesce_requests: bool = False
    #: finished-result cache directory (ISSUE 16): with a directory
    #: (or a ``result_cache=`` instance passed to the server), a
    #: submission whose content-addressed result key is already stored
    #: resolves IMMEDIATELY from the cache — zero solve dispatches,
    #: zero host-to-device transfers (counter-gated) — and every
    #: harvested result is admitted back. None = no result caching
    #: (the default: serving stays solve-through).
    result_cache_dir: "str | None" = None
    #: spill-on-shutdown directory (docs/serving.md "Durability
    #: model"): ``close(cancel_pending=True)`` persists each queued-but-
    #: undispatched request's full submission payload here (atomic
    #: writes, the checkpoint ledger's discipline) before resolving its
    #: future with :class:`ServerClosed`, and a restarted server
    #: re-admits them with :meth:`NMFXServer.readmit` — results are
    #: bit-identical to direct submission (the serving exactness
    #: contract; absolute deadlines do not survive the restart and are
    #: dropped). None = shutdown discards queued requests (the
    #: pre-ISSUE-9 behavior).
    spill_dir: "str | None" = None
    #: fleet-telemetry ledger (ISSUE 14, docs/observability.md "Fleet
    #: telemetry"): with a directory, the server runs a
    #: ``TelemetryPublisher`` daemon writing atomic registry snapshots
    #: (+ instance identity and heartbeat) here every
    #: ``telemetry_interval_s``; a ``FleetCollector`` over the same
    #: directory merges N replicas into one fleet view. None = no
    #: publishing (the single-process default).
    telemetry_dir: "str | None" = None
    #: snapshot publish cadence for ``telemetry_dir``
    telemetry_interval_s: float = 2.0
    #: fleet identity (ISSUE 15): the role this server publishes under
    #: in telemetry snapshots and heartbeats — "server" standalone,
    #: "replica" when owned by a ``ReplicaPool`` behind an
    #: ``NMFXRouter`` (the fleet view and ``nmfx-top`` render the two
    #: distinctly; a router health-checks only rows it owns)
    role: str = "server"
    #: explicit telemetry instance name (None = the publisher's
    #: ``<role>-<host>-<pid>`` default; a replica pool names its
    #: members so heartbeats and snapshots join on one identity)
    instance: "str | None" = None
    #: with a port, serve the registry's Prometheus exposition over a
    #: stdlib HTTP endpoint (``nmfx.obs.export.serve_metrics``) for
    #: scraper-based deployments; 0 = ephemeral port (read it from
    #: ``NMFXServer.metrics_port``). None = no endpoint.
    metrics_port: "int | None" = None
    #: mesh tier (ISSUE 19, docs/serving.md "Mesh tier"): the device
    #: mesh this server solves over, as a ``distributed.parse_mesh_spec``
    #: string — "R" (restart-only), "RxF", or "RxFxS". None = the
    #: single-device engine stack (exec-cache, packing — today's
    #: behavior). A spec makes the server a MESH replica: dispatches run
    #: the grid-sharded sweep over ``build_replica_mesh(mesh_spec)``,
    #: the heartbeat advertises the device count, and the router prices
    #: atlas-shaped requests onto it. Participates in comparison like
    #: every field (two servers on different meshes are different
    #: serving policies).
    mesh_spec: "str | None" = None

    def __post_init__(self):
        if self.max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        if self.max_pending_bytes < 0:
            raise ValueError("max_pending_bytes must be >= 0")
        if self.max_batch_requests < 1:
            raise ValueError("max_batch_requests must be >= 1")
        if self.max_batch_lanes < 1:
            raise ValueError("max_batch_lanes must be >= 1")
        if self.batch_linger_s < 0:
            raise ValueError("batch_linger_s must be >= 0")
        if (self.default_timeout_s is not None
                and self.default_timeout_s <= 0):
            raise ValueError("default_timeout_s must be positive or None")
        if (self.iter_rate_estimate is not None
                and self.iter_rate_estimate <= 0):
            raise ValueError("iter_rate_estimate must be positive or None")
        if self.harvest_workers < 1:
            raise ValueError("harvest_workers must be >= 1")
        if self.dispatch_retries < 0:
            raise ValueError("dispatch_retries must be >= 0")
        if self.retry_backoff_s < 0:
            raise ValueError("retry_backoff_s must be >= 0")
        if self.watchdog_interval_s <= 0:
            raise ValueError("watchdog_interval_s must be positive")
        if self.telemetry_interval_s <= 0:
            raise ValueError("telemetry_interval_s must be positive")
        if self.metrics_port is not None and not \
                0 <= self.metrics_port <= 65535:
            raise ValueError("metrics_port must be in [0, 65535] or "
                             "None")
        if not self.role:
            raise ValueError("role must be non-empty")
        if self.mesh_spec is not None:
            from nmfx.distributed import parse_mesh_spec

            parse_mesh_spec(self.mesh_spec)  # raises MeshSpecError


def serve_key_fields() -> frozenset:
    """The :class:`ServeConfig` fields that participate in comparison —
    the introspection hook lint rule NMFX001 cross-references (the
    ``DataKey``/``SolverConfig`` discipline). Reading ``field.compare``
    keeps it honest: a field added with ``compare=False`` would be
    invisible to the dataclass hash/eq two policies are compared by,
    and shows up here (and fails lint) as uncovered."""
    return frozenset(f.name for f in dataclasses.fields(ServeConfig)
                     if f.compare)


@dataclasses.dataclass
class RequestStats:
    """Per-request serving spans, readable on the returned future
    (``future.stats``) once the request resolves; partial values are
    visible earlier (queue_wait_s lands at dispatch)."""

    #: the request's server-assigned id (the submission sequence
    #: number) — the SAME id every structured-tracer span of this
    #: request carries in its ``args`` (``request_id``), so a span in
    #: an exported Chrome trace joins back to this stats record
    request_id: "int | None" = None
    #: seconds between submit and dispatch (queue residency)
    queue_wait_s: "float | None" = None
    #: seconds of the dispatch step itself: placement, lane packing,
    #: executable lookup/compile and the async dispatch call
    pack_s: "float | None" = None
    #: seconds the completion worker blocked on the device for this
    #: request's arrays (device solve + device queueing behind
    #: dispatch-mates)
    solve_s: "float | None" = None
    #: seconds of host-side harvest (hclust/cophenetic/cutree + result
    #: assembly)
    harvest_s: "float | None" = None
    #: submit → future-resolved wall
    latency_s: "float | None" = None
    #: how many requests shared this request's dispatch (1 = solo)
    packed_requests: "int | None" = None
    #: this request's lane count (Σ restarts over its ranks)
    lanes: "int | None" = None
    #: the deadline-clamped per-lane iteration budget, when the
    #: scheduler clamped one (None = dispatched at the configured
    #: max_iter); the exactness contract is then against a solo run at
    #: this max_iter
    budget_iters: "int | None" = None
    #: solver quality the request was actually served at: "exact", or
    #: "sketched" when the request ran the compressed engine — by its
    #: own config, or degraded there by quality-elastic scheduling
    #: (then ``degraded_cause`` names why). Mirrors
    #: ``ConsensusResult.quality`` on the resolved future.
    quality: str = "exact"
    #: why quality-elastic scheduling degraded this request
    #: ("deadline" | "overload"), None when it ran as requested
    degraded_cause: "str | None" = None


class _ServeFuture(Future):
    """Future[ConsensusResult] with the request's serving spans."""

    def __init__(self, stats: RequestStats):
        super().__init__()
        self.stats = stats


@dataclasses.dataclass
class _Request:
    seq: int
    a: np.ndarray
    col_names: tuple
    ks: tuple
    restarts: int
    seed: int
    scfg: SolverConfig
    icfg: InitConfig
    label_rule: str
    linkage: str
    grid_slots: int
    grid_tail_slots: object
    priority: int
    deadline: "float | None"  # absolute time.monotonic seconds
    future: _ServeFuture
    stats: RequestStats
    compat: "tuple | None"  # packing-compatibility key; None = solo only
    submitted: float = 0.0
    #: numeric-quarantine survivor floor (ConsensusConfig.min_restarts)
    min_restarts: int = 1
    #: quality-elastic degradation verdict ("deadline" | "overload");
    #: None = serve as requested. Set at admission (overload) or
    #: dispatch (deadline); the harvester tags the result from
    #: ``quality`` below, so no path can return an untagged sketched
    #: result
    degrade_cause: "str | None" = None
    #: the quality the request will actually be served at
    quality: str = "exact"
    #: content-addressed result-cache key (ISSUE 16); None when the
    #: request is ineligible (deadline'd, or caching+coalescing off)
    cache_key: "str | None" = None
    #: the (content fingerprint, shape, src dtype) triple behind
    #: ``cache_key`` — kept so the harvest-time put can re-key a
    #: mid-flight quality degradation without re-hashing the bytes
    cache_fp: "tuple | None" = None
    #: the quality ``cache_key`` was computed under at submit
    cache_quality: str = "exact"

    @property
    def lanes(self) -> int:
        return len(self.ks) * self.restarts

    def order_key(self) -> tuple:
        dl = self.deadline if self.deadline is not None else float("inf")
        return (-self.priority, dl, self.seq)


class Engine(Protocol):
    """What the scheduler needs from the execution stack — the ONE
    interface ``sweep``/``exec_cache``/``data_cache``/``harvest`` unify
    behind (tests drive the scheduler against fakes of this; the
    MPI-FAUN-style multi-device sharding lands behind it as a psum in
    ``dispatch_*`` without touching the queue/packing logic above)."""

    def compatibility_key(self, req: _Request) -> "tuple | None":
        """Hashable key under which requests may share one dispatch's
        lanes; None when the request can only dispatch solo."""
        ...

    def place(self, req: _Request) -> object:
        """Start the request's host→device placement (asynchronous);
        the returned handle feeds ``dispatch_*``. May return None when
        the solo path does its own placement."""
        ...

    def dispatch_solo(self, req: _Request, placed: object,
                      scfg: SolverConfig) -> "Mapping[int, KSweepOutput]":
        """Dispatch one request (async) and return its per-rank device
        outputs. ``scfg`` may be the request's config with a deadline-
        clamped ``max_iter``."""
        ...

    def dispatch_packed(self, reqs: "Sequence[_Request]", placed: object
                        ) -> "list[Mapping[int, KSweepOutput]]":
        """Dispatch one packed executable whose lanes span every request
        (all sharing one compatibility key); returns per-request
        per-rank device outputs, in request order."""
        ...


class ExecCacheEngine:
    """The production :class:`Engine`: requests serve through the
    shape-bucketed executable cache (solo), the packed multi-request
    builder (``sweep._build_packed_serve_fn``), and the device-resident
    input cache; non-cacheable configurations fall back to the plain
    sweep path so every algorithm stays servable."""

    def __init__(self, exec_cache=None, profiler=None):
        from nmfx.exec_cache import ExecCache
        from nmfx.profiling import NullProfiler

        self.exec_cache = exec_cache if exec_cache is not None \
            else ExecCache()
        self._prof = profiler if profiler is not None else NullProfiler()

    # -- request shaping ---------------------------------------------------
    @staticmethod
    def _ccfg(req: _Request) -> ConsensusConfig:
        return ConsensusConfig(ks=req.ks, restarts=req.restarts,
                               seed=req.seed, label_rule=req.label_rule,
                               linkage=req.linkage,
                               grid_slots=req.grid_slots,
                               grid_tail_slots=req.grid_tail_slots,
                               min_restarts=req.min_restarts)

    def compatibility_key(self, req: _Request) -> "tuple | None":
        from nmfx.data_cache import default_cache

        if req.icfg.method != "random":
            # NNDSVD lane batches are built outside the executable per
            # true shape — solo only
            return None
        ccfg = self._ccfg(req)
        if not self.exec_cache.cacheable(ccfg, req.scfg, None):
            return None
        bucket = self.exec_cache.bucket_shape(*req.a.shape)
        # the DataKey IS the data half of the compatibility contract:
        # same content fingerprint + placement = the same resident
        # padded device buffer the packed executable reads
        dkey = default_cache().key_for(req.a, req.scfg.dtype,
                                       pad_shape=bucket, mesh=None)
        tail = req.grid_tail_slots
        if isinstance(tail, list):
            tail = tuple(tail)
        return (dkey, bucket, req.scfg, req.icfg, req.label_rule,
                req.grid_slots, tail)

    def place(self, req: _Request):
        ccfg = self._ccfg(req)
        if not self.exec_cache.cacheable(ccfg, req.scfg, None):
            return None  # the plain sweep path places through the cache
        return self.exec_cache.prefetch(req.a, req.scfg, None,
                                        profiler=self._prof)

    # -- dispatch ----------------------------------------------------------
    def dispatch_solo(self, req: _Request, placed, scfg: SolverConfig):
        ccfg = self._ccfg(req)
        if placed is not None and self.exec_cache.cacheable(ccfg, scfg,
                                                            None):
            return self.exec_cache.run_sweep(placed, ccfg, scfg,
                                             req.icfg, None,
                                             profiler=self._prof)
        from nmfx.sweep import sweep

        return sweep(req.a, ccfg, scfg, req.icfg, None,
                     profiler=self._prof)

    def dispatch_packed(self, reqs, placed):
        import jax
        import jax.numpy as jnp

        from nmfx.exec_cache import _unpad, start_host_fetch
        from nmfx.ops.packed_mu import flip_budget
        from nmfx.sweep import _build_packed_serve_fn

        req0 = reqs[0]
        # one lane group per (request, rank); LPT order (rank
        # descending), deadline/priority/arrival-aware within equal
        # ranks — urgent requests' lanes load into slots first
        groups = sorted(
            ((k, r) for r in reqs for k in r.ks),
            key=lambda g: (-g[0],) + g[1].order_key())
        layout = tuple((k, r.restarts) for k, r in groups)
        tail = req0.grid_tail_slots
        if isinstance(tail, list):
            tail = tuple(tail)
        from nmfx import faults

        fn = _build_packed_serve_fn(layout, req0.scfg, req0.label_rule,
                                    req0.grid_slots, tail, placed.bucket,
                                    req0.icfg,
                                    fault_token=faults.trace_token())
        # canonical chain: fold_in(key(seed), k) per group, split over
        # the restart axis inside the executable — identical draws to
        # each request's solo path
        roots = jnp.stack([
            jax.random.fold_in(jax.random.key(r.seed), k)
            for k, r in groups])
        m_true, n_true = placed.true_shape
        flip = flip_budget(req0.scfg.class_flip_tol, n_true)
        outs = fn(placed.a_pad, roots,
                  jnp.asarray(m_true, jnp.int32),
                  jnp.asarray(n_true, jnp.int32),
                  jnp.asarray(flip, jnp.int32))
        per_req: "dict[int, dict]" = {r.seq: {} for r in reqs}
        for (k, r), out in zip(groups, outs):
            per_req[r.seq][k] = _unpad(out, m_true, n_true)
        with self._prof.phase("xfer.overlap"):
            start_host_fetch(per_req)
        return [per_req[r.seq] for r in reqs]


class MeshEngine:
    """The mesh-tier :class:`Engine` (ISSUE 19): every dispatch runs
    the grid-sharded sweep over one fixed device mesh
    (``ServeConfig.mesh_spec`` → ``distributed.build_replica_mesh``).
    Solo-only by design — cross-request lane packing composes restarts
    into one executable whose pool geometry depends on the batch, which
    would break the meshed-vs-unmeshed exactness contract the mesh
    parity suite pins; the mesh's parallelism comes from sharding the
    solve itself (communication-avoiding restart axis + Gram-first grid
    axes), not from batching tenants."""

    def __init__(self, mesh_spec: str, *, devices=None, profiler=None):
        from nmfx.distributed import build_replica_mesh, parse_mesh_spec
        from nmfx.profiling import NullProfiler

        self.mesh_spec = mesh_spec
        self.shape = parse_mesh_spec(mesh_spec)
        self.mesh = build_replica_mesh(mesh_spec, devices=devices)
        self._prof = profiler if profiler is not None else NullProfiler()

    @property
    def n_devices(self) -> int:
        return int(self.mesh.devices.size)

    def compatibility_key(self, req: _Request) -> "tuple | None":
        return None  # solo only (see class docstring)

    def place(self, req: _Request):
        return None  # sweep() owns meshed placement

    def dispatch_solo(self, req: _Request, placed, scfg: SolverConfig):
        from nmfx.sweep import sweep

        ccfg = ExecCacheEngine._ccfg(req)
        return sweep(req.a, ccfg, scfg, req.icfg, self.mesh,
                     profiler=self._prof)

    def dispatch_packed(self, reqs, placed):
        raise RuntimeError(
            "MeshEngine is solo-only (compatibility_key is always "
            "None); a packed dispatch reaching it is a scheduler bug")


@guarded_by("_lock", "_queue", "_queued", "_pending_bytes", "_closed",
            "_paused", "_inflight", "_crash", "_sched_clean", "_down",
            "_heartbeat")
@guarded_by("_tracked_lock", "_tracked", "_coalesce", "_followers")
@guarded_by("_harvest_cond", "_harvest_q", "_harvest_owned")
class NMFXServer:
    """Async multi-tenant consensus-NMF server over one device.

    ``submit(...)`` enqueues a request and returns a
    ``Future[ConsensusResult]`` immediately; a single scheduler thread
    owns the device and continuously packs compatible requests'
    restarts into shared executable lanes (see the module docstring);
    completion workers harvest each request the moment its arrays
    exist, so the device never waits on host rank selection.

    Lifecycle: workers spawn lazily on the first submit; ``close()``
    (or the context manager) drains in-flight requests and joins the
    threads. One server instance per process/device is the intended
    shape — it owns the exec-cache LRU and the dispatch order.
    """

    def __init__(self, serve_cfg: ServeConfig = ServeConfig(), *,
                 engine: "Engine | None" = None, exec_cache=None,
                 result_cache=None, profiler=None, start: bool = True):
        from nmfx.profiling import NullProfiler

        if engine is not None and exec_cache is not None:
            raise ValueError("pass either engine or exec_cache, not both")
        self.cfg = serve_cfg
        self._prof = profiler if profiler is not None else NullProfiler()
        if engine is not None:
            self.engine: Engine = engine
        elif serve_cfg.mesh_spec is not None:
            if exec_cache is not None:
                raise ValueError(
                    "mesh_spec selects the MeshEngine, which does not "
                    "serve through an executable cache — pass either "
                    "mesh_spec or exec_cache, not both")
            self.engine = MeshEngine(serve_cfg.mesh_spec,
                                     profiler=self._prof)
        else:
            self.engine = ExecCacheEngine(exec_cache,
                                          profiler=self._prof)
        # finished-result cache (ISSUE 16): an explicit instance wins;
        # else a configured directory builds one; else caching is off
        if result_cache is None and serve_cfg.result_cache_dir is not None:
            from nmfx.result_cache import ResultCache

            result_cache = ResultCache(
                cache_dir=serve_cfg.result_cache_dir, layer="server")
        self.result_cache = result_cache
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: "list[tuple[tuple, _Request]]" = []  # heap
        self._queued = 0
        self._pending_bytes = 0
        self._seq = itertools.count()
        self._closed = False
        self._paused = not start
        self._scheduler: "threading.Thread | None" = None
        self._harvest_q: "list[tuple[_Request, object, float] | None]" = []
        self._harvest_cond = threading.Condition()
        self._harvesters: "list[threading.Thread]" = []
        self._inflight = 0  # dispatched, not yet resolved
        # -- watchdog state (docs/serving.md "Failure model"): every
        # unresolved request is tracked from submit to resolution, so a
        # scheduler crash can never strand a Future — the watchdog
        # resolves whatever the dead scheduler held (ServerCrashed),
        # skipping requests the (still-alive) harvesters own
        # own lock (ordered strictly AFTER self._lock): _untrack runs
        # as a Future done-callback on whatever thread resolved the
        # future — including threads holding self._lock (_expire_locked,
        # close(cancel_pending=True)) — so it must not touch self._lock
        self._tracked_lock = threading.Lock()
        self._tracked: "dict[int, _Request]" = {}
        # in-flight coalescing registry (ISSUE 16): result-cache key →
        # leader request / attached followers. Guarded by _tracked_lock
        # (NOT self._lock): the leader's fan-out runs as a Future
        # done-callback, which may fire on threads already holding
        # self._lock (the close(cancel_pending=True) path) — same
        # constraint as _untrack; lock order stays _lock → _tracked
        self._coalesce: "dict[str, _Request]" = {}
        self._followers: "dict[str, list[_Request]]" = {}
        self._harvest_owned: "set[int]" = set()  # guarded by _harvest_cond
        self._crash: "BaseException | None" = None  # set by _scheduler_main
        self._sched_clean = False  # scheduler exited via close(), not crash
        self._down: "BaseException | None" = None  # crashed, no restart
        self._watchdog: "threading.Thread | None" = None
        self._heartbeat = 0.0  # scheduler loop progress (introspection)
        # baseline registry cut for stats_snapshot(): the delta since
        # SERVER START, not process start (several servers may share
        # one process across a test session)
        self._metrics_t0 = _metrics.registry().snapshot()
        # fleet observatory wiring (ISSUE 14): the SLO engine always
        # runs (stats_snapshot()["slo"] — evaluation is host-side
        # arithmetic on snapshot deltas); the telemetry publisher and
        # the /metrics HTTP endpoint spin up only when configured
        from nmfx.obs import slo as _slo

        self._slo = _slo.SLOEngine()
        self._publisher = None
        self._metrics_server = None
        self.metrics_port: "int | None" = None
        try:
            if serve_cfg.metrics_port is not None:
                from nmfx.obs.export import serve_metrics

                self._metrics_server = serve_metrics(
                    serve_cfg.metrics_port)
                self.metrics_port = self._metrics_server.port
            # the publisher starts LAST: it is a daemon that keeps
            # heart-beating into the fleet ledger, so nothing that can
            # still fail may run after it — a half-constructed server
            # must never read as a live replica to a router/autoscaler
            if serve_cfg.telemetry_dir is not None:
                from nmfx.obs.export import TelemetryPublisher

                # status_fn: this SERVER's queue/inflight levels ride
                # the snapshot payload itself, so N in-process replicas
                # sharing one registry still publish honest per-
                # instance load rows (the process-wide gauges can only
                # carry the last writer's level)
                self._publisher = TelemetryPublisher(
                    serve_cfg.telemetry_dir, role=serve_cfg.role,
                    instance=serve_cfg.instance,
                    interval_s=serve_cfg.telemetry_interval_s,
                    status_fn=self._telemetry_status).start()
        except BaseException:
            # a failed __init__ (e.g. metrics_port already bound)
            # never runs close(): tear down whatever started, then
            # re-raise the construction failure
            if self._metrics_server is not None:
                self._metrics_server.shutdown()
                self._metrics_server.server_close()
            raise
        self.counters = {"submitted": 0, "completed": 0, "failed": 0,
                         "cancelled": 0, "deadline_expired": 0,
                         "rejected": 0, "dispatches": 0,
                         "packed_dispatches": 0, "packed_requests": 0,
                         "total_lanes": 0, "packed_lanes": 0,
                         "budget_clamped": 0, "spilled": 0,
                         "readmitted": 0, "quality_degraded": 0,
                         "result_cache_hits": 0, "coalesced": 0}

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self) -> "NMFXServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def pause(self) -> None:
        """Hold dispatch (requests keep queueing) — deterministic batch
        construction for tests and maintenance windows."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def close(self, cancel_pending: bool = False) -> None:
        """Stop accepting requests; drain the queue and in-flight work,
        then join the worker threads. ``cancel_pending=True`` instead
        fails queued (not yet dispatched) requests with
        :class:`ServerClosed` — routed through the spill path first
        when ``ServeConfig.spill_dir`` is set, so an operator shutdown
        (or a supervisor's SIGTERM handler calling close) loses no
        queued work: a restarted server re-admits the spilled requests
        via :meth:`readmit`."""
        cancelled: "list[_Request]" = []
        with self._cond:
            if not self._closed:
                self._closed = True
                if cancel_pending:
                    cancelled = [req for _, req in self._queue]
                    self._queue.clear()
                    self._queued = 0
                    self._pending_bytes = 0
                    self._sync_gauges()
                self._paused = False  # a paused close must still drain
                self._cond.notify_all()
            scheduler = self._scheduler
        # spill + resolve OUTSIDE the lock: serializing up to the
        # admission bound's worth of matrices under _cond would stall
        # the watchdog and completion bookkeeping for the whole write;
        # nothing reads _queue after _closed flipped under the lock
        for req in cancelled:
            if not req.future.set_running_or_notify_cancel():
                continue  # caller already cancelled it: never spill —
                # readmit() must not resurrect cancelled work
            path = self._spill(req)
            err = ServerClosed(
                "server closed before dispatch"
                + (f"; request spilled to {path} — a restarted server "
                   "re-admits it via NMFXServer.readmit()"
                   if path else ""))
            # machine-readable spill join (ISSUE 15): a router draining
            # this replica reads the path off the typed error and
            # claims the record for re-admission on a survivor
            err.spill_path = path
            req.future.set_exception(err)
            with self._lock:
                self.counters["failed"] += 1
        if scheduler is not None:
            scheduler.join()
        with self._cond:
            self._cond.notify_all()  # wake the watchdog promptly
        # the watchdog exits once it has observed the closed+dead (or
        # closed+crashed — it still resolves the crash's strays first)
        # scheduler; join AFTER the scheduler so a crash racing close()
        # is fully handled before the harvest drain below
        watchdog = self._watchdog
        if watchdog is not None:
            watchdog.join()
        with self._harvest_cond:
            for _ in self._harvesters:
                self._harvest_q.append(None)
            self._harvest_cond.notify_all()
        for t in self._harvesters:
            t.join()
        # fleet-telemetry teardown AFTER the drain: the publisher's
        # final snapshot carries the fully-drained counters, then this
        # instance goes stale in the fleet view (counters retained,
        # gauges dropped — nmfx.obs.aggregate)
        if self._publisher is not None:
            self._publisher.close()
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
            self._metrics_server.server_close()

    # -- spill-on-shutdown / re-admission (ISSUE 9) ------------------------
    def _spill(self, req: _Request) -> "str | None":
        """Persist one queued request's submission payload under
        ``ServeConfig.spill_dir`` (atomic tmp+rename via the checkpoint
        ledger's writer, which also passes the ``ckpt.write`` chaos
        site). Best-effort: a write failure degrades warn-once to the
        plain discard (the pre-spill behavior), never blocks close()."""
        if self.cfg.spill_dir is None:
            return None
        import os

        from nmfx.faults import warn_once

        # identity for the cross-process timeline (ISSUE 14): the
        # spilling server's request id rides in the payload, the
        # readmitting server books a serve.readmit join against it,
        # and merge_traces aligns both processes' traces — a
        # spilled-and-readmitted request reads as ONE timeline
        meta = spill_meta(
            request_id=req.seq, ks=req.ks, restarts=req.restarts,
            seed=req.seed, scfg=req.scfg, icfg=req.icfg,
            label_rule=req.label_rule, linkage=req.linkage,
            grid_slots=req.grid_slots,
            grid_tail_slots=req.grid_tail_slots,
            min_restarts=req.min_restarts, priority=req.priority,
            col_names=req.col_names)
        try:
            path = write_spill_record(
                os.path.join(
                    self.cfg.spill_dir,
                    f"{SPILL_PREFIX}{os.getpid()}_"
                    f"{next(_spill_seq)}.npz"),
                req.a, meta)
        except Exception as e:
            warn_once(
                "serve-spill-failed",
                f"failed to spill queued request #{req.seq} to "
                f"{self.cfg.spill_dir!r} ({e!r}); the request is "
                "discarded like a spill-less shutdown")
            return None
        with self._lock:
            self.counters["spilled"] += 1
        _flight.record("serve.spill", request_id=req.seq, path=path)
        _trace.default_tracer().instant(
            "serve.spill", cat="serve",
            args={"request_id": req.seq})
        return path

    def readmit(self, spill_dir: "str | None" = None, *,
                claimant: "str | None" = None,
                break_claims_after_s: "float | None" = None) -> list:
        """Re-admit every request a previous server spilled on shutdown
        (``spill_dir`` defaults to this server's
        ``ServeConfig.spill_dir``): each spill record is CLAIMED
        (:func:`claim_spill` — O_EXCL exclusive, so two
        routers/survivors draining one directory partition the records
        instead of both readmitting them; tests/test_multiprocess.py
        races it), resubmitted through the normal :meth:`submit` path —
        bit-identical results to the original submission by the serving
        exactness contract — and removed (record then claim) once
        admitted. Records another consumer holds are skipped; pass
        ``break_claims_after_s`` to break claims whose owner provably
        died between claiming and readmitting (the claim's age is the
        evidence). Torn/corrupt spill records are skipped warn-once
        (the ledger's torn-record tolerance); an admission rejection
        (``QueueFull``) stops the loop warn-once, RELEASING that
        record's claim so it stays re-admittable by anyone. Returns the
        futures of everything admitted."""
        import os

        from nmfx.faults import warn_once

        d = spill_dir if spill_dir is not None else self.cfg.spill_dir
        if d is None:
            raise ValueError("no spill directory: pass spill_dir= or "
                             "set ServeConfig.spill_dir")
        who = claimant if claimant is not None \
            else f"readmit-{os.getpid()}"
        futures = []
        for path in list_spills(d):
            if spill_claimant(path) is not None:
                if break_claims_after_s is None or not break_spill_claim(
                        path, older_than_s=break_claims_after_s):
                    continue  # another consumer owns it
            if not claim_spill(path, who):
                continue  # lost the claim race — the winner readmits
            try:
                a, meta = load_spill_record(path)
                kwargs = spill_submit_kwargs(meta)
                data = spill_dataset(a, meta)
            except Exception as e:
                release_spill_claim(path)
                warn_once(
                    "serve-spill-corrupt",
                    f"spilled request record {path!r} is torn/corrupt "
                    f"({e!r}); skipping it — re-submit the request "
                    "manually if it still matters")
                continue
            try:
                fut = self.submit(data, **kwargs)
            except QueueFull as e:
                release_spill_claim(path)
                warn_once(
                    "serve-readmit-queue-full",
                    f"re-admission stopped at {path!r}: {e}; this and "
                    "the remaining spill records stay on disk — call "
                    "readmit() again once the queue drains")
                break
            with self._lock:
                self.counters["readmitted"] += 1
            # the cross-process join (ISSUE 14): the readmitted
            # request's NEW id booked against the spilling server's
            # original — merge_traces lines the two processes up
            origin = meta.get("request_id")
            _flight.record("serve.readmit",
                           request_id=fut.stats.request_id,
                           origin_request_id=origin,
                           origin_pid=meta.get("spill_pid"))
            _trace.default_tracer().instant(
                "serve.readmit", cat="serve",
                args={"request_id": fut.stats.request_id,
                      "origin_request_id": origin})
            futures.append(fut)
            # record first, claim second: a crash between the two
            # leaves an ORPHAN claim (record already admitted), which
            # the sweep below — and every later consumer — cleans up;
            # the reverse order would briefly leave the record
            # unclaimed and double-admittable
            try:
                os.unlink(path)
            except OSError as e:
                warn_once("serve-spill-unlink",
                          f"could not remove re-admitted spill record "
                          f"{path!r} ({e}); remove it manually or the "
                          "next readmit will submit it again")
            release_spill_claim(path)
        # orphan-claim sweep: a claim whose record is gone marks a
        # fully-admitted request whose consumer died before releasing
        if os.path.isdir(d):
            for name in os.listdir(d):
                if not name.endswith(_CLAIM_SUFFIX):
                    continue
                rec = os.path.join(d, name[:-len(_CLAIM_SUFFIX)])
                if not os.path.exists(rec):
                    release_spill_claim(rec)
        return futures

    # -- submission --------------------------------------------------------
    def submit(self, data, ks: Sequence[int] = (2, 3, 4, 5),
               restarts: int = 10, *, seed: int = 123,
               solver_cfg: "SolverConfig | None" = None,
               init_cfg: "InitConfig | None" = None,
               label_rule: str = "argmax", linkage: str = "average",
               grid_slots: int = 48, grid_tail_slots="auto",
               min_restarts: int = 1,
               priority: int = 0, deadline: "float | None" = None,
               timeout: "float | None" = None) -> _ServeFuture:
        """Enqueue one consensus request; returns a
        ``Future[ConsensusResult]`` immediately.

        Arguments mirror ``nmfconsensus`` (the result is bit-identical
        to calling it with the same arguments — the exactness
        contract), plus the serving controls: ``priority`` (higher
        dispatches first), ``timeout`` (seconds from now) or
        ``deadline`` (absolute ``time.monotonic()`` seconds) — expiry
        while queued resolves the future to :class:`DeadlineExceeded`
        without dispatching. ``future.cancel()`` works until dispatch;
        ``future.stats`` carries the per-request serving spans.
        ``min_restarts`` is the numeric-quarantine survivor floor
        (``ConsensusConfig.min_restarts``): a rank with fewer surviving
        restarts resolves the future to a typed
        ``nmfx.faults.InsufficientRestarts``.
        """
        from nmfx.api import _as_matrix

        arr, col_names = _as_matrix(data)
        arr = np.asarray(arr)
        if not np.isfinite(arr).all():
            raise ValueError("input matrix contains non-finite values")
        if (arr < 0).any():
            raise ValueError("input matrix must be non-negative")
        ks = tuple(dict.fromkeys(int(k) for k in ks))
        if not ks:
            raise ValueError("ks must be non-empty")
        if min(ks) < 2:
            raise ValueError("all k must be >= 2")
        if max(ks) > arr.shape[1]:
            raise ValueError(f"k={max(ks)} exceeds the number of samples "
                             f"({arr.shape[1]})")
        if restarts < 1:
            raise ValueError("restarts must be >= 1")
        if not 1 <= min_restarts <= restarts:
            raise ValueError(
                f"min_restarts must be in [1, restarts={restarts}], "
                f"got {min_restarts}")
        if deadline is not None and timeout is not None:
            raise ValueError("pass either deadline or timeout, not both")
        if timeout is None and deadline is None \
                and self.cfg.default_timeout_s is not None:
            timeout = self.cfg.default_timeout_s
        if timeout is not None:
            deadline = time.monotonic() + timeout
        scfg = solver_cfg if solver_cfg is not None else SolverConfig()
        icfg = init_cfg if init_cfg is not None else InitConfig()
        seq = next(self._seq)
        stats = RequestStats(request_id=seq, lanes=len(ks) * restarts)
        req = _Request(seq=seq, a=arr,
                       col_names=tuple(col_names), ks=ks,
                       restarts=restarts, seed=seed, scfg=scfg,
                       icfg=icfg, label_rule=label_rule, linkage=linkage,
                       grid_slots=grid_slots,
                       grid_tail_slots=grid_tail_slots,
                       priority=priority, deadline=deadline,
                       future=_ServeFuture(stats), stats=stats,
                       compat=None, submitted=time.monotonic(),
                       min_restarts=min_restarts)
        if scfg.backend == "sketched":
            # the caller ASKED for the compressed engine: the result is
            # sketched-quality by request, tagged but not a degradation
            req.quality = "sketched"
            stats.quality = "sketched"
        degradable = self._sketch_eligible(scfg)
        # request economics (ISSUE 16): key the request content-
        # addressed and try the finished-result cache BEFORE admission
        # — a warm hit resolves without queueing, dispatching, or
        # touching the device (the zero-dispatch/zero-h2d contract,
        # counter-gated). Deadline'd requests are ineligible (a cached
        # or coalesced outcome has its own timing semantics).
        if deadline is None and (self.result_cache is not None
                                 or self.cfg.coalesce_requests):
            arr_c = np.ascontiguousarray(arr)
            fp = hashlib.sha256(
                arr_c.view(np.uint8).reshape(-1)).hexdigest()
            req.cache_fp = (fp, tuple(arr.shape), arr_c.dtype.str)
            req.cache_quality = req.quality
            req.cache_key = self._result_key(req, req.quality)
            if self.result_cache is not None:
                cached = self.result_cache.lookup(req.cache_key)
                if cached is not None:
                    req.stats.latency_s = time.monotonic() - req.submitted
                    req.stats.quality = cached.quality
                    with self._lock:
                        self.counters["submitted"] += 1
                        self.counters["completed"] += 1
                        self.counters["result_cache_hits"] += 1
                    req.future.set_result(cached)
                    _e2e_hist.observe(req.stats.latency_s,
                                      outcome="completed")
                    return req.future
        # admission pre-check BEFORE the O(bytes) fingerprint: under
        # overload QueueFull is the hot path, and rejecting must stay
        # cheap; the authoritative (race-free) check re-runs at enqueue
        with self._cond:
            self._admit_locked(arr.nbytes, degradable=degradable)
        # the compatibility fingerprint (one sha256 pass over the host
        # bytes) is computed HERE on the caller's thread, keeping the
        # scheduler thread's pop-to-dispatch path hash-free
        req.compat = self.engine.compatibility_key(req)
        with self._cond:
            coalescing = (req.cache_key is not None
                          and self.cfg.coalesce_requests
                          and not self._closed and self._down is None)
            if coalescing:
                with self._tracked_lock:
                    leader = self._coalesce.get(req.cache_key)
                    attach = (leader is not None
                              and not leader.future.done())
                    if attach:
                        self._followers.setdefault(
                            req.cache_key, []).append(req)
                if attach:
                    # follower: no admission, no queue slot, no
                    # dispatch — the leader's outcome fans out
                    self.counters["submitted"] += 1
                    self.counters["coalesced"] += 1
                    with self._tracked_lock:
                        self._tracked[req.seq] = req
                    req.future.add_done_callback(
                        lambda _f, seq=req.seq: self._untrack(seq))
                    _coalesced_total.inc(layer="server")
                    _flight.record("serve.coalesce", request_id=req.seq,
                                   leader=leader.seq,
                                   key=req.cache_key[:12])
                    return req.future
            cause = self._admit_locked(arr.nbytes, degradable=degradable)
            if coalescing:
                # admitted: register as the key's leader — strictly
                # AFTER admission, so a QueueFull raise can never
                # strand a registry entry followers would attach to.
                # Submissions serialize on self._cond, so no identical
                # submit can interleave between the attach-check above
                # and this registration; the fan-out callback only
                # REMOVES entries it still owns, so a stale leader can
                # never orphan this one's followers.
                with self._tracked_lock:
                    self._coalesce[req.cache_key] = req
                req.future.add_done_callback(
                    lambda _f, key=req.cache_key, lead=req:
                        self._coalesce_fanout(key, lead))
            if cause is not None:
                # quality-elastic soft admission: the request admission
                # control would have SHED is served degraded instead —
                # solo (a degraded request must not share lanes with
                # exact mates), tagged at dispatch
                req.degrade_cause = cause
                req.quality = "sketched"
                req.compat = None
            heapq.heappush(self._queue, (req.order_key(), req))
            self._queued += 1
            self._pending_bytes += arr.nbytes
            self._sync_gauges()
            self.counters["submitted"] += 1
            # watchdog registry: tracked until the future resolves, so
            # a scheduler crash can enumerate (and fail, typed) every
            # request it would otherwise strand
            with self._tracked_lock:
                self._tracked[req.seq] = req
            req.future.add_done_callback(
                lambda _f, seq=req.seq: self._untrack(seq))
            self._ensure_workers()
            self._cond.notify_all()
        return req.future

    def _untrack(self, seq: int) -> None:
        with self._tracked_lock:
            self._tracked.pop(seq, None)

    def _result_key(self, req: _Request, quality: str) -> str:
        """The request's content-addressed result key (ISSUE 16) —
        ``result_cache.result_key`` over the precomputed content
        fingerprint and the request's full consensus/solver/init
        configuration, at ``quality``."""
        from nmfx.result_cache import result_key

        fp, shape, src_dtype = req.cache_fp
        ccfg = ConsensusConfig(ks=req.ks, restarts=req.restarts,
                               seed=req.seed, label_rule=req.label_rule,
                               linkage=req.linkage,
                               grid_slots=req.grid_slots,
                               grid_tail_slots=req.grid_tail_slots,
                               min_restarts=req.min_restarts)
        return result_key(fp, shape, src_dtype, req.scfg, ccfg,
                          req.icfg, quality)

    def _coalesce_fanout(self, key: str, leader: _Request) -> None:
        """Leader done-callback: release the in-flight registry entry
        and share the leader's outcome with every attached follower.

        Runs on whatever thread resolved the leader's future —
        including threads holding ``self._lock`` (the
        ``close(cancel_pending=True)`` path) — so it takes ONLY
        ``_tracked_lock`` (the ``_untrack`` constraint). It pops the
        follower list only while it still owns the registry entry: if
        a new leader already replaced this one (an identical submit
        raced the resolution), the followers are inherited by the new
        leader — identical key, identical eventual outcome."""
        with self._tracked_lock:
            if self._coalesce.get(key) is not leader:
                return  # superseded: followers ride the new leader
            del self._coalesce[key]
            followers = self._followers.pop(key, [])
        if not followers:
            return
        fut = leader.future
        if fut.cancelled():
            self._coalesce_promote(key, followers)
            return
        err = fut.exception()
        result = None if err is not None else fut.result()
        now = time.monotonic()
        resolved = 0
        for f in followers:
            if f.future.done():
                continue  # e.g. the watchdog already failed it, typed
            f.stats.latency_s = now - f.submitted
            try:
                if err is not None:
                    f.future.set_exception(err)
                    _e2e_hist.observe(f.stats.latency_s,
                                      outcome="failed")
                else:
                    f.stats.quality = result.quality
                    f.future.set_result(result)
                    _e2e_hist.observe(f.stats.latency_s,
                                      outcome="completed")
                resolved += 1
            except Exception:  # nmfx: ignore[NMFX006] -- lost a
                # resolution race: the follower's Future is already
                # resolved (cancel/close), nothing is swallowed
                continue
        if resolved:
            # safe to take self._lock here: leaders are deadline-free,
            # so nothing resolves one under _cond (_expire_locked) —
            # every leader-resolution site (harvester, watchdog, the
            # close(cancel_pending=True) drain, a caller's cancel())
            # runs lock-free
            with self._lock:
                self.counters["failed" if err is not None
                              else "completed"] += resolved
        _flight.record("serve.coalesce_fanout", leader=leader.seq,
                       key=key[:12], followers=resolved,
                       outcome="error" if err is not None else "result")

    def _coalesce_promote(self, key: str,
                          followers: "list[_Request]") -> None:
        """The leader was cancelled before dispatch: promote the first
        still-live follower into the queue as the new leader and
        re-attach the rest — followers never inherit a cancellation
        they didn't ask for. Only ever reached from a caller-thread
        ``future.cancel()`` (cancellation finalizes on the cancelling
        thread), so taking the scheduler condition here is safe."""
        live = [f for f in followers if not f.future.done()]
        if not live:
            return
        head, rest = live[0], live[1:]
        err = None
        with self._cond:
            if self._closed or self._down is not None:
                err = ServerClosed(
                    "server closed while promoting coalesced followers "
                    "of a cancelled leader")
            else:
                with self._tracked_lock:
                    self._coalesce[key] = head
                    if rest:
                        self._followers.setdefault(key, []).extend(rest)
                head.future.add_done_callback(
                    lambda _f, k=key, lead=head:
                        self._coalesce_fanout(k, lead))
                heapq.heappush(self._queue, (head.order_key(), head))
                self._queued += 1
                self._pending_bytes += head.a.nbytes
                self._sync_gauges()
                self._ensure_workers()
                self._cond.notify_all()
        if err is not None:
            for f in live:
                if not f.future.done():
                    try:
                        f.future.set_exception(err)
                    except Exception:  # nmfx: ignore[NMFX006] -- lost
                        # a resolution race: the Future resolved
                        # concurrently (cancel/close), nothing swallowed
                        continue
            return
        _flight.record("serve.coalesce_promote", request_id=head.seq,
                       key=key[:12], followers=len(rest))

    def _telemetry_status(self) -> dict:
        """Per-INSTANCE load levels for the telemetry snapshot payload
        (``nmfx.obs.export.build_snapshot``'s ``status``): a router's
        health checker and ``nmfx-top`` read these instead of the
        process-wide gauges, which N in-process replicas would
        overwrite each other on."""
        with self._lock:
            return {"queue_depth": self._queued,
                    "inflight": self._inflight}

    def _sync_gauges(self) -> None:
        """Export the queue/inflight LEVELS to the registry gauges the
        fleet view reads (nmfx_serve_queue_depth / nmfx_serve_inflight)
        — called wherever either level changes. The registry lock is a
        leaf, so this is safe under self._lock/self._cond."""
        _queue_depth_gauge.set(self._queued)
        _inflight_gauge.set(self._inflight)

    @staticmethod
    def _sketch_eligible(scfg: SolverConfig) -> bool:
        """Whether quality-elastic scheduling CAN degrade a request
        with this config to the sketched engine: the algorithm needs a
        compressed form, a screening config already owns its own
        sketched pass, and a request that ASKED for sketched has
        nothing to degrade to."""
        from nmfx.config import SKETCHED_ALGORITHMS

        return (scfg.algorithm in SKETCHED_ALGORITHMS
                and not scfg.screen and scfg.backend != "sketched")

    def _admit_locked(self, nbytes: int,
                      degradable: bool = False) -> "str | None":
        """Admission control (caller holds the lock): typed rejection
        when the queue is over its depth or pending-byte bound. Under
        ``ServeConfig.quality_elastic``, a DEPTH overrun on a
        ``degradable`` request soft-admits instead (returns
        "overload" — the quality-elastic degradation cause) while the
        depth stays under 2× the bound; the pending-bytes bound stays
        hard (it protects host memory, not latency)."""
        if self._closed:
            raise ServerClosed("server is closed")
        if self._down is not None:
            raise ServerCrashed(
                "the scheduler crashed and ServeConfig.restart_scheduler "
                "is False — the server is down") from self._down
        cause = None
        if self._queued >= self.cfg.max_queue_depth:
            if (self.cfg.quality_elastic and degradable
                    and self._queued < 2 * self.cfg.max_queue_depth):
                cause = "overload"
            else:
                self.counters["rejected"] += 1
                raise QueueFull(
                    f"queue depth {self._queued} at the configured bound "
                    f"({self.cfg.max_queue_depth})")
        if self._pending_bytes + nbytes > self.cfg.max_pending_bytes:
            self.counters["rejected"] += 1
            raise QueueFull(
                f"pending input bytes would exceed the "
                f"{self.cfg.max_pending_bytes}-byte admission bound")
        return cause

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            c = dict(self.counters)
            c.update(queued=self._queued, inflight=self._inflight,
                     pending_bytes=self._pending_bytes,
                     packing_efficiency=(
                         c["packed_lanes"] / c["total_lanes"]
                         if c["total_lanes"] else None))
            return c

    def stats_snapshot(self) -> dict:
        """The process-wide metrics registry's DELTA since this server
        was constructed (``nmfx.obs.metrics.MetricsRegistry.delta``):
        counters and histogram counts/sums are windowed to this
        server's lifetime, gauges report their current level — the
        structured successor to :meth:`stats` (docs/serving.md
        "Observability"). Plain data; each metric's ``series`` dict is
        keyed by label-value TUPLES (``()`` for unlabeled series), so
        stringify the keys before ``json.dumps`` — for wire formats
        use :meth:`metrics_text` instead.

        The ``"perf"`` key carries the per-dispatch roofline
        attribution summary (``nmfx.obs.costmodel.perf_summary`` —
        model FLOPs/bytes, achieved FLOP/s, MFU, arithmetic intensity
        and the compute-vs-bandwidth verdict per dispatch kind;
        docs/observability.md "Performance attribution").

        The ``"slo"`` key carries the server's SLO engine status
        (``nmfx.obs.slo`` — per-objective multi-window burn rates and
        alert states, evaluated over the process registry right now;
        alert TRANSITIONS also land in the flight recorder)."""
        snap = _metrics.registry().delta(self._metrics_t0)
        snap["perf"] = _costmodel.perf_summary()
        snap["slo"] = self._slo.evaluate()
        return snap

    def metrics_text(self) -> str:
        """Prometheus text exposition of the process-wide registry —
        the ``/metrics`` payload an operator's scraper ingests (serve
        latency histograms, dispatch/lane counters, cache and compile
        counters; docs/observability.md "Metric naming"). Process-wide
        and cumulative by Prometheus convention; for this server's
        window use :meth:`stats_snapshot`."""
        return _metrics.registry().prometheus_text()

    # -- scheduler ---------------------------------------------------------
    def _ensure_workers(self) -> None:
        # caller holds the lock
        if self._scheduler is None:
            self._sched_clean = False
            self._scheduler = threading.Thread(
                target=self._scheduler_main, daemon=True,
                name="nmfx-serve-sched")
            self._scheduler.start()
        if self._watchdog is None:
            self._watchdog = threading.Thread(
                target=self._run_watchdog, daemon=True,
                name="nmfx-serve-watchdog")
            self._watchdog.start()
        while len(self._harvesters) < self.cfg.harvest_workers:
            t = threading.Thread(target=self._run_harvester, daemon=True,
                                 name="nmfx-serve-harvest")
            t.start()
            self._harvesters.append(t)

    def _expire_locked(self, now: float) -> None:
        """Resolve queued requests whose deadline passed — typed
        DeadlineExceeded, never dispatched. Caller holds the lock."""
        keep = []
        for entry in self._queue:
            req = entry[1]
            if req.future.cancelled():
                self._drop_locked(req, "cancelled")
            elif req.deadline is not None and now >= req.deadline:
                self._drop_locked(req, "deadline")
                if req.future.set_running_or_notify_cancel():
                    req.stats.queue_wait_s = now - req.submitted
                    req.stats.latency_s = now - req.submitted
                    _e2e_hist.observe(req.stats.latency_s,
                                      outcome="deadline")
                    req.future.set_exception(DeadlineExceeded(
                        "deadline expired after "
                        f"{now - req.submitted:.3f}s in queue; the "
                        "request was never dispatched"))
            else:
                keep.append(entry)
        if len(keep) != len(self._queue):
            self._queue[:] = keep
            heapq.heapify(self._queue)

    def _drop_locked(self, req: _Request, why: str) -> None:
        self._queued -= 1
        self._pending_bytes -= req.a.nbytes
        self._sync_gauges()
        self.counters["cancelled" if why == "cancelled"
                      else "deadline_expired"] += 1

    def _next_deadline_locked(self) -> "float | None":
        dls = [r.deadline for _, r in self._queue
               if r.deadline is not None]
        return min(dls) if dls else None

    def _pop_locked(self) -> "_Request | None":
        while self._queue:
            _, req = heapq.heappop(self._queue)
            if req.future.cancelled():
                self._drop_locked(req, "cancelled")
                continue
            self._queued -= 1
            self._pending_bytes -= req.a.nbytes
            self._sync_gauges()
            return req
        return None

    def _take_compatible_locked(self, head: _Request, lanes: int,
                                taken: int) -> "list[_Request]":
        """Pull queued requests sharing ``head``'s compatibility key, in
        priority order, within the batch bounds. Caller holds the
        lock."""
        mates: "list[_Request]" = []
        keep = []
        for entry in sorted(self._queue):
            req = entry[1]
            if (taken + len(mates) < self.cfg.max_batch_requests
                    and req.compat == head.compat
                    and not req.future.cancelled()
                    and (req.deadline is None
                         or time.monotonic() < req.deadline)
                    # a request whose deadline clamps its iteration
                    # budget must dispatch SOLO (the contract above):
                    # packed lanes run at the shared max_iter, and a
                    # mate expiring mid-solve would have its computed
                    # results discarded — left queued, it pops as head
                    # and dispatches clamped
                    and not self._budget_clamps(req)
                    and lanes + req.lanes <= self.cfg.max_batch_lanes):
                mates.append(req)
                lanes += req.lanes
                self._queued -= 1
                self._pending_bytes -= req.a.nbytes
            else:
                keep.append(entry)
        if mates:
            self._queue[:] = keep
            heapq.heapify(self._queue)
            self._sync_gauges()
        return mates

    def _scheduler_main(self) -> None:
        """Scheduler thread body: the loop, plus the crash fence. An
        exception escaping ``_run_scheduler`` used to kill the one
        thread that owns the device and leave every queued Future
        hanging forever (the ISSUE 7 motivation); now it is recorded
        and the watchdog resolves every stranded Future with a typed
        :class:`ServerCrashed` — never a hang."""
        try:
            self._run_scheduler()
            with self._cond:
                self._sched_clean = True
        except BaseException as e:  # nmfx: ignore[NMFX006] -- watchdog resolves strays
            with self._cond:
                self._crash = e
                self._cond.notify_all()

    def _run_scheduler(self) -> None:
        from nmfx import faults

        while True:
            with self._cond:
                self._heartbeat = time.monotonic()
                while True:
                    now = time.monotonic()
                    self._expire_locked(now)
                    if self._queue and not self._paused:
                        break
                    if self._closed:
                        return
                    dl = self._next_deadline_locked()
                    self._cond.wait(timeout=None if dl is None
                                    else max(dl - now, 0.0))
                head = self._pop_locked()
                if head is None:
                    continue
                # chaos site: scheduler death with a request IN FLIGHT
                # (popped from the queue, dispatch not yet started) —
                # the worst-placed crash: the request is in no queue, so
                # only the watchdog's tracked-request registry can still
                # resolve its Future (tests/test_faults.py pins that it
                # does)
                faults.inject("serve.scheduler")
                batch = [head]
                packable = (self.cfg.pack and head.compat is not None
                            and not self._budget_clamps(head))
                if packable:
                    batch += self._take_compatible_locked(
                        head, head.lanes, 1)
            if (packable and len(batch) < self.cfg.max_batch_requests
                    and self.cfg.batch_linger_s > 0):
                batch = self._linger(head, batch)
            if head.deadline is not None \
                    and time.monotonic() >= head.deadline:
                # expired between queue and dispatch: resolve typed,
                # return its mates to the queue unharmed
                self._resolve_expired(head)
                with self._cond:
                    for req in batch[1:]:
                        heapq.heappush(self._queue,
                                       (req.order_key(), req))
                        self._queued += 1
                        self._pending_bytes += req.a.nbytes
                    self._sync_gauges()
                continue
            self._dispatch(batch)

    # -- watchdog ----------------------------------------------------------
    def _run_watchdog(self) -> None:
        """Heartbeat-checked scheduler monitor (docs/serving.md
        "Failure model"): polls every ``ServeConfig.watchdog_interval_s``
        for a recorded scheduler crash (``_scheduler_main``'s fence) or
        a scheduler thread that died WITHOUT recording one (an exotic
        interpreter-level death — the heartbeat's last reading is then
        the only evidence). On crash: every tracked, unresolved request
        the harvesters don't own resolves to a typed
        :class:`ServerCrashed` chaining the scheduler's exception —
        never a hang — and, with ``ServeConfig.restart_scheduler``, a
        fresh scheduler thread takes over NEW submissions (work pending
        at crash time is failed loudly, never replayed: at-most-once
        dispatch)."""
        from nmfx.faults import warn_once

        while True:
            with self._cond:
                cause = self._crash
                sched = self._scheduler
                if cause is None and sched is not None \
                        and not sched.is_alive() and not self._sched_clean:
                    cause = RuntimeError(
                        "scheduler thread died without recording an "
                        "exception (last heartbeat "
                        f"{time.monotonic() - self._heartbeat:.1f}s ago)")
                if cause is None:
                    if self._closed and (
                            sched is None or not sched.is_alive()):
                        return
                    self._cond.wait(
                        timeout=self.cfg.watchdog_interval_s)
                    continue
                # crash: collect the strays atomically with the queue
                # reset, so a submit racing the restart lands on the
                # fresh queue and is never failed spuriously
                self._crash = None
                self._scheduler = None
                self._queue.clear()
                self._queued = 0
                self._pending_bytes = 0
                self._sync_gauges()
                restart = self.cfg.restart_scheduler and not self._closed
                if not restart:
                    self._down = cause
                with self._tracked_lock:  # lock order: _lock → _tracked
                    strays = list(self._tracked.values())
            with self._harvest_cond:
                owned = set(self._harvest_owned)
            failed = 0
            for req in strays:
                if req.seq in owned:
                    continue  # a live harvester will resolve it
                fut = req.future
                if fut.done():
                    continue
                fut.set_running_or_notify_cancel()
                if fut.done():
                    continue
                req.stats.latency_s = time.monotonic() - req.submitted
                err = ServerCrashed(
                    "the scheduler thread died while this request was "
                    "pending; it was never (or only partially) "
                    "dispatched and is failed rather than replayed "
                    "(at-most-once dispatch)")
                err.__cause__ = cause
                fut.set_exception(err)
                failed += 1
                _flight.record("serve.watchdog",
                               action="resolve_stranded",
                               request_id=req.seq)
            with self._lock:
                self.counters["failed"] += failed
            warn_once(
                "scheduler-crash",
                f"serve scheduler crashed ({cause!r}); {failed} pending "
                "request(s) resolved with ServerCrashed"
                + (", scheduler restarted" if restart
                   else ", server is down (restart_scheduler=False)"))
            _flight.record("serve.watchdog", action="scheduler_crash",
                           error=cause, resolved=failed,
                           restarted=restart)
            # the crash postmortem (docs/observability.md "Flight
            # recorder"): the retained event ring — armed/fired fault
            # sites, the dispatches and degradations leading up to the
            # crash, and the stray resolutions just booked — written as
            # one artifact (when a dump directory is configured; always
            # retained in-process via nmfx.obs.flight.last_dump)
            _flight.dump("serve-scheduler-crash",
                         extra={"error": cause,
                                "resolved_requests": failed,
                                "scheduler_restarted": restart})
            if restart:
                with self._cond:
                    if not self._closed:
                        self._ensure_workers()

    def _linger(self, head: _Request,
                batch: "list[_Request]") -> "list[_Request]":
        """Continuous-batching linger: hold ``head``'s dispatch briefly
        so near-simultaneous compatible arrivals share its lanes."""
        until = time.monotonic() + self.cfg.batch_linger_s
        lanes = sum(r.lanes for r in batch)
        with self._cond:
            while (len(batch) < self.cfg.max_batch_requests
                   and not self._closed):
                remaining = until - time.monotonic()
                if remaining <= 0:
                    break
                batch += self._take_compatible_locked(head, lanes,
                                                      len(batch))
                lanes = sum(r.lanes for r in batch)
                if len(batch) >= self.cfg.max_batch_requests:
                    break
                self._cond.wait(timeout=remaining)
            batch += self._take_compatible_locked(head, lanes, len(batch))
        return batch

    def _budget_clamps(self, req: _Request) -> bool:
        return (req.deadline is not None
                and self.cfg.iter_rate_estimate is not None)

    def _budget_iters(self, req: _Request) -> "int | None":
        """Deadline → per-lane iteration budget: the remaining wall at
        the estimated iteration rate, rounded UP to a power-of-two
        multiple of check_every (bounding executable churn to
        log(max_iter) distinct budgets), clamped to the configured
        max_iter. The lanes then stop via the per-lane in-kernel budget
        — the only eviction a launched dispatch admits."""
        if not self._budget_clamps(req):
            return None
        remaining = req.deadline - time.monotonic()
        want = int(remaining * self.cfg.iter_rate_estimate)
        ce = req.scfg.check_every
        step = ce
        while step < max(want, 1):
            step *= 2
        return min(step, req.scfg.max_iter)

    def _resolve_expired(self, req: _Request,
                         mid_solve: bool = False) -> None:
        now = time.monotonic()
        req.stats.latency_s = now - req.submitted
        with self._lock:
            self.counters["deadline_expired"] += 1
        if req.future.cancelled() or req.future.done():
            return
        if not mid_solve and not req.future.set_running_or_notify_cancel():
            return
        # observed only when the future actually resolves as a
        # deadline — a cancelled request must not skew the
        # outcome-labeled latency series
        _e2e_hist.observe(req.stats.latency_s, outcome="deadline")
        msg = ("deadline expired mid-solve; the request's lanes were "
               "stopped by the per-lane iteration budget and its "
               "results discarded" if mid_solve else
               "deadline expired before dispatch")
        req.future.set_exception(DeadlineExceeded(msg))

    def _dispatch(self, batch: "list[_Request]") -> None:
        from nmfx.faults import warn_once

        t0 = time.monotonic()
        live = [r for r in batch
                if r.future.set_running_or_notify_cancel()]
        with self._lock:
            self.counters["cancelled"] += len(batch) - len(live)
        if not live:
            return
        tracer = _trace.default_tracer()
        for req in live:
            req.stats.queue_wait_s = t0 - req.submitted
            # retroactive span: the queue residency that just ended at
            # this dispatch — carries the request id (RequestStats ids
            # in span args, ISSUE 10 satellite)
            tracer.complete("serve.queue_wait", req.stats.queue_wait_s,
                            cat="serve", args={"request_id": req.seq})
            _queue_wait_hist.observe(req.stats.queue_wait_s)
        if len(live) >= 2:
            try:
                with tracer.span(
                        "serve.dispatch", cat="serve",
                        args={"request_ids": [r.seq for r in live],
                              "packed": True,
                              "lanes": sum(r.lanes for r in live)}), \
                        self._prof.phase("serve.pack"):
                    placed = self.engine.place(live[0])
                    raws = self.engine.dispatch_packed(live, placed)
            except BaseException as e:
                # degradation rung 1 (docs/serving.md "Failure model"):
                # a failed PACKED dispatch retries each request solo —
                # failure isolation becomes per-request, and a fault in
                # the shared packed path cannot take down its mates
                warn_once(
                    "packed-dispatch-fallback",
                    f"packed dispatch of {len(live)} requests failed "
                    f"({e!r}); retrying each request solo — results "
                    "are unaffected, the cross-request batching win is "
                    "lost for this batch")
            else:
                self._handoff(live, raws, t0, packed=True)
                return
        # solo: a single head, or every member of a failed packed batch
        for req in live:
            scfg = req.scfg
            budget = self._budget_iters(req)
            cause = req.degrade_cause
            if (cause is None and budget is not None
                    and budget < scfg.max_iter
                    and self.cfg.quality_elastic
                    and self._sketch_eligible(scfg)):
                # quality elasticity, cause "deadline": the deadline
                # would clamp the exact solve's iteration budget —
                # serve the CHEAPER engine at its full budget instead
                # of a truncated exact solve
                cause = "deadline"
            if cause is not None:
                req.degrade_cause = cause
                req.quality = "sketched"
                scfg = dataclasses.replace(req.scfg, backend="sketched")
                req.stats.quality = "sketched"
                req.stats.degraded_cause = cause
                _quality_degraded_total.inc(cause=cause)
                _flight.record("serve.quality_degraded",
                               request_id=req.seq, cause=cause)
                with self._lock:
                    self.counters["quality_degraded"] += 1
            elif budget is not None and budget < scfg.max_iter:
                scfg = dataclasses.replace(scfg, max_iter=budget)
                req.stats.budget_iters = budget
                with self._lock:
                    self.counters["budget_clamped"] += 1
            try:
                with tracer.span(
                        "serve.dispatch", cat="serve",
                        args={"request_ids": [req.seq],
                              "packed": False, "lanes": req.lanes}), \
                        self._prof.phase("serve.pack"):
                    raw = self._dispatch_solo_retrying(req, scfg)
            except BaseException as e:
                with self._lock:
                    self.counters["failed"] += 1
                req.stats.latency_s = time.monotonic() - req.submitted
                if not req.future.done():
                    _e2e_hist.observe(req.stats.latency_s,
                                      outcome="failed")
                    req.future.set_exception(e)
            else:
                self._handoff([req], [raw], t0, packed=False)

    def _dispatch_solo_retrying(self, req: _Request, scfg: SolverConfig):
        """Degradation rung 2: each solo dispatch gets
        ``ServeConfig.dispatch_retries`` additional attempts with
        exponential backoff (``retry_backoff_s * 2**i`` before retry
        ``i``); exhausting them raises a typed :class:`RequestFailed`
        whose ``__cause__`` chains the last underlying failure."""
        from nmfx.faults import warn_once

        last: "BaseException | None" = None
        for attempt in range(self.cfg.dispatch_retries + 1):
            if attempt:
                time.sleep(self.cfg.retry_backoff_s * 2 ** (attempt - 1))
            try:
                # a quality-degraded dispatch runs the sketched engine,
                # which the exec-cache path cannot serve — place() would
                # key off the ORIGINAL exact config and pad+transfer a
                # device buffer the dispatch then ignores (a wasted full
                # H2D exactly when the server is overloaded)
                placed = (None if scfg.backend == "sketched"
                          else self.engine.place(req))
                return self.engine.dispatch_solo(req, placed, scfg)
            except BaseException as e:  # retried; typed RequestFailed
                last = e                # below when exhausted
                # flight event per ATTEMPT (warn_once dedups the log
                # line; the postmortem needs every retry)
                _flight.record("serve.retry", request_id=req.seq,
                               attempt=attempt + 1,
                               retries=self.cfg.dispatch_retries,
                               error=e)
                warn_once(
                    "solo-dispatch-retry",
                    f"solo dispatch attempt {attempt + 1} failed "
                    f"({e!r}); "
                    + (f"retrying (up to {self.cfg.dispatch_retries} "
                       "retr(y/ies) with exponential backoff)"
                       if self.cfg.dispatch_retries else
                       "no retries configured"))
        raise RequestFailed(
            f"every dispatch attempt failed "
            f"({self.cfg.dispatch_retries + 1} solo attempt(s)"
            + (" after the packed attempt" if req.compat is not None
               else "") + ")") from last

    def _handoff(self, live: "list[_Request]", raws: list, t0: float,
                 packed: bool) -> None:
        """Book a successful dispatch and hand each request to the
        completion workers (who own its Future from here — the
        watchdog's ``_harvest_owned`` contract)."""
        t1 = time.monotonic()
        lanes = sum(r.lanes for r in live)
        _note_dispatch(len(live), lanes)
        _flight.record("serve.dispatch",
                       request_ids=[r.seq for r in live],
                       packed=packed, lanes=lanes,
                       pack_s=round(t1 - t0, 6))
        _pack_hist.observe(t1 - t0)
        with self._lock:
            self.counters["dispatches"] += 1
            self.counters["total_lanes"] += lanes
            if packed:
                self.counters["packed_dispatches"] += 1
                self.counters["packed_requests"] += len(live)
                self.counters["packed_lanes"] += lanes
            self._inflight += len(live)
            self._sync_gauges()
        for req, raw in zip(live, raws):
            req.stats.pack_s = t1 - t0
            req.stats.packed_requests = len(live)
            with self._harvest_cond:
                self._harvest_owned.add(req.seq)
                self._harvest_q.append((req, raw, t1))
                self._harvest_cond.notify()

    # -- completion --------------------------------------------------------
    def _run_harvester(self) -> None:
        from nmfx import faults
        from nmfx.api import ConsensusResult
        from nmfx.faults import InsufficientRestarts, warn_once
        from nmfx.harvest import harvest_rank

        while True:
            with self._harvest_cond:
                while not self._harvest_q:
                    self._harvest_cond.wait()
                item = self._harvest_q.pop(0)
            if item is None:
                return
            req, raw, t_disp = item
            try:
                t_h0 = time.perf_counter()
                fetch_s = select_s = 0.0
                per_k = {}
                for k in req.ks:
                    try:
                        # chaos site: a completion (harvest) worker
                        # dying mid-rank — same site the streamed
                        # pipeline's workers pass (nmfx/harvest.py)
                        faults.inject("harvest.worker")
                        kres, f_s, s_s = harvest_rank(
                            k, raw[k], req.linkage, self._prof,
                            req.min_restarts)
                    except InsufficientRestarts:
                        raise  # deterministic: a re-run cannot succeed
                    except BaseException as e:
                        # recovery: the same device output through the
                        # same host math, inline — exact; a second
                        # failure resolves the future via the outer
                        # handler
                        warn_once(
                            "harvest-worker-fallback",
                            f"serve completion worker failed on rank "
                            f"{k} ({e!r}); re-running that rank's "
                            "harvest inline — results are unaffected")
                        kres, f_s, s_s = harvest_rank(
                            k, raw[k], req.linkage, self._prof,
                            req.min_restarts)
                    per_k[k] = kres
                    fetch_s += f_s
                    select_s += s_s
                # retroactive span over this request's whole harvest
                # (device-blocked fetch + rank selection, every rank):
                # the per-rank xfer.d2h_overlap / post.rank_selection
                # spans harvest_rank booked nest inside it on this
                # worker thread
                _trace.default_tracer().complete(
                    "serve.harvest", time.perf_counter() - t_h0,
                    cat="serve", args={"request_id": req.seq})
                req.stats.solve_s = fetch_s
                req.stats.harvest_s = select_s
                _solve_hist.observe(fetch_s)
                now = time.monotonic()
                # per-REQUEST roofline attribution (ISSUE 13): model
                # FLOPs of the lanes this request actually ran over its
                # dispatch→harvested wall. Packed mates' walls overlap
                # (each counts the shared device solve), so the serve
                # kind reads as request-level throughput — the
                # dispatch-level kernel MFU lives under the exec.*/
                # sweep.* kinds (docs/observability.md)
                if _costmodel.attribution_enabled():
                    scfg_served = (
                        dataclasses.replace(req.scfg, backend="sketched")
                        if req.quality == "sketched" else req.scfg)
                    _costmodel.attribute_dispatch(
                        "serve", scfg_served, req.a.shape[0],
                        req.a.shape[1],
                        {k: np.asarray(r.iterations)
                         for k, r in per_k.items()},
                        now - t_disp)
                req.stats.latency_s = now - req.submitted
                if req.deadline is not None and now >= req.deadline:
                    self._resolve_expired(req, mid_solve=True)
                else:
                    # req.quality is the ONE quality funnel: "sketched"
                    # whenever the request was served by the compressed
                    # engine (by its own config, or degraded there) —
                    # the tagging invariant the lint fixture in
                    # tests/test_serve_quality.py pins (every
                    # ConsensusResult construction here must set it)
                    result = ConsensusResult(ks=req.ks, per_k=per_k,
                                             col_names=req.col_names,
                                             quality=req.quality)
                    if (self.result_cache is not None
                            and req.cache_fp is not None):
                        # degraded requests re-key at their ACTUAL
                        # served quality — a sketched answer must never
                        # be replayed to exact-quality submissions
                        pkey = (req.cache_key
                                if result.quality == req.cache_quality
                                else self._result_key(req,
                                                      result.quality))
                        try:
                            self.result_cache.put(pkey, result)
                        except Exception:  # nmfx: ignore[NMFX006] -- best-
                            # effort admission: cache trouble (disk
                            # full, perms) never fails the solve
                            pass
                    req.future.set_result(result)
                    _e2e_hist.observe(req.stats.latency_s,
                                      outcome="completed")
                    with self._lock:
                        self.counters["completed"] += 1
            except BaseException as e:  # resolves the request's Future
                with self._lock:
                    self.counters["failed"] += 1
                if not req.future.done():
                    _e2e_hist.observe(time.monotonic() - req.submitted,
                                      outcome="failed")
                    req.future.set_exception(e)
            finally:
                with self._harvest_cond:
                    self._harvest_owned.discard(req.seq)
                with self._lock:
                    self._inflight -= 1
                    self._sync_gauges()
