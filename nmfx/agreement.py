"""Consensus-level agreement between clustering results — the sketched
engine's accuracy contract (ISSUE 12).

The sketched engine (``backend="sketched"``, ``nmfx/solvers/sketched.py``)
is approximate by construction, so no bit-exact gate applies; what
consensus NMF actually CONSUMES from a solver is per-sample cluster
structure, and that is where the contract is pinned: the memberships two
pipelines derive from their consensus matrices must agree statistically
(adjusted Rand index / pairwise co-membership agreement), and their
cophenetic correlations must sit within a recorded gap. This module is
that yardstick — host-side numpy, no jax imports, usable from tests and
the bench ``detail.sketched`` stage alike.

All label comparisons are PERMUTATION-INVARIANT: both ARI and pairwise
agreement read only the co-membership structure, never the label values
(a relabeled partition scores identically — pinned by
tests/test_agreement.py).
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

__all__ = ["adjusted_rand_index", "consensus_agreement",
           "cophenetic_gap", "membership_agreement"]


def _as_labels(x) -> np.ndarray:
    arr = np.asarray(x).ravel()
    if arr.size == 0:
        raise ValueError("labelings must be non-empty")
    return arr


def membership_agreement(a, b) -> float:
    """Pairwise co-membership agreement of two labelings of the same
    samples: the fraction of sample PAIRS (i < j) on which the two
    partitions agree — both place the pair together, or both apart.
    1.0 = identical partitions (up to relabeling); a single sample
    (no pairs) is vacuously 1.0. This is the unadjusted Rand index —
    kept alongside :func:`adjusted_rand_index` because its absolute
    scale ("x% of pairs agree") is the operator-readable number the
    bench records."""
    a = _as_labels(a)
    b = _as_labels(b)
    if a.shape != b.shape:
        raise ValueError(
            f"labelings must have equal length, got {a.size} vs {b.size}")
    if a.size < 2:
        return 1.0
    iu = np.triu_indices(a.size, k=1)
    co_a = (a[:, None] == a[None, :])[iu]
    co_b = (b[:, None] == b[None, :])[iu]
    return float(np.mean(co_a == co_b))


def adjusted_rand_index(a, b) -> float:
    """Adjusted Rand index (Hubert & Arabie 1985) of two labelings of
    the same samples: pair-counting agreement corrected for chance —
    1.0 = identical partitions (up to relabeling), ~0 = what random
    labelings score, negative = worse than chance.

    Degenerate partitions (both all-one-cluster, or both
    all-singletons) make the adjustment's denominator zero; they are
    defined here as 1.0 when the two partitions are identical as
    partitions (the scikit-learn convention) — the cases where "no
    structure" agrees with "no structure"."""
    a = _as_labels(a)
    b = _as_labels(b)
    if a.shape != b.shape:
        raise ValueError(
            f"labelings must have equal length, got {a.size} vs {b.size}")
    n = a.size
    if n < 2:
        return 1.0
    # contingency table over the label sets actually present
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    na, nb = ai.max() + 1, bi.max() + 1
    ct = np.zeros((na, nb), dtype=np.int64)
    np.add.at(ct, (ai, bi), 1)

    def comb2(x):
        x = np.asarray(x, dtype=np.float64)
        return x * (x - 1.0) / 2.0

    sum_idx = comb2(ct).sum()
    sum_a = comb2(ct.sum(axis=1)).sum()
    sum_b = comb2(ct.sum(axis=0)).sum()
    total = comb2(n)
    expected = sum_a * sum_b / total
    max_idx = 0.5 * (sum_a + sum_b)
    if max_idx == expected:
        # degenerate: both partitions trivial (all-together or
        # all-apart). Identical structure -> perfect agreement.
        return 1.0 if membership_agreement(a, b) == 1.0 else 0.0
    return float((sum_idx - expected) / (max_idx - expected))


def cophenetic_gap(res_a, res_b,
                   ks: "Sequence[int] | None" = None) -> float:
    """Max |rho_a − rho_b| over the shared ranks of two
    :class:`~nmfx.api.ConsensusResult`\\ s — the rank-selection half of
    the agreement contract (two engines that cluster alike must also
    RANK alike)."""
    shared = _shared_ks(res_a, res_b, ks)
    return max(abs(res_a.per_k[k].rho - res_b.per_k[k].rho)
               for k in shared)


def _shared_ks(res_a, res_b, ks):
    shared = tuple(k for k in res_a.ks if k in set(res_b.ks))
    if ks is not None:
        ks = tuple(ks)
        missing = [k for k in ks if k not in shared]
        if missing:
            raise ValueError(
                f"rank(s) {missing} not present in both results "
                f"(shared: {list(shared)})")
        shared = ks
    if not shared:
        raise ValueError("the two results share no ranks")
    return shared


def consensus_agreement(res_a, res_b,
                        ks: "Sequence[int] | None" = None
                        ) -> "Mapping[str, object]":
    """Full agreement report between two
    :class:`~nmfx.api.ConsensusResult`\\ s (typically one exact, one
    sketched) over their shared ranks (or an explicit ``ks`` subset):

    ``per_k``
        ``{k: {"ari", "membership_agreement", "rho_gap"}}`` — ARI and
        pairwise agreement of the cutree memberships, and that rank's
        |Δrho|.
    ``min_ari`` / ``mean_ari`` / ``max_rho_gap``
        the scalars gates pin (tests/test_sketched.py; the bench
        ``detail.sketched`` stage exits 2 on a miss).
    """
    shared = _shared_ks(res_a, res_b, ks)
    per_k = {}
    for k in shared:
        ma, mb = res_a.per_k[k].membership, res_b.per_k[k].membership
        per_k[k] = {
            "ari": adjusted_rand_index(ma, mb),
            "membership_agreement": membership_agreement(ma, mb),
            "rho_gap": abs(res_a.per_k[k].rho - res_b.per_k[k].rho),
        }
    aris = [v["ari"] for v in per_k.values()]
    return {
        "per_k": per_k,
        "min_ari": min(aris),
        "mean_ari": float(np.mean(aris)),
        "max_rho_gap": max(v["rho_gap"] for v in per_k.values()),
    }
