"""Out-of-core tile pipeline: atlas-scale solves that stream A (ISSUE 17).

nmfx's in-core engines require A on device for every update; the atlases
real users submit do not fit. "Distributed Out-of-Memory NMF" (arxiv
2202.09518) gives the decomposition this module implements on a single
device: partition A into feature-axis (row) blocks sized to a device
budget, keep W/H — and the whole vmapped restart pool — device-resident,
and stream the blocks through the mu/hals updates with the NEXT tile's
``device_put`` overlapped against the CURRENT tile's compute (the same
double-buffer idiom as ``data_cache._chunked_put``, promoted from a
first-touch trick into the steady-state iteration loop). MPI-FAUN
(arxiv 1609.09154) supplies the algebra that makes tiling work at all:
mu and hals consume A only through the Gram-style contractions WᵀA and
AHᵀ, so each tile's contribution reduces into k×n / k×k terms and the
full matrix never needs to exist on device at once.

Per-iteration schedule — ONE pass over A, not two:

* head (no A): the H half-step. mu reads the carried numerator
  C = WᵀA (accumulated by the previous pass) and computes
  H ← update(H, C, (WᵀW)H); hals replays its k coordinate updates from
  the carried (WᵀA, WᵀW). Then HHᵀ is formed from the fresh H.
* tile pass (streams A): for each row block t in FIXED tile order, the
  W half-step on the resident slice W[t] using A_t·Hᵀ and the shared
  HHᵀ, followed by accumulation of the NEXT iteration's carried Grams
  (W_newᵀA and, for hals, W_newᵀW_new) in float32 — the mu W-update's
  "fresh H" and H-update's "previous W" semantics fall out exactly.

The residual needed by hals's TolFun check and by every final result is
free: ‖A − WH‖² = ‖A‖² − 2⟨WᵀA, H⟩ + ⟨WᵀW, HHᵀ⟩, and the pass already
produced WᵀA — so convergence checks never trigger an extra pass.

Engine-family contract (``checkpoint.engine_family`` / docs/serving.md):

* A config that resolves to ONE tile on a dense input never reaches this
  module — ``sweep.sweep`` delegates it to the in-core path with
  ``tile_rows=None``, so "tiled but fits" is bit-identical to dense by
  construction (same jit graph, same cache/fingerprint identity).
* Multi-tile (or sparse) solves run here as their own engine family
  ``"tiled"``: fixed tile order + f32 accumulators make the reduction
  deterministic, so streamed runs are bitwise reproducible against
  themselves (prefetch on or off, resumed or uninterrupted) and
  statistically gated against dense (``nmfx/agreement.py``).

Sparse inputs (``nmfx.sparse.SparseMatrix``) stream each row block as a
device BCOO and contract stored nonzeros only, via ONE stacked
sparse×dense GEMM over lane-stacked factors per contraction — never a
vmap over BCOO ops. Tile nse is padded to the plan-wide maximum with
explicit zeros so every tile shares one compiled executable.
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import sparse as jsparse

from nmfx.config import TILED_ALGORITHMS, InitConfig, SolverConfig
from nmfx.init import random_init
from nmfx.obs import metrics as _metrics
from nmfx.profiling import NullProfiler
from nmfx.solvers.base import StopReason, clamp, matmul_precision_ctx
from nmfx.solvers.mu import _mu_update
from nmfx.sparse import SparseMatrix, note_sparse_tile

__all__ = [
    "TilePlan", "TileStream", "TiledState", "TiledPoolResult",
    "plan_for", "resolve_tile_rows", "tile_budget_bytes",
    "set_tile_budget_bytes", "set_tile_prefetch", "tile_prefetch_enabled",
    "run_tiled_pool", "sweep_one_k_tiled", "solve_chunk_tiled",
    "partial_payload", "resume_from_payload",
]

#: profiler phase names (``xfer.`` prefix = overlap class, see
#: ``profiling.OVERLAP_PREFIXES``): dispatch cost vs blocking wait on a
#: prefetched tile — the bench's h2d-overlap ratio is 1 − wait/solve
TILE_XFER_PHASE = "xfer.h2d_tile"
TILE_WAIT_PHASE = "xfer.h2d_tile_wait"

_tile_passes_total = _metrics.counter(
    "nmfx_tile_passes_total",
    "full streaming passes over A by the out-of-core tile pipeline")
_tile_h2d_bytes_total = _metrics.counter(
    "nmfx_tile_h2d_bytes_total",
    "bytes of tile payloads transferred host-to-device by the tile "
    "pipeline")
_tile_partial_resumes_total = _metrics.counter(
    "nmfx_tile_partial_resumes_total",
    "tiled chunk solves resumed mid-matrix from a partial checkpoint "
    "record")


def note_partial_resume() -> None:
    """Book one mid-matrix resume (called by ``nmfx/checkpoint.py``)."""
    _tile_partial_resumes_total.inc()


# -- device budget -----------------------------------------------------------

#: default per-tile working-set budget: two resident buffers (current +
#: prefetched) must fit, so tiles are sized to budget/2
_DEFAULT_TILE_BUDGET_BYTES = 256 << 20

_budget_override: "int | None" = None


def set_tile_budget_bytes(nbytes: "int | None") -> None:
    """Process-wide override of the tile budget (None restores the
    env/default chain) — the bench's larger-than-device-memory rung
    forces this small on CPU to exercise real multi-tile streaming."""
    global _budget_override
    if nbytes is not None and int(nbytes) < 1:
        raise ValueError(f"tile budget must be >= 1 byte, got {nbytes}")
    _budget_override = None if nbytes is None else int(nbytes)


def tile_budget_bytes() -> int:
    """Device-budget for streamed tiles: override > env
    ``NMFX_TILE_BUDGET_BYTES`` > default (256 MiB)."""
    if _budget_override is not None:
        return _budget_override
    env = os.environ.get("NMFX_TILE_BUDGET_BYTES", "").strip()
    if env:
        return max(1, int(env))
    return _DEFAULT_TILE_BUDGET_BYTES


_prefetch_enabled = True


def set_tile_prefetch(on: bool) -> None:
    """Toggle next-tile prefetch (double-buffering). Streaming results
    are bit-identical either way — the toggle exists so tests/bench can
    PIN that, and measure what overlap buys."""
    global _prefetch_enabled
    _prefetch_enabled = bool(on)


def tile_prefetch_enabled() -> bool:
    return _prefetch_enabled


# -- tile plan ---------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TilePlan:
    """Deterministic feature-axis partition of an (m, n) matrix into
    row blocks of ``tile_rows`` (last block ragged). The plan is part of
    a tiled sweep's identity: the multi-tile reduction order depends on
    it, so the checkpoint fingerprint hashes ``as_meta()`` and a changed
    plan cold-starts rather than resuming foreign partials."""

    m: int
    n: int
    tile_rows: int

    def __post_init__(self):
        if self.m < 1 or self.n < 1:
            raise ValueError(f"degenerate matrix ({self.m}, {self.n})")
        if not 1 <= self.tile_rows:
            raise ValueError(f"tile_rows must be >= 1, got {self.tile_rows}")
        object.__setattr__(self, "tile_rows", min(self.tile_rows, self.m))

    @property
    def n_tiles(self) -> int:
        return -(-self.m // self.tile_rows)

    @property
    def boundaries(self) -> "tuple[tuple[int, int], ...]":
        return tuple((r0, min(r0 + self.tile_rows, self.m))
                     for r0 in range(0, self.m, self.tile_rows))

    def as_meta(self) -> dict:
        return {"m": self.m, "n": self.n, "tile_rows": self.tile_rows,
                "n_tiles": self.n_tiles}


def resolve_tile_rows(tile_rows, m: int, n: int, itemsize: int,
                      avg_row_bytes: "float | None" = None,
                      budget: "int | None" = None) -> int:
    """Resolve a ``SolverConfig.tile_rows`` knob to a concrete block
    height. ``"auto"`` sizes blocks so two (current + prefetched) fit
    the byte budget; ints clamp to [1, m]."""
    if isinstance(tile_rows, int) and not isinstance(tile_rows, bool):
        return max(1, min(tile_rows, m))
    if tile_rows != "auto":
        raise ValueError(
            f"cannot resolve tile_rows={tile_rows!r} (expected an int or "
            "'auto')")
    if budget is None:
        budget = tile_budget_bytes()
    row_bytes = float(avg_row_bytes) if avg_row_bytes else float(n * itemsize)
    row_bytes = max(row_bytes, 1.0)
    rows = int(budget // (2.0 * row_bytes))
    return max(1, min(rows, m))


def plan_for(source, solver_cfg: SolverConfig) -> TilePlan:
    """The tile plan a config implies for ``source`` (host dense array
    or :class:`~nmfx.sparse.SparseMatrix`). ``tile_rows=None`` on a
    sparse source means one whole-matrix tile (sparse inputs always run
    the tiled engine — there is no dense in-core path to delegate to)."""
    m, n = int(source.shape[0]), int(source.shape[1])
    itemsize = jnp.dtype(solver_cfg.dtype).itemsize
    tr = solver_cfg.tile_rows
    if tr is None:
        return TilePlan(m, n, m)
    avg_row_bytes = None
    if isinstance(source, SparseMatrix):
        # stored-nonzero payload per row: value + (row, col) int32 pair
        avg_row_bytes = (source.nnz / max(m, 1)) * (itemsize + 8)
    rows = resolve_tile_rows(tr, m, n, itemsize, avg_row_bytes=avg_row_bytes)
    return TilePlan(m, n, rows)


# -- tile stream -------------------------------------------------------------

class TileStream:
    """Streams a host matrix's row blocks onto the device, double-
    buffered: tile t+1's ``device_put`` is dispatched before tile t is
    consumed, so the transfer rides under tile t's update compute. The
    host bytes per tile are identical with prefetch on or off, so the
    toggle cannot change results — only overlap.

    Dense sources yield ``(mt, n)`` device arrays; sparse sources yield
    device BCOO blocks whose nse is padded to the plan-wide maximum
    with explicit zeros (index (0, 0), value 0 — contributing exact
    zeros to every contraction) so all tiles share one compiled
    executable per pass function.

    Accounting: dispatch time books to ``xfer.h2d_tile`` and the
    blocking wait on an unfinished transfer to ``xfer.h2d_tile_wait``
    (both overlap-class phases, ``profiling.OVERLAP_PREFIXES``); bytes
    to ``nmfx_tile_h2d_bytes_total``, and sparse payloads additionally
    through ``nmfx.sparse.note_sparse_tile``.
    """

    def __init__(self, source, plan: TilePlan, dtype,
                 profiler=None, prefetch: "bool | None" = None):
        if tuple(int(s) for s in source.shape) != (plan.m, plan.n):
            raise ValueError(
                f"source shape {tuple(source.shape)} does not match plan "
                f"({plan.m}, {plan.n})")
        self.source = source
        self.plan = plan
        self.dtype = np.dtype(dtype)
        self.profiler = profiler if profiler is not None else NullProfiler()
        self.prefetch = (tile_prefetch_enabled() if prefetch is None
                         else bool(prefetch))
        self.sparse = isinstance(source, SparseMatrix)
        if self.sparse:
            nnzs = [int(source.indptr[r1] - source.indptr[r0])
                    for r0, r1 in plan.boundaries]
            self._pad_nse = max(max(nnzs), 1)

    def _put(self, t: int):
        """Dispatch tile t's host->device transfer (async)."""
        r0, r1 = self.plan.boundaries[t]
        t0 = time.perf_counter()
        if self.sparse:
            idx, data = self.source.tile_coo(r0, r1, self.dtype)
            nnz = len(data)
            pad = self._pad_nse - nnz
            if pad:
                idx = np.concatenate(
                    [idx, np.zeros((pad, 2), np.int32)], axis=0)
                data = np.concatenate([data, np.zeros(pad, self.dtype)])
            dev = (jax.device_put(data), jax.device_put(idx))
            nbytes = data.nbytes + idx.nbytes
            note_sparse_tile(nnz, nbytes)
        else:
            block = np.ascontiguousarray(
                np.asarray(self.source[r0:r1], self.dtype))
            dev = jax.device_put(block)
            nbytes = block.nbytes
        _tile_h2d_bytes_total.inc(nbytes)
        self.profiler.add_seconds(TILE_XFER_PHASE,
                                  time.perf_counter() - t0)
        return dev

    def _wait(self, dev, t: int):
        """Block until tile t's transfer finished; wrap sparse tiles."""
        r0, r1 = self.plan.boundaries[t]
        t0 = time.perf_counter()
        if self.sparse:
            data, idx = dev
            data.block_until_ready()
            idx.block_until_ready()
            out = jsparse.BCOO((data, idx), shape=(r1 - r0, self.plan.n))
        else:
            dev.block_until_ready()
            out = dev
        self.profiler.add_seconds(TILE_WAIT_PHASE,
                                  time.perf_counter() - t0)
        return out

    def tiles(self):
        """One full pass over A in fixed tile order: yields
        ``(t, r0, r1, a_t)`` with ``a_t`` ready on device."""
        _tile_passes_total.inc()
        nt = self.plan.n_tiles
        pending: "dict[int, Any]" = {}
        for t in range(nt):
            if t not in pending:
                pending[t] = self._put(t)
            if self.prefetch and t + 1 < nt and t + 1 not in pending:
                pending[t + 1] = self._put(t + 1)
            a_t = self._wait(pending.pop(t), t)
            r0, r1 = self.plan.boundaries[t]
            yield t, r0, r1, a_t


# -- tiled engine ------------------------------------------------------------

class TiledState(NamedTuple):
    """Device-resident restart-pool carry (leading axis = restarts).
    Only A is atlas-sized; W/H and the convergence bookkeeping are
    m·k / k·n per lane and stay resident — so the per-lane freeze
    masking and TolX deltas read resident state directly, mirroring
    ``solvers.base.State`` under the batched while_loop."""

    w: jax.Array  # (R, m, k)
    h: jax.Array  # (R, k, n)
    w_prev: jax.Array  # (R, m, k)
    h_prev: jax.Array  # (R, k, n)
    iteration: jax.Array  # (R,) i32
    dnorm: jax.Array  # (R,) residual at last check, inf until computed
    classes: jax.Array  # (R, n) i32
    stable: jax.Array  # (R,) i32
    done: jax.Array  # (R,) bool
    stop_reason: jax.Array  # (R,) i32 StopReason


class TiledPoolResult(NamedTuple):
    w: jax.Array  # (R, m, k)
    h: jax.Array  # (R, k, n)
    iterations: jax.Array  # (R,)
    dnorm: jax.Array  # (R,) final ||A - W H||_F / sqrt(m n)
    stop_reason: jax.Array  # (R,)


def _contract_ah(a_t, h):
    """(A_t)·Hᵀ over the lane stack: (mt, n) × (R, k, n) -> (R, mt, k).

    Sparse tiles use ONE stacked sparse×dense GEMM — H reshaped to
    (n, R·k) — instead of vmapping BCOO ops over lanes (BCOO has no
    batching rule worth trusting here, and one big GEMM is the shape
    sparse kernels are good at)."""
    r, k, n = h.shape
    if isinstance(a_t, jsparse.BCOO):
        hs = jnp.transpose(h, (2, 0, 1)).reshape(n, r * k)
        out = jsparse.bcoo_dot_general(
            a_t, hs, dimension_numbers=(((1,), (0,)), ((), ())))
        return jnp.transpose(out.reshape(-1, r, k), (1, 0, 2))
    return jnp.einsum("mn,rkn->rmk", a_t, h)


def _contract_wa(a_t, w_t):
    """(W_t)ᵀ·A_t over the lane stack: (R, mt, k) × (mt, n) -> (R, k, n).

    This is each tile's contribution to the carried Gram numerator
    WᵀA — the term the NEXT iteration's H half-step consumes."""
    r, mt, k = w_t.shape
    if isinstance(a_t, jsparse.BCOO):
        ws = jnp.transpose(w_t, (1, 0, 2)).reshape(mt, r * k)
        out = jsparse.bcoo_dot_general(
            a_t, ws, dimension_numbers=(((0,), (0,)), ((), ())))
        return jnp.transpose(out.reshape(-1, r, k), (1, 2, 0))
    return jnp.einsum("rmk,mn->rkn", w_t, a_t)


def _zero_carry(algorithm: str, r: int, k: int, n: int):
    """Fresh f32 Gram accumulators for one streaming pass (fixed tile
    order + f32 makes the multi-tile reduction deterministic — the
    bitwise self-consistency half of the engine-family contract)."""
    if algorithm == "mu":
        return (jnp.zeros((r, k, n), jnp.float32),)
    return (jnp.zeros((r, k, n), jnp.float32),
            jnp.zeros((r, k, k), jnp.float32))


@partial(jax.jit, static_argnames=("cfg",))
def _head_update(state: TiledState, carry, cfg: SolverConfig):
    """The A-free half of one iteration: previous-factor snapshots and
    per-lane iteration advance (masked exactly like the batched
    while_loop in ``solvers.base.run_loop``), then the H half-step from
    the carried Grams, then HHᵀ from the fresh H. Returns the updated
    state and HHᵀ for the tile pass."""
    active = ~state.done
    mask = active[:, None, None]
    w_prev = jnp.where(mask, state.w, state.w_prev)
    h_prev = jnp.where(mask, state.h, state.h_prev)
    iteration = state.iteration + active.astype(jnp.int32)
    h0 = state.h
    dtype = h0.dtype
    with matmul_precision_ctx(cfg.matmul_precision):
        if cfg.algorithm == "mu":
            # H ← H ∘ (WᵀA) / ((WᵀW)H + ε), numerator from the carry
            gram = jnp.einsum("rmk,rml->rkl", state.w, state.w)
            denomh = jnp.einsum("rkl,rln->rkn", gram, h0)
            h = _mu_update(h0, carry[0].astype(dtype), denomh, cfg)
        else:  # hals: k coordinate updates against the carried Grams
            wta = carry[0].astype(dtype)
            wtw = carry[1].astype(dtype)
            eps = cfg.div_eps
            h = h0
            k = h0.shape[1]
            for j in range(k):
                hj = h[:, j, :] + (
                    wta[:, j, :]
                    - jnp.einsum("rl,rln->rn", wtw[:, j, :], h)
                ) / (wtw[:, j, j][:, None] + eps)
                h = h.at[:, j, :].set(clamp(hj, cfg.zero_threshold))
        h = jnp.where(mask, h, h0)
        hht = jnp.einsum("rkn,rln->rkl", h, h)
    state = state._replace(h=h, w_prev=w_prev, h_prev=h_prev,
                           iteration=iteration)
    return state, hht


@partial(jax.jit, static_argnames=("cfg",))
def _tile_update(state: TiledState, hht, carry, inner, a_t, r0,
                 cfg: SolverConfig):
    """One tile of the W half-step + next-carry accumulation.

    ``r0`` is a traced scalar (one compiled executable per tile SHAPE,
    not per tile index — at most two: uniform + ragged-last). Frozen
    lanes keep their W slice bit-for-bit, and their carry contribution
    is recomputed from unchanged factors, so it is identical every
    pass — the invariant that lets a resumed solve replay exactly."""
    r, m, k = state.w.shape
    mt = a_t.shape[0]
    active = ~state.done
    mask = active[:, None, None]
    w_t = lax.dynamic_slice(state.w, (0, r0, 0), (r, mt, k))
    with matmul_precision_ctx(cfg.matmul_precision):
        aht = _contract_ah(a_t, state.h)  # (R, mt, k), H is fresh
        if cfg.algorithm == "mu":
            denomw = jnp.einsum("rmk,rkl->rml", w_t, hht)
            w_new = _mu_update(w_t, aht, denomw, cfg)
        else:  # hals: coordinate updates are row-local => tile-local
            eps = cfg.div_eps
            w_new = w_t
            for j in range(k):
                wj = w_new[:, :, j] + (
                    aht[:, :, j]
                    - jnp.einsum("rml,rl->rm", w_new, hht[:, j, :])
                ) / (hht[:, j, j][:, None] + eps)
                w_new = w_new.at[:, :, j].set(
                    clamp(wj, cfg.zero_threshold))
        w_new = jnp.where(mask, w_new, w_t)
        cw = _contract_wa(a_t, w_new)  # (R, k, n)
        inner = inner + jnp.sum(
            cw.astype(jnp.float32) * state.h.astype(jnp.float32),
            axis=(1, 2))
        if cfg.algorithm == "mu":
            carry = (carry[0] + cw.astype(jnp.float32),)
        else:
            wtw_t = jnp.einsum("rmk,rml->rkl", w_new, w_new)
            carry = (carry[0] + cw.astype(jnp.float32),
                     carry[1] + wtw_t.astype(jnp.float32))
    w = lax.dynamic_update_slice(state.w, w_new, (0, r0, 0))
    return state._replace(w=w), carry, inner


@partial(jax.jit, static_argnames=("cfg",))
def _tile_accumulate(state: TiledState, carry, inner, a_t, r0,
                     cfg: SolverConfig):
    """Gram accumulation WITHOUT a factor update: the prologue pass
    (builds iteration 1's carry from W0) and the final residual pass
    (rebuilds ⟨WᵀA, H⟩ for the last dnorm) share this."""
    r, m, k = state.w.shape
    mt = a_t.shape[0]
    w_t = lax.dynamic_slice(state.w, (0, r0, 0), (r, mt, k))
    with matmul_precision_ctx(cfg.matmul_precision):
        cw = _contract_wa(a_t, w_t)
        inner = inner + jnp.sum(
            cw.astype(jnp.float32) * state.h.astype(jnp.float32),
            axis=(1, 2))
        if cfg.algorithm == "mu":
            carry = (carry[0] + cw.astype(jnp.float32),)
        else:
            wtw_t = jnp.einsum("rmk,rml->rkl", w_t, w_t)
            carry = (carry[0] + cw.astype(jnp.float32),
                     carry[1] + wtw_t.astype(jnp.float32))
    return carry, inner


def _gram_dnorm(state: TiledState, inner, nrm_a_sq,
                cfg: SolverConfig):
    """RMS residual from Gram terms only — no pass over A:
    ‖A − WH‖² = ‖A‖² − 2⟨WᵀA, H⟩ + ⟨WᵀW, HHᵀ⟩, clamped at 0 against
    f32 cancellation near convergence. ``inner`` is the streaming
    pass's ⟨WᵀA, H⟩; the k×k Grams come from resident factors."""
    m = state.w.shape[1]
    n = state.h.shape[2]
    with matmul_precision_ctx(cfg.matmul_precision):
        gram = jnp.einsum("rmk,rml->rkl", state.w, state.w)
        hht = jnp.einsum("rkn,rln->rkl", state.h, state.h)
    cross = jnp.sum(gram.astype(jnp.float32) * hht.astype(jnp.float32),
                    axis=(1, 2))
    sq = jnp.maximum(nrm_a_sq - 2.0 * inner + cross, 0.0)
    return jnp.sqrt(sq / (m * n)).astype(state.dnorm.dtype)


@partial(jax.jit, static_argnames=("cfg",))
def _tiled_check(state: TiledState, inner, nrm_a_sq, cfg: SolverConfig):
    """Per-lane convergence tests, a faithful batched transcription of
    ``solvers.base.check_convergence`` (same order: nonfinite guard
    FIRST, then class stability, TolX, TolFun; same formulas, same i32
    stop-reason discipline). Transcribed rather than reused because the
    base TolFun branch recomputes the residual from full A — which the
    out-of-core engine cannot hold; here the Gram-form ``new_dnorm``
    from the just-finished pass stands in. mu checks class+TolX, hals
    additionally TolFun — matching each solver's in-core ``step``."""
    use_class = cfg.use_class_stop
    use_tolfun = cfg.algorithm == "hals"
    it = state.iteration
    is_check = (it > 1) & (it % cfg.check_every == 0) & (~state.done)
    done = state.done
    reason = state.stop_reason

    if cfg.nonfinite_guard:
        bad_w = ~jnp.all(jnp.isfinite(state.w), axis=(1, 2))
        bad_h = ~jnp.all(jnp.isfinite(state.h), axis=(1, 2))
        faulted = is_check & (bad_w | bad_h)
        done = done | faulted
        is_check = is_check & ~faulted
        reason = jnp.where(faulted, jnp.int32(StopReason.NUMERIC_FAULT),
                           reason)

    classes = state.classes
    stable = state.stable
    if use_class:
        new_classes = jnp.argmax(state.h, axis=1).astype(jnp.int32)
        n = new_classes.shape[1]
        flip_tol = int(cfg.class_flip_tol * n + 1e-9)
        mism = jnp.sum((new_classes != state.classes).astype(jnp.int32),
                       axis=1)
        same = mism <= flip_tol
        stable = jnp.where(is_check,
                           jnp.where(same, state.stable + 1, 0),
                           state.stable)
        classes = jnp.where((is_check & ~same)[:, None], new_classes,
                            state.classes)
        hit = is_check & (stable >= cfg.stable_checks)
        done = done | hit
        reason = jnp.where(hit, jnp.int32(StopReason.CLASS_STABLE),
                           reason)

    if cfg.use_tol_checks:
        sqrteps = jnp.sqrt(jnp.finfo(state.w.dtype).eps)
        dw = (jnp.max(jnp.abs(state.w - state.w_prev), axis=(1, 2))
              / (sqrteps + jnp.max(jnp.abs(state.w_prev), axis=(1, 2))))
        dh = (jnp.max(jnp.abs(state.h - state.h_prev), axis=(1, 2))
              / (sqrteps + jnp.max(jnp.abs(state.h_prev), axis=(1, 2))))
        delta = jnp.maximum(dw, dh)
        hit = is_check & (delta < cfg.tol_x) & ~done
        done = done | hit
        reason = jnp.where(hit, jnp.int32(StopReason.TOL_X), reason)

    dnorm = state.dnorm
    if use_tolfun and cfg.use_tol_checks:
        new_dnorm = _gram_dnorm(state, inner, nrm_a_sq, cfg)
        hit = (is_check & jnp.isfinite(state.dnorm)
               & (state.dnorm - new_dnorm <= cfg.tol_fun * state.dnorm)
               & ~done)
        dnorm = jnp.where(is_check, new_dnorm, state.dnorm)
        done = done | hit
        reason = jnp.where(hit, jnp.int32(StopReason.TOL_FUN), reason)

    return state._replace(classes=classes, stable=stable, done=done,
                          stop_reason=reason, dnorm=dnorm)


@partial(jax.jit, static_argnames=("cfg",))
def _final_dnorm(state: TiledState, inner, nrm_a_sq,
                 cfg: SolverConfig):
    """Every lane's final residual (in-core ``run_loop`` recomputes it
    unconditionally after the loop; so does the tiled engine, from the
    dedicated final accumulation pass)."""
    return state._replace(dnorm=_gram_dnorm(state, inner, nrm_a_sq, cfg))


# -- partial-progress payloads (mid-matrix checkpoint records) ---------------

_STATE_FIELDS = ("w", "h", "w_prev", "h_prev", "iteration", "dnorm",
                 "classes", "stable", "done", "stop_reason")


def partial_payload(state: TiledState, carry, step: int
                    ) -> "dict[str, np.ndarray]":
    """Flatten mid-solve progress to host arrays for an npz partial
    record (``nmfx/checkpoint.py``): the full pool state, the carried
    Grams the next head consumes, and the completed step count."""
    out = {f: np.asarray(v)
           for f, v in zip(_STATE_FIELDS, state)}
    for i, c in enumerate(carry):
        out[f"carry{i}"] = np.asarray(c)
    out["step"] = np.asarray(int(step), np.int64)
    return out


def resume_from_payload(payload) -> "tuple[TiledState, tuple, int]":
    """Inverse of :func:`partial_payload`. Device round-trip of the
    saved f32 arrays is exact, and every pass function is
    deterministic on identical inputs — so a resumed solve is bitwise
    the uninterrupted one (the NMFX007 parity gate for this engine)."""
    state = TiledState(*(jnp.asarray(payload[f]) for f in _STATE_FIELDS))
    n_carry = sum(1 for f in payload.keys() if str(f).startswith("carry"))
    carry = tuple(jnp.asarray(payload[f"carry{i}"])
                  for i in range(n_carry))
    note_partial_resume()
    return state, carry, int(payload["step"])


# -- host driver -------------------------------------------------------------

def _source_sq_norm(source, dtype, plan: TilePlan) -> float:
    """‖A‖² of the dtype-cast source, float64-accumulated host-side
    (tile-blocked so it never materializes a dense atlas) — the
    constant term of the Gram-form residual."""
    if isinstance(source, SparseMatrix):
        data = np.asarray(source.data, dtype).astype(np.float64)
        return float(np.sum(data * data))
    total = 0.0
    for r0, r1 in plan.boundaries:
        blk = np.asarray(source[r0:r1], dtype).astype(np.float64)
        total += float(np.sum(blk * blk))
    return total


def run_tiled_pool(source, keys, k: int, solver_cfg: SolverConfig,
                   init_cfg: InitConfig, *, plan: "TilePlan | None" = None,
                   profiler=None, poison: tuple = (), resume=None,
                   on_check=None) -> TiledPoolResult:
    """Solve a restart pool out-of-core: one host-driven loop whose
    per-iteration schedule is head (A-free H half-step) then one
    streaming W-pass over A, with per-lane freeze masks replicating the
    batched while_loop semantics of the in-core driver (checks fire at
    ``check_every`` multiples past iteration 1; frozen lanes never
    advance). ``keys`` are the EXPLICIT per-restart keys — a slice of
    the canonical ``split(fold_in(root, k), restarts)`` chain, same as
    every other engine.

    ``resume`` is a :func:`partial_payload` mapping to continue from;
    ``on_check(step, state, carry)`` fires after every convergence
    check (device-synced) — the checkpoint layer saves partials and
    rehearses preemptions there."""
    from nmfx.sweep import _poison_restart_lanes

    if solver_cfg.algorithm not in TILED_ALGORITHMS:
        raise ValueError(
            "the out-of-core tile pipeline implements the Gram-"
            f"accumulation algorithms {TILED_ALGORITHMS}, got "
            f"algorithm={solver_cfg.algorithm!r}")
    if init_cfg.method != "random":
        raise ValueError(
            "tiled solves need init method 'random' (shape-only, key-"
            "deterministic); nndsvd reads the full matrix, which an "
            "out-of-core solve cannot hold")
    if profiler is None:
        profiler = NullProfiler()
    dtype = jnp.dtype(solver_cfg.dtype)
    m, n = int(source.shape[0]), int(source.shape[1])
    if plan is None:
        plan = plan_for(source, solver_cfg)
    stream = TileStream(source, plan, dtype, profiler=profiler)
    nrm_a_sq = jnp.asarray(_source_sq_norm(source, dtype, plan),
                           jnp.float32)

    keys = jnp.asarray(keys)
    r = keys.shape[0]
    if resume is None:
        w0, h0 = jax.vmap(
            lambda kk: random_init(kk, m, n, k, init_cfg, dtype))(keys)
        w0 = _poison_restart_lanes(w0, poison)
        state = TiledState(
            w=w0, h=h0, w_prev=w0, h_prev=h0,
            iteration=jnp.zeros((r,), jnp.int32),
            dnorm=jnp.full((r,), jnp.inf, dtype),
            classes=jnp.full((r, n), -1, jnp.int32),
            stable=jnp.zeros((r,), jnp.int32),
            done=jnp.zeros((r,), bool),
            stop_reason=jnp.full((r,), StopReason.MAX_ITER, jnp.int32))
        carry = _zero_carry(solver_cfg.algorithm, r, k, n)
        inner = jnp.zeros((r,), jnp.float32)
        # prologue: iteration 1's Gram carry from W0 (in-core step 1
        # computes WᵀA/WᵀW from W0 directly; here it streams)
        for _, r0, _r1, a_t in stream.tiles():
            carry, inner = _tile_accumulate(state, carry, inner, a_t,
                                            r0, solver_cfg)
        start = 0
    else:
        state, carry, start = resume_from_payload(resume)

    done_host = np.asarray(state.done)
    for step in range(start + 1, solver_cfg.max_iter + 1):
        if done_host.all():
            break
        state, hht = _head_update(state, carry, solver_cfg)
        carry = _zero_carry(solver_cfg.algorithm, r, k, n)
        inner = jnp.zeros((r,), jnp.float32)
        for _, r0, _r1, a_t in stream.tiles():
            state, carry, inner = _tile_update(state, hht, carry, inner,
                                               a_t, r0, solver_cfg)
        if step > 1 and step % solver_cfg.check_every == 0:
            state = _tiled_check(state, inner, nrm_a_sq, solver_cfg)
            done_host = np.asarray(state.done)
            if on_check is not None:
                on_check(step, state, carry)

    # final residual for every lane, from one dedicated accumulation
    # pass (also covers the resumed-when-already-done edge, where the
    # iteration loop above never ran)
    carry_f = _zero_carry(solver_cfg.algorithm, r, k, n)
    inner_f = jnp.zeros((r,), jnp.float32)
    for _, r0, _r1, a_t in stream.tiles():
        carry_f, inner_f = _tile_accumulate(state, carry_f, inner_f,
                                            a_t, r0, solver_cfg)
    state = _final_dnorm(state, inner_f, nrm_a_sq, solver_cfg)
    return TiledPoolResult(w=state.w, h=state.h,
                           iterations=state.iteration,
                           dnorm=state.dnorm,
                           stop_reason=state.stop_reason)


# -- sweep epilogues ---------------------------------------------------------

def sweep_one_k_tiled(source, key, k: int, restarts: int,
                      solver_cfg: SolverConfig, init_cfg: InitConfig,
                      label_rule: str = "argmax",
                      keep_factors: bool = False, profiler=None,
                      poison: tuple = ()):
    """One rank's consensus sweep through the tiled engine — the
    out-of-core analogue of the vmapped ``_solve_batch`` path, sharing
    the canonical key chain and the exact quarantine/consensus/argmin
    epilogue helpers so downstream semantics cannot drift."""
    from nmfx.consensus import labels_from_h
    from nmfx.sweep import (KSweepOutput, _quarantine_lanes,
                            _quarantined_consensus)

    keys = jax.random.split(key, restarts)
    res = run_tiled_pool(source, keys, k, solver_cfg, init_cfg,
                         profiler=profiler, poison=poison)
    labels = jax.vmap(partial(labels_from_h, rule=label_rule))(res.h)
    labels, dnorm_best, faulted = _quarantine_lanes(
        labels, res.dnorm, res.stop_reason)
    cons = _quarantined_consensus(labels, k, restarts, faulted)
    best = jnp.argmin(dnorm_best)
    return KSweepOutput(
        consensus=cons, iterations=res.iterations, dnorms=res.dnorm,
        stop_reasons=res.stop_reason, labels=labels,
        best_w=res.w[best], best_h=res.h[best],
        all_w=res.w if keep_factors else None,
        all_h=res.h if keep_factors else None)


def solve_chunk_tiled(source, keys, k: int, solver_cfg: SolverConfig,
                      init_cfg: InitConfig, label_rule: str,
                      poison: tuple = (), profiler=None, resume=None,
                      on_check=None):
    """One restart-chunk through the tiled engine, returning the same
    ``ChunkSweepOutput`` record payload as ``_build_chunk_sweep_fn``'s
    executor (labels quarantine-masked to -1, raw dnorms, chunk-local
    first-min best among survivors) so the durable ledger's finalize
    step is engine-agnostic."""
    from nmfx.consensus import labels_from_h
    from nmfx.sweep import ChunkSweepOutput, _quarantine_lanes

    res = run_tiled_pool(source, keys, k, solver_cfg, init_cfg,
                         profiler=profiler, poison=poison,
                         resume=resume, on_check=on_check)
    labels = jax.vmap(partial(labels_from_h, rule=label_rule))(res.h)
    labels, dnorm_best, _ = _quarantine_lanes(labels, res.dnorm,
                                              res.stop_reason)
    best = jnp.argmin(dnorm_best).astype(jnp.int32)
    return ChunkSweepOutput(labels, res.iterations, res.dnorm,
                            res.stop_reason, best, res.w[best],
                            res.h[best])
