"""Concurrency-discipline declarations: which lock owns which state.

The threaded service tier (serve scheduler, router maintenance,
replica heartbeats, harvest workers, cache compile pools) documents
its locking discipline in comments; this module turns those comments
into machine-checkable declarations. ``nmfx-lint``'s NMFX012 rule
(``nmfx/analysis/concurrency/``) reads them SYNTACTICALLY — a class
decorated ``@guarded_by("_lock", "_queue", ...)`` promises that every
access to ``self._queue`` outside a ``with self._lock`` scope is a
bug — and the runtime lock-order witness
(``nmfx/analysis/witness.py``) cross-validates the derived lock graph
against actual acquisition orders in the threaded test suites.

Usage::

    from nmfx.guards import guarded_by

    @guarded_by("_lock", "_queue", "_inflight", "counters")
    @guarded_by("_tracked_lock", "_tracked", "_followers")
    class NMFXServer: ...

Stacked decorators declare one guarded set per lock. A
``threading.Condition`` built on a declared lock counts as that lock
(the linter resolves the alias from the ``Condition(self._lock)``
construction site). Module-level state is declared with a top-level
call::

    module_guarded("_warned_lock", "_warned")

Both forms are runtime no-ops beyond recording metadata — they import
nothing from the analysis package and add zero per-access overhead.
"""

from __future__ import annotations

#: module dotted path -> {lock name -> guarded global names}; filled by
#: :func:`module_guarded` at import time of the declaring module
GUARDED_BY: "dict[str, dict[str, tuple[str, ...]]]" = {}


def guarded_by(lock_attr: str, *attrs: str):
    """Class decorator: ``attrs`` are instance attributes that must only
    be accessed while ``self.<lock_attr>`` is held. Metadata lands in
    ``cls.__nmfx_guarded__`` (lock attr -> guarded attr tuple); the
    decorated class is returned unchanged."""

    def deco(cls):
        # copy — a subclass decorating again must not mutate the base's
        # registry through the inherited reference
        reg = dict(getattr(cls, "__nmfx_guarded__", {}))
        reg[lock_attr] = tuple(attrs)
        cls.__nmfx_guarded__ = reg
        return cls

    return deco


def module_guarded(lock_name: str, *names: str, module: "str | None" = None):
    """Declare module-level globals guarded by a module-level lock.
    Call at module top level; the linter reads the call site
    syntactically, so ``lock_name``/``names`` must be string literals."""
    import inspect

    if module is None:
        frame = inspect.currentframe()
        caller = frame.f_back if frame is not None else None
        module = caller.f_globals.get("__name__", "?") if caller else "?"
    GUARDED_BY.setdefault(module, {})[lock_name] = tuple(names)
