"""NMF solver family — eight update rules sharing one while_loop driver.

TPU-native re-designs of the reference's five C solvers
(reference ``libnmf/nmf_{mu,als,neals,pg,alspg}.c``) plus the BROAD
original's Brunet divergence rule (``kl``), Kim & Park sparse NMF
(``snmf``), and Cichocki & Phan HALS (``hals``): eight in all, each a pure
``step`` function over arrays, jit-compiled into a ``lax.while_loop`` and
vmappable over the restart axis.
"""

from nmfx.solvers.base import SolverResult, StopReason, solve
from nmfx.solvers import als, alspg, hals, kl, mu, neals, pg, snmf

SOLVERS = {
    "mu": mu,
    "als": als,
    "neals": neals,
    "pg": pg,
    "alspg": alspg,
    # beyond the reference: the BROAD original's Brunet divergence updates
    # (the reference replaces them with Euclidean mu — solvers/kl.py)
    "kl": kl,
    # beyond the reference: Kim & Park sparse NMF (solvers/snmf.py)
    "snmf": snmf,
    # beyond the reference: Cichocki & Phan HALS (solvers/hals.py)
    "hals": hals,
}

__all__ = ["SOLVERS", "SolverResult", "StopReason", "solve", "mu", "als",
           "neals", "pg", "alspg", "kl", "snmf", "hals"]
