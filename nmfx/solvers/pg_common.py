"""Projected-gradient NNLS subproblem (Lin 2007), shared by pg and alspg.

One generic routine replaces the reference's mirrored pair
``pg_subprob_h`` / ``pg_subprob_w`` (reference ``libnmf/pg_subprob_h.c:75-202``,
``libnmf/pg_subprob_w.c:78-208``): both half-problems are

    min_{X >= 0}  1/2 <X, G X> - <C, X>     (G = the k×k Gram, C = cross term)

— for H: G = WᵀW, C = WᵀA, X = H; for W: G = HHᵀ, C = HAᵀ, X = Wᵀ (the
reference writes the W variant untransposed to dodge BLAS transposes; with
einsum-level codegen that contortion buys nothing on TPU).

Line-search semantics follow the reference exactly: step ``alpha`` persists
across outer iterations, up to 20 inner trials, shrink/grow factor 0.1,
sufficient decrease ``0.99·⟨g,d⟩ + 0.5·⟨Gd,d⟩ < 0``, first-trial direction
choice, and the previous-candidate-equality bailout in grow mode
(pg_subprob_h.c:116-195).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from nmfx.config import SolverConfig
from nmfx.solvers.base import clamp


class SubprobResult(NamedTuple):
    x: jax.Array
    grad: jax.Array  # gradient at the returned x
    iterations: jax.Array  # outer iterations entered (drives alspg tol tightening)


def projgrad_norm_sq(grad: jax.Array, x: jax.Array) -> jax.Array:
    """Squared norm of the projected gradient: entries where grad<0 or x>0
    (reference pg_subprob_h.c:102-106)."""
    mask = (grad < 0) | (x > 0)
    return jnp.sum(jnp.where(mask, grad * grad, jnp.zeros_like(grad)))


class _Inner(NamedTuple):
    alpha: jax.Array
    xp: jax.Array  # previous candidate (grow mode)
    xres: jax.Array  # accepted iterate
    trial: jax.Array
    finished: jax.Array
    decrease: jax.Array  # direction flag fixed on the first trial


class _Outer(NamedTuple):
    x: jax.Array
    grad: jax.Array
    alpha: jax.Array
    it: jax.Array
    done: jax.Array


def _line_search(x, grad, gram, alpha0, cfg: SolverConfig):
    """One inner search: returns (new x, new alpha)."""
    zt = cfg.zero_threshold
    sigma = cfg.ls_sigma  # 0.01 → the 0.99 in the reference's test
    beta = cfg.ls_beta

    def trial_point(alpha):
        xn = clamp(x - alpha * grad, zt)
        d = xn - x
        gradd = jnp.vdot(grad, d)
        dqd = jnp.vdot(gram @ d, d)
        suff = (1.0 - sigma) * gradd + 0.5 * dqd < 0
        return xn, suff

    def body(c: _Inner) -> _Inner:
        xn, suff = trial_point(c.alpha)
        first = c.trial == 1
        decrease = jnp.where(first, ~suff, c.decrease)
        xp = jnp.where(first, x, c.xp)
        eq = jnp.all(xp == xn)
        stop_decr = decrease & suff
        stop_grow = (~decrease) & (~suff | eq)
        finished = stop_decr | stop_grow
        xres = jnp.where(stop_decr, xn, jnp.where(stop_grow, xp, c.xres))
        alpha = jnp.where(
            finished, c.alpha,
            jnp.where(decrease, c.alpha * beta, c.alpha / beta))
        xp = jnp.where(finished | decrease, xp, xn)
        return _Inner(alpha, xp, xres, c.trial + 1, finished, decrease)

    def cond(c: _Inner):
        return (~c.finished) & (c.trial <= cfg.ls_max_steps)

    init = _Inner(alpha0, x, x, jnp.ones((), jnp.int32),
                  jnp.zeros((), bool), jnp.zeros((), bool))
    out = lax.while_loop(cond, body, init)
    return out.xres, out.alpha


def solve_subproblem(gram, ctc, x0, tol, cfg: SolverConfig) -> SubprobResult:
    """Projected-gradient descent on the NNLS subproblem to tolerance ``tol``
    (absolute, on the projected-gradient norm) or ``cfg.sub_max_iter`` outer
    iterations."""

    def cond(c: _Outer):
        return (~c.done) & (c.it < cfg.sub_max_iter)

    def body(c: _Outer) -> _Outer:
        grad = gram @ c.x - ctc
        pg = jnp.sqrt(projgrad_norm_sq(grad, c.x))
        hit = pg < tol
        x_new, alpha_new = _line_search(c.x, grad, gram, c.alpha, cfg)
        x = jnp.where(hit, c.x, x_new)
        alpha = jnp.where(hit, c.alpha, alpha_new)
        return _Outer(x, grad, alpha, c.it + 1, hit)

    dtype = x0.dtype
    init = _Outer(x0, jnp.zeros_like(x0), jnp.ones((), dtype),
                  jnp.zeros((), jnp.int32), jnp.zeros((), bool))
    out = lax.while_loop(cond, body, init)
    grad_final = gram @ out.x - ctc
    return SubprobResult(out.x, grad_final, out.it)
