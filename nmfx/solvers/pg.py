"""Direct projected-gradient NMF (Lin 2007, joint W/H step).

TPU-native re-design of reference ``libnmf/nmf_pg.c:85-473``: per iteration,
gradients of 1/2‖A − WH‖² w.r.t. both factors, a projected step
``(W,H) ← max((W,H) − α·∇, 0)`` with the step size adapted ×/÷10 under the
Armijo-like test ``newobj − obj ≤ 0.01·⟨∇, Δ⟩`` and the equal-candidate
bailout in grow mode (nmf_pg.c:247-417). Iteration 1 instead polishes H with
the NNLS subproblem at absolute tolerance 0.001 and seeds the objective
(nmf_pg.c:203-225). Stops when the projected-gradient norm falls below
``tol_pg ×`` its initial value (nmf_pg.c:228-243).

The reference's inner adaptation loops are unbounded ``while(1)``; here they
are bounded at 40 trials (α spans 40 decades — beyond float range) so the
compiled loop provably terminates.

Performance shape (profiled, benchmarks/RESULTS.md "pg / alspg profile"):
compute-bound at ~25 ms per batched iteration on the north-star config —
each outer iteration is 4–6 full-matrix GEMM passes (gradients + line-search
trial objectives), ~100× packed mu's per-iteration cost. Not fixable by
precision (TPU default is already bf16) or by the Gram-trace objective
(measured slower); the cost is the algorithm. The projected-gradient stop
rarely fires at scale (the reference's own tol default 2e-16 never does) —
``max_iter`` is the honest budget knob.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from nmfx.config import SolverConfig
from nmfx.solvers import base
from nmfx.solvers.pg_common import projgrad_norm_sq, solve_subproblem

_MAX_TRIALS = 40


class Aux(NamedTuple):
    initgrad: jax.Array
    obj: jax.Array  # 1/2 ||A - W H||_F^2
    alpha: jax.Array


def init_aux(a, w0, h0, cfg: SolverConfig):
    dtype = w0.dtype
    return Aux(jnp.zeros((), dtype), jnp.zeros((), dtype),
               jnp.ones((), dtype))


def _grads(a, w, h):
    gradw = w @ (h @ h.T) - a @ h.T
    gradh = (w.T @ w) @ h - w.T @ a
    return gradw, gradh


def _objective(a, w, h):
    d = a - w @ h
    return 0.5 * jnp.sum(d * d)


class _JInner(NamedTuple):
    alpha: jax.Array
    wp: jax.Array
    hp: jax.Array
    objp: jax.Array
    wres: jax.Array
    hres: jax.Array
    objres: jax.Array
    trial: jax.Array
    finished: jax.Array


def _joint_search(a, w, h, gradw, gradh, obj, alpha0, cfg: SolverConfig):
    """Adaptive-step projected line search on the joint (W, H) move."""
    sigma = cfg.ls_sigma
    zt = cfg.zero_threshold

    def trial(alpha):
        wn = base.clamp(w - alpha * gradw, zt)
        hn = base.clamp(h - alpha * gradh, zt)
        newobj = _objective(a, wn, hn)
        compval = jnp.vdot(gradw, wn - w) + jnp.vdot(gradh, hn - h)
        fail = (newobj - obj) > sigma * compval
        return wn, hn, newobj, fail

    wn0, hn0, obj0, fail0 = trial(alpha0)
    decrease = fail0  # direction fixed by the first trial (nmf_pg.c:288)

    def body(c: _JInner) -> _JInner:
        alpha = jnp.where(decrease, c.alpha * cfg.ls_beta,
                          c.alpha / cfg.ls_beta)
        wn, hn, newobj, fail = trial(alpha)
        eq = jnp.all(wn == c.wp) & jnp.all(hn == c.hp)
        stop_decr = decrease & ~fail
        stop_grow = (~decrease) & (fail | eq)
        finished = stop_decr | stop_grow
        wres = jnp.where(stop_decr, wn, jnp.where(stop_grow, c.wp, c.wres))
        hres = jnp.where(stop_decr, hn, jnp.where(stop_grow, c.hp, c.hres))
        objres = jnp.where(stop_decr, newobj,
                           jnp.where(stop_grow, c.objp, c.objres))
        # grow mode backs alpha off to the accepted candidate's step
        alpha_out = jnp.where(stop_grow, alpha * cfg.ls_beta, alpha)
        keep_prev = finished | decrease
        wp = jnp.where(keep_prev, c.wp, wn)
        hp = jnp.where(keep_prev, c.hp, hn)
        objp = jnp.where(keep_prev, c.objp, newobj)
        return _JInner(alpha_out, wp, hp, objp, wres, hres, objres,
                       c.trial + 1, finished)

    def cond(c: _JInner):
        return (~c.finished) & (c.trial <= _MAX_TRIALS)

    init = _JInner(alpha0, wn0, hn0, obj0, w, h, obj,
                   jnp.ones((), jnp.int32), jnp.zeros((), bool))
    out = lax.while_loop(cond, body, init)
    return out.wres, out.hres, out.objres, out.alpha


def step(a, state: base.State, cfg: SolverConfig,
         check: bool = True) -> base.State:
    # pg's convergence test is its own cheap projected-gradient norm,
    # evaluated every iteration as the reference does — `check` is unused
    del check
    aux: Aux = state.aux
    w, h = state.w, state.h
    gradw, gradh = _grads(a, w, h)

    def first_iter(_):
        initgrad = jnp.sqrt(jnp.sum(gradw * gradw) + jnp.sum(gradh * gradh))
        res = solve_subproblem(w.T @ w, w.T @ a, h,
                               jnp.asarray(0.001, w.dtype), cfg)
        obj = _objective(a, w, res.x)
        return state._replace(h=res.x, aux=Aux(initgrad, obj, aux.alpha))

    def later_iter(_):
        projnorm = jnp.sqrt(projgrad_norm_sq(gradw, w)
                            + projgrad_norm_sq(gradh, h))
        hit = projnorm < cfg.tol_pg * aux.initgrad
        wn, hn, obj, alpha = _joint_search(a, w, h, gradw, gradh, aux.obj,
                                           aux.alpha, cfg)
        new = state._replace(
            w=jnp.where(hit, w, wn),
            h=jnp.where(hit, h, hn),
            done=state.done | hit,
            # int32-pinned: an IntEnum is not weakly typed on every jax,
            # and under x64 the promotion to int64 would make this cond
            # branch's State disagree with first_iter's
            stop_reason=jnp.where(hit,
                                  jnp.int32(base.StopReason.PG_TOL),
                                  state.stop_reason),
            aux=Aux(aux.initgrad,
                    jnp.where(hit, aux.obj, obj),
                    jnp.where(hit, aux.alpha, alpha)),
        )
        return new

    return lax.cond(state.iteration == 1, first_iter, later_iter, None)
