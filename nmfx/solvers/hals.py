"""HALS NMF (Cichocki & Phan 2009) — capability extension.

Beyond the reference: hierarchical alternating least squares is the
standard modern fast NMF update — per sweep it costs the same two big
GEMMs as mu (WᵀA and AHᵀ plus the k×k Grams), but its coordinate-wise
exact minimizations typically converge in far fewer iterations. Each
half-step updates one factor component at a time against the *current*
values of the others:

    for j = 1..k:   H[j,:] ← max( H[j,:] + ((WᵀA)[j,:] − (WᵀW)[j,:]·H)
                                   / (WᵀW)[j,j], 0 )
    for j = 1..k:   W[:,j] ← max( W[:,j] + ((AHᵀ)[:,j] − W·(HHᵀ)[:,j])
                                   / (HHᵀ)[j,j], 0 )

(the W pass uses the freshly updated H, mirroring mu's fresh-factor
ordering, reference ``nmf_mu.c:198-216``). The inner loop over j is a
compile-time Python unroll — k is static under jit and small, and each
update is a rank-1-shaped AXPY the VPU handles; the FLOPs live in the
shared GEMM precomputations, exactly where the MXU wants them.

Division guard: a component whose Gram diagonal collapses to zero (dead
column) keeps its current value instead of dividing by zero — ``div_eps``
in the denominator, matching the mu rule's guard placement.

Grid sharding: WᵀA / WᵀW psum over the feature axis and AHᵀ / HHᵀ over
the sample axis (``base.shard_reducers`` — the same placement as
mu/kl/neals/snmf); the per-component AXPYs are local. Zero-padded
rows/columns stay zero: their numerator columns are zero and updates add
multiples of zero rows.

Convergence: TolX/TolFun every 2nd iteration plus the class-stability
stop when enabled, like the other Gram-family solvers.
"""

from __future__ import annotations

from nmfx.config import SolverConfig
from nmfx.solvers import base


def init_aux(a, w0, h0, cfg: SolverConfig,
             shard: base.ShardInfo | None = None):
    return ()


def step(a, state: base.State, cfg: SolverConfig, check: bool = True,
         shard: base.ShardInfo | None = None) -> base.State:
    w, h = state.w, state.h
    k = w.shape[1]
    eps = cfg.div_eps
    fsum, ssum = base.shard_reducers(shard)

    # H pass: shared GEMMs once, then k coordinate updates on fresh rows
    wta = fsum(w.T @ a)  # (k, n)
    wtw = fsum(w.T @ w)  # (k, k)
    for j in range(k):
        hj = h[j] + (wta[j] - wtw[j] @ h) / (wtw[j, j] + eps)
        h = h.at[j].set(base.clamp(hj, cfg.zero_threshold))

    # W pass with the fresh H
    aht = ssum(a @ h.T)  # (m, k)
    hht = ssum(h @ h.T)  # (k, k)
    for j in range(k):
        wj = w[:, j] + (aht[:, j] - w @ hht[:, j]) / (hht[j, j] + eps)
        w = w.at[:, j].set(base.clamp(wj, cfg.zero_threshold))

    state = state._replace(w=w, h=h)
    if not check:
        return state
    return base.check_convergence(state, cfg, a=a,
                                  use_class=cfg.use_class_stop,
                                  use_tolx=True, use_tolfun=True,
                                  shard=shard)
