"""KL-divergence multiplicative updates (Brunet et al. 2004).

Capability extension beyond the reference: the reference bills itself as a
"Parallel version of the BROAD nmfconsensus.R script" (reference
``README.md:4``) but swaps the BROAD script's Brunet divergence updates for
Euclidean MU (reference ``libnmf/nmf_mu.c``, Lee-Seung Frobenius rule). This
solver restores the original BROAD model family so users of the upstream
``nmfconsensus.R`` can reproduce its factorizations here:

    H ← H ∘ (Wᵀ(A ⊘ WH)) / (Wᵀ1)
    W ← W ∘ ((A ⊘ WH)Hᵀ) / (1Hᵀ)    (using the fresh H)

which monotonically decreases the generalized KL divergence

    D(A ‖ WH) = Σᵢⱼ [ Aᵢⱼ log(Aᵢⱼ / (WH)ᵢⱼ) − Aᵢⱼ + (WH)ᵢⱼ ].

Convergence control reuses the shared driver: the class-stability stop (the
same consensus-oriented criterion Brunet's script applies to its
connectivity matrix) plus the optional TolX test. The m×n quotient
A ⊘ (WH) is materialized per half-step as a GEMM operand — per-restart HBM
cost is O(mn), so very large (m, n, restarts) sweeps should chunk the
restart axis.
"""

from __future__ import annotations

import jax.numpy as jnp

from nmfx.config import SolverConfig
from nmfx.solvers import base


def init_aux(a, w0, h0, cfg: SolverConfig):
    return ()


def kl_divergence(a, w, h, eps: float = 1e-9):
    """Generalized KL divergence D(A ‖ WH); the objective this rule descends
    (0 ≤, 0 iff A == WH). The A log A term is handled with the usual
    0·log 0 = 0 convention."""
    wh = w @ h + eps
    logq = jnp.where(a > 0, jnp.log(jnp.maximum(a, eps) / wh), 0.0)
    return jnp.sum(a * logq - a + wh)


def step(a, state: base.State, cfg: SolverConfig,
         check: bool = True) -> base.State:
    w0, h0 = state.w, state.h
    eps = cfg.div_eps
    # H update: quotient against the current reconstruction
    q = a / (w0 @ h0 + eps)
    h = h0 * (w0.T @ q) / (jnp.sum(w0, axis=0)[:, None] + eps)
    h = base.clamp(h, cfg.zero_threshold)
    # W update with the fresh H (same fresh-factor ordering as mu.step,
    # reference nmf_mu.c:198-216)
    q = a / (w0 @ h + eps)
    w = w0 * (q @ h.T) / (jnp.sum(h, axis=1)[None, :] + eps)
    w = base.clamp(w, cfg.zero_threshold)

    state = state._replace(w=w, h=h)
    if not check:
        return state
    return base.check_convergence(state, cfg, use_class=cfg.use_class_stop,
                                  use_tolx=True)
