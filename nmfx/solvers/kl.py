"""KL-divergence multiplicative updates (Brunet et al. 2004).

Capability extension beyond the reference: the reference bills itself as a
"Parallel version of the BROAD nmfconsensus.R script" (reference
``README.md:4``) but swaps the BROAD script's Brunet divergence updates for
Euclidean MU (reference ``libnmf/nmf_mu.c``, Lee-Seung Frobenius rule). This
solver restores the original BROAD model family so users of the upstream
``nmfconsensus.R`` can reproduce its factorizations here:

    H ← H ∘ (Wᵀ(A ⊘ WH)) / (Wᵀ1)
    W ← W ∘ ((A ⊘ WH)Hᵀ) / (1Hᵀ)    (using the fresh H)

which monotonically decreases the generalized KL divergence

    D(A ‖ WH) = Σᵢⱼ [ Aᵢⱼ log(Aᵢⱼ / (WH)ᵢⱼ) − Aᵢⱼ + (WH)ᵢⱼ ].

Convergence control reuses the shared driver: the class-stability stop (the
same consensus-oriented criterion Brunet's script applies to its
connectivity matrix) plus the optional TolX test. The m×n quotient
A ⊘ (WH) is materialized per half-step as a GEMM operand — per-restart HBM
cost is O(mn), which makes kl the one solver that *needs* the grid
(feature/sample) mesh axes at scale: under ``shard`` the quotient is a
purely local (m_loc × n_loc) block (W row-sharded × H column-sharded gives
the local reconstruction directly), and each update's contracted term
psums over the corresponding mesh axis — m-contractions (WᵀQ and W's
column sums) over the feature axis, n-contractions (QHᵀ and H's row sums)
over the sample axis — exactly where the packed mu path places its Gram
psums (ops/packed_mu.py). Without a mesh, ``restart_chunk`` remains the
fallback memory bound.
"""

from __future__ import annotations

import jax.numpy as jnp

from nmfx.config import SolverConfig
from nmfx.solvers import base


def init_aux(a, w0, h0, cfg: SolverConfig,
             shard: base.ShardInfo | None = None):
    return ()


def kl_divergence(a, w, h, eps: float = 1e-9):
    """Generalized KL divergence D(A ‖ WH); the objective this rule descends
    (0 ≤, 0 iff A == WH). The A log A term is handled with the usual
    0·log 0 = 0 convention."""
    wh = w @ h + eps
    logq = jnp.where(a > 0, jnp.log(jnp.maximum(a, eps) / wh), 0.0)
    return jnp.sum(a * logq - a + wh)


def step(a, state: base.State, cfg: SolverConfig, check: bool = True,
         shard: base.ShardInfo | None = None) -> base.State:
    w0, h0 = state.w, state.h
    eps = cfg.div_eps
    fsum, ssum = base.shard_reducers(shard)

    # H update: quotient against the current reconstruction. Under shard the
    # quotient block is local (row-shard of W × column-shard of H); the two
    # m-contracted terms psum over the feature axis. Zero-padded rows of
    # A/W contribute exact zeros to both.
    q = a / (w0 @ h0 + eps)
    h = h0 * fsum(w0.T @ q) / (fsum(jnp.sum(w0, axis=0))[:, None] + eps)
    h = base.clamp(h, cfg.zero_threshold)
    # W update with the fresh H (same fresh-factor ordering as mu.step,
    # reference nmf_mu.c:198-216); n-contracted terms psum over samples
    q = a / (w0 @ h + eps)
    w = w0 * ssum(q @ h.T) / (ssum(jnp.sum(h, axis=1))[None, :] + eps)
    w = base.clamp(w, cfg.zero_threshold)

    state = state._replace(w=w, h=h)
    if not check:
        return state
    return base.check_convergence(state, cfg, use_class=cfg.use_class_stop,
                                  use_tolx=True, shard=shard)
