"""Sparse NMF (Kim & Park 2007, SNMF/R) — capability extension.

Beyond the reference: sparsity-constrained consensus NMF is the standard
modern variant of this pipeline (e.g. cNMF-style program discovery), and
the alternating-nonnegative-least-squares structure drops straight into
the ``neals`` machinery the reference already motivates:

    min ½‖A − WH‖²_F  +  η‖W‖²_F  +  β Σⱼ ‖H[:,j]‖₁²

Each half-step is the regularized normal-equation solve of the augmented
least-squares systems (Kim & Park's [W; √β·1ₖᵀ] / [Hᵀ; √η·Iₖ] rows):

    H = max( (WᵀW + β·1ₖ1ₖᵀ) \\ (WᵀA), 0 )
    W = max( ((HHᵀ + η·Iₖ) \\ (HAᵀ))ᵀ, 0 )

i.e. ``neals`` with an all-ones L1-coupling block on the H Gram and a
ridge on the W Gram. ``sparsity_beta`` controls H's column sparsity;
``ridge_eta`` bounds ‖W‖ (default: max(A)², the paper's choice). The
same trace-scaled jitter as neals keeps the Cholesky well-posed for
β = η = 0, where this reduces exactly to neals.

Convergence: TolX/TolFun every 2nd iteration, plus the class-stability
stop when enabled — H sparsity makes per-sample argmax labels
particularly crisp, which is the point of using it for consensus runs.

Grid sharding: like neals, both half-steps are Gram solves whose
contractions psum along the mesh's feature/sample axes under ``shard``;
the β/η regularizers and the jitter are added after the psums (global
terms), and the default η = max(A)² pmaxes over the tiles.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from nmfx.config import SolverConfig
from nmfx.solvers import base


def init_aux(a, w0, h0, cfg: SolverConfig,
             shard: base.ShardInfo | None = None):
    eta = cfg.ridge_eta
    if eta is None:
        amax = jnp.max(a)  # Kim & Park's default eta = max(A)^2
        if shard is not None:
            # A is tiled: the default must be the GLOBAL max (zero padding
            # cannot win — A is non-negative)
            for ax in (shard.feature_axis, shard.sample_axis):
                if ax is not None:
                    amax = lax.pmax(amax, ax)
        eta = amax ** 2
    return jnp.asarray(eta, w0.dtype)


def step(a, state: base.State, cfg: SolverConfig, check: bool = True,
         shard: base.ShardInfo | None = None) -> base.State:
    w0 = state.w
    eta = state.aux
    k = w0.shape[1]
    fsum, ssum = base.shard_reducers(shard)
    beta = jnp.asarray(cfg.sparsity_beta, w0.dtype)
    ones = jnp.ones((k, k), w0.dtype)
    # regularizers are added AFTER the psums: they are global terms, not
    # per-shard contributions (same placement as neals' jitter)
    h = base.clamp(
        base.solve_gram_reg(fsum(w0.T @ w0) + beta * ones,
                            fsum(w0.T @ a)),
        cfg.zero_threshold)
    wt = base.solve_gram_reg(
        ssum(h @ h.T) + eta * jnp.eye(k, dtype=w0.dtype), ssum(h @ a.T))
    w = base.clamp(wt.T, cfg.zero_threshold)
    state = state._replace(w=w, h=h)
    if not check:
        return state
    return base.check_convergence(state, cfg, a=a,
                                  use_class=cfg.use_class_stop,
                                  use_tolx=True, use_tolfun=True,
                                  shard=shard)
