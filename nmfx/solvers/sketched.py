"""Random-projection compressed NMF updates — the ``backend="sketched"``
engine (ISSUE 12; "Faster-than-fast NMF", arxiv 1812.04315).

Every restarts/s win since the seed came from overhead removal; this
engine is the first to cut the per-iteration FLOPs themselves. Both
factors stay FULL size — only the update *computations* compress: per
restart, two random projections

    L : (r_l, m)   row sketch      R : (n, r_c)   column sketch

are drawn once from the canonical per-(seed, k, restart) key chain
(``fold_in`` of the restart key — deterministic, mesh/pad independent,
the ``restart_factors`` reproducibility contract extended to sketches),
and the Gram-family terms of the MU/HALS updates contract against the
pre-sketched matrices instead of A:

    H update:   WᵀA  →  (LW)ᵀ(LA)        WᵀW  →  (LW)ᵀ(LW)
    W update:   AHᵀ  →  (AR)(HR)ᵀ        HHᵀ  →  (HR)(HR)ᵀ

L·A (r_l × n) and A·R (m × r_c) are computed ONCE per restart outside
the iteration loop; per iteration the m/n-sized contractions are the
four sketched GEMMs — L·W (2rmk), (LW)ᵀ(LA) (2krn), H·R (2knr) and
(AR)(HR)ᵀ (2mkr) — so the per-iteration cost drops from mu's
4mnk + 4k²(m+n) to ~4rk(m+n) plus O(rk²)/O(k²(m+n)) small terms — a
~n/r / ~m/r compression of the two data-sized GEMMs
(:func:`sketched_model_flops` is the shape-derived accounting the bench
stage records).

Nesterov acceleration (``SketchConfig.momentum``) evaluates each update
at the extrapolated point ``X̄ = max(X + beta_t (X − X_prev), 0)`` with
the standard t-sequence ``t⁺ = (1 + √(1+4t²))/2``,
``beta = (t − 1)/t⁺`` — the momentum half of the paper.

Accuracy contract: labels come from the full H and the final residual
is computed UNCOMPRESSED (``base.run_loop``'s epilogue — the "final
uncompressed pass"), but the factor trajectories are approximate, so
the contract is STATISTICAL at the consensus level: membership
agreement / ARI vs the exact engine over seeds (``nmfx/agreement.py``),
pinned by tests/test_sketched.py and gated by the bench
``detail.sketched`` stage. Never bit-exact — every surface that
promises bit-exactness (checkpoint ledgers, exec-cache serving,
``--verify``) refuses this backend loudly.

The same machinery powers restart screening (``SolverConfig.screen`` —
:func:`screen_pass` ranks the restart pool by the doubly
compressed objective ‖(LA)R − (LW)(HR)‖²) and quality-elastic serving
(``ServeConfig.quality_elastic`` degrades deadline-pressured /
overload-shed requests to this engine, result tagged
``ConsensusResult.quality = "sketched"``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from nmfx.config import SKETCHED_ALGORITHMS, SolverConfig
from nmfx.solvers import base

#: fold_in constants deriving the sketch keys from a restart's
#: canonical key — distinct from the (kw, kh) init split, so arming the
#: sketched engine never perturbs the exact engines' init draws
_FOLD_L = 0x5E7C
_FOLD_R = 0x5E7D


def resolve_dim(cfg: SolverConfig, m: int, n: int, k: int) -> int:
    """The sketch dimension r actually used at shape (m, n), rank k:
    ``SketchConfig.dim`` ("auto" → ``max(4k + 8, 40)`` — the rank-
    proportional JL oversampling with an absolute floor; measured on
    the 4-group 1000×200 design at k=4, r=24 left consensus ARI vs
    exact at 0.5–0.7 while r≥40 restored 1.0 across seeds), clamped
    into [k+1, min(m, n)] so the sketch always oversamples the rank
    and never exceeds the data (at which point it would be a permuted
    exact engine paying extra FLOPs)."""
    d = cfg.sketch.dim
    r = max(4 * k + 8, 40) if d == "auto" else int(d)
    return max(k + 1, min(r, m, n))


def sketched_model_flops(m: int, n: int, k: int, r: int) -> float:
    """Shape-derived model FLOPs of ONE sketched iteration for ONE
    restart — the bench ``detail.sketched`` stage's analytic accounting
    (CPU containers cannot produce meaningful wall-clock compression,
    the FLOP ratio vs ``bench._mu_model_flops`` is hardware-independent).
    Per iteration: L·W (2rmk) + (LW)ᵀ(LA) (2krn) + (LW)ᵀ(LW) (2rk²) +
    (WᵀW)H (2nk²) for H; H·R (2knr) + (AR)(HR)ᵀ (2mkr) + (HR)(HRᵀ)
    (2rk²) + W(HHᵀ) (2mk²) for W. The one-time L·A / A·R sketches
    (2·r·m·n each) amortize over the iterations and are excluded, as
    the exact model excludes its O(mk+kn) elementwise terms."""
    return 4.0 * r * k * (m + n) + 4.0 * r * k * k + 2.0 * k * k * (m + n)


def sketch_operators(key: jax.Array, m: int, n: int, r: int,
                     dtype) -> tuple[jax.Array, jax.Array]:
    """Per-restart projections (L, R) from the restart's canonical key:
    scaled i.i.d. Gaussians L ~ N(0, 1/r)^(r×m), R ~ N(0, 1/r)^(n×r) —
    the classic Johnson-Lindenstrauss sketch (the paper's structured
    variants trade constants, not asymptotics; Gaussians keep the draw
    one fused op on every backend)."""
    kl_, kr_ = (jax.random.fold_in(key, _FOLD_L),
                jax.random.fold_in(key, _FOLD_R))
    scale = jnp.asarray(1.0, dtype) / jnp.sqrt(jnp.asarray(r, dtype))
    left = jax.random.normal(kl_, (r, m), dtype) * scale
    right = jax.random.normal(kr_, (n, r), dtype) * scale
    return left, right


def _h_gram_terms(w, la, left):
    lw = left @ w  # (r, k)
    return lw.T @ la, lw.T @ lw  # (k, n), (k, k)


def _w_gram_terms(h, ar, right):
    hr = h @ right  # (k, r)
    return ar @ hr.T, hr @ hr.T  # (m, k), (k, k)


def _apply_mu(w, h, la, ar, left, right, cfg):
    """One projected-gradient step per factor on the SKETCHED least-
    squares objectives — the Nesterov-iteration form of the paper.

    The exact engine's multiplicative ratio is NOT transplantable here:
    a Gaussian sketch does not preserve non-negativity, so the sketched
    numerator (LW)ᵀ(LA) goes transiently negative, and the mu rule's
    exact-zero short-circuit would then kill that factor entry
    PERMANENTLY (a zero entry never revives under a multiplicative
    update) — measured as lanes stalling at ~10× the exact residual.
    The additive projected step max(X − ∇/L̂, 0) recovers from a
    negative gradient sample the next iteration. L̂ = ‖Gram‖_F + ε is a
    cheap upper bound on the Lipschitz constant (Frobenius ≥ spectral),
    so the step is always stable, merely conservative."""
    wta, wtw = _h_gram_terms(w, la, left)
    lh = jnp.sqrt(jnp.sum(wtw * wtw)) + cfg.div_eps
    h = base.clamp(jnp.maximum(h - (wtw @ h - wta) / lh, 0.0),
                   cfg.zero_threshold)
    aht, hht = _w_gram_terms(h, ar, right)
    lw_ = jnp.sqrt(jnp.sum(hht * hht)) + cfg.div_eps
    w = base.clamp(jnp.maximum(w - (w @ hht - aht) / lw_, 0.0),
                   cfg.zero_threshold)
    return w, h


def _apply_hals(w, h, la, ar, left, right, cfg):
    """Compressed HALS: the coordinate updates of solvers/hals.py with
    every Gram term contracted through the sketches; the per-component
    AXPYs are identical (they never touch A)."""
    k = w.shape[1]
    eps = cfg.div_eps
    wta, wtw = _h_gram_terms(w, la, left)
    for j in range(k):
        hj = h[j] + (wta[j] - wtw[j] @ h) / (wtw[j, j] + eps)
        h = h.at[j].set(base.clamp(jnp.maximum(hj, 0.0),
                                   cfg.zero_threshold))
    aht, hht = _w_gram_terms(h, ar, right)
    for j in range(k):
        wj = w[:, j] + (aht[:, j] - w @ hht[:, j]) / (hht[j, j] + eps)
        w = w.at[:, j].set(base.clamp(jnp.maximum(wj, 0.0),
                                      cfg.zero_threshold))
    return w, h


_APPLY = {"mu": _apply_mu, "hals": _apply_hals}


def init_aux(a, w0, h0, cfg: SolverConfig, key: jax.Array):
    """Solver-specific carry: the one-time sketches L·A / A·R, the
    projections, and the Nesterov state (previous accepted iterates +
    the t-sequence scalar)."""
    m, n = a.shape
    k = w0.shape[1]
    r = resolve_dim(cfg, m, n, k)
    left, right = sketch_operators(key, m, n, r, a.dtype)
    la = left @ a  # (r, n), once per restart
    ar = a @ right  # (m, r), once per restart
    return (la, ar, left, right, w0, h0,
            jnp.asarray(1.0, a.dtype))


def step(a, state: base.State, cfg: SolverConfig,
         check: bool = True) -> base.State:
    """One compressed iteration with optional Nesterov extrapolation.

    ``state.aux = (la, ar, left, right, w_acc, h_acc, t)`` where
    (w_acc, h_acc) are the PREVIOUS accepted iterates the momentum
    extrapolates against (distinct from ``state.w_prev``, which
    ``run_loop`` overwrites every iteration for TolX)."""
    la, ar, left, right, w_acc, h_acc, t = state.aux
    w0, h0 = state.w, state.h
    apply_fn = _APPLY[cfg.algorithm]
    if cfg.sketch.momentum:
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        beta = (t - 1.0) / t_next
        wb = jnp.maximum(w0 + beta * (w0 - w_acc), 0.0)
        hb = jnp.maximum(h0 + beta * (h0 - h_acc), 0.0)
        w, h = apply_fn(wb, hb, la, ar, left, right, cfg)
    else:
        t_next = t
        w, h = apply_fn(w0, h0, la, ar, left, right, cfg)
    state = state._replace(w=w, h=h,
                           aux=(la, ar, left, right, w0, h0, t_next))
    if not check:
        return state
    # class-stability + TolX only: both are O(kn + mk) on the full
    # factors; TolFun would need the uncompressed m×n residual every
    # check — the one cost the compression exists to avoid (the final
    # dnorm in run_loop's epilogue stays uncompressed)
    return base.check_convergence(state, cfg, use_class=cfg.use_class_stop,
                                  use_tolx=True)


def solve_sketched(a: jax.Array, w0: jax.Array, h0: jax.Array,
                   key: jax.Array,
                   cfg: SolverConfig) -> base.SolverResult:
    """One compressed factorization from a restart's canonical key.
    Vmappable over (w0, h0, key) exactly like the exact driver.

    The final UNCOMPRESSED pass: after the compressed loop stops,
    ``SketchConfig.polish_iters`` exact update iterations (the full
    mu/hals rule against A itself) run before the result is read, and
    the final ``dnorm`` is the true uncompressed RMS residual — so the
    labels the consensus layer consumes come from an exact-update
    neighborhood, not a sketch-noise-rattled iterate (without this,
    long compressed budgets measurably wander the final labels; see
    ``SketchConfig.polish_iters``)."""
    if cfg.algorithm not in SKETCHED_ALGORITHMS:
        raise ValueError(
            f"sketched engine supports {SKETCHED_ALGORITHMS}, got "
            f"{cfg.algorithm!r}")
    from nmfx.solvers import SOLVERS

    polish = cfg.sketch.polish_iters
    with base.matmul_precision_ctx(cfg.matmul_precision):
        res = base.run_loop(a, w0, h0, cfg, step,
                            init_aux(a, w0, h0, cfg, key))
        if polish == 0:
            return res
        mod = SOLVERS[cfg.algorithm]
        state = base.init_state(a, res.w, res.h,
                                mod.init_aux(a, res.w, res.h, cfg))
        for _ in range(polish):
            state = state._replace(w_prev=state.w, h_prev=state.h,
                                   iteration=state.iteration + 1)
            state = mod.step(a, state, cfg, check=False)
        return base.SolverResult(
            w=state.w, h=state.h,
            iterations=res.iterations + polish,
            dnorm=base.residual_norm(a, state.w, state.h),
            stop_reason=res.stop_reason)


def compressed_objective(a: jax.Array, w: jax.Array, h: jax.Array,
                         key: jax.Array, cfg: SolverConfig) -> jax.Array:
    """Doubly compressed objective ‖(LA)R − (LW)(HR)‖²_F — an
    O(r²·(k + n/m share)) proxy for the true residual, used by the
    screening pass to RANK restarts (only the ordering matters, so no
    normalizer). Uses the restart's own (L, R), drawn from the same
    key chain as the solve."""
    m, n = a.shape
    k = w.shape[1]
    r = resolve_dim(cfg, m, n, k)
    left, right = sketch_operators(key, m, n, r, a.dtype)
    lar = (left @ a) @ right  # (r, r)
    d = lar - (left @ w) @ (h @ right)
    return jnp.sum(d * d)


def screen_pass(a: jax.Array, w0: jax.Array, h0: jax.Array,
                key: jax.Array, cfg: SolverConfig) -> jax.Array:
    """One restart's cheap screening pass: ``sketch.screen_iters``
    compressed iterations (no convergence checks — the budget IS the
    point), then the compressed objective. Returns a scalar score;
    lower = more promising."""
    iters = cfg.sketch.screen_iters
    apply_fn = _APPLY[cfg.algorithm]
    aux = init_aux(a, w0, h0, cfg, key)
    la, ar, left, right = aux[0], aux[1], aux[2], aux[3]

    def body(carry, _):
        w, h, w_acc, h_acc, t = carry
        if cfg.sketch.momentum:
            t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
            beta = (t - 1.0) / t_next
            wb = jnp.maximum(w + beta * (w - w_acc), 0.0)
            hb = jnp.maximum(h + beta * (h - h_acc), 0.0)
            w2, h2 = apply_fn(wb, hb, la, ar, left, right, cfg)
        else:
            t_next = t
            w2, h2 = apply_fn(w, h, la, ar, left, right, cfg)
        return (w2, h2, w, h, t_next), None

    with base.matmul_precision_ctx(cfg.matmul_precision):
        (w, h, _, _, _), _ = jax.lax.scan(
            body, (w0, h0, w0, h0, jnp.asarray(1.0, a.dtype)),
            None, length=iters)
        lar = (left @ a) @ right
        d = lar - (left @ w) @ (h @ right)
        return jnp.sum(d * d)


def sweep_lanes(a: jax.Array, w0s: jax.Array, h0s: jax.Array,
                keys: jax.Array, cfg: SolverConfig) -> base.SolverResult:
    """Vmapped batch of compressed solves — the sketched engine's
    restart-batch form the sweep builder consumes."""
    return jax.vmap(partial(solve_sketched, a, cfg=cfg))(w0s, h0s, keys)
