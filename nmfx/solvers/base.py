"""Shared solver driver: one ``lax.while_loop`` for all eight update rules.

The reference implements convergence control separately (and inconsistently)
in each C solver; here every solver exposes

* ``init_aux(a, w0, h0, cfg) -> aux``   — solver-specific carry (pytree)
* ``step(a, state, cfg) -> state``      — one full iteration incl. its own
                                          convergence decision

and this module runs the loop, vmap-compatible (JAX's while_loop batching rule
runs a batch until every element's predicate is false, masking updates — which
is exactly the per-restart early-stop semantics SURVEY.md §7 calls out as hard
part #1).

Convergence helpers mirror the reference's C utilities:
``residual_norm`` = calculateNorm (reference ``libnmf/calculatenorm.c:44-78``),
``maxchange`` = calculateMaxchange (reference ``libnmf/calculatemaxchange.c:42-71``).
"""

from __future__ import annotations

import enum
from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from nmfx.config import SolverConfig


def matmul_precision_ctx(precision: str):
    """Context applying a SolverConfig.matmul_precision at trace time
    ("default" = leave JAX's platform default untouched)."""
    import contextlib

    if precision == "default":
        return contextlib.nullcontext()
    return jax.default_matmul_precision(precision)


class ShardInfo(NamedTuple):
    """Mesh-axis names + unsharded dims for a solver running inside
    ``shard_map`` on a feature/sample-tiled A (the workload's tensor- and
    sequence-parallel axes, SURVEY.md §5). ``None`` axes are off. Hashable,
    so it can ride static args. Used by solvers whose per-restart
    intermediates are O(m·n) and therefore *need* the grid axes at scale —
    kl's quotient (solvers/kl.py) — with the same psum placement as the
    packed mu path (ops/packed_mu.py): m-contracted terms reduce over
    ``feature_axis``, n-contracted terms over ``sample_axis``."""

    feature_axis: str | None = None
    sample_axis: str | None = None
    m_total: int | None = None  # unsharded (unpadded) row count
    n_total: int | None = None  # unsharded (unpadded) column count


def shard_reducers(shard: ShardInfo | None):
    """The pair of conditional psums every grid-sharded solver update
    needs: ``(fsum, ssum)`` reduce over the feature / sample mesh axis
    when present, else pass through. One definition so a future change to
    the reduction scheme cannot silently desynchronize one solver."""
    f_ax = shard.feature_axis if shard is not None else None
    s_ax = shard.sample_axis if shard is not None else None

    def fsum(x):
        return lax.psum(x, f_ax) if f_ax is not None else x

    def ssum(x):
        return lax.psum(x, s_ax) if s_ax is not None else x

    return fsum, ssum


class StopReason(enum.IntEnum):
    MAX_ITER = 0
    #: per-column argmax of H unchanged for `stable_checks` consecutive checks
    #: (the only live stop in the reference's exercised solver, nmf_mu.c:253-282)
    CLASS_STABLE = 1
    #: max-change of W and H below TolX (reference delta < TolX)
    TOL_X = 2
    #: relative residual decrease below TolFun (intended semantics of the
    #: reference's dead `dnorm <= TolFun*dnorm0` check — see SolverConfig)
    TOL_FUN = 3
    #: projected-gradient norm below tol * initial gradient norm (Lin 2007;
    #: reference nmf_pg.c:228-243 / nmf_alspg.c:193-209)
    PG_TOL = 4
    #: numeric quarantine (``SolverConfig.nonfinite_guard``): the lane's
    #: factors went non-finite and the lane was stopped and masked out of
    #: the consensus/labels/best-restart reductions exactly like a pad
    #: lane — its recorded factors/dnorm are diagnostic only
    NUMERIC_FAULT = 5
    #: restart screening (``SolverConfig.screen``): the lane's cheap
    #: sketched pass ranked below the ``screen_keep`` cut, so it never
    #: received exact iterations — masked from the consensus/labels/
    #: best-restart reductions exactly like a pad or quarantined lane
    #: (the ``min_restarts`` floor counts it as a non-survivor); its
    #: recorded iteration count is the screening budget spent
    SCREENED = 6


class State(NamedTuple):
    """Loop carry. ``w``/``h`` are the current factors; ``w_prev``/``h_prev``
    the previous iteration's (for TolX); ``aux`` is solver-specific."""

    w: jax.Array
    h: jax.Array
    w_prev: jax.Array
    h_prev: jax.Array
    iteration: jax.Array  # i32, iterations completed
    dnorm: jax.Array  # residual at last check (f32), inf until computed
    classes: jax.Array  # (n,) i32 per-sample argmax label at last check
    stable: jax.Array  # i32 consecutive stable checks
    done: jax.Array  # bool
    stop_reason: jax.Array  # i32 StopReason
    aux: Any


class SolverResult(NamedTuple):
    w: jax.Array
    h: jax.Array
    iterations: jax.Array
    dnorm: jax.Array  # final ||A - W H||_F / sqrt(m n)
    stop_reason: jax.Array


def residual_norm(a: jax.Array, w: jax.Array, h: jax.Array,
                  shard: ShardInfo | None = None) -> jax.Array:
    """RMS residual ||A - W H|| / sqrt(m*n).

    The reference materializes an m*n scratch D = A - W*H for this
    (calculatenorm.c:44-78); XLA fuses the subtraction into the reduction so
    no scratch ever hits HBM. Under ``shard`` the local block's square-sum
    psums over the grid axes (zero-padded rows/columns contribute exact
    zeros) and the RMS normalizer uses the unsharded dims.
    """
    m, n = a.shape
    d = a - w @ h
    sq = jnp.sum(d * d)
    if shard is not None:
        if shard.feature_axis is not None:
            sq = lax.psum(sq, shard.feature_axis)
            m = shard.m_total
        if shard.sample_axis is not None:
            sq = lax.psum(sq, shard.sample_axis)
            n = shard.n_total
    return jnp.sqrt(sq / (m * n))


def maxchange(mat: jax.Array, mat0: jax.Array,
              axis_name: str | None = None) -> jax.Array:
    """max|mat - mat0| / (sqrt(eps) + max|mat0|) (calculatemaxchange.c:42-71).

    ``axis_name``: mesh axis the matrix is sharded over — the ratio is of
    *global* maxima, so both ingredients pmax before dividing."""
    sqrteps = jnp.sqrt(jnp.finfo(mat.dtype).eps)
    diff = jnp.max(jnp.abs(mat - mat0))
    ref = jnp.max(jnp.abs(mat0))
    if axis_name is not None:
        diff = lax.pmax(diff, axis_name)
        ref = lax.pmax(ref, axis_name)
    return diff / (sqrteps + ref)


def class_labels(h: jax.Array) -> jax.Array:
    """Per-sample cluster label = argmax over H's rows.

    Intended semantics of both the C early-stop (biggestInRow, nmf_mu.c:258-261,
    which reads out of bounds — quirk Q1) and the BROAD method; the reference R
    layer instead takes the argmin (quirk Q3), available via
    ConsensusConfig.label_rule="argmin".
    """
    return jnp.argmax(h, axis=0).astype(jnp.int32)


def clamp(x: jax.Array, zero_threshold: float) -> jax.Array:
    """Zero out negatives and sub-threshold values (reference ZERO_THRESHOLD
    clamp applied after every update, e.g. nmf_als.c:247-250)."""
    return jnp.where(x <= zero_threshold, jnp.zeros_like(x), x)


def solve_gram_reg(gram: jax.Array, rhs: jax.Array) -> jax.Array:
    """Cholesky-solve ``(gram + λI) x = rhs`` with a trace-scaled Tikhonov
    jitter λ = 10·eps·mean(diag): always well-posed under jit/vmap, and
    indistinguishable from the plain solve for healthy systems — the shared
    shape-stable answer to the reference's lazy singular-fallback
    (``libnmf/nmf_neals.c:206-291``). Used by neals and snmf."""
    import jax.scipy.linalg as jsl

    k = gram.shape[0]
    lam = 10 * jnp.finfo(gram.dtype).eps * (jnp.trace(gram) / k)
    gram = gram + (lam + jnp.finfo(gram.dtype).tiny) * jnp.eye(
        k, dtype=gram.dtype)
    return jsl.cho_solve(jsl.cho_factor(gram), rhs)


def check_convergence(
    state: State,
    cfg: SolverConfig,
    *,
    a: jax.Array | None = None,
    use_class: bool = False,
    use_tolx: bool = False,
    use_tolfun: bool = False,
    shard: ShardInfo | None = None,
) -> State:
    """Apply the generic convergence tests after a step.

    Tests run every ``cfg.check_every``-th iteration for iteration > 1
    (reference: even iterations only, nmf_mu.c:253 / nmf_als.c:338). All
    bookkeeping is branchless (jnp.where on scalars) so it vmaps and keeps the
    while_loop body a single fused XLA computation.

    Under ``shard`` every test reduces to the same *global* decision on each
    device of a factorization's grid group (label mismatches psum over the
    sample axis, max-change pmaxes over the axis each factor is sharded on,
    the residual psums over both), so the batched while_loop stays in
    lockstep SPMD across the group.
    """
    it = state.iteration
    is_check = (it > 1) & (it % cfg.check_every == 0) & (~state.done)
    done = state.done
    reason = state.stop_reason
    f_ax = shard.feature_axis if shard is not None else None
    s_ax = shard.sample_axis if shard is not None else None

    if cfg.nonfinite_guard:
        # numeric quarantine FIRST: a non-finite lane must stop with
        # NUMERIC_FAULT before the class/TolX tests can read its NaN
        # labels or deltas (NaN comparisons are all False, but a stable
        # counter banked before divergence could still fire). Under a
        # factor-sharded mesh the verdict is global: W is row-sharded
        # over features, H column-sharded over samples, so each factor's
        # local non-finite flag reduces over its own axis.
        bad_w = ~jnp.all(jnp.isfinite(state.w))
        bad_h = ~jnp.all(jnp.isfinite(state.h))
        if f_ax is not None:
            bad_w = lax.psum(bad_w.astype(jnp.int32), f_ax) > 0
        if s_ax is not None:
            bad_h = lax.psum(bad_h.astype(jnp.int32), s_ax) > 0
        faulted = is_check & (bad_w | bad_h)
        done = done | faulted
        is_check = is_check & ~faulted
        reason = jnp.where(faulted, jnp.int32(StopReason.NUMERIC_FAULT),
                           reason)

    classes = state.classes
    stable = state.stable
    if use_class:
        # noise-tolerant snapshot rule: count label mismatches against a held
        # reference labeling (state.classes); within tolerance -> counter up,
        # snapshot kept; beyond -> counter reset, snapshot := current labels.
        # At flip_tol=0 this is exactly the reference's consecutive-check
        # rule (nmf_mu.c:253-282): after every check the snapshot equals the
        # current labels (either reset to them, or unchanged with zero
        # mismatch, i.e. already equal), so each comparison is against the
        # previous check. See SolverConfig.class_flip_tol.
        new_classes = class_labels(state.h)
        n_glob = new_classes.shape[0]
        if s_ax is not None:
            if shard.n_total is None:
                raise ValueError(
                    "class-stability check with sample_axis needs n_total "
                    "(the unsharded column count); the local shard width "
                    "would make the flip tolerance ~#shards too strict")
            n_glob = shard.n_total
        # +eps before flooring: 0.3 * 10 is 2.999... in binary float and
        # int() would land one flip below the documented floor(tol * n)
        flip_tol = int(cfg.class_flip_tol * n_glob + 1e-9)
        mism = jnp.sum((new_classes != state.classes).astype(jnp.int32))
        if s_ax is not None:
            # labels live on column shards: the mismatch count is global
            mism = lax.psum(mism, s_ax)
        same = mism <= flip_tol
        stable = jnp.where(is_check, jnp.where(same, state.stable + 1, 0),
                           state.stable)
        classes = jnp.where(is_check & ~same, new_classes, state.classes)
        hit = is_check & (stable >= cfg.stable_checks)
        done = done | hit
        # jnp.int32(enum): an IntEnum is NOT weak-typed, so under
        # jax_enable_x64 (the parity configuration) a bare enum constant
        # canonicalizes to int64 and poisons the i32 stop_reason carry —
        # a while-carry type error the lint jaxpr layer (NMFX101) traces
        # for on every registered engine
        reason = jnp.where(hit, jnp.int32(StopReason.CLASS_STABLE), reason)

    if use_tolx and cfg.use_tol_checks:
        # W is row-sharded over the feature axis (replicated over samples),
        # H column-sharded over the sample axis (replicated over features)
        delta = jnp.maximum(maxchange(state.w, state.w_prev, f_ax),
                            maxchange(state.h, state.h_prev, s_ax))
        hit = is_check & (delta < cfg.tol_x) & ~done
        done = done | hit
        reason = jnp.where(hit, jnp.int32(StopReason.TOL_X), reason)

    dnorm = state.dnorm
    if use_tolfun and cfg.use_tol_checks:
        assert a is not None
        new_dnorm = residual_norm(a, state.w, state.h, shard)
        # relative decrease vs the residual at the previous check
        hit = (is_check & jnp.isfinite(state.dnorm)
               & (state.dnorm - new_dnorm <= cfg.tol_fun * state.dnorm) & ~done)
        dnorm = jnp.where(is_check, new_dnorm, state.dnorm)
        done = done | hit
        reason = jnp.where(hit, jnp.int32(StopReason.TOL_FUN), reason)

    return state._replace(classes=classes, stable=stable, done=done,
                          stop_reason=reason, dnorm=dnorm)


def init_state(a: jax.Array, w0: jax.Array, h0: jax.Array, aux: Any) -> State:
    n = h0.shape[1]
    f = w0.dtype
    return State(
        w=w0,
        h=h0,
        w_prev=w0,
        h_prev=h0,
        iteration=jnp.zeros((), jnp.int32),
        dnorm=jnp.array(jnp.inf, f),
        classes=jnp.full((n,), -1, jnp.int32),
        stable=jnp.zeros((), jnp.int32),
        done=jnp.zeros((), bool),
        stop_reason=jnp.full((), StopReason.MAX_ITER, jnp.int32),
        aux=aux,
    )


def run_loop(a, w0, h0, cfg: SolverConfig, step_fn, aux,
             shard: ShardInfo | None = None) -> SolverResult:
    """Drive ``step_fn`` to convergence under jit.

    The loop body unrolls ``check_every`` solver steps and only the last one
    runs the (possibly O(mnk)) convergence tests — mirroring the reference's
    check-every-2nd-iteration scheme structurally, so off-iterations never
    compute a residual that a ``where``/``cond`` would discard (under vmap a
    cond lowers to a select that executes both branches).

    ``shard``: the step_fn is expected to have the same ShardInfo bound (its
    collectives make every convergence decision identical across a
    factorization's grid group, keeping this loop lockstep); here it scopes
    only the final residual.
    """
    state0 = init_state(a, w0, h0, aux)

    def one_step(state: State, check: bool) -> State:
        state = state._replace(
            w_prev=state.w, h_prev=state.h, iteration=state.iteration + 1
        )
        return step_fn(a, state, cfg, check)

    def cond(state: State):
        return (~state.done) & (state.iteration + cfg.check_every
                                <= cfg.max_iter)

    def body(state: State):
        for i in range(cfg.check_every):
            state = one_step(state, check=(i == cfg.check_every - 1))
        return state

    final = lax.while_loop(cond, body, state0)

    # tail: if max_iter is not a multiple of check_every, finish the last
    # few iterations one at a time (checking each — at most check_every-1)
    def tail_cond(state: State):
        return (~state.done) & (state.iteration < cfg.max_iter)

    final = lax.while_loop(tail_cond, lambda s: one_step(s, True), final)
    return SolverResult(
        w=final.w,
        h=final.h,
        iterations=final.iteration,
        dnorm=residual_norm(a, final.w, final.h, shard),
        stop_reason=final.stop_reason,
    )


@partial(jax.jit, static_argnames=("cfg",))
def solve(a: jax.Array, w0: jax.Array, h0: jax.Array,
          cfg: SolverConfig = SolverConfig()) -> SolverResult:
    """Factorize A ≈ W·H with the configured algorithm.

    Jittable and vmappable; the single-restart analogue of the reference's
    ``doNMF`` R→C bridge (reference ``nmf.r:23-51``), minus the process
    boundary and with all eight solvers wired (the reference only wires mu —
    "calls to add: nmf_als, mu, neals, alspg, pg", nmf.r:40).
    """
    from nmfx.solvers import SOLVERS  # local import to avoid cycle

    if cfg.backend == "sketched" or cfg.screen:
        # the compressed engine draws per-restart projections from a
        # KEY this signature doesn't carry, and screening is a sweep-
        # pool concept — silently running the exact rule here would be
        # a quality mismatch against the sweep's recorded lanes
        raise ValueError(
            "solve() runs the exact engines; backend='sketched' needs "
            "a per-restart key (use nmfx.solvers.sketched."
            "solve_sketched — nmf()/restart_factors() route there "
            "automatically) and screen=True only exists at the sweep "
            "layer")
    if cfg.tile_rows is not None:
        # this signature takes a device-resident A; the out-of-core
        # streaming loop lives at the sweep layer
        raise ValueError(
            "tile_rows streams A from host through nmfx.tiles; solve() "
            "is the in-core single-restart engine (sweep()/nmf() route "
            "tiled configs automatically)")
    dtype = jnp.dtype(cfg.dtype)
    a = jnp.asarray(a, dtype)
    w0 = jnp.asarray(w0, dtype)
    h0 = jnp.asarray(h0, dtype)
    mod = SOLVERS[cfg.algorithm]
    # the context applies at trace time; cfg is a static arg, so each
    # precision gets its own jit cache entry
    with matmul_precision_ctx(cfg.matmul_precision):
        aux = mod.init_aux(a, w0, h0, cfg)
        return run_loop(a, w0, h0, cfg, mod.step, aux)
