"""ALS with projected-gradient subproblems (Lin 2007, alternating variant).

TPU-native re-design of reference ``libnmf/nmf_alspg.c:75-290``: each outer
iteration solves the W-then-H NNLS subproblems with the shared
projected-gradient subsolver (pg_common; reference pg_subprob_w/h), tightening
a subproblem's tolerance ×0.1 whenever it converges in a single iteration
(nmf_alspg.c:220-228). Stops when the joint projected-gradient norm falls
below ``tol_pg ×`` its initial value (nmf_alspg.c:193-209), using the
gradients returned by the previous iteration's subsolvers, as the reference
does.

Performance shape (profiled, benchmarks/RESULTS.md "pg / alspg profile"):
latency-bound, not compute- or dispatch-bound — each outer iteration is two
sequential chains of up to ``sub_max_iter`` dependent tiny-GEMM
sub-iterations (~0.14 ms per dependent step on TPU), and under vmap every
restart waits for the worst lane's chain. No batching shortens a dependency
chain; prefer mu for anything but parity checks and small problems.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from nmfx.config import SolverConfig
from nmfx.solvers import base
from nmfx.solvers.pg_common import projgrad_norm_sq, solve_subproblem


class Aux(NamedTuple):
    gradw: jax.Array  # (m, k)
    gradh: jax.Array  # (k, n)
    initgrad: jax.Array
    tolw: jax.Array
    tolh: jax.Array


def init_aux(a, w0, h0, cfg: SolverConfig):
    # initial gradients of 1/2||A - WH||^2 (nmf_alspg.c:155-179)
    gradw = w0 @ (h0 @ h0.T) - a @ h0.T
    gradh = (w0.T @ w0) @ h0 - w0.T @ a
    initgrad = jnp.sqrt(jnp.sum(gradw * gradw) + jnp.sum(gradh * gradh))
    tol0 = jnp.maximum(jnp.asarray(cfg.tol_pg, w0.dtype), 0.001) * initgrad
    return Aux(gradw, gradh, initgrad, tol0, tol0)


def step(a, state: base.State, cfg: SolverConfig,
         check: bool = True) -> base.State:
    # alspg's convergence test is its own projected-gradient norm, evaluated
    # every iteration as the reference does — `check` is unused
    del check
    aux: Aux = state.aux
    w, h = state.w, state.h

    projnorm = jnp.sqrt(projgrad_norm_sq(aux.gradw, w)
                        + projgrad_norm_sq(aux.gradh, h))
    hit = projnorm < cfg.tol_pg * aux.initgrad

    # W subproblem on X = Wᵀ: gram = HHᵀ, cross = HAᵀ (reference avoids the
    # transpose with a mirrored C routine; on TPU the transpose is free)
    res_w = solve_subproblem(h @ h.T, h @ a.T, w.T, aux.tolw, cfg)
    w_new = res_w.x.T
    tolw = jnp.where(res_w.iterations == 1, cfg.ls_beta * aux.tolw, aux.tolw)

    res_h = solve_subproblem(w_new.T @ w_new, w_new.T @ a, h, aux.tolh, cfg)
    tolh = jnp.where(res_h.iterations == 1, cfg.ls_beta * aux.tolh, aux.tolh)

    state = state._replace(
        w=jnp.where(hit, w, w_new),
        h=jnp.where(hit, h, res_h.x),
        done=state.done | hit,
        # int32-pinned, as in pg.step: an IntEnum is not weakly typed on
        # every jax, and int64 promotion under x64 would split the cond
        # branches' State dtypes
        stop_reason=jnp.where(hit, jnp.int32(base.StopReason.PG_TOL),
                              state.stop_reason),
        aux=Aux(jnp.where(hit, aux.gradw, res_w.grad.T),
                jnp.where(hit, aux.gradh, res_h.grad),
                aux.initgrad,
                jnp.where(hit, aux.tolw, tolw),
                jnp.where(hit, aux.tolh, tolh)),
    )
    return state
