"""Multiplicative-update NMF (Lee–Seung / Berry 2006).

TPU-native re-design of the reference's exercised solver (reference
``libnmf/nmf_mu.c:84-317``): the six per-iteration dgemms plus elementwise
updates become four matmuls (the k×k Grams are shared) with XLA-fused
elementwise epilogues; the class-stability early stop runs on-device with
correct indexing (fixing quirk Q1, the out-of-bounds scan at nmf_mu.c:256-265).

Update rule per iteration (nmf_mu.c:174-216):

    H ← H ∘ (WᵀA) / (WᵀW·H + ε),  then clamp to zero threshold
    W ← W ∘ (AHᵀ) / (W·HHᵀ + ε)   (using the NEW H), then clamp

with the reference's exact-zero short-circuit: an element whose previous value
or numerator is exactly 0 stays 0 (nmf_mu.c:184-191).

Convergence (all checks every 2nd iteration): class-stability stop after 200
stable checks (live in the reference) plus the documented-but-disabled
delta < TolX test (nmf_mu.c:278-281), enabled here via cfg.use_tol_checks.
"""

from __future__ import annotations

import jax.numpy as jnp

from nmfx.config import SolverConfig
from nmfx.solvers import base


def init_aux(a, w0, h0, cfg: SolverConfig):
    return ()


def _mu_update(prev, numer, denom, cfg: SolverConfig):
    ratio = prev * (numer / (denom + cfg.div_eps))
    ratio = jnp.where((prev == 0) | (numer == 0), jnp.zeros_like(ratio), ratio)
    return base.clamp(ratio, cfg.zero_threshold)


def step(a, state: base.State, cfg: SolverConfig,
         check: bool = True) -> base.State:
    w0, h0 = state.w, state.h
    # H update: numer = WᵀA, denom = (WᵀW)·H
    numerh = w0.T @ a
    denomh = (w0.T @ w0) @ h0
    h = _mu_update(h0, numerh, denomh, cfg)
    # W update with the fresh H: numer = A·Hᵀ, denom = W·(H·Hᵀ)
    numerw = a @ h.T
    denomw = w0 @ (h @ h.T)
    w = _mu_update(w0, numerw, denomw, cfg)

    state = state._replace(w=w, h=h)
    if not check:
        return state
    return base.check_convergence(state, cfg, use_class=cfg.use_class_stop,
                                  use_tolx=True)
