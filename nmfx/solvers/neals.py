"""Normal-equation ALS NMF.

TPU-native re-design of reference ``libnmf/nmf_neals.c:180-470``:

    H = max((WᵀW) \\ (WᵀA), 0)
    W = max(((HHᵀ) \\ (HAᵀ))ᵀ, 0)

solved on the k×k Gram (reference dgesv LU, nmf_neals.c:200-204,302-306).
When the Gram is singular the reference lazily switches that half-step to a
QR least-squares path (nmf_neals.c:206-291,308-393) — which itself divides
by a zero diagonal for exactly rank-deficient factors. Here the Gram gets a
trace-scaled Tikhonov jitter before a Cholesky solve (SURVEY.md §7 hard
part #5's plan): always well-posed, one code path under jit/vmap, and
indistinguishable from the plain solve for healthy systems (the jitter is
~10·eps relative to the Gram's scale).

Convergence: TolX/TolFun checks every 2nd iteration as in als.
"""

from __future__ import annotations

from nmfx.config import SolverConfig
from nmfx.solvers import base


def init_aux(a, w0, h0, cfg: SolverConfig):
    return ()


def _solve_normal(factor, rhs_gram):
    """solve(factorᵀfactor + λI, rhs_gram) via the shared jittered Cholesky
    (base.solve_gram_reg)."""
    return base.solve_gram_reg(factor.T @ factor, rhs_gram)


def step(a, state: base.State, cfg: SolverConfig,
         check: bool = True) -> base.State:
    w0 = state.w
    h = base.clamp(_solve_normal(w0, w0.T @ a), cfg.zero_threshold)
    wt = _solve_normal(h.T, h @ a.T)
    w = base.clamp(wt.T, cfg.zero_threshold)
    state = state._replace(w=w, h=h)
    if not check:
        return state
    return base.check_convergence(state, cfg, a=a, use_tolx=True,
                                  use_tolfun=True)
