"""Normal-equation ALS NMF.

TPU-native re-design of reference ``libnmf/nmf_neals.c:180-470``:

    H = max((WᵀW) \\ (WᵀA), 0)
    W = max(((HHᵀ) \\ (HAᵀ))ᵀ, 0)

solved on the k×k Gram (reference dgesv LU, nmf_neals.c:200-204,302-306).
When the Gram is singular the reference lazily switches that half-step to a
QR least-squares path (nmf_neals.c:206-291,308-393) — which itself divides
by a zero diagonal for exactly rank-deficient factors. Here the Gram gets a
trace-scaled Tikhonov jitter before a Cholesky solve (SURVEY.md §7 hard
part #5's plan): always well-posed, one code path under jit/vmap, and
indistinguishable from the plain solve for healthy systems (the jitter is
~10·eps relative to the Gram's scale).

Convergence: TolX/TolFun checks every 2nd iteration as in als.

Grid sharding: both half-steps are Gram solves, and the Grams contract
along exactly the axes the mesh tiles — WᵀW and WᵀA over features, HHᵀ
and HAᵀ over samples — so under ``shard`` each becomes one psum pair and
the k×k solves run replicated (same placement as mu's packed Grams and
kl's quotient contractions). Zero-padded rows/columns re-derive as exact
zeros every iteration (their right-hand-side columns are zero).
"""

from __future__ import annotations

from nmfx.config import SolverConfig
from nmfx.solvers import base


def init_aux(a, w0, h0, cfg: SolverConfig,
             shard: base.ShardInfo | None = None):
    return ()


def step(a, state: base.State, cfg: SolverConfig, check: bool = True,
         shard: base.ShardInfo | None = None) -> base.State:
    w0 = state.w
    fsum, ssum = base.shard_reducers(shard)
    h = base.clamp(
        base.solve_gram_reg(fsum(w0.T @ w0), fsum(w0.T @ a)),
        cfg.zero_threshold)
    wt = base.solve_gram_reg(ssum(h @ h.T), ssum(h @ a.T))
    w = base.clamp(wt.T, cfg.zero_threshold)
    state = state._replace(w=w, h=h)
    if not check:
        return state
    return base.check_convergence(state, cfg, a=a, use_tolx=True,
                                  use_tolfun=True, shard=shard)
