"""Normal-equation ALS NMF.

TPU-native re-design of reference ``libnmf/nmf_neals.c:180-470``:

    H = max((WᵀW) \\ (WᵀA), 0)
    W = max(((HHᵀ) \\ (HAᵀ))ᵀ, 0)

solved by LU on the k×k Gram (reference dgesv, nmf_neals.c:200-204,302-306).
When the Gram is singular the reference lazily switches that half-step to the
QR least-squares path of nmf_als (nmf_neals.c:206-291,308-393); here the
fallback is a ``lax.cond`` on non-finite solve output into the same QR solve
als uses — no shape-changing branches (SURVEY.md §7 hard part #5).

Convergence: TolX/TolFun checks every 2nd iteration as in als.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from nmfx.config import SolverConfig
from nmfx.solvers import base
from nmfx.solvers.als import lstsq_qr


def init_aux(a, w0, h0, cfg: SolverConfig):
    return ()


def _solve_normal(factor, rhs_gram, fallback_b):
    """solve(factorᵀfactor, rhs_gram) with QR fallback on singularity.

    ``rhs_gram`` is factorᵀ·B; ``fallback_b`` is B for the QR path.
    """
    gram = factor.T @ factor
    sol = jnp.linalg.solve(gram, rhs_gram)
    ok = jnp.all(jnp.isfinite(sol))
    return lax.cond(ok, lambda: sol, lambda: lstsq_qr(factor, fallback_b))


def step(a, state: base.State, cfg: SolverConfig,
         check: bool = True) -> base.State:
    w0 = state.w
    h = base.clamp(_solve_normal(w0, w0.T @ a, a), cfg.zero_threshold)
    wt = _solve_normal(h.T, h @ a.T, a.T)
    w = base.clamp(wt.T, cfg.zero_threshold)
    state = state._replace(w=w, h=h)
    if not check:
        return state
    return base.check_convergence(state, cfg, a=a, use_tolx=True,
                                  use_tolfun=True)
