"""Alternating least squares NMF.

TPU-native re-design of the reference's QR-with-column-pivoting ALS (reference
``libnmf/nmf_als.c:209-360``): each half-step solves the unconstrained least
squares problem and clamps negatives to zero.

    H = argmin ‖W·X − A‖_F   → min-norm least squares, clamp
    W = argmin ‖Xᵀ·H − A‖_F  → min-norm least squares, clamp

The reference pivots (dgeqp3) and un-permutes with strided dcopy
(nmf_als.c:216-298) purely for rank-deficiency robustness; XLA has no pivoted
QR, so the half-steps use SVD-based minimum-norm least squares — strictly
more robust than pivoting (a rank-deficient W/H yields the min-norm
solution instead of a division by a zero R diagonal), one code path under
vmap. Convergence: delta < TolX or relative residual decrease below TolFun,
every 2nd iteration (nmf_als.c:338-352; see SolverConfig for the fixed
dnorm0 ordering quirk).
"""

from __future__ import annotations

import jax.numpy as jnp

from nmfx.config import SolverConfig
from nmfx.solvers import base


def init_aux(a, w0, h0, cfg: SolverConfig):
    return ()


def lstsq_min_norm(f, b):
    """min_X ||f @ X - b||_F, minimum-norm for rank-deficient f."""
    return jnp.linalg.lstsq(f, b)[0]


def step(a, state: base.State, cfg: SolverConfig,
         check: bool = True) -> base.State:
    w0 = state.w
    h = base.clamp(lstsq_min_norm(w0, a), cfg.zero_threshold)
    # W: solve min ||H.T @ X - A.T|| for X = W.T
    wt = lstsq_min_norm(h.T, a.T)
    w = base.clamp(wt.T, cfg.zero_threshold)
    state = state._replace(w=w, h=h)
    if not check:
        return state
    return base.check_convergence(state, cfg, a=a, use_tolx=True,
                                  use_tolfun=True)
