"""Durable sweeps: checkpoint/resume ledger, preemption tolerance, and
the chunk execution engine behind them.

PR 7 made the serving stack survive in-process faults; this module makes
the PROCESS expendable: a preemption, OOM-kill, or host crash 90%
through a large ``ks x restarts`` sweep no longer loses every completed
restart. Distributed out-of-memory NMF (arxiv 2202.09518) assumes
exactly this block-resumable execution, and MPI-FAUN-style restart-grid
sharding (arxiv 1609.09154) is only production-viable when losing one
device/host re-runs that shard's work, not the whole job — the elastic
runner in ``nmfx/distributed.py`` builds on this ledger.

Design:

* **Deterministic chunk plan.** Each rank's restarts partition into
  fixed boundaries ``[0,c), [c,2c), ...`` (``CheckpointConfig
  .every_n_restarts``; default one chunk per rank). The plan is
  persisted in the manifest, so the killed run, the resume, and any
  uninterrupted reference all execute the IDENTICAL per-chunk batch
  compositions — the property that makes resume bit-identical even on
  engines whose per-lane float results depend on batch composition.
* **Content-addressed manifest.** The input matrix (its
  ``data_cache.DataKey`` content fingerprint), every result-affecting
  ``SolverConfig``/``ConsensusConfig``/``InitConfig`` field (the
  coverage :func:`manifest_key_fields` declares and lint rule NMFX007
  enforces — the ``exec_cache`` persist-key discipline), and the
  jax/device environment. A mismatch on open triggers a clean COLD
  START (warn + clear records + recompute), never a wrong resume and
  never a crash.
* **Per-(k, restart-chunk) completion records.** Atomic tmp+rename
  writes; a torn/corrupt/mismatched record is skipped with one warning
  and its chunk re-runs (self-healing, like ``SweepRegistry.try_load``).
  Records hold per-restart labels/iterations/dnorms/stop-reasons plus
  the chunk's best-restart candidate — everything finalize needs.
* **Order-free exact finalize.** The consensus accumulates from the
  per-restart label records in canonical restart order as INTEGER
  connectivity counts (host int64 — exact, associative), then divides
  by the quarantine survivor count in float64: bit-identical regardless
  of which chunks loaded from disk and which re-ran, and regardless of
  completion order. Best-restart selection replays the global
  first-minimum ``argmin`` over the assembled dnorm array.
* **Preemption tolerance.** ``faults.fire("proc.preempt")`` between a
  chunk's solve and its commit raises :class:`Preempted` (the rehearsal
  for SIGKILL landing mid-chunk: the in-flight chunk is lost, every
  committed record survives); :func:`install_signal_flush` hooks
  SIGTERM/SIGINT to flush any time-batched (``every_s``) buffered
  records before the process dies.

Contract note: a checkpointed run is bit-identical to every other
checkpointed run of the same (data, config, plan) — interrupted or not
— but agrees with the NON-checkpointed sweep only to float tolerance
(the device path reduces the consensus in float32 on-device; engines
with batch-composition-dependent reduction orders also regroup).
``tests/test_checkpoint.py`` pins both sides.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import re
import signal
import threading
import time

import numpy as np

from nmfx.config import (CheckpointConfig, ConsensusConfig, InitConfig,
                         SolverConfig)
from nmfx.guards import guarded_by
from nmfx.obs import flight as _flight
from nmfx.obs import metrics as _metrics
from nmfx.obs import trace as _trace

__all__ = ["MANIFEST_CONSENSUS_EXCLUDED", "Preempted", "SweepCheckpoint",
           "chunks_loaded_count", "chunks_solved_count", "engine_family",
           "install_signal_flush", "manifest_key_fields", "plan_chunks",
           "run_checkpointed_sweep", "solve_chunk_host"]

_log = logging.getLogger("nmfx")

_MANIFEST_NAME = "manifest.json"
#: completion-record filenames — the ONLY files (plus the ledger's own
#: shard heartbeats below) a cold-start clear may delete; the legacy
#: SweepRegistry's per-rank ``k<k>.npz`` and any user files in the
#: directory are never touched
_RECORD_RE = re.compile(r"^k\d+_r\d+-\d+\.npz$")
#: mid-chunk PARTIAL progress for out-of-core (tiled/sparse) solves —
#: a snapshot of the tiled solver state at a convergence-check boundary
#: (ISSUE 17). Same clear/fingerprint discipline as completion records;
#: a partial never substitutes for a completion record, it only lets a
#: preempted atlas-scale chunk resume mid-matrix instead of from
#: iteration zero.
_PART_RE = re.compile(r"^k\d+_r\d+-\d+\.part\.npz$")
#: shard heartbeat files (:meth:`SweepCheckpoint.heartbeat`) — cleared
#: on cold start too, or a prior incarnation's stale heartbeats would
#: report phantom dead shards through :meth:`shard_status`
_SHARD_RE = re.compile(r"^shard_\d+\.json$")
#: v1: ISSUE 9 — the initial durable-ledger format. v2: ISSUE 16 —
#: ``restarts`` left the manifest fingerprint (per-chunk records are
#: restart-BUDGET independent; prefix-stable PRNG chains make chunk
#: ``[r0, r1)`` byte-identical under any budget that contains it), so a
#: widened budget EXTENDS a compatible ledger — solving only the delta
#: chunks — instead of cold-starting. v1 ledgers (whose fingerprints
#: included restarts) cold-start once, cleanly.
_FORMAT_VERSION = 2

#: AUTHORITATIVE list of ConsensusConfig fields excluded from the
#: checkpoint manifest. Every entry must be declared checkpoint-exempt
#: in ``ConsensusConfig.CHECKPOINT_EXEMPT_FIELDS`` (which records the
#: per-field rationale) — lint rule NMFX007 cross-references the two
#: lists, so a result-affecting field can never be dropped from the
#: manifest silently (the stale-resume class NMFX001 kills for the
#: registry fingerprint).
MANIFEST_CONSENSUS_EXCLUDED = ("ks", "linkage", "min_restarts",
                               "keep_factors", "grid_exec", "grid_slots",
                               "grid_tail_slots", "restarts")


class Preempted(BaseException):
    """The armed ``proc.preempt`` fault site fired between a chunk's
    solve and its commit — the chaos rehearsal of a preemption/SIGKILL
    landing mid-chunk. ``BaseException`` on purpose: no graceful
    ``except Exception`` recovery layer (serve retries, harvest
    fallbacks) may swallow a preemption and keep computing."""


# -- honesty counters ------------------------------------------------------
# registry instruments (nmfx.obs.metrics); the *_count() functions
# below are the back-compat read shims the resume-contract gates keep
# using (ISSUE 10)
_chunks_solved_total = _metrics.counter(
    "nmfx_ckpt_chunks_solved_total",
    "restart-chunks actually solved on device through the checkpoint "
    "engine (loaded records do not count)")
_chunks_loaded_total = _metrics.counter(
    "nmfx_ckpt_chunks_loaded_total",
    "restart-chunks served from completion records on disk")
# declared identically in nmfx.result_cache (which this module must not
# import — it imports manifest_key_fields from here); the registry's
# idempotent get-or-create hands both sites one shared series
_extended_total = _metrics.counter(
    "nmfx_result_cache_extended_total",
    "checkpointed sweeps that resumed a compatible ledger under a "
    "widened budget (more restarts / more ranks) and solved only the "
    "delta chunks")


def chunks_solved_count() -> int:
    """Restart-chunks this process actually SOLVED on device through the
    checkpoint engine (loaded records do not count) — the counter the
    resume contract is gated on: a fully-checkpointed re-run must leave
    it untouched. Reads ``nmfx_ckpt_chunks_solved_total``."""
    return int(_chunks_solved_total.total())


def chunks_loaded_count() -> int:
    """Restart-chunks served from completion records on disk
    (``nmfx_ckpt_chunks_loaded_total``)."""
    return int(_chunks_loaded_total.total())


def _note(solved: int = 0, loaded: int = 0) -> None:
    if solved:
        _chunks_solved_total.inc(solved)
    if loaded:
        _chunks_loaded_total.inc(loaded)


# -- manifest --------------------------------------------------------------
def engine_family(solver_cfg: SolverConfig) -> str:
    """The engine the CHUNK EXECUTOR runs this configuration through
    (``sweep._build_chunk_sweep_fn``): "pallas"/"packed" for the
    packed-family mu backends, "vmap" (the generic driver) for
    everything else — including the non-mu whole-grid opt-ins, whose
    slot-scheduled engine has no explicit-key chunk form. Hashed into
    the manifest so a ledger can never resume under a different engine
    family.

    ``tile_rows`` set resolves to the out-of-core streaming engine
    ``"tiled"`` (``nmfx/tiles.py``) — conservatively: a single-tile
    config that ``sweep()`` would delegate to the dense path still says
    "tiled" here, which can only SPLIT identities of bit-identical
    programs, never alias different ones (the delegated path consults
    this after tile_rows is stripped). Sparse inputs without
    ``tile_rows`` also run tiled, but their manifests can never collide
    with a dense run's anyway — the data payload carries the sparse
    content fingerprint and the tile plan (``_fingerprint``)."""
    from nmfx.sweep import _use_packed

    if solver_cfg.tile_rows is not None:
        return "tiled"
    if solver_cfg.backend == "pallas":
        return "pallas"
    return "packed" if _use_packed(solver_cfg) else "vmap"


def manifest_key_fields() -> "dict[str, frozenset]":
    """The config fields the checkpoint manifest covers, per config
    class — the introspection hook lint rule NMFX007 cross-references
    (the NMFX001 discipline): every result-affecting
    ``SolverConfig``/``ConsensusConfig`` field must appear here or be
    declared execution-strategy-/finalize-only. The manifest payload is
    BUILT from these sets (``_fingerprint``), so the hook cannot drift
    from the hash."""
    from nmfx.registry import FINGERPRINT_SOLVER_EXCLUDED

    return {
        "solver": (frozenset(f.name
                             for f in dataclasses.fields(SolverConfig))
                   - set(FINGERPRINT_SOLVER_EXCLUDED)),
        "consensus": (frozenset(
            f.name for f in dataclasses.fields(ConsensusConfig))
            - set(MANIFEST_CONSENSUS_EXCLUDED)),
    }


def _env_info() -> dict:
    """The execution environment half of the manifest (the exec-cache
    persist-key discipline): per-restart float trajectories are only
    guaranteed reproducible on the same jax/jaxlib and device kind, so
    a ledger written elsewhere cold-starts instead of resuming."""
    import jax

    try:
        import jaxlib

        jaxlib_v = jaxlib.__version__
    except (ImportError, AttributeError):  # pragma: no cover
        jaxlib_v = "unknown"
    return {"jax": jax.__version__, "jaxlib": jaxlib_v,
            "device_kind": jax.devices()[0].device_kind}


def _fingerprint(a, ccfg: ConsensusConfig,
                 scfg: SolverConfig, icfg: InitConfig) -> str:
    """sha256 over everything that determines a completion record's
    numbers: the input's DataKey content fingerprint (or the sparse
    triplet fingerprint for :class:`~nmfx.sparse.SparseMatrix` inputs),
    the covered solver/consensus fields (``manifest_key_fields`` —
    backend hashed as the chunk executor's resolved engine family), the
    full init config, and the format version. Out-of-core runs
    additionally hash the resolved TILE PLAN: a multi-tile chunk's
    floats depend on the tile-blocked reduction order, so a changed
    plan (different budget, different tile_rows) must cold-start, never
    "resume" foreign records."""
    from nmfx.data_cache import default_cache
    from nmfx.sparse import SparseMatrix

    if isinstance(a, SparseMatrix):
        data = {"fingerprint": a.fingerprint(),
                "src_dtype": str(a.data.dtype),
                "shape": list(a.shape), "dtype": str(scfg.dtype),
                "sparse": True}
    else:
        dkey = default_cache().key_for(np.asarray(a), scfg.dtype)
        data = {"fingerprint": dkey.fingerprint,
                "src_dtype": dkey.src_dtype,
                "shape": list(dkey.shape), "dtype": dkey.dtype}
    covered = manifest_key_fields()
    solver = {name: getattr(scfg, name)
              for name in sorted(covered["solver"])}
    solver["backend"] = engine_family(scfg)
    solver["experimental"] = dataclasses.asdict(scfg.experimental)
    consensus = {name: getattr(ccfg, name)
                 for name in sorted(covered["consensus"])}
    payload = {
        "data": data,
        "solver": solver,
        "consensus": consensus,
        "init": dataclasses.asdict(icfg),
        "format": _FORMAT_VERSION,
    }
    if scfg.tile_rows is not None or isinstance(a, SparseMatrix):
        from nmfx import tiles as _tiles

        payload["tile_plan"] = _tiles.plan_for(a, scfg).as_meta()
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True, default=str).encode()
    ).hexdigest()


def plan_chunks(restarts: int, chunk: "int | None") -> tuple:
    """The deterministic chunk plan: fixed boundaries ``[0,c), [c,2c),
    …`` (tail chunk smaller). ``chunk=None`` = one chunk per rank."""
    c = restarts if chunk is None else min(chunk, restarts)
    return tuple((r0, min(r0 + c, restarts))
                 for r0 in range(0, restarts, c))


# -- atomic write helper (shared with the serve spill path) ----------------
def atomic_save_npz(path: str, arrays: dict) -> None:
    """``np.savez`` through a tmp file + ``os.replace`` so a crash
    mid-write never leaves a torn record a resume would trust. Passes
    the ``ckpt.write`` chaos site: an armed write fault raises before
    any bytes land (callers degrade warn-once — durability lost for
    that record, results unaffected)."""
    from nmfx import faults

    faults.inject("ckpt.write")
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:  # handle: savez won't append ".npz"
            np.savez(fh, **arrays)
        os.replace(tmp, path)
    except BaseException:
        # a failed write (disk full — the ckpt.write rehearsal) must
        # not strand its partial tmp file on an already-full disk
        try:
            os.unlink(tmp)
        except OSError:  # nmfx: ignore[NMFX006] -- tmp never created /
            pass         # already gone; the original error re-raises
        raise


@guarded_by("_pending_lock", "_pending")
class SweepCheckpoint:
    """Directory of per-(rank, restart-chunk) completion records behind
    one content-addressed manifest — the durable sweep ledger."""

    def __init__(self, directory: str, fingerprint: str, env: dict,
                 plan: tuple, restarts: int, shape: tuple,
                 every_s: "float | None" = None, resume: bool = True):
        from nmfx.faults import warn_once

        self.directory = directory
        self.fingerprint = fingerprint
        self.plan = tuple(plan)
        self.restarts = restarts
        self.shape = tuple(shape)
        self.every_s = every_s
        os.makedirs(directory, exist_ok=True)
        self._pending: "list[tuple[int, int, int, object]]" = []
        self._pending_lock = threading.Lock()
        self._last_flush = time.monotonic()
        #: this open EXTENDED an existing compatible ledger (same data/
        #: config/env fingerprint, different restart budget or chunk
        #: plan) — records kept, only missing plan chunks will solve
        self.extended = False
        meta = {"fingerprint": fingerprint, "env": env,
                "plan": [list(c) for c in self.plan],
                "restarts": restarts, "format": _FORMAT_VERSION}
        old = self._read_manifest()
        if old is None and os.path.exists(
                os.path.join(directory, "registry.json")):
            # a LEGACY SweepRegistry directory (nmfx/registry.py): its
            # per-rank k<k>.npz records are a different format this
            # ledger cannot resume from — say so instead of silently
            # recomputing next to them
            warn_once(
                "ckpt-legacy-registry",
                f"{directory!r} holds a legacy per-rank SweepRegistry; "
                "the durable ledger cannot resume from its records "
                "(they are left untouched). Use "
                "nmfconsensus(checkpoint_dir=...) to resume the legacy "
                "registry, or point the checkpoint at a fresh directory")
        fresh = old is None
        if not resume and not fresh:
            warn_once("ckpt-no-resume",
                      f"checkpoint ledger at {directory!r} cleared on "
                      "request (resume=False); recomputing from scratch")
            self._clear_records()
            fresh = True
        elif not fresh and old != meta:
            same_run = all(old.get(f) == meta[f]
                           for f in ("fingerprint", "env", "format"))
            if same_run:
                # same data/config/environment, different restart
                # budget or chunk plan: INCREMENTAL EXTENSION (ISSUE
                # 16). The records stay — chunk [r0, r1) solves under
                # keys split(fold_in(key(seed), k), R)[r0:r1], which
                # counter-mode threefry makes independent of the budget
                # R — and try_load serves exactly the records whose
                # boundaries appear in the NEW plan, so only the delta
                # chunks solve and the result is bit-identical to a
                # from-scratch run at the extended budget. Records at
                # stale boundaries are left on disk (content-addressed
                # by (k, r0, r1) + fingerprint; a later plan that
                # matches them reuses them again).
                self.extended = True
                _flight.record("ckpt.extend", directory=directory,
                               old_restarts=old.get("restarts"),
                               new_restarts=restarts)
            else:
                # the one rule: NEVER a wrong resume. A manifest
                # written for different data/config/env (or by a
                # different format) means the records describe a
                # different run — cold start.
                warn_once(
                    "ckpt-manifest-mismatch",
                    f"checkpoint ledger at {directory!r} was written "
                    "for a different (data, config, environment) "
                    "combination — starting a CLEAN COLD START "
                    "(existing records cleared and recomputed), never "
                    "a wrong resume")
                self._clear_records()
                fresh = True
        if fresh or self.extended:
            tmp = os.path.join(directory, _MANIFEST_NAME + ".tmp")
            with open(tmp, "wt") as f:
                json.dump(meta, f)
            os.replace(tmp, os.path.join(directory, _MANIFEST_NAME))

    def _read_manifest(self) -> "dict | None":
        path = os.path.join(self.directory, _MANIFEST_NAME)
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, OSError) as e:
            # nmfx: ignore[NMFX006] -- warn_once + cold start below
            from nmfx.faults import warn_once

            warn_once("ckpt-manifest-corrupt",
                      f"checkpoint manifest at {path!r} is unreadable "
                      f"({e}); treating the ledger as foreign and cold-"
                      "starting")
            return None

    @classmethod
    def open(cls, a, ccfg: ConsensusConfig, scfg: SolverConfig,
             icfg: InitConfig,
             cp_cfg: CheckpointConfig) -> "SweepCheckpoint":
        from nmfx.sparse import SparseMatrix

        arr = a if isinstance(a, SparseMatrix) else np.asarray(a)
        return cls(cp_cfg.directory,
                   _fingerprint(arr, ccfg, scfg, icfg), _env_info(),
                   plan_chunks(ccfg.restarts, cp_cfg.every_n_restarts),
                   ccfg.restarts, arr.shape,
                   every_s=cp_cfg.every_s, resume=cp_cfg.resume)

    # -- records -----------------------------------------------------------
    def _path(self, k: int, r0: int, r1: int) -> str:
        return os.path.join(self.directory, f"k{k}_r{r0}-{r1}.npz")

    def has(self, k: int, r0: int, r1: int) -> bool:
        return os.path.exists(self._path(k, r0, r1))

    def completed_chunks(self, k: int) -> "list[tuple[int, int]]":
        return [(r0, r1) for r0, r1 in self.plan if self.has(k, r0, r1)]

    def record_count(self) -> int:
        return sum(1 for name in os.listdir(self.directory)
                   if _RECORD_RE.match(name))

    def _clear_records(self) -> None:
        # delete ONLY this ledger's own files (completion records +
        # shard heartbeats) — never foreign files a user parked in the
        # directory (saved results, serve spill records, the legacy
        # SweepRegistry's k<k>.npz)
        for name in os.listdir(self.directory):
            if (_RECORD_RE.match(name) is None
                    and _PART_RE.match(name) is None
                    and _SHARD_RE.match(name) is None):
                continue
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:  # nmfx: ignore[NMFX006] -- best-effort clear;
                pass         # a survivor fails the record validation below

    def save(self, k: int, r0: int, r1: int, rec) -> None:
        """Commit one chunk's :class:`ChunkSweepOutput` (host arrays).
        With ``every_s`` the record is buffered and lands on the next
        time-triggered/explicit/signal :meth:`flush`; otherwise it is
        written immediately (maximum durability). A write failure —
        injected (``ckpt.write``) or real (disk full) — degrades
        warn-once: the run continues, only that record's durability is
        lost."""
        if self.every_s is None:
            self._write(k, r0, r1, rec)
            return
        with self._pending_lock:
            self._pending.append((k, r0, r1, rec))
            due = time.monotonic() - self._last_flush >= self.every_s
        if due:
            self.flush()

    def flush(self) -> None:
        """Write every buffered record now — the SIGTERM/SIGINT flush
        hook's body (:func:`install_signal_flush`), also called at rank
        boundaries and at the end of the sweep. Async-signal-tolerant:
        pops under the lock, writes outside it."""
        while True:
            with self._pending_lock:
                if not self._pending:
                    self._last_flush = time.monotonic()
                    return
                k, r0, r1, rec = self._pending.pop(0)
            self._write(k, r0, r1, rec)

    def _write(self, k: int, r0: int, r1: int, rec) -> None:
        from nmfx.faults import warn_once

        arrays = {name: np.asarray(v)
                  for name, v in zip(rec._fields, rec)}
        arrays["record_fingerprint"] = np.asarray(self.fingerprint)
        try:
            with _trace.default_tracer().span(
                    "ckpt.commit", cat="ckpt",
                    args={"k": k, "r0": r0, "r1": r1}):
                atomic_save_npz(self._path(k, r0, r1), arrays)
            _flight.record("ckpt.commit", k=k, r0=r0, r1=r1)
        except Exception as e:
            warn_once(
                "ckpt-write-failed",
                f"failed to persist checkpoint record k={k} "
                f"r=[{r0},{r1}) ({e!r}); the sweep continues — only "
                "this chunk's durability is lost (it will recompute on "
                "resume)")

    def try_load(self, k: int, r0: int, r1: int):
        """Load one chunk's record as a host ``ChunkSweepOutput``, or
        None for missing/torn/corrupt/foreign records (warn-once +
        re-run that chunk — self-healing, never a crash). Passes the
        ``ckpt.load`` chaos site so the torn-record tolerance is
        rehearsable."""
        from nmfx import faults
        from nmfx.faults import warn_once
        from nmfx.sweep import ChunkSweepOutput

        path = self._path(k, r0, r1)
        if not os.path.exists(path):
            return None
        c = r1 - r0
        m, n = self.shape
        try:
            faults.inject("ckpt.load")
            with np.load(path, allow_pickle=False) as z:
                if str(z["record_fingerprint"]) != self.fingerprint:
                    raise ValueError("record fingerprint does not match "
                                     "the manifest")
                rec = ChunkSweepOutput(**{f: z[f]
                                          for f in ChunkSweepOutput._fields})
            expect = {"labels": (c, n), "iterations": (c,),
                      "dnorms": (c,), "stop_reasons": (c,),
                      "best_local": (), "best_w": (m, k),
                      "best_h": (k, n)}
            for name, shape in expect.items():
                got = getattr(rec, name).shape
                if got != shape:
                    raise ValueError(f"field {name} has shape {got}, "
                                     f"expected {shape}")
            if not 0 <= int(rec.best_local) < c:
                raise ValueError("best_local out of chunk range")
        except Exception as e:
            warn_once(
                "ckpt-record-corrupt",
                f"checkpoint record {path!r} is torn/corrupt/foreign "
                f"({e!r}); skipping it and re-running that chunk — "
                "results are unaffected, only that chunk's resume win "
                "is lost")
            return None
        _note(loaded=1)
        return rec

    # -- mid-chunk partials (out-of-core solves, ISSUE 17) -----------------
    def _partial_path(self, k: int, r0: int, r1: int) -> str:
        return os.path.join(self.directory, f"k{k}_r{r0}-{r1}.part.npz")

    def save_partial(self, k: int, r0: int, r1: int, payload) -> None:
        """Persist a tiled solver's mid-chunk state snapshot
        (``nmfx.tiles.partial_payload``) at a check boundary. Atomic +
        fingerprint-stamped like completion records; write failures
        degrade warn-once (only the mid-matrix resume win is lost)."""
        from nmfx.faults import warn_once

        arrays = dict(payload)
        arrays["record_fingerprint"] = np.asarray(self.fingerprint)
        try:
            with _trace.default_tracer().span(
                    "ckpt.partial", cat="ckpt",
                    args={"k": k, "r0": r0, "r1": r1}):
                atomic_save_npz(self._partial_path(k, r0, r1), arrays)
        except Exception as e:
            warn_once(
                "ckpt-partial-write-failed",
                f"failed to persist partial checkpoint k={k} "
                f"r=[{r0},{r1}) ({e!r}); the solve continues — a "
                "preemption before the next partial restarts this chunk "
                "from its last durable snapshot")

    def try_load_partial(self, k: int, r0: int, r1: int):
        """Load a mid-chunk partial as the ``resume=`` payload dict for
        ``nmfx.tiles.run_tiled_pool``, or None for missing/torn/foreign
        partials (warn-once + restart the chunk from iteration zero —
        self-healing, never a crash)."""
        from nmfx import faults
        from nmfx.faults import warn_once

        path = self._partial_path(k, r0, r1)
        if not os.path.exists(path):
            return None
        try:
            faults.inject("ckpt.load")
            with np.load(path, allow_pickle=False) as z:
                if str(z["record_fingerprint"]) != self.fingerprint:
                    raise ValueError("partial fingerprint does not match "
                                     "the manifest")
                payload = {name: z[name] for name in z.files
                           if name != "record_fingerprint"}
        except Exception as e:
            warn_once(
                "ckpt-partial-corrupt",
                f"partial checkpoint {path!r} is torn/corrupt/foreign "
                f"({e!r}); discarding it and re-running the chunk from "
                "iteration zero — results are unaffected")
            return None
        return payload

    def clear_partial(self, k: int, r0: int, r1: int) -> None:
        """Drop a chunk's partial once its completion record committed
        (or it was found stale) — partials are scaffolding, never
        results."""
        try:
            os.unlink(self._partial_path(k, r0, r1))
        except OSError:  # nmfx: ignore[NMFX006] -- already absent is fine
            pass

    # -- shard heartbeat/completion ledger (elastic recovery) --------------
    @property
    def heartbeat_ledger(self):
        """The shared :class:`nmfx.obs.export.HeartbeatLedger` this
        sweep's shard heartbeats write through (``shard_<i>.json`` in
        the checkpoint directory) — the write/read discipline factored
        out in ISSUE 15 so the elastic runner and the replica pool
        behind ``NMFXRouter`` share one liveness idiom."""
        if getattr(self, "_hb_ledger", None) is None:
            from nmfx.obs.export import HeartbeatLedger

            self._hb_ledger = HeartbeatLedger(self.directory,
                                              prefix="shard_")
        return self._hb_ledger

    def heartbeat(self, shard: int, **info) -> None:
        """Record shard liveness/progress (``shard_<i>.json``, atomic,
        best-effort — the shared ledger's contract). The elastic runner
        (``nmfx/distributed.py``) writes one per completed unit and a
        final ``alive=False`` on shard death; cross-process deployments
        read :meth:`shard_status` to detect shards whose heartbeat went
        stale and re-dispatch their incomplete chunks (completion
        records are the ground truth — a re-dispatched chunk that WAS
        committed is simply skipped). The payload always carries the
        writing process's pid (plus any caller fields — the elastic
        runner adds its cross-process ``trace_id``), so a fleet view
        over N sharding processes can attribute each shard heartbeat to
        its process and join it with that process's telemetry snapshots
        and trace exports (docs/observability.md "Fleet telemetry")."""
        self.heartbeat_ledger.beat(str(shard), shard=shard, **info)

    def shard_status(self, stale_after_s: "float | None" = None) -> dict:
        """``{shard: heartbeat_payload}``; with ``stale_after_s`` each
        payload gains ``stale=True/False`` (and ``age_s``) from its
        last-write age — :meth:`HeartbeatLedger.status`, keyed back by
        the numeric shard id."""
        status = self.heartbeat_ledger.status(stale_after_s)
        return {payload.get("shard"): payload
                for payload in status.values()}


# -- chunk execution -------------------------------------------------------
def solve_chunk_host(a_dev, k: int, r0: int, r1: int,
                     ccfg: ConsensusConfig, scfg: SolverConfig,
                     icfg: InitConfig, keys=None, ck=None, mesh=None):
    """Solve restarts ``[r0, r1)`` of rank ``k`` and materialize the
    chunk's record on host. ``keys`` is the rank's full canonical key
    array (``split(fold_in(key(seed), k), restarts)``) — recomputed here
    when absent — so a chunk's draws are independent of which process,
    shard, or attempt runs it (the same-key-chains-same-results
    property elastic recovery rests on).

    Out-of-core chunks (``scfg.tile_rows`` set, or a
    :class:`~nmfx.sparse.SparseMatrix` ``a_dev``) route through the
    streaming tiled engine instead of the in-core vmapped driver; with
    a ``ck`` ledger they additionally persist mid-chunk partials at
    check boundaries (and pass the ``proc.preempt`` site AT those
    boundaries — after the partial saved — so the rehearsed kill lands
    MID-MATRIX and resume restarts from the snapshot, not iteration 0).

    Passes the ``proc.preempt`` chaos site AFTER the solve completes
    but BEFORE the caller can commit the record: a fired preemption
    raises :class:`Preempted`, losing exactly the in-flight chunk —
    the rehearsal of SIGKILL mid-chunk.

    ``mesh``: a restart-only sub-mesh to shard the chunk's lanes over
    (``ElasticShardRunner`` meshed mode — a shard owning a device SET;
    ISSUE 19). Per-lane math is unchanged, so the record stays
    bit-identical to the unmeshed executor's; refused for the tiled/
    sparse streaming paths, whose engines are single-device."""
    import jax

    from nmfx import faults
    from nmfx.sparse import SparseMatrix
    from nmfx.sweep import _build_chunk_sweep_fn

    if scfg.backend == "sketched" or scfg.screen:
        # the common funnel of BOTH the checkpointed sweep and the
        # elastic shard runner — guarded here so no durable path can
        # silently execute the exact vmapped driver for a config that
        # asked for the statistical/whole-pool engines (see
        # run_checkpointed_sweep's matching guard for the rationale)
        raise ValueError(
            "durable chunk execution does not support "
            "backend='sketched' or screen=True (bit-identical replay "
            "vs statistical/whole-pool contracts); use an exact "
            "unscreened engine")
    if keys is None:
        keys = jax.random.split(
            jax.random.fold_in(jax.random.key(ccfg.seed), k),
            ccfg.restarts)
    poison = tuple(r - r0 for r in faults.poison_restarts(k, ccfg.restarts)
                   if r0 <= r < r1)
    if mesh is not None and (scfg.tile_rows is not None
                             or isinstance(a_dev, SparseMatrix)):
        raise ValueError(
            "meshed chunk execution does not compose with the tiled/"
            "sparse streaming engines (single-device tile pipelines); "
            "drop the mesh or the tile/sparse input")
    if scfg.tile_rows is not None or isinstance(a_dev, SparseMatrix):
        from nmfx import tiles

        resume = ck.try_load_partial(k, r0, r1) if ck is not None else None
        on_check = None
        if ck is not None:
            def on_check(step, state, carry):
                ck.save_partial(k, r0, r1,
                                tiles.partial_payload(state, carry, step))
                # fire AFTER the partial landed: the rehearsed preempt
                # kills mid-matrix with the snapshot durable, so resume
                # continues from this very check boundary
                if faults.fire("proc.preempt"):
                    raise Preempted(
                        f"injected preemption mid-matrix at step {step} "
                        f"of chunk k={k} r=[{r0},{r1}) — the partial "
                        "snapshot just saved survives for resume")
        host = jax.device_get(tiles.solve_chunk_tiled(
            a_dev, keys[r0:r1], k, scfg, icfg, ccfg.label_rule,
            poison=poison, resume=resume, on_check=on_check))
        _note(solved=1)
        if faults.fire("proc.preempt"):
            raise Preempted(
                f"injected preemption after solving chunk k={k} "
                f"r=[{r0},{r1}) and before its commit — this chunk is "
                "lost; every committed record survives for resume")
        return host
    fn = _build_chunk_sweep_fn(k, r1 - r0, scfg, icfg, ccfg.label_rule,
                               poison, faults.trace_token(), mesh=mesh)
    host = jax.device_get(fn(a_dev, keys[r0:r1]))
    _note(solved=1)
    if faults.fire("proc.preempt"):
        raise Preempted(
            f"injected preemption after solving chunk k={k} "
            f"r=[{r0},{r1}) and before its commit — this chunk is "
            "lost; every committed record survives for resume")
    return host


def _finalize_rank(k: int, recs: dict, ccfg: ConsensusConfig,
                   shape: tuple):
    """Rebuild rank ``k``'s host ``KSweepOutput`` from its chunk
    records, in canonical restart order. Exact by construction: the
    connectivity accumulates as int64 counts (associative — completion
    order can never matter), the survivor division happens once in
    float64, and best-restart selection replays the global first-min
    ``argmin`` over the assembled dnorm array."""
    from nmfx.solvers.base import StopReason
    from nmfx.sweep import KSweepOutput

    restarts = ccfg.restarts
    m, n = shape
    first = next(iter(recs.values()))
    labels = np.empty((restarts, n), np.int32)
    iters = np.empty((restarts,), np.asarray(first.iterations).dtype)
    dnorms = np.empty((restarts,), np.asarray(first.dnorms).dtype)
    stops = np.empty((restarts,), np.asarray(first.stop_reasons).dtype)
    for (r0, r1), rec in sorted(recs.items()):
        labels[r0:r1] = rec.labels
        iters[r0:r1] = rec.iterations
        dnorms[r0:r1] = rec.dnorms
        stops[r0:r1] = rec.stop_reasons
    faulted = stops == int(StopReason.NUMERIC_FAULT)
    # integer one-hot connectivity reduction: quarantined lanes drop out
    # (zero contribution, like pads), every surviving label is in
    # [0, k), and int64 addition is associative — exact and identical
    # to a restart-by-restart accumulation, at one einsum instead of
    # `restarts` sequential n×n passes
    surv = labels[~faulted]  # (R_surv, n)
    onehot = (surv[:, :, None] == np.arange(k)[None, None, :]) \
        .astype(np.int64)
    counts = np.einsum("rik,rjk->ij", onehot, onehot)
    n_fault = int(faulted.sum())
    div = max(restarts - n_fault, 1) if n_fault else restarts
    cons = counts / np.float64(div)
    dnorm_best = np.where(faulted, np.inf, dnorms.astype(np.float64))
    best = int(np.argmin(dnorm_best))
    best_rec = next(rec for (r0, r1), rec in sorted(recs.items())
                    if r0 <= best < r1)
    r0_best = next(r0 for (r0, r1) in recs if r0 <= best < r1)
    if int(best_rec.best_local) + r0_best != best and n_fault < restarts:
        # a record that passed validation but nominates a different lane
        # than the global replay can only be foreign/corrupt data
        raise ValueError(
            f"checkpoint records for k={k} are inconsistent: chunk "
            f"[{r0_best},…) nominates restart "
            f"{int(best_rec.best_local) + r0_best} as its best but the "
            f"global replay selects {best}; the ledger is corrupt — "
            "delete the directory and re-run")
    return KSweepOutput(
        consensus=cons, iterations=iters, dnorms=dnorms,
        stop_reasons=stops, labels=labels,
        best_w=np.asarray(best_rec.best_w),
        best_h=np.asarray(best_rec.best_h), all_w=None, all_h=None)


def run_checkpointed_sweep(a, cfg: ConsensusConfig,
                           solver_cfg: SolverConfig,
                           init_cfg: InitConfig,
                           cp_cfg: CheckpointConfig,
                           profiler=None, on_rank=None) -> dict:
    """The durable sweep engine: execute the (k x restart) grid through
    the per-(k, chunk) ledger, re-running ONLY chunks without a valid
    completion record, and finalize each rank exactly from the records
    (see module docstring). Returns ``{k: KSweepOutput}`` of host
    arrays — both harvest modes consume it unchanged."""
    import jax

    from nmfx.data_cache import place_resilient

    if profiler is None:
        from nmfx.profiling import NullProfiler

        profiler = NullProfiler()
    if cfg.keep_factors:
        raise ValueError(
            "keep_factors is not supported on checkpointed sweeps (the "
            "ledger persists per-restart stats and best candidates, not "
            "every factor stack); recompute any restart exactly with "
            "nmfx.restart_factors")
    if solver_cfg.backend == "sketched" or solver_cfg.screen:
        # the ledger's resume contract is BIT-IDENTICAL replay of plan
        # chunks; the sketched engine's contract is statistical and the
        # screening pass ranks across the WHOLE restart pool (a chunk
        # cannot know its lanes' survivor status) — neither has a valid
        # chunk form, and the chunk executor would otherwise silently
        # run the exact vmapped driver instead
        raise ValueError(
            "checkpointed sweeps do not support backend='sketched' or "
            "screen=True (the durable ledger replays per-(k, chunk) "
            "records bit-identically; the sketched/screened paths are "
            "whole-pool and statistical) — drop the checkpoint or use "
            "an exact unscreened engine")
    from nmfx.sparse import SparseMatrix

    tiled = solver_cfg.tile_rows is not None or isinstance(a, SparseMatrix)
    arr = a if isinstance(a, SparseMatrix) else np.asarray(a)
    ck = SweepCheckpoint.open(arr, cfg, solver_cfg, init_cfg, cp_cfg)
    restore = install_signal_flush(ck)
    a_dev = None
    out: dict = {}
    loaded_total = 0
    solved_total = 0
    try:
        for k in cfg.ks:
            recs: dict = {}
            missing = []
            for r0, r1 in ck.plan:
                with profiler.phase("ckpt.load"):
                    rec = ck.try_load(k, r0, r1)
                if rec is None:
                    missing.append((r0, r1))
                else:
                    recs[(r0, r1)] = rec
                    loaded_total += 1
            if missing:
                solved_total += len(missing)
                if a_dev is None:  # fully-resumed sweeps never transfer
                    # out-of-core chunks stream A from the HOST source
                    # tile-by-tile (nmfx.tiles) — pinning the whole
                    # matrix device-resident is exactly what tile_rows
                    # exists to avoid
                    a_dev = arr if tiled else place_resilient(
                        arr, solver_cfg, None, profiler=profiler)
                keys = jax.random.split(
                    jax.random.fold_in(jax.random.key(cfg.seed), k),
                    cfg.restarts)
                for r0, r1 in missing:
                    with profiler.phase(f"solve.ckpt.k={k}"):
                        try:
                            rec = solve_chunk_host(a_dev, k, r0, r1, cfg,
                                                   solver_cfg, init_cfg,
                                                   keys=keys, ck=ck)
                        except Preempted:
                            ck.flush()  # the SIGTERM-grace analogue:
                            raise       # committed work must survive
                    with profiler.phase("checkpoint"):
                        ck.save(k, r0, r1, rec)
                        ck.clear_partial(k, r0, r1)
                    recs[(r0, r1)] = rec
            with profiler.phase("ckpt.finalize"):
                out[k] = _finalize_rank(k, recs, cfg, arr.shape)
            ck.flush()  # rank boundary: buffered records land
            if on_rank is not None:
                on_rank(k, out[k])
        if loaded_total > 0 and (ck.extended or solved_total > 0):
            # an incremental run that actually REUSED records while
            # producing new work — a widened restart budget (manifest
            # rewritten, ck.extended) or a widened ks / partial resume
            # (ks is manifest-exempt by design, so the manifest matches
            # exactly; records loaded AND delta chunks solved). The
            # request-economics signal nmfx-top/bench read. A fully-
            # loaded warm re-run is a pure replay, not an extension;
            # a widened budget that found nothing to reuse is a solve.
            _extended_total.inc()
            _flight.record("result_cache.extend", directory=ck.directory,
                           loaded=loaded_total, restarts=cfg.restarts,
                           ks=list(cfg.ks))
        return {k: out[k] for k in cfg.ks}
    finally:
        ck.flush()
        restore()


def install_signal_flush(ck: SweepCheckpoint):
    """Hook SIGTERM/SIGINT so a preemption notice flushes the ledger's
    buffered (``every_s``) records before the process dies, then defers
    to the previous disposition (a previously-installed handler runs;
    the default disposition re-raises as ``KeyboardInterrupt`` /
    ``SystemExit(128+sig)``; an ignored signal stays ignored). Returns
    a zero-argument restore callable; a no-op off the main thread
    (signal handlers are main-thread-only — the serve/harvest worker
    threads rely on their own drain paths)."""
    installed: dict = {}

    def _handler(signum, frame):
        ck.flush()
        prev = installed.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev is signal.SIG_IGN:
            return
        elif signum == signal.SIGINT:
            raise KeyboardInterrupt
        else:
            raise SystemExit(128 + signum)

    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            installed[sig] = signal.signal(sig, _handler)
    except ValueError:
        # not the main interpreter thread: signal.signal fails on the
        # FIRST call, so nothing was installed and there is nothing to
        # restore — the caller simply runs without the flush hook
        return lambda: None

    def restore():
        for sig, prev in installed.items():
            signal.signal(sig, prev)

    return restore
