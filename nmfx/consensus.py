"""Connectivity / consensus matrices, on-device.

TPU-native re-design of reference ``computeConsensusMatrixFromClusterings``
(``nmf.r:121-144``): per-restart cluster labels from H, pairwise
same-cluster connectivity, averaged over restarts. The reference builds each
restart's n×n connectivity with ``outer(l, l, ==)`` and Reduce('+')s them on
the host; here the whole reduction is one one-hot einsum on the MXU and the
restart axis never leaves the device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def labels_from_h(h: jax.Array, rule: str = "argmax") -> jax.Array:
    """Per-sample cluster label from H (k×n).

    ``argmax`` = intended BROAD semantics (dominant metagene);
    ``argmin`` = the reference R layer's observed behavior
    (``apply(H, 2, order)[1,]`` takes the SMALLEST loading, nmf.r:128 —
    quirk Q3 in SURVEY.md §3.2).
    """
    if rule == "argmax":
        return jnp.argmax(h, axis=0).astype(jnp.int32)
    if rule == "argmin":
        return jnp.argmin(h, axis=0).astype(jnp.int32)
    raise ValueError(f"rule must be 'argmax' or 'argmin', got {rule!r}")


def connectivity(labels: jax.Array) -> jax.Array:
    """0/1 connectivity matrix of one labelling (n,) -> (n, n)."""
    return (labels[:, None] == labels[None, :]).astype(jnp.float32)


def consensus_matrix(labels: jax.Array, k: int) -> jax.Array:
    """Mean connectivity across restarts: (R, n) int labels -> (n, n).

    One-hot einsum form: C = (1/R) Σ_r E_r E_rᵀ with E_r the n×k one-hot
    label matrix — a batched matmul XLA maps straight onto the MXU, replacing
    the reference's host-side outer-product loop (nmf.r:140-143).
    """
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32)  # (R, n, k)
    r = labels.shape[0]
    return jnp.einsum("rik,rjk->ij", onehot, onehot) / r
