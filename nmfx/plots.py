"""Plot outputs: consensus heatmaps, all-k grid, cophenetic curve.

Covers the reference's plotting side layer (``matrix.abs.plot``,
``ConsPlot``, ``metagene.plot``, cophenetic curve; reference
``nmf.r:271-349`` and ``nmf.r:191-249``) with matplotlib instead of base-R
graphics. Import is deferred/gated so headless or matplotlib-free
environments still get all numerical outputs.
"""

from __future__ import annotations

import numpy as np

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt


def consensus_heatmap(mat: np.ndarray, path: str, title: str = "",
                      membership: np.ndarray | None = None) -> None:
    """Ordered consensus-matrix heatmap with optional class-boundary tags
    (reference ConsPlot's tag strip, nmf.r:314-336)."""
    fig, ax = plt.subplots(figsize=(6, 6))
    im = ax.imshow(mat, cmap="viridis", vmin=0.0, vmax=1.0,
                   interpolation="nearest")
    if membership is not None:
        bounds = np.flatnonzero(np.diff(membership)) + 0.5
        for b in bounds:
            ax.axhline(b, color="white", lw=0.8)
            ax.axvline(b, color="white", lw=0.8)
    ax.set_title(title)
    ax.set_xlabel("samples")
    ax.set_ylabel("samples")
    fig.colorbar(im, ax=ax, shrink=0.8)
    fig.savefig(path, bbox_inches="tight")
    plt.close(fig)


def metagene_plot(h: np.ndarray, path: str, title: str = "") -> None:
    """Per-metagene amplitude lines (reference metagene.plot, nmf.r:294-304)."""
    fig, ax = plt.subplots(figsize=(8, 4))
    for i, row in enumerate(np.asarray(h)):
        ax.plot(row, lw=2, label=f"metagene {i + 1}")
    ax.set_xlabel("samples")
    ax.set_ylabel("amplitude")
    ax.set_title(title)
    ax.legend(fontsize=8)
    fig.savefig(path, bbox_inches="tight")
    plt.close(fig)


def matrix_plot(mat: np.ndarray, path: str, title: str = "") -> None:
    """Generic matrix-magnitude heatmap (reference ``matrix.abs.plot``'s
    value-inverted rainbow, nmf.r:271-292 — here |values| on a perceptually
    uniform map)."""
    fig, ax = plt.subplots(figsize=(6, 6))
    im = ax.imshow(np.abs(np.asarray(mat)), cmap="viridis", aspect="auto",
                   interpolation="nearest")
    ax.set_title(title)
    fig.colorbar(im, ax=ax, shrink=0.8)
    fig.savefig(path, bbox_inches="tight")
    plt.close(fig)


def pca_plot(a: np.ndarray, path: str,
             labels: np.ndarray | None = None, title: str = "") -> None:
    """Samples scattered on the first two principal components, optionally
    colored by cluster label (reference ``plotPCA``, test_nmf.r:9-23 —
    defined for eyeballing group structure, never wired into the flow)."""
    a = np.asarray(a, np.float64)
    centered = a - a.mean(axis=1, keepdims=True)
    _, _, vt = np.linalg.svd(centered, full_matrices=False)
    pcs = vt[:2].T  # (n_samples, 2)
    fig, ax = plt.subplots(figsize=(6, 5))
    if labels is None:
        ax.scatter(pcs[:, 0], pcs[:, 1], s=30)
    else:
        sc = ax.scatter(pcs[:, 0], pcs[:, 1], c=np.asarray(labels),
                        cmap="tab10", s=30)
        fig.colorbar(sc, ax=ax, shrink=0.8, label="cluster")
    ax.set_xlabel("PC1")
    ax.set_ylabel("PC2")
    ax.set_title(title)
    fig.savefig(path, bbox_inches="tight")
    plt.close(fig)


def cophenetic_curve(ks, rhos, path: str) -> None:
    """rho-vs-k selection curve (reference nmf.r:227-231; same y-range rule
    ``[1 - 2*(1 - min(rho)), 1]``)."""
    ks = np.asarray(ks)
    rhos = np.asarray(rhos)
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.plot(ks, rhos, "-s", color="black", markersize=7)
    lo = 1 - 2 * (1 - rhos.min())
    ax.set_ylim(min(lo, rhos.min() - 0.01), 1.0)
    ax.set_xlabel("k")
    ax.set_ylabel("Cophenetic correlation")
    ax.set_title("Cophenetic Coefficient")
    fig.savefig(path, bbox_inches="tight")
    plt.close(fig)


def all_k_grid(result, path: str) -> None:
    """Grid of ordered consensus matrices over all k (reference 4×4 summary
    page, nmf.r:217-232)."""
    ks = result.ks
    cols = min(4, len(ks))
    rows = -(-len(ks) // cols)
    fig, axes = plt.subplots(rows, cols, figsize=(3 * cols, 3 * rows),
                             squeeze=False)
    for ax in axes.flat:
        ax.axis("off")
    for ax, k in zip(axes.flat, ks):
        r = result.per_k[k]
        ax.axis("on")
        ax.imshow(r.ordered_consensus, cmap="viridis", vmin=0, vmax=1,
                  interpolation="nearest")
        ax.set_title(f"k={k}  rho={r.rho:.4f}", fontsize=9)
        ax.set_xticks([])
        ax.set_yticks([])
    fig.savefig(path, bbox_inches="tight")
    plt.close(fig)


def save_all(result, prefix: str) -> list[str]:
    """Write the full plot set for a ConsensusResult."""
    written = []
    for k in result.ks:
        r = result.per_k[k]
        path = f"{prefix}consensus.plot.k{k}.pdf"
        consensus_heatmap(r.ordered_consensus, path,
                          title=f"Consensus matrix k={k}",
                          membership=r.membership[r.order])
        written.append(path)
        # metagene amplitudes of the best restart, samples in dendrogram
        # order (the reference sketches this at nmf.r:200-204, commented out)
        path = f"{prefix}metagenes.k{k}.pdf"
        metagene_plot(r.best_h[:, r.order], path,
                      title=f"Metagenes (best restart), k={k}")
        written.append(path)
    path = f"{prefix}consensus.all.k.plot.pdf"
    all_k_grid(result, path)
    written.append(path)
    path = f"{prefix}cophenetic.plot.pdf"
    cophenetic_curve(result.ks, result.rhos, path)
    written.append(path)
    return written
