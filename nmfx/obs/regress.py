"""Bench-trajectory regression observatory (ISSUE 13, ``nmfx-perf``).

The repo records one ``BENCH_r<NN>.json`` per hardware round, but until
now the only cross-round signal was the headline ``vs_best`` scalar —
the r03→r04 warm-wall drift (1.384 s → 2.041 s) sat in plain sight for
two rounds because nothing compared the rest of the record. This module
is the noise-aware trajectory judge:

* **Load + normalize** every ``BENCH_r*.json`` in a directory —
  accepting both the driver's wrapper form (``{"parsed": record}``)
  and bare records — and extract a curated metric set through
  schema-drift-tolerant paths (r01 had only ``value``/
  ``restarts_per_s``; ``mfu_solve`` appears in r04; per-backend reps
  in r05; the serving/chaos/durability/obs stages have never produced
  hardware numbers and will first appear in r06, where they self-judge
  as ``new`` rather than crash the comparison).
* **Noise-aware comparison**: every wall metric is already the
  min-of-same-session-reps (the bench's recorded protocol — the only
  statistic comparable across this environment's ±50% session swings),
  and each metric carries a RELATIVE regression threshold sized to its
  observed noise (wall metrics 25–35%, utilization metrics 15%);
  ``--threshold-scale`` widens or tightens the whole set.
* **Verdict + trend report**: :func:`compare` returns a
  machine-readable verdict (regressions vs the best prior round, with
  margins and which round set the bar) and :func:`markdown_report`
  renders the full metric×round trend table. The ``nmfx-perf``
  entrypoint prints both; ``bench.py --regress`` runs the same
  comparison on the record it just produced and exits 2 on regression
  — the gate that makes the eventual hardware r06 run self-judging.

Stdlib-only, like the rest of ``nmfx.obs``.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import NamedTuple

__all__ = ["METRICS", "MetricSpec", "compare", "extract_metrics",
           "load_rounds", "main", "markdown_report"]

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


class MetricSpec(NamedTuple):
    """One tracked bench metric: where it lives across schema
    generations (``paths`` tried in order — dotted keys, with
    ``list[key=value]`` selectors for the serve ladder), which
    direction is better, and the relative change vs the best prior
    round that counts as a regression."""

    name: str
    paths: tuple
    direction: str  # "lower" | "higher"
    threshold: float  # relative regression threshold
    note: str = ""


#: the tracked trajectory. Thresholds are sized to the metric's
#: observed cross-round noise under the min-of-reps protocol: warm
#: walls swing ~±20% between sessions even at their minima (r03-r05),
#: cold/compile walls more, MFU is a ratio of same-session numbers and
#: moves little. Serving-stack metrics (exec_cache/serve/durability/
#: obs) have no prior hardware rounds yet — they enter the trajectory
#: as "new" at r06 and gate from r07 on.
METRICS = (
    MetricSpec("consensus_sweep_wall_s", ("value",), "lower", 0.25,
               "headline warm wall (min of same-session reps)"),
    MetricSpec("consensus_e2e_wall_s",
               ("detail.consensus_e2e_wall_s",), "lower", 0.25,
               "warm wall incl. rank selection (r07+ protocol)"),
    MetricSpec("restarts_per_s", ("detail.restarts_per_s",), "higher",
               0.25),
    MetricSpec("cold_wall_s", ("detail.cold_wall_s",), "lower", 0.35,
               "from-nothing first-request wall (compile included)"),
    MetricSpec("compile_wall_s", ("detail.compile_wall_s",), "lower",
               0.40),
    MetricSpec("mfu", ("detail.mfu",), "higher", 0.15),
    MetricSpec("mfu_solve", ("detail.mfu_solve",), "higher", 0.15,
               "solve-phase utilization — the kernel-work steering "
               "metric"),
    MetricSpec("pallas_min_s", ("detail.backends.pallas.min_s",),
               "lower", 0.25),
    MetricSpec("pallas_mfu_solve",
               ("detail.backends.pallas.mfu_solve",), "higher", 0.15),
    # --- serving stack (first hardware numbers land at r06) ---------
    MetricSpec("exec_hit_dispatch_s",
               ("detail.exec_cache.hit_dispatch_s",), "lower", 0.35,
               "warm-bucket compile-free dispatch"),
    MetricSpec("exec_miss_compile_s",
               ("detail.exec_cache.miss_compile_s",), "lower", 0.50),
    MetricSpec("cold_persist_wall_s",
               ("detail.exec_cache.cold_persist_wall_s",), "lower",
               0.35, "fresh-process deserialize-and-dispatch wall"),
    MetricSpec("serve_p50_latency_s",
               ("detail.serve.ladder[offered_load=1.0].p50_latency_s",),
               "lower", 0.35),
    MetricSpec("serve_p99_latency_s",
               ("detail.serve.ladder[offered_load=1.0].p99_latency_s",),
               "lower", 0.50, "tail latency is the noisiest surface"),
    MetricSpec("serve_burst_goodput_req_per_s",
               ("detail.serve.ladder[offered_load=burst]"
                ".goodput_req_per_s",), "higher", 0.35),
    MetricSpec("serve_chaos_goodput_retention",
               ("detail.serve.chaos.goodput_retention",), "higher",
               0.25),
    # --- service tier (ISSUE 15: router + replica pool) -------------
    MetricSpec("fleet_router_p50_ratio",
               ("detail.serve.fleet.overhead.p50_ratio",), "lower",
               0.25,
               "router-vs-direct p50; the bench's own gate is the "
               "hard 1.05x (+50ms) bound, this tracks drift"),
    MetricSpec("fleet_goodput_3_replicas",
               ("detail.serve.fleet.scaling[replicas=3]"
                ".goodput_req_per_s",), "higher", 0.35),
    MetricSpec("fleet_chaos_goodput_req_per_s",
               ("detail.serve.fleet.chaos.goodput_req_per_s",),
               "higher", 0.35,
               "goodput with one of 3 replicas SIGKILLed mid-ladder"),
    MetricSpec("durability_resume_overhead_s",
               ("detail.durability.resume_overhead_s",), "lower", 0.50),
    MetricSpec("obs_overhead_frac", ("detail.obs.overhead_frac",),
               "lower", 1.0,
               "telemetry overhead; the bench's own gate is the hard "
               "3% bound, this only tracks drift round-over-round"),
    MetricSpec("sketched_flops_compression",
               ("detail.sketched.flops_compression_per_restart",),
               "higher", 0.20,
               "analytic, shape-derived — hardware-independent"),
    # --- request economics (ISSUE 16: cache/coalesce/extend) --------
    MetricSpec("econ_result_cache_hit_rate",
               ("detail.serve.economics.hit_rate",), "higher", 0.50,
               "mixed-arm hit fraction; the split between hits and "
               "coalesces is timing-dependent, so the threshold is "
               "loose — reuse_rate is the deterministic sum"),
    MetricSpec("econ_coalesce_rate",
               ("detail.serve.economics.coalesce_rate",), "higher",
               0.90,
               "mixed-arm coalesce fraction; see hit-rate note"),
    MetricSpec("econ_goodput_vs_cold",
               ("detail.serve.economics.goodput_vs_cold",), "higher",
               0.35,
               "warm-replay goodput over the cold-solve baseline; "
               "the bench's own gate is the hard 5x bound"),
    MetricSpec("econ_extend_speedup",
               ("detail.serve.economics.extend_speedup",), "higher",
               0.35,
               "from-scratch wall over incremental-extend wall at a "
               "2x-widened restart budget, bit-identity gated"),
    # --- mesh tier (ISSUE 19: multi-chip solves) --------------------
    # forced-CPU-device curves: host-dependent walls, loose thresholds;
    # the bench's own exit-2 gates (bit-identity, comm-vs-HLO,
    # placement correctness) are the hard contracts
    MetricSpec("mesh_strong_restarts_per_s_x4",
               ("detail.mesh.strong[shards=4].restarts_per_s",),
               "higher", 0.50,
               "fixed-total-restart throughput on a 4-shard restart "
               "mesh (pad lanes subtracted)"),
    MetricSpec("mesh_weak_restarts_per_s_x4",
               ("detail.mesh.weak[shards=4].restarts_per_s",),
               "higher", 0.50,
               "fixed-per-shard throughput on a 4-shard restart mesh"),
    MetricSpec("mesh_fleet_wall_s",
               ("detail.mesh.fleet.wall_s",), "lower", 0.50,
               "heterogeneous-fleet rung wall (2 atlas on the mesh "
               "class + 2 small on the 1-chip class)"),
    # --- atlas-scale solves (ISSUE 17: tiles + sparse ingestion) ----
    MetricSpec("atlas_tiled_restarts_per_s",
               ("detail.atlas.out_of_core.restarts_per_s",), "higher",
               0.35,
               "throughput of the larger-than-budget multi-tile rung "
               "(forced-small budget); hardware-host measurement"),
    MetricSpec("atlas_sparse_speedup_99",
               ("detail.atlas.sparse.density_99.speedup_vs_dense",),
               "higher", 0.50,
               "99%-sparse BCOO ingestion wall vs the densified twin; "
               "crossover is host-GEMM-dependent, threshold loose"),
    MetricSpec("atlas_resume_overhead_s",
               ("detail.atlas.resume.resume_overhead_s",), "lower",
               0.50,
               "mid-matrix kill/resume overhead of the tiled durable "
               "ledger; bit-identity gated by the bench itself"),
    # --- kernel schedule (ISSUE 20: fused kernels + autotune) -------
    MetricSpec("mfu_solve_pallas_fused",
               ("detail.kernel.fused_vs_phased.fused.mfu_solve",),
               "higher", 0.15,
               "solve-phase MFU of the fused join-the-updates mu "
               "kernel at the north-star shape — the ≥0.18 steering "
               "metric; its phased twin in the same record is "
               "bit-compat gated by the bench itself"),
    MetricSpec("autotune_warm_hit",
               ("detail.kernel.autotune.warm_hit",), "higher", 0.01,
               "1.0 iff the warm-process resolution came entirely "
               "from the persisted store (hits>0, searches==0 by the "
               "nmfx_autotune_* counter deltas) — a binary contract, "
               "any drop regresses"),
)


# --------------------------------------------------------------------------
# record loading / metric extraction
# --------------------------------------------------------------------------

def _resolve_path(obj, path: str):
    """Walk one dotted path; ``seg[key=value]`` selects the first
    element of a list whose ``key`` stringifies to ``value``. Returns
    None on any miss."""
    cur = obj
    # split on dots OUTSIDE bracket selectors only ("[offered_load=1.0]"
    # keeps its dot)
    for seg in re.split(r"\.(?![^\[\]]*\])", path):
        m = re.fullmatch(r"([^\[]+)\[([^=\]]+)=([^\]]+)\]", seg)
        sel = None
        if m:
            seg, sel = m.group(1), (m.group(2), m.group(3))
        if not isinstance(cur, dict) or seg not in cur:
            return None
        cur = cur[seg]
        if sel is not None:
            if not isinstance(cur, list):
                return None
            key, want = sel
            cur = next((e for e in cur
                        if isinstance(e, dict)
                        and str(e.get(key)) == want), None)
            if cur is None:
                return None
    return cur


def extract_metrics(record: dict) -> "dict[str, float]":
    """Normalize one bench record (wrapper or bare form) into the
    tracked metric set; metrics a round's schema predates are simply
    absent."""
    parsed = record.get("parsed", record)
    if not isinstance(parsed, dict):
        return {}
    out = {}
    for spec in METRICS:
        for path in spec.paths:
            val = _resolve_path(parsed, path)
            if isinstance(val, (int, float)) and not isinstance(val,
                                                                bool):
                out[spec.name] = float(val)
                break
    return out


def load_rounds(directory: str) -> "list[dict]":
    """Every readable ``BENCH_r*.json`` in ``directory`` as
    ``{"round", "file", "metrics"}``, sorted by round number;
    unreadable or non-record files are skipped (the ``_best_prior_
    record`` discipline — a corrupt round must not kill the judge)."""
    rounds = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return []
    for name in names:
        m = _ROUND_RE.fullmatch(name)
        if not m:
            continue
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict):
            continue
        metrics = extract_metrics(rec)
        if metrics:
            rounds.append({"round": int(m.group(1)), "file": name,
                           "metrics": metrics})
    rounds.sort(key=lambda r: r["round"])
    return rounds


# --------------------------------------------------------------------------
# comparison
# --------------------------------------------------------------------------

def compare(rounds: "list[dict]", candidate: "dict | None" = None,
            threshold_scale: float = 1.0) -> dict:
    """Judge ``candidate`` (default: the newest loaded round) against
    the BEST prior value of every tracked metric.

    Rules (the min-of-reps / relative-threshold protocol): the
    candidate's value is compared against the best over ALL prior
    rounds (min for lower-better, max for higher-better — the same
    best-ever bar ``vs_best`` uses, so one lucky round permanently
    raises it), the margin is relative to that bar, and a metric
    regresses when it is worse by more than ``threshold ×
    threshold_scale``. Metrics with no prior round report as ``new``;
    metrics the candidate lacks but priors had report as ``missing``
    (a stage that silently stopped producing numbers is itself a
    finding)."""
    if candidate is None:
        if not rounds:
            return {"status": "no-data", "regressions": [],
                    "improvements": [], "new": [], "missing": [],
                    "ok": [], "candidate": None}
        candidate, rounds = rounds[-1], rounds[:-1]
    cand_metrics = candidate["metrics"]
    verdict = {"candidate": {k: candidate[k]
                             for k in ("round", "file")
                             if k in candidate},
               "prior_rounds": [r["file"] for r in rounds],
               "regressions": [], "improvements": [], "ok": [],
               "new": [], "missing": []}
    for spec in METRICS:
        cand = cand_metrics.get(spec.name)
        priors = [(r["metrics"][spec.name], r["file"]) for r in rounds
                  if spec.name in r["metrics"]]
        if cand is None:
            if priors:
                verdict["missing"].append({
                    "metric": spec.name,
                    "note": "prior rounds recorded this metric but "
                            "the candidate does not"})
            continue
        if not priors:
            verdict["new"].append({"metric": spec.name, "value": cand})
            continue
        best, best_file = (min(priors) if spec.direction == "lower"
                           else max(priors))
        # the margin denominator gets an absolute floor so a zero (or
        # rounded-to-zero) bar neither makes the metric permanently
        # unjudgeable (rel forced to 0) nor explodes the margin: with
        # best == 0 any nonzero worse candidate is a maximal regression
        # and any equal-or-better one is clean — which is what a tiny
        # floor yields
        denom = max(abs(best), 1e-9)
        if spec.direction == "lower":
            rel = (cand - best) / denom
        else:
            rel = (best - cand) / denom
        entry = {"metric": spec.name, "value": cand, "best": best,
                 "best_round": best_file,
                 "worse_by": round(rel, 4),
                 "threshold": round(spec.threshold * threshold_scale,
                                    4),
                 "direction": spec.direction}
        if spec.note:
            entry["note"] = spec.note
        if rel > spec.threshold * threshold_scale:
            verdict["regressions"].append(entry)
        elif rel < 0:
            verdict["improvements"].append(entry)
        else:
            verdict["ok"].append(entry)
    verdict["status"] = ("regression" if verdict["regressions"]
                         else "ok")
    return verdict


# --------------------------------------------------------------------------
# reporting
# --------------------------------------------------------------------------

def markdown_report(rounds: "list[dict]",
                    verdict: "dict | None" = None) -> str:
    """Metric × round trend table plus the verdict summary, as
    markdown (written by ``nmfx-perf --markdown``)."""
    lines = ["# nmfx bench trajectory", ""]
    if not rounds:
        lines.append("_no BENCH_r*.json rounds found_")
        return "\n".join(lines)
    names = [spec.name for spec in METRICS
             if any(spec.name in r["metrics"] for r in rounds)]
    header = "| metric | " + " | ".join(r["file"]
                                        .removeprefix("BENCH_")
                                        .removesuffix(".json")
                                        for r in rounds) + " |"
    lines.append(header)
    lines.append("|" + "---|" * (len(rounds) + 1))
    by_name = {spec.name: spec for spec in METRICS}
    for name in names:
        cells = []
        for r in rounds:
            v = r["metrics"].get(name)
            cells.append("-" if v is None else f"{v:g}")
        arrow = "↓" if by_name[name].direction == "lower" else "↑"
        lines.append(f"| {name} {arrow} | " + " | ".join(cells) + " |")
    lines.append("")
    if verdict is not None:
        lines.append(f"**Verdict: {verdict['status']}**")
        for kind, rows in (("Regressions", verdict["regressions"]),
                           ("Improvements", verdict["improvements"]),
                           ("New (no prior)", verdict["new"]),
                           ("Missing", verdict["missing"])):
            if not rows:
                continue
            lines.append("")
            lines.append(f"## {kind}")
            for row in rows:
                if "worse_by" in row:
                    lines.append(
                        f"- `{row['metric']}`: {row['value']:g} vs "
                        f"best {row['best']:g} ({row['best_round']}) — "
                        f"{'worse' if row['worse_by'] > 0 else 'better'}"
                        f" by {abs(row['worse_by']):.1%} "
                        f"(threshold {row['threshold']:.0%})")
                else:
                    lines.append(
                        f"- `{row['metric']}`"
                        + (f": {row['value']:g}" if "value" in row
                           else "")
                        + (f" — {row['note']}" if "note" in row
                           else ""))
    return "\n".join(lines)


def main(argv: "list[str] | None" = None) -> int:
    """``nmfx-perf`` — judge the bench trajectory. Exit codes: 0 = no
    regression, 2 = regression vs the best prior round, 1 = no usable
    rounds."""
    p = argparse.ArgumentParser(
        prog="nmfx-perf",
        description="Noise-aware BENCH_r*.json trajectory judge: "
                    "compares the newest (or --candidate) round's "
                    "tracked metrics against the best prior round "
                    "under per-metric relative thresholds; prints a "
                    "trend report and exits 2 on regression "
                    "(docs/observability.md 'Regression "
                    "observatory').")
    p.add_argument("--dir", default=None,
                   help="directory holding BENCH_r*.json (default: "
                        "the repo root this package sits in)")
    p.add_argument("--candidate", default=None, metavar="FILE",
                   help="judge this record (wrapper or bare JSON) "
                        "against ALL loaded rounds instead of "
                        "treating the newest round as the candidate")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the machine-readable verdict here "
                        "('-' = stdout)")
    p.add_argument("--markdown", default=None, metavar="PATH",
                   help="write the markdown trend report here")
    p.add_argument("--threshold-scale", type=float, default=1.0,
                   help="multiply every per-metric regression "
                        "threshold (default 1.0; e.g. 0.5 = stricter)")
    args = p.parse_args(argv)
    directory = args.dir
    if directory is None:
        directory = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    rounds = load_rounds(directory)
    candidate = None
    if args.candidate is not None:
        try:
            with open(args.candidate) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            print(f"nmfx-perf: unreadable candidate {args.candidate}: "
                  f"{e}", file=sys.stderr)
            return 1
        candidate = {"file": os.path.basename(args.candidate),
                     "metrics": extract_metrics(rec)}
    if not rounds and candidate is None:
        print(f"nmfx-perf: no BENCH_r*.json rounds under {directory}",
              file=sys.stderr)
        return 1
    verdict = compare(rounds, candidate,
                      threshold_scale=args.threshold_scale)
    trend_rounds = rounds + ([candidate] if candidate is not None
                             else [])
    report = markdown_report(trend_rounds, verdict)
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write(report + "\n")
    else:
        print(report)
    if args.json == "-":
        print(json.dumps(verdict))
    elif args.json:
        with open(args.json, "w") as f:
            json.dump(verdict, f, indent=1)
    if verdict["status"] == "regression":
        print(f"nmfx-perf: REGRESSION — "
              f"{len(verdict['regressions'])} metric(s) worse than "
              "their best prior round beyond threshold",
              file=sys.stderr)
        return 2
    print(f"nmfx-perf: {verdict['status']} "
          f"({len(verdict['improvements'])} improved, "
          f"{len(verdict['ok'])} within threshold, "
          f"{len(verdict['new'])} new)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
