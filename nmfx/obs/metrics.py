"""Typed metrics registry: counters, gauges, histograms, one process-
wide namespace, Prometheus text exposition.

Before this module the stack's health numbers were scattered module
globals — ``exec_cache.compile_count()``, ``data_cache.
transfer_count()``/``h2d_bytes()``, ``serve.dispatch_count()``/
``packing_efficiency()``, ``checkpoint.chunks_solved_count()`` — each
with its own lock and no common read surface. Those functions survive
as BACK-COMPAT SHIMS (every counter-gated test and bench gate keeps
passing unchanged), but the numbers now live here, in one registry a
server can snapshot atomically and export.

Naming scheme (docs/observability.md): ``nmfx_<subsystem>_<what>``
with a ``_total`` suffix on counters and a ``_seconds``/``_bytes``
unit suffix where applicable — the Prometheus conventions, so
``prometheus_text()`` scrapes cleanly.

* :class:`Counter` — monotonically increasing; labeled series.
* :class:`Gauge` — last-set value per labeled series.
* :class:`Histogram` — streaming fixed-bucket distribution (count /
  sum / min / max / cumulative bucket counts, O(1) memory per series)
  with bucket-interpolated :meth:`Histogram.quantile` — the serve
  latency surfaces (queue-wait, pack, solve, e2e) record here.

Atomicity: ALL instrument mutation and the registry's
:meth:`MetricsRegistry.snapshot` run under ONE registry lock, so a
snapshot is a consistent cut across every series (the concurrent-writer
stress test in tests/test_obs.py pins exact final counts), and
``snapshot()``/``delta()`` give the windowed view
``NMFXServer.stats_snapshot()`` is built on. Instrument events are
coarse (dispatches, transfers, compiles — not per-iteration), so one
lock is contention-free in practice.
"""

from __future__ import annotations

import threading

from nmfx.guards import guarded_by

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "bucket_quantile", "counter", "gauge", "histogram",
           "merge_bucket_state", "registry", "render_prometheus",
           "snapshot_delta"]

#: default histogram bucket upper bounds, in seconds — spans queue
#: waits (sub-ms) through cold compiles (tens of seconds)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def bucket_quantile(buckets: "tuple[float, ...]", state: dict,
                    q: float) -> "float | None":
    """Bucket-interpolated quantile over one histogram STATE dict
    (``{"count", "min", "max", "bucket_counts"}``) — the Prometheus
    ``histogram_quantile`` estimator, factored out of
    :meth:`Histogram.quantile` so the fleet collector
    (``nmfx.obs.aggregate``) computes quantiles over MERGED states with
    the identical math. Because the state is a pure bucket-count sum,
    the quantile of a bucket-wise merge equals the quantile of one
    histogram that observed the union of the instances' observations —
    the fleet-merge exactness contract tests/test_fleet.py pins.

    Returns None before any observation. ``q`` in [0, 1]."""
    if not 0.0 <= q <= 1.0:
        raise ValueError("q must be in [0, 1]")
    if not state or state.get("count", 0) == 0:
        return None
    counts = state["bucket_counts"]
    total, lo, hi = state["count"], state["min"], state["max"]
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            lower = buckets[i - 1] if i >= 1 else 0.0
            upper = (buckets[i] if i < len(buckets)
                     else hi)  # +inf bucket: cap at observed max
            frac = (rank - cum) / c
            est = lower + (upper - lower) * max(frac, 0.0)
            # the true extremes are tracked exactly; never
            # extrapolate past them
            return min(max(est, lo), hi)
        cum += c
    return hi


def merge_bucket_state(dst: dict, src: dict) -> dict:
    """Accumulate one histogram STATE dict into another, in place:
    counts/sums/per-bucket counts add, min/max combine. The ONE copy of
    the bucket-wise merge arithmetic behind the fleet collector's
    cross-instance merge and nmfx-top's cross-series combine — both
    must agree with :func:`bucket_quantile`'s union-exactness contract,
    so the arithmetic lives once. Returns ``dst``."""
    dst["count"] += src["count"]
    dst["sum"] += src["sum"]
    for i, c in enumerate(src["bucket_counts"]):
        dst["bucket_counts"][i] += c
    for fn, field in ((min, "min"), (max, "max")):
        vals = [v for v in (dst[field], src[field]) if v is not None]
        dst[field] = fn(vals) if vals else None
    return dst


def _label_key(labelnames: "tuple[str, ...]", labels: dict) -> tuple:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"expected labels {labelnames}, got {tuple(labels)}")
    return tuple(str(labels[name]) for name in labelnames)


class _Metric:
    """Shared series bookkeeping; subclasses define the per-series
    state and mutation. The lock is the REGISTRY's (one lock for the
    whole namespace — see the module docstring's atomicity note)."""

    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: "tuple[str, ...]", lock: threading.Lock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._series: dict = {}

    def _zero(self):
        raise NotImplementedError

    def _get_locked(self, key: tuple):
        state = self._series.get(key)
        if state is None:
            state = self._series[key] = self._zero()
        return state

    def series(self) -> dict:
        """{label-values-tuple: plain-value-or-state-dict} snapshot."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        return dict(self._series)


class Counter(_Metric):
    """Monotonic counter; ``inc()`` only (a decreasing "counter" is a
    gauge). ``value()`` reads one labeled series, ``total()`` sums
    across all series of the metric."""

    kind = "counter"

    def _zero(self) -> float:
        return 0.0

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only increase; use a Gauge")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._series[key] = self._get_locked(key) + amount

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._series.get(key, 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._series.values())


class Gauge(_Metric):
    """Last-written value per series (queue depth, inflight count,
    resident cache bytes)."""

    kind = "gauge"

    def _zero(self) -> float:
        return 0.0

    def set(self, value: float, **labels) -> None:
        # host-only registry code; NMFX005's reachability scan matches
        # this method name against traced `.at[i].set(...)` call sites
        key = _label_key(self.labelnames, labels)
        with self._lock:
            v = float(value)  # nmfx: ignore[NMFX005] -- host scalar
            self._series[key] = v

    def inc(self, amount: float = 1, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._series[key] = self._get_locked(key) + amount

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._series.get(key, 0.0)


class Histogram(_Metric):
    """Streaming fixed-bucket histogram: per series, O(1) state
    (count, sum, min, max, one count per bucket bound) regardless of
    observation volume — the latency surfaces stay cheap under heavy
    serve traffic. :meth:`quantile` interpolates inside the bucket the
    target rank lands in (the Prometheus ``histogram_quantile``
    estimator), which is exact enough for p50/p99 gating as long as
    the bounds bracket the latencies of interest."""

    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: "tuple[float, ...]" = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = b

    def _zero(self) -> dict:
        return {"count": 0, "sum": 0.0, "min": None, "max": None,
                "bucket_counts": [0] * (len(self.buckets) + 1)}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        v = float(value)
        with self._lock:
            st = self._get_locked(key)
            st["count"] += 1
            st["sum"] += v
            st["min"] = v if st["min"] is None else min(st["min"], v)
            st["max"] = v if st["max"] is None else max(st["max"], v)
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    st["bucket_counts"][i] += 1
                    break
            else:
                st["bucket_counts"][-1] += 1  # +inf bucket

    def quantile(self, q: float, **labels) -> "float | None":
        """Bucket-interpolated quantile estimate for one series
        (:func:`bucket_quantile`); None before any observation.
        q in [0, 1]."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                return None
            st = {**st, "bucket_counts": list(st["bucket_counts"])}
        return bucket_quantile(self.buckets, st, q)

    def _snapshot_locked(self) -> dict:
        return {key: {**st, "bucket_counts": list(st["bucket_counts"])}
                for key, st in self._series.items()}


@guarded_by("_lock", "_metrics")
class MetricsRegistry:
    """One namespace of typed instruments. ``counter``/``gauge``/
    ``histogram`` are idempotent get-or-create (re-importing a module
    that declares its instruments is safe); redeclaring a name with a
    different type or label set is a loud error."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "dict[str, _Metric]" = {}

    def _declare(self, cls, name, help, labelnames, **kw) -> _Metric:
        labelnames = tuple(labelnames)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}")
                return m
            m = cls(name, help, labelnames, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: "tuple[str, ...]" = ()) -> Counter:
        return self._declare(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: "tuple[str, ...]" = ()) -> Gauge:
        return self._declare(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: "tuple[str, ...]" = (),
                  buckets: "tuple[float, ...]" = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._declare(Histogram, name, help, labelnames,
                             buckets=buckets)

    def get(self, name: str) -> "_Metric | None":
        with self._lock:
            return self._metrics.get(name)

    # -- snapshot / delta --------------------------------------------------
    def snapshot(self) -> dict:
        """Atomic consistent cut of every series: one lock acquisition
        covers the whole registry, so no writer lands between two
        metrics' reads. Returns plain data —
        ``{name: {"type", "labels", "series": {label-tuple: value}}}``
        — safe to hold across a run and feed to :meth:`delta`."""
        with self._lock:
            return {name: {"type": m.kind, "labels": m.labelnames,
                           "series": m._snapshot_locked()}
                    for name, m in self._metrics.items()}

    def delta(self, prev: dict) -> dict:
        """What changed since ``prev`` (an earlier :meth:`snapshot`):
        counters and histogram counts/sums subtract, gauges report
        their CURRENT value (a gauge is a level, not a flow). Series
        absent from ``prev`` subtract from zero. The windowed view
        ``NMFXServer.stats_snapshot()`` returns."""
        return snapshot_delta(self.snapshot(), prev)

    # -- exposition --------------------------------------------------------
    def prometheus_text(self) -> str:
        """The Prometheus text exposition format (the ``/metrics``
        wire format): HELP/TYPE headers then one line per series;
        histograms expose cumulative ``_bucket{le=...}`` plus ``_sum``
        and ``_count``. Served by ``NMFXServer.metrics_text()``, the
        ``serve_metrics`` HTTP endpoint (``nmfx.obs.export``), and
        written by the CLI's ``--metrics-out``."""
        snap = self.snapshot()
        with self._lock:
            for name, rec in snap.items():
                m = self._metrics.get(name)
                if m is not None:
                    rec["help"] = m.help
                    if m.kind == "histogram":
                        rec["buckets"] = m.buckets
        return render_prometheus(snap)


def snapshot_delta(cur: dict, prev: dict) -> dict:
    """The windowed-view arithmetic behind :meth:`MetricsRegistry
    .delta`, over two snapshot-SHAPED dicts: counters and histogram
    counts/sums/bucket-counts subtract, gauges pass through as their
    current level. Shared with the fleet collector's
    ``fleet_delta`` (``nmfx.obs.aggregate``), so a fleet window and a
    process window are the same math."""
    out: dict = {}
    for name, rec in cur.items():
        prev_series = (prev.get(name) or {}).get("series", {})
        series = {}
        for key, val in rec["series"].items():
            if rec["type"] == "counter":
                series[key] = val - prev_series.get(key, 0.0)
            elif rec["type"] == "histogram":
                p = prev_series.get(key)
                series[key] = {
                    "count": val["count"]
                    - (p["count"] if p else 0),
                    "sum": val["sum"] - (p["sum"] if p else 0.0),
                    "bucket_counts": [
                        c - (p["bucket_counts"][i] if p else 0)
                        for i, c in
                        enumerate(val["bucket_counts"])],
                    # extremes are cumulative (cheap state holds no
                    # window); reported as-is
                    "min": val["min"], "max": val["max"],
                }
            else:
                series[key] = val
        out[name] = {"type": rec["type"], "labels": rec["labels"],
                     "series": series}
        # enrichment keys (fleet snapshots and registry_snapshot carry
        # them) survive the windowing — a delta's histogram is only
        # interpretable against its bucket bounds
        for extra in ("help", "buckets"):
            if extra in rec:
                out[name][extra] = rec[extra]
    return out


def render_prometheus(snap: dict) -> str:
    """Render one snapshot-shaped dict as Prometheus text exposition.
    Entries may carry ``help`` (HELP header) and, for histograms, MUST
    carry ``buckets`` (the ``le=`` bounds). Factored out of the
    registry so the fleet collector's MERGED snapshot exports through
    the identical formatter (``nmfx.obs.aggregate``)."""
    def fmt_labels(labelnames, key, extra=()):
        pairs = [f'{n}="{v}"' for n, v in zip(labelnames, key)]
        pairs += [f'{n}="{v}"' for n, v in extra]
        return "{" + ",".join(pairs) + "}" if pairs else ""

    def fmt_val(v: float) -> str:
        return repr(int(v)) if float(v).is_integer() else repr(v)

    lines = []
    for name in sorted(snap):
        rec = snap[name]
        if rec["series"]:
            lines.append(f"# HELP {name} {rec.get('help', '')}")
            lines.append(f"# TYPE {name} {rec['type']}")
        for key in sorted(rec["series"]):
            val = rec["series"][key]
            if rec["type"] == "histogram":
                cum = 0
                bounds = [*rec["buckets"], "+Inf"]
                for bound, c in zip(bounds, val["bucket_counts"]):
                    cum += c
                    lines.append(
                        name + "_bucket"
                        + fmt_labels(rec["labels"], key,
                                     [("le", bound)])
                        + f" {cum}")
                lines.append(name + "_sum"
                             + fmt_labels(rec["labels"], key)
                             + f" {fmt_val(val['sum'])}")
                lines.append(name + "_count"
                             + fmt_labels(rec["labels"], key)
                             + f" {val['count']}")
            else:
                lines.append(name
                             + fmt_labels(rec["labels"], key)
                             + f" {fmt_val(val)}")
    return "\n".join(lines) + ("\n" if lines else "")


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry every nmfx subsystem's instruments
    live in."""
    return _registry


def counter(name: str, help: str = "",
            labelnames: "tuple[str, ...]" = ()) -> Counter:
    """Get-or-create a counter on the process-wide registry."""
    return _registry.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: "tuple[str, ...]" = ()) -> Gauge:
    return _registry.gauge(name, help, labelnames)


def histogram(name: str, help: str = "",
              labelnames: "tuple[str, ...]" = (),
              buckets: "tuple[float, ...]" = DEFAULT_BUCKETS) -> Histogram:
    return _registry.histogram(name, help, labelnames, buckets)
