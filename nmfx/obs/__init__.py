"""nmfx.obs — unified observability: tracing, metrics, flight recorder.

One coherent telemetry layer over the serving stack (ISSUE 10), three
pillars, all stdlib-only (importable without jax — safe from signal
handlers and test harness hooks):

* :mod:`nmfx.obs.trace` — thread-aware structured span tracer with
  Chrome trace-event export (Perfetto / ``chrome://tracing``). The
  ``Profiler`` (``nmfx/profiling.py``) is a thin aggregating view over
  it: every phase it books is also a span on the tracer's timeline,
  so enabling the tracer turns the existing phase instrumentation —
  serve queue/pack/dispatch, exec-cache compile/persist/deserialize,
  data-cache h2d, sweep solve, streamed harvest, checkpoint commit —
  into one nested per-thread timeline per request.
* :mod:`nmfx.obs.metrics` — typed counters/gauges/histograms behind
  one process-wide registry with labeled series, atomic
  ``snapshot()``/``delta()``, and Prometheus text exposition
  (``NMFXServer.metrics_text()``, CLI ``--metrics-out``). The
  scattered module counters (``exec_cache.compile_count`` etc.) now
  live here behind back-compat shims.
* :mod:`nmfx.obs.flight` — bounded ring of recent structured events
  (dispatches, retries, degradations, fault fires, evictions,
  checkpoint commits, watchdog actions) dumped as a redacted JSON
  postmortem on scheduler crash, test hang, or SIGTERM.

The performance observatory (ISSUE 13) rides the same substrate:

* :mod:`nmfx.obs.costmodel` — analytic per-engine FLOPs/bytes cost
  models (NMFX009-enforced coverage, cross-checked against
  ``compiled.cost_analysis()``), a per-device-kind peak table, and
  per-dispatch roofline attribution exporting the ``nmfx_perf_*``
  histograms with a compute- vs bandwidth-bound verdict per dispatch.
* :mod:`nmfx.obs.regress` — the ``nmfx-perf`` bench-trajectory judge:
  loads every ``BENCH_r*.json``, normalizes schema drift, compares
  the newest round against the best prior one under noise-aware
  per-metric thresholds, and renders the trend report.

The fleet observatory (ISSUE 14) turns the process-local pillars into
a multi-process system view:

* :mod:`nmfx.obs.export` — per-process telemetry publisher: a daemon
  thread writing atomic JSON registry snapshots (+ instance identity
  and heartbeat) into a shared ``telemetry_dir`` (the checkpoint
  heartbeat-ledger idiom generalized), plus an optional stdlib
  ``http.server`` Prometheus endpoint (``serve_metrics``).
* :mod:`nmfx.obs.aggregate` — the fleet collector: merges N instance
  snapshots into one view (counters sum, gauges key by instance,
  histograms merge bucket-wise so merged quantiles equal
  union-of-observations quantiles, stale instances keep counters but
  drop gauges) with ``fleet_snapshot``/``fleet_delta``/Prometheus
  exposition mirroring the single-process registry API.
* :mod:`nmfx.obs.slo` — declarative objectives (availability, latency
  bound, goodput/MFU floors) evaluated as multi-window burn rates over
  snapshot deltas; alert transitions land in the flight recorder and
  ``NMFXServer.stats_snapshot()["slo"]``.
* :mod:`nmfx.obs.top` — the ``nmfx-top`` live terminal (and ``--html``
  static) fleet dashboard over a telemetry_dir.

See docs/observability.md for the API tour, the metric naming scheme,
and the dump format.
"""

from __future__ import annotations

from nmfx.obs import (aggregate, costmodel, export, flight, metrics,
                      regress, slo, trace)
from nmfx.obs.aggregate import FleetCollector
from nmfx.obs.export import TelemetryPublisher, serve_metrics
from nmfx.obs.flight import FlightRecorder
from nmfx.obs.metrics import MetricsRegistry, registry
from nmfx.obs.slo import Objective, SLOEngine
from nmfx.obs.trace import Tracer, default_tracer, merge_traces, traced

__all__ = ["FleetCollector", "FlightRecorder", "MetricsRegistry",
           "Objective", "SLOEngine", "TelemetryPublisher", "Tracer",
           "aggregate", "costmodel", "default_tracer", "export",
           "flight", "merge_traces", "metrics", "regress", "registry",
           "serve_metrics", "slo", "trace", "traced"]
