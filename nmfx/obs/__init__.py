"""nmfx.obs — unified observability: tracing, metrics, flight recorder.

One coherent telemetry layer over the serving stack (ISSUE 10), three
pillars, all stdlib-only (importable without jax — safe from signal
handlers and test harness hooks):

* :mod:`nmfx.obs.trace` — thread-aware structured span tracer with
  Chrome trace-event export (Perfetto / ``chrome://tracing``). The
  ``Profiler`` (``nmfx/profiling.py``) is a thin aggregating view over
  it: every phase it books is also a span on the tracer's timeline,
  so enabling the tracer turns the existing phase instrumentation —
  serve queue/pack/dispatch, exec-cache compile/persist/deserialize,
  data-cache h2d, sweep solve, streamed harvest, checkpoint commit —
  into one nested per-thread timeline per request.
* :mod:`nmfx.obs.metrics` — typed counters/gauges/histograms behind
  one process-wide registry with labeled series, atomic
  ``snapshot()``/``delta()``, and Prometheus text exposition
  (``NMFXServer.metrics_text()``, CLI ``--metrics-out``). The
  scattered module counters (``exec_cache.compile_count`` etc.) now
  live here behind back-compat shims.
* :mod:`nmfx.obs.flight` — bounded ring of recent structured events
  (dispatches, retries, degradations, fault fires, evictions,
  checkpoint commits, watchdog actions) dumped as a redacted JSON
  postmortem on scheduler crash, test hang, or SIGTERM.

The performance observatory (ISSUE 13) rides the same substrate:

* :mod:`nmfx.obs.costmodel` — analytic per-engine FLOPs/bytes cost
  models (NMFX009-enforced coverage, cross-checked against
  ``compiled.cost_analysis()``), a per-device-kind peak table, and
  per-dispatch roofline attribution exporting the ``nmfx_perf_*``
  histograms with a compute- vs bandwidth-bound verdict per dispatch.
* :mod:`nmfx.obs.regress` — the ``nmfx-perf`` bench-trajectory judge:
  loads every ``BENCH_r*.json``, normalizes schema drift, compares
  the newest round against the best prior one under noise-aware
  per-metric thresholds, and renders the trend report.

See docs/observability.md for the API tour, the metric naming scheme,
and the dump format.
"""

from __future__ import annotations

from nmfx.obs import costmodel, flight, metrics, regress, trace
from nmfx.obs.flight import FlightRecorder
from nmfx.obs.metrics import MetricsRegistry, registry
from nmfx.obs.trace import Tracer, default_tracer, traced

__all__ = ["FlightRecorder", "MetricsRegistry", "Tracer", "costmodel",
           "default_tracer", "flight", "metrics", "regress",
           "registry", "trace", "traced"]
