"""Structured tracing: thread-aware spans exported as Chrome trace JSON.

The serve stack's wall time is spent across threads — the scheduler
packs and dispatches, completion workers block on the device and run
rank selection, the compile pool builds executables — and a per-phase
seconds table (``nmfx/profiling.py``) cannot show WHERE inside one
request's life the time went. This tracer records every phase/span as a
timestamped interval on the thread that ran it, bounded in memory, and
exports the Chrome trace-event format (``chrome://tracing`` /
`Perfetto <https://ui.perfetto.dev>`_), so one served request renders
as a nested timeline: queue-wait → pack → dispatch on the scheduler
thread, solve/fetch/rank-selection on the harvest workers. MPI-FAUN
(arxiv 1609.09154) attributes wall time to compute vs communication at
exit; this is the same accounting, live and per-span.

Design rules:

* **One process-wide tracer, off by default.** ``default_tracer()`` is
  the sink every ``Profiler``/``NullProfiler`` phase and every serve
  span writes through; while disabled a recording attempt costs one
  attribute read (the < 3% overhead gate in bench ``detail.obs`` is on
  the ENABLED path — the disabled path is free by construction).
* **Bounded.** Events land in a ring of ``max_events``; overflow drops
  the OLDEST events and counts them (``dropped``) — tracing can stay on
  in a long-lived server without unbounded growth, like the flight
  recorder (``nmfx/obs/flight.py``) but for spans.
* **Retroactive spans.** ``complete(name, dur_s)`` books an interval
  that just ENDED — the shape ``Profiler.add_seconds`` needs (harvest
  workers measure first, record after) — with its start back-computed,
  so worker-thread spans nest correctly without wrapping their code in
  a context manager.

Export: ``export(path)`` writes ``{"traceEvents": [...]}`` with "X"
(complete) and "i" (instant) events in microseconds plus "M" metadata
events naming each thread. Load it in Perfetto or ``chrome://tracing``
(docs/observability.md "Reading a trace").
"""

from __future__ import annotations

import contextlib
import functools
import json
import threading
import time
from collections import deque

__all__ = ["Tracer", "default_tracer", "disable", "enable",
           "merge_traces", "traced"]

#: default ring capacity — a served request is a few dozen spans, so
#: this holds thousands of requests of history at ~100 B/event
_DEFAULT_MAX_EVENTS = 100_000


class Tracer:
    """Thread-aware span recorder with Chrome trace-event export.

    All mutation is lock-guarded (spans arrive concurrently from the
    scheduler, harvest workers, and compile pool); the ``enabled``
    check deliberately runs OUTSIDE the lock — a stale read can at
    worst drop or admit one event at the enable/disable edge, and the
    hot path must not serialize on a lock while tracing is off.
    """

    def __init__(self, max_events: int = _DEFAULT_MAX_EVENTS):
        if max_events < 1:
            raise ValueError("max_events must be >= 1")
        self.enabled = False
        self._lock = threading.Lock()
        self._events: "deque[dict]" = deque(maxlen=max_events)
        self._recorded = 0  # total admitted, including since-dropped
        self._thread_names: "dict[int, str]" = {}
        #: perf_counter epoch all timestamps are relative to
        self._t0 = time.perf_counter()
        #: the same instant on the WALL clock — exported in the trace
        #: metadata so :func:`merge_traces` can align traces recorded
        #: by different processes (each process's perf_counter zero is
        #: arbitrary; the wall clock is the shared axis)
        self._t0_epoch = time.time()

    # -- recording ---------------------------------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _admit(self, ev: dict) -> None:
        tid = threading.get_ident()
        ev["tid"] = tid
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            self._events.append(ev)
            self._recorded += 1

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "phase",
             args: "dict | None" = None):
        """Record the enclosed region as one complete ("X") event on
        the calling thread. Nesting is positional: Chrome/Perfetto nest
        events on one thread by interval containment, so nested
        ``span``/``phase`` calls render as a flame without explicit
        parent links."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            self._admit({"name": name, "cat": cat, "ph": "X",
                         "ts": (t0 - self._t0) * 1e6, "dur": dur * 1e6,
                         "args": args})

    def complete(self, name: str, dur_s: float, cat: str = "phase",
                 args: "dict | None" = None) -> None:
        """Book a span that just ENDED (start = now − ``dur_s``) — the
        retroactive shape measured-then-recorded call sites need
        (``Profiler.add_seconds``, the serve queue-wait span)."""
        if not self.enabled:
            return
        end = self._now_us()
        self._admit({"name": name, "cat": cat, "ph": "X",
                     "ts": end - dur_s * 1e6, "dur": dur_s * 1e6,
                     "args": args})

    def instant(self, name: str, cat: str = "mark",
                args: "dict | None" = None) -> None:
        """Record a zero-duration event (a ``Profiler.mark``, a cache
        hit, a watchdog action) — "i" in the Chrome format."""
        if not self.enabled:
            return
        self._admit({"name": name, "cat": cat, "ph": "i", "s": "t",
                     "ts": self._now_us(), "args": args})

    # -- lifecycle ---------------------------------------------------------
    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._recorded = 0

    @property
    def dropped(self) -> int:
        """Events lost to the ring bound since the last clear()."""
        with self._lock:
            return self._recorded - len(self._events)

    def event_count(self) -> int:
        with self._lock:
            return len(self._events)

    # -- export ------------------------------------------------------------
    def events(self) -> "list[dict]":
        """Snapshot of the retained events (oldest first)."""
        with self._lock:
            return [dict(ev) for ev in self._events]

    def chrome_trace(self) -> dict:
        """The Chrome trace-event JSON object: retained events plus "M"
        metadata naming each thread, all on one pid (this process)."""
        import os

        pid = os.getpid()
        with self._lock:
            events = [dict(ev) for ev in self._events]
            names = dict(self._thread_names)
        out = []
        for tid, tname in sorted(names.items()):
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": tname}})
        for ev in events:
            ev["pid"] = pid
            if ev.get("args") is None:
                ev.pop("args", None)
            out.append(ev)
        return {"traceEvents": out, "displayTimeUnit": "ms",
                "metadata": {"nmfx_pid": pid,
                             "nmfx_t0_epoch_s": self._t0_epoch}}

    def export(self, path: str) -> str:
        """Write :meth:`chrome_trace` to ``path``; returns ``path``.
        Load in Perfetto (ui.perfetto.dev) or ``chrome://tracing``."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


_tracer = Tracer()


def default_tracer() -> Tracer:
    """The process-wide tracer every profiler phase and serve span
    records through."""
    return _tracer


def enable(max_events: "int | None" = None) -> Tracer:
    """Turn the process-wide tracer on (optionally re-bounding the
    ring). Does NOT clear already-retained events — call ``clear()``
    for a fresh window."""
    if max_events is not None and max_events != _tracer._events.maxlen:
        with _tracer._lock:
            _tracer._events = deque(_tracer._events, maxlen=max_events)
    _tracer.enabled = True
    return _tracer


def disable() -> None:
    _tracer.enabled = False


def traced(name_or_fn=None, cat: str = "fn"):
    """Decorator form of :meth:`Tracer.span` — ``@traced`` uses the
    function's qualname, ``@traced("custom.name")`` overrides it. Zero
    overhead beyond one enabled check while tracing is off."""
    def deco(fn, name=None):
        span_name = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            tr = _tracer
            if not tr.enabled:
                return fn(*a, **kw)
            with tr.span(span_name, cat=cat):
                return fn(*a, **kw)
        return wrapper

    if callable(name_or_fn):
        return deco(name_or_fn)
    return lambda fn: deco(fn, name=name_or_fn)


def merge_traces(traces, path: "str | None" = None,
                 names=None) -> dict:
    """Join N exported Chrome traces into ONE cross-process timeline.

    ``traces`` is a sequence of file paths (as written by
    :meth:`Tracer.export`) or already-loaded trace dicts. Each trace's
    timestamps are shifted onto a shared axis using the
    ``nmfx_t0_epoch_s`` wall-clock anchor the exporter embeds (the
    earliest anchor becomes zero); a trace without an anchor (foreign
    tooling, pre-ISSUE-14 exports) keeps its own relative time at
    offset zero — still rendered, just not aligned. Every merged trace
    contributes a ``process_name`` metadata event (from ``names``, the
    source filename, or its pid), so Perfetto shows one labeled track
    group per process and the cross-process joins — a spilled request's
    ``serve.spill``/``serve.readmit`` instants sharing a request id, an
    elastic sweep's per-shard ``elastic.unit`` spans sharing a trace
    id — line up on one wall-clock axis.

    Caveat: pids are the track-group key; two processes that genuinely
    share a pid (different hosts) would fold onto one group — name
    them apart via ``names``. Returns the merged trace dict; with
    ``path``, also writes it there."""
    loaded = []
    for i, t in enumerate(traces):
        label = None
        if isinstance(t, (str, bytes)) or hasattr(t, "__fspath__"):
            import os

            fname = os.fspath(t)
            with open(fname) as f:
                t = json.load(f)
            label = os.path.basename(fname)
        if names is not None and i < len(names):
            label = names[i]
        loaded.append((t, label))
    anchors = [t.get("metadata", {}).get("nmfx_t0_epoch_s")
               for t, _ in loaded]
    known = [a for a in anchors if a is not None]
    base = min(known) if known else None
    out: "list[dict]" = []
    for (t, label), anchor in zip(loaded, anchors):
        shift_us = ((anchor - base) * 1e6
                    if anchor is not None and base is not None else 0.0)
        pids = set()
        for ev in t.get("traceEvents", ()):
            ev = dict(ev)
            if "pid" in ev:
                pids.add(ev["pid"])
            if "ts" in ev and ev.get("ph") != "M":
                ev["ts"] = ev["ts"] + shift_us
            out.append(ev)
        for pid in sorted(pids, key=str):
            out.append({"name": "process_name", "ph": "M", "pid": pid,
                        "args": {"name": label if label is not None
                                 else f"pid {pid}"}})
    merged = {"traceEvents": out, "displayTimeUnit": "ms",
              "metadata": {"nmfx_merged": len(loaded),
                           "nmfx_t0_epoch_s": base}}
    if path is not None:
        with open(path, "w") as f:
            json.dump(merged, f)
    return merged
