"""SLO engine: declarative objectives, multi-window burn-rate alerting.

The decision layer of the fleet observatory (ISSUE 14): raw latency
histograms and counters do not answer "should a router shed load" or
"should an autoscaler page someone" — an error BUDGET does. This module
evaluates declarative objectives over registry-snapshot deltas (process
or fleet — both are the same snapshot shape) as multi-window burn
rates, the SRE-workbook alerting scheme: an alert needs BOTH a short
and a long window burning, so a single bad second cannot page (the
short window alone is too twitchy) and a slow leak cannot hide (the
long window alone is too slow to clear).

Burn-rate model: every objective reduces a windowed delta to a **bad
fraction** in ``[0, 1]`` and owns an **error budget** (``1 - target``);
``burn = bad_frac / budget`` — burn 1.0 consumes the budget exactly at
the sustainable rate, burn 14.4 exhausts a 30-day budget in ~2 days.

* ``availability``: bad = requests resolving with a bad outcome
  (``outcomes_bad``) over all requests, from an outcome-labeled
  histogram's counts (``nmfx_serve_e2e_seconds{outcome}``).
* ``latency``: bad = requests slower than ``bound_s``, resolved from
  cumulative bucket counts (pick ``bound_s`` on a bucket bound; an
  off-bucket bound conservatively snaps DOWN, counting the whole
  straddling bucket as bad).
* ``floor``: a throughput/utilization floor — ``value="rate"`` reads
  events/second over the window (goodput), ``value="mean"`` reads the
  histogram's windowed mean (MFU); bad = the relative shortfall below
  ``floor`` (0 when at or above it, 1 when at zero). ``floor=0``
  disables burning while keeping the objective on the dashboard.

Window pairs default to the workbook's fast (5m & 1h at 14.4×) and
slow (6h & 3d at 1×) pairs. The engine keeps its own bounded snapshot
history, so it needs no TSDB: each ``evaluate()`` appends the current
snapshot and diffs against the closest retained cut at each window's
horizon (histories shorter than a window use the oldest cut — burn
over the observed lifetime, which is the honest answer at startup).

Alert transitions (ok → fast_burn/slow_burn and back) land in the
flight recorder (``slo.transition``) and on the
``nmfx_slo_alerts_total`` counter; every evaluation re-exports the
per-(objective, window) burn gauges. ``NMFXServer.stats_snapshot()
["slo"]`` carries the latest status; crash postmortems embed
:func:`last_status`. Stdlib-only, like the rest of ``nmfx.obs``.
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
import time
from collections import deque

from nmfx.obs import metrics as _metrics

__all__ = ["DEFAULT_OBJECTIVES", "Objective", "SLOEngine", "WindowPair",
           "last_status", "registry_snapshot"]


def registry_snapshot(registry: "_metrics.MetricsRegistry | None" = None
                      ) -> dict:
    """A registry snapshot with histogram bucket bounds attached — the
    engine's default ``snapshot_fn``. The raw ``MetricsRegistry
    .snapshot()`` carries series state only; the latency objective
    resolves its bound against bucket bounds, which fleet snapshots
    (``nmfx.obs.aggregate``) already embed and this helper adds for the
    process-local case."""
    reg = registry if registry is not None else _metrics.registry()
    snap = reg.snapshot()
    for name, rec in snap.items():
        if rec["type"] == "histogram":
            m = reg.get(name)
            if m is not None:
                rec["buckets"] = m.buckets
    return snap

_burn_gauge = _metrics.gauge(
    "nmfx_slo_burn_rate",
    "error-budget burn rate per objective and window (1.0 = budget "
    "consumed exactly at the sustainable rate)",
    labelnames=("objective", "window"))
_alerts_total = _metrics.counter(
    "nmfx_slo_alerts_total",
    "SLO alert state transitions", labelnames=("objective", "state"))


@dataclasses.dataclass(frozen=True)
class WindowPair:
    """One multi-window alert arm: the alert fires only when BOTH
    windows' burn rates exceed ``threshold``."""

    name: str          # the alert state it drives ("fast"/"slow")
    short_s: float
    long_s: float
    threshold: float


#: the SRE-workbook pairs: page-grade fast burn, ticket-grade slow burn
DEFAULT_PAIRS = (
    WindowPair("fast", short_s=300.0, long_s=3600.0, threshold=14.4),
    WindowPair("slow", short_s=21600.0, long_s=259200.0, threshold=1.0),
)


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective over a registry metric (see the
    module docstring for the three kinds)."""

    name: str
    kind: str                          # "availability"|"latency"|"floor"
    metric: str = "nmfx_serve_e2e_seconds"
    #: good-fraction target; the error budget is ``1 - target``
    target: float = 0.99
    #: latency kind: the bound a request must resolve under
    bound_s: "float | None" = None
    #: availability kind: outcome label values that consume budget
    outcomes_bad: "tuple[str, ...]" = ("failed", "deadline")
    #: floor kind: the minimum acceptable value (0 = never burns)
    floor: float = 0.0
    #: floor kind: "rate" = count/window_s, "mean" = sum/count
    value: str = "rate"
    #: error-budget override (defaults to ``1 - target``)
    budget: "float | None" = None

    def __post_init__(self):
        if self.kind not in ("availability", "latency", "floor"):
            raise ValueError(f"unknown objective kind {self.kind!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1)")
        if self.kind == "latency" and self.bound_s is None:
            raise ValueError("latency objectives need bound_s")
        if self.kind == "floor" and self.value not in ("rate", "mean"):
            raise ValueError("floor value must be 'rate' or 'mean'")
        if self.budget is not None and self.budget <= 0:
            raise ValueError("budget must be positive")

    @property
    def error_budget(self) -> float:
        return self.budget if self.budget is not None \
            else 1.0 - self.target


#: the stock serving objectives: availability and tail latency burn by
#: default; the goodput/MFU floors ship at floor=0 (visible on the
#: dashboard, never burning) until a deployment sets real floors
DEFAULT_OBJECTIVES = (
    Objective("availability", kind="availability"),
    Objective("latency_p99", kind="latency", target=0.99, bound_s=60.0),
    Objective("goodput", kind="floor", value="rate", floor=0.0,
              budget=0.25),
    Objective("mfu", kind="floor", metric="nmfx_perf_mfu",
              value="mean", floor=0.0, budget=0.25),
)


def _series_delta(cur: dict, prev: dict, metric: str) -> "dict | None":
    """Delta of ONE metric's series between two snapshots (the
    ``metrics.snapshot_delta`` arithmetic, without walking the whole
    namespace)."""
    rec = cur.get(metric)
    if rec is None:
        return None
    one = {metric: rec}
    prev_one = {metric: prev[metric]} if metric in prev else {}
    return _metrics.snapshot_delta(one, prev_one)[metric]


def _bad_frac(obj: Objective, rec: "dict | None",
              window_s: float) -> "float | None":
    """Reduce one windowed metric delta to the objective's bad
    fraction; None when the metric is absent or the kind needs a
    histogram the snapshot doesn't carry."""
    if rec is None:
        return None
    if rec["type"] != "histogram":
        return None
    series = rec["series"]
    if obj.kind == "availability":
        try:
            idx = rec["labels"].index("outcome")
        except ValueError:
            return None
        total = sum(st["count"] for st in series.values())
        if total <= 0:
            return 0.0
        bad = sum(st["count"] for key, st in series.items()
                  if key[idx] in obj.outcomes_bad)
        return bad / total
    if obj.kind == "latency":
        buckets = rec.get("buckets")
        if not buckets:
            return None
        # conservative snap-down: the whole bucket straddling bound_s
        # counts as over-bound
        i = bisect.bisect_right(list(buckets), obj.bound_s) - 1
        total = bad = 0
        for st in series.values():
            total += st["count"]
            cum_le = sum(st["bucket_counts"][:i + 1]) if i >= 0 else 0
            bad += st["count"] - cum_le
        return bad / total if total > 0 else 0.0
    # floor
    if obj.floor <= 0:
        return 0.0
    if obj.value == "rate":
        got = sum(st["count"] for st in series.values()) \
            / max(window_s, 1e-9)
    else:
        count = sum(st["count"] for st in series.values())
        if count <= 0:
            return None  # no observations: nothing to judge a mean on
        got = sum(st["sum"] for st in series.values()) / count
    return min(max((obj.floor - got) / obj.floor, 0.0), 1.0)


class SLOEngine:
    """Evaluate objectives as multi-window burn rates over successive
    snapshots (process registry by default; pass a fleet collector's
    ``fleet_snapshot`` as ``snapshot_fn`` for the fleet-wide view)."""

    def __init__(self, objectives=DEFAULT_OBJECTIVES, *,
                 snapshot_fn=None, pairs=DEFAULT_PAIRS,
                 max_history: int = 4096):
        self.objectives = tuple(objectives)
        self.pairs = tuple(pairs)
        self._snapshot_fn = snapshot_fn if snapshot_fn is not None \
            else registry_snapshot
        self._lock = threading.Lock()
        self._history: "deque[tuple[float, dict]]" = deque()
        #: retention is TIME-spaced, not count-bounded: cuts land at
        #: least ``_spacing`` apart (the longest window's horizon
        #: resolved into max_history steps — ~95 s for the 3d default),
        #: so a caller evaluating every second cannot silently shrink
        #: the 6h/3d windows to minutes by churning a count-bounded
        #: ring; the retained count stays <= max_history by
        #: construction (age pruning at 1.5x the longest window)
        self._spacing = (max(p.long_s for p in self.pairs) * 1.5
                         / max(max_history, 2))
        self._state: "dict[str, str]" = {o.name: "ok"
                                         for o in self.objectives}
        self._last: "dict | None" = None

    def _ref(self, horizon: float) -> "tuple[float, dict] | None":
        """The newest retained cut at or before ``horizon`` (else the
        oldest — lifetime burn). Caller holds the lock."""
        if not self._history:
            return None
        ref = self._history[0]
        for t, snap in self._history:
            if t > horizon:
                break
            ref = (t, snap)
        return ref

    def evaluate(self, now: "float | None" = None) -> dict:
        """Take one snapshot, compute every objective's per-window burn
        rates and alert state, export the burn gauges, and record any
        state TRANSITION in the flight recorder + the alert counter.
        ``now`` is injectable for tests/replays (defaults to
        ``time.time()`` — the snapshot ledger's clock)."""
        from nmfx.obs import flight as _flight

        now = time.time() if now is None else float(now)
        snap = self._snapshot_fn()
        with self._lock:
            # time-spaced retention: a cut lands only when the last
            # retained one is at least _spacing old (the CURRENT snap
            # is always the diff source below regardless), keeping the
            # oldest cut per slot so a baseline survives fast callers
            if not self._history \
                    or now - self._history[-1][0] >= self._spacing:
                self._history.append((now, snap))
            horizon = now - max(p.long_s for p in self.pairs) * 1.5
            while len(self._history) > 1 \
                    and self._history[0][0] < horizon:
                self._history.popleft()
            refs = {}
            windows = sorted({w for p in self.pairs
                              for w in (p.short_s, p.long_s)})
            for w in windows:
                refs[w] = self._ref(now - w)
        status = {"t": now, "objectives": {}, "alerting": []}
        for obj in self.objectives:
            burns: "dict[float, float | None]" = {}
            for w in windows:
                ref = refs[w]
                if ref is None:
                    burns[w] = None
                    continue
                ref_t, ref_snap = ref
                rec = _series_delta(snap, ref_snap, obj.metric)
                elapsed = max(now - ref_t, 1e-9)
                frac = _bad_frac(obj, rec, elapsed)
                burns[w] = (None if frac is None
                            else frac / obj.error_budget)
            state = "ok"
            for pair in self.pairs:
                bs, bl = burns.get(pair.short_s), burns.get(pair.long_s)
                if bs is not None and bl is not None \
                        and bs > pair.threshold and bl > pair.threshold:
                    state = f"{pair.name}_burn"
                    break
            for w, b in burns.items():
                if b is not None:
                    _burn_gauge.set(b, objective=obj.name,
                                    window=_window_name(w))
            with self._lock:
                prev_state = self._state[obj.name]
                self._state[obj.name] = state
            if state != prev_state:
                _alerts_total.inc(objective=obj.name, state=state)
                _flight.record("slo.transition", objective=obj.name,
                               from_state=prev_state, to_state=state,
                               burns={_window_name(w): round(b, 3)
                                      for w, b in burns.items()
                                      if b is not None})
            entry = {"kind": obj.kind, "state": state,
                     "error_budget": obj.error_budget,
                     "burn": {_window_name(w): b
                              for w, b in burns.items()}}
            if obj.kind == "latency":
                entry["bound_s"] = obj.bound_s
            if obj.kind == "floor":
                entry["floor"] = obj.floor
            status["objectives"][obj.name] = entry
            if state != "ok":
                status["alerting"].append(obj.name)
        with self._lock:
            self._last = status
        global _last_status
        _last_status = status
        return status

    def status(self) -> "dict | None":
        """The most recent :meth:`evaluate` result (None before the
        first)."""
        with self._lock:
            return self._last


def _window_name(seconds: float) -> str:
    for bound, unit in ((86400, "d"), (3600, "h"), (60, "m")):
        if seconds >= bound and seconds % bound == 0:
            return f"{int(seconds // bound)}{unit}"
    return f"{int(seconds)}s"


#: the most recent evaluation by ANY engine in this process — embedded
#: in flight-recorder postmortems so a crash artifact carries the SLO
#: context that preceded it (None until something evaluates)
_last_status: "dict | None" = None


def last_status() -> "dict | None":
    return _last_status
