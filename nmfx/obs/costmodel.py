"""Analytic cost models + per-dispatch roofline attribution (ISSUE 13).

The ROADMAP's kernel item ("locality-tuned Pallas kernels toward >= 18%
solve-MFU") is blocked on measurement, not code: nothing in the tree
could say whether an update is compute- or bandwidth-bound. PL-NMF
(arxiv 1904.07935) frames NMF update performance as a locality/roofline
question — attribution (FLOPs, bytes moved, arithmetic intensity per
dispatch) must be first-class before kernel work can be steered — and
MPI-FAUN (arxiv 1609.09154) reasons from per-phase flop/word counts the
same way. This module is that instrument:

* **Cost models** — analytic per-iteration-per-lane FLOPs *and*
  bytes-moved models for every registered (algorithm, engine-family)
  pair, promoted out of ``bench.py``'s three coarse per-algorithm
  formulas into ONE literal registry-keyed table (``_FLOPS``/``_BYTES``)
  that the lint rule NMFX009 cross-references against the live engine
  routing tables (``engine_universe``), so a new algorithm or family
  can never ship without a model. Models cover the UPDATE math only;
  convergence-check costs (cadence-amortized, O(model/check_every))
  are deliberately excluded, like the original ``bench._mu_model_flops``
  excluded elementwise terms — and the exclusion is what the XLA
  cross-check below is calibrated against.
* **XLA cross-check** — :func:`xla_iteration_cost` compiles unrolled
  update steps per engine and differences ``compiled.cost_analysis()``
  (via ``nmfx._compat.compiled_cost_analysis``) between two unroll
  depths, so fixed setup cost cancels and the per-iteration analytic
  model is validated against what XLA actually emits
  (tests/test_costmodel.py pins per-engine tolerances).
* **Per-dispatch attribution** — sweep/exec-cache/serve dispatches call
  :func:`attribute_dispatch` with their measured solve wall and the
  per-lane iteration counts; achieved FLOP/s, model-FLOP utilization
  (MFU) against a per-device-kind peak table, and arithmetic intensity
  export as the ``nmfx_perf_*`` histograms, and a roofline verdict
  ("compute-bound at 0.16 MFU" vs "bandwidth-bound at 0.71 of peak
  BW") surfaces in ``Profiler.report()``,
  ``NMFXServer.stats_snapshot()``, and the CLI ``--perf-report``.

Import discipline: like the rest of ``nmfx.obs`` this module is
importable without jax — everything touching jax or the solver registry
imports lazily inside functions.
"""

from __future__ import annotations

import threading
from collections import deque

from nmfx.obs import metrics as _metrics

__all__ = [
    "COSTMODEL_EXEMPT", "DEVICE_PEAKS", "attribute_dispatch",
    "attribution_enabled", "check_costmodel_coverage",
    "comm_covered_algorithms", "comm_model", "covered_engines",
    "device_peak", "disable_attribution", "dispatch_cost",
    "enable_attribution", "engine_universe", "iteration_bytes",
    "iteration_flops", "perf_report", "perf_summary",
    "recent_attributions", "reset_perf", "set_device_peak",
    "set_sparse_density", "sparse_density", "xla_comm_cost",
    "xla_iteration_cost",
]

#: algorithms deliberately WITHOUT a cost model, with the rationale the
#: NMFX009 rule preserves: pg/alspg spend data-dependent inner work per
#: outer iteration (projected-gradient line-search trials, alspg
#: subproblem iterations capped by ``sub_max_iter``), so no
#: shape-derived per-iteration FLOP count exists — any constant would
#: be wrong by an unbounded, data-dependent factor. The lint rule
#: checks this tuple both ways: an exempt algorithm must not silently
#: gain a model entry (the exemption would rot), and every exemption
#: must name a registered algorithm.
COSTMODEL_EXEMPT = ("pg", "alspg")

#: defaults mirrored from SolverConfig for cfg=None callers — read from
#: the cfg whenever one is provided, so these literals only matter for
#: model queries made without a config in hand
_DEFAULT_CHECK_EVERY = 2
_DEFAULT_PALLAS_CHECK_BLOCK = 4


# --------------------------------------------------------------------------
# analytic models: FLOPs per iteration per lane
# --------------------------------------------------------------------------

def _mu_flops(m, n, k, cfg=None):
    """The six-GEMM mu update (reference nmf_mu.c:174-216) — H: WᵀA
    (2mnk) + WᵀW (2mk²) + (WᵀW)H (2nk²); W: AHᵀ (2mnk) + HHᵀ (2nk²) +
    W(HHᵀ) (2mk²). Elementwise terms (O(mk + kn)) omitted —
    sub-percent at bench shapes."""
    return 4.0 * m * n * k + 4.0 * k * k * (m + n)


def _hals_flops(m, n, k, cfg=None):
    """hals matches mu to leading order: the same two big data GEMMs +
    two Grams, with the per-component coordinate passes summing to the
    same 2k²(m+n) as mu's Gram-product terms (solvers/hals.py)."""
    return 4.0 * m * n * k + 4.0 * k * k * (m + n)


def _kl_flops(m, n, k, cfg=None):
    """One kl (Brunet) iteration (solvers/kl.py): two quotient
    reconstructions W@H (2·2mnk), two quotient contractions WᵀQ / QHᵀ
    (2·2mnk), and the two elementwise quotient passes (one add + one
    divide over m×n each: 4mn) — 8mnk + 4mn to leading order."""
    return 8.0 * m * n * k + 4.0 * m * n


def _neals_flops(m, n, k, cfg=None):
    """Normal-equation ALS (solvers/neals.py): per half-step one Gram
    (2mk² / 2nk²), one data GEMM WᵀA / HAᵀ (2mnk each), and the
    jittered-Cholesky k×k solve (k³/3 factor + 2k² per rhs column →
    2nk² / 2mk²) — 4mnk + 4k²(m+n) + (2/3)k³."""
    return (4.0 * m * n * k + 4.0 * k * k * (m + n)
            + (2.0 / 3.0) * k ** 3)


def _snmf_flops(m, n, k, cfg=None):
    """snmf = neals with the β-coupling/ridge additions on the k×k Grams
    (solvers/snmf.py) — O(k²), invisible at model precision."""
    return _neals_flops(m, n, k, cfg)


def _als_flops(m, n, k, cfg=None):
    """QR-free ALS (solvers/als.py): each half-step is an SVD-based
    min-norm lstsq. The data-sized work is the pseudo-inverse
    application x = V·S⁻¹·(Uᵀ·A): 2mnk + 2nk² per half-step (and the
    transposed twin), plus the (m, k)/(n, k) SVD itself — O(k²(m+n))
    with a LAPACK constant taken as 8 (Golub–Van Loan R-SVD flop count
    ~ 6mk² + 20k³ ≈ 8mk² at bench k ≪ m). NOTE: the SVD lowers to a
    LAPACK custom call whose FLOPs XLA's cost analysis does NOT count,
    so the cross-check gates this model against the GEMM share only
    (tests/test_costmodel.py documents the one-sided band)."""
    return 4.0 * m * n * k + 10.0 * k * k * (m + n)


#: density of the sparse input the tiled dispatches are contracting
#: (1.0 = dense input). A module-level hint, not a model argument,
#: because the attribution call sites (:func:`attribute_dispatch`)
#: carry only (m, n, iteration counts) — the sweep layer stamps the
#: density when it routes a SparseMatrix (``sweep._sweep_tiled``), the
#: same way the device-peak override extends the peak table.
_sparse_density = 1.0


def set_sparse_density(density: float) -> None:
    """Record the stored-nonzero density of the sparse input the next
    tiled dispatches contract (ISSUE 17). The tiled models scale their
    data-sized FLOP/byte terms by it — MPI-FAUN's point that sparse NMF
    pays only for nnz, not m·n. Reset to 1.0 for dense tiled inputs."""
    global _sparse_density
    d = float(density)
    if not 0.0 <= d <= 1.0:
        raise ValueError(f"density must be in [0, 1], got {density!r}")
    _sparse_density = d


def sparse_density() -> float:
    """The current sparse-density hint the tiled models apply."""
    return _sparse_density


def _tiled_flops(m, n, k, cfg=None):
    """Out-of-core tiled mu/hals iteration (``nmfx/tiles.py``): the
    SAME leading-order math as the in-core engines — two data-sized
    contractions (WᵀA for the next carry, A·Hᵀ-shaped terms inside the
    streaming W pass) plus the k²-sized Gram products — except the
    data terms contract stored nonzeros only, so they scale by the
    density hint: 4·d·mnk + 4k²(m + n)."""
    return (4.0 * _sparse_density * m * n * k
            + 4.0 * k * k * (m + n))


def _sketched_flops(m, n, k, cfg=None):
    """Compressed mu/hals iteration — delegates to the engine's own
    shape-derived accounting (``nmfx.solvers.sketched.
    sketched_model_flops``: 4rk(m+n) + 4rk² + 2k²(m+n)), the single
    source the bench ``detail.sketched`` stage already records. The
    once-per-restart L·A / A·R sketches and the trailing
    ``polish_iters`` exact iterations amortize over the compressed loop
    and are excluded, as the exact models exclude their own
    fixed/elementwise terms."""
    from nmfx.solvers.sketched import resolve_dim, sketched_model_flops

    cfg = _resolve_cfg(cfg)
    r = resolve_dim(cfg, int(m), int(n), int(k))
    return sketched_model_flops(m, n, k, r)


# --------------------------------------------------------------------------
# analytic models: bytes moved per iteration per lane
# --------------------------------------------------------------------------
#
# Byte models count the HBM traffic of the major arrays under the
# steady-state fusion XLA actually achieves: the m×n data operand per
# read/materialization, and a small constant number of factor-sized
# (mk + kn) passes per update (reads for GEMM operands and the
# elementwise epilogue, one write each). k×k Grams and O(k) scalars are
# noise at model precision. The point of the model is ARITHMETIC
# INTENSITY (flops/bytes) for the roofline verdict — a few-10s-percent
# constant error moves a dispatch along the roofline, it does not move
# it across the ridge at real shapes (AI ≈ k/2 for mu at f32: orders of
# magnitude from the ridge on every TPU in the peak table).

def _a_itemsize(cfg, family, algorithm) -> float:
    """Bytes per element of the A operand as the iteration loop reads
    it: the packed/pallas engines stream A pre-truncated to bf16 under
    matmul_precision='bfloat16' (``sched_mu._streams_bf16_a`` — kl
    excluded: its quotient is elementwise, not MXU-rounded), everything
    else reads the solve dtype."""
    s = _itemsize(cfg)
    if (family in ("packed", "pallas") and algorithm != "kl"
            and cfg is not None
            and getattr(cfg, "matmul_precision", "default") == "bfloat16"):
        return 2.0
    return s


def _itemsize(cfg) -> float:
    dt = getattr(cfg, "dtype", "float32") if cfg is not None else "float32"
    return 2.0 if "16" in str(dt) else 4.0


def _dense_bytes(m, n, k, cfg, family, algorithm, a_reads=2.0,
                 factor_passes=8.0, mn_passes=0.0):
    """Shared dense-update byte model: ``a_reads`` passes over the m×n
    operand, ``mn_passes`` extra m×n materializations (kl's quotients),
    ``factor_passes`` factor-sized (mk + kn) passes."""
    s = _itemsize(cfg)
    sa = _a_itemsize(cfg, family, algorithm)
    return (a_reads * m * n * sa + mn_passes * m * n * s
            + factor_passes * (m * k + k * n) * s)


def _mu_bytes(m, n, k, cfg=None, family="vmap"):
    # WᵀA + AHᵀ read A once each; W/H each: GEMM-operand reads (~2),
    # prev read + update write in the fused elementwise epilogue (~2)
    return _dense_bytes(m, n, k, cfg, family, "mu")


def _hals_bytes(m, n, k, cfg=None, family="vmap"):
    # the k unrolled coordinate passes each re-touch the updating
    # factor (the einsum over the full H/W per component plus the
    # row/column rewrite), so factor traffic scales with k — measured
    # against cost_analysis at small shapes: ~8 + 5k factor passes
    return _dense_bytes(m, n, k, cfg, family, "hals",
                        factor_passes=8.0 + 5.0 * k)


def _kl_bytes(m, n, k, cfg=None, family="vmap"):
    # per half-step over m×n: reconstruction write + read, quotient
    # write + read, and the A read (5 passes; ×2 halves = 10 m×n
    # passes — within 4% of cost_analysis at the checked shapes)
    return _dense_bytes(m, n, k, cfg, family, "kl", a_reads=2.0,
                        mn_passes=8.0, factor_passes=6.0)


def _neals_bytes(m, n, k, cfg=None, family="vmap"):
    return _dense_bytes(m, n, k, cfg, family, "neals", factor_passes=8.0)


def _snmf_bytes(m, n, k, cfg=None, family="vmap"):
    return _dense_bytes(m, n, k, cfg, family, "snmf", factor_passes=8.0)


def _als_bytes(m, n, k, cfg=None, family="vmap"):
    # lstsq touches A twice (Uᵀ·A and the transposed half-step) plus
    # SVD workspace passes over the (m, k)/(n, k) factors
    return _dense_bytes(m, n, k, cfg, family, "als", factor_passes=10.0)


def _pallas_block_bytes(m, n, k, cfg, algo):
    """Shared per-iteration HBM model for the slot scheduler's block
    kernels: A streams per iteration while the factors stay
    VMEM-resident for the whole launch, so the W/H round-trip amortizes
    over the ``check_every × check_block`` in-launch iterations.

    The PHASED kernels read A twice per iteration (once per
    half-update). The round-7 fused mu kernel
    (``experimental.fused_updates="fused"``) joins the halves on each
    streamed tile, so a T-iteration launch reads A T+1 times instead of
    2T — an A-read factor of (T+1)/T that approaches 1.0 as the
    resident cadence grows (the PL-NMF join-the-updates amortization;
    cross-validated against ``compiled_cost_analysis`` in
    tests/test_costmodel.py)."""
    cfg_ce = (getattr(cfg, "check_every", _DEFAULT_CHECK_EVERY)
              if cfg is not None else _DEFAULT_CHECK_EVERY)
    cb = (getattr(cfg, "check_block", "auto")
          if cfg is not None else "auto")
    if cb == "auto":
        cb = _DEFAULT_PALLAS_CHECK_BLOCK
    launch_iters = max(cfg_ce * int(cb), 1)
    s = _itemsize(cfg)
    sa = _a_itemsize(cfg, "pallas", algo)
    fused = (algo == "mu" and cfg is not None
             and getattr(getattr(cfg, "experimental", None),
                         "fused_updates", "auto") == "fused")
    a_passes = (launch_iters + 1.0) / launch_iters if fused else 2.0
    return (a_passes * m * n * sa
            + 2.0 * (m * k + k * n) * s / launch_iters)


def _pallas_mu_bytes(m, n, k, cfg=None, family="pallas"):
    """The mu block kernels (phased or fused per
    ``experimental.fused_updates``) — see ``_pallas_block_bytes`` for
    the locality story and the fused single-A-read amortization."""
    return _pallas_block_bytes(m, n, k, cfg, "mu")


def _pallas_hals_bytes(m, n, k, cfg=None, family="pallas"):
    """The hals coordinate-sweep block kernel: A streams twice per
    iteration (Gram accumulation + the W half's A·Hᵀ), the per-component
    sweeps touch only the VMEM-resident work tiles (no HBM factor
    traffic beyond the amortized launch round-trip), so the byte shape
    matches the phased mu kernel's."""
    return _pallas_block_bytes(m, n, k, cfg, "hals")


def _tiled_bytes_common(m, n, k, cfg, factor_passes):
    """Tiled byte model: the pipelined schedule reads A exactly ONCE
    per iteration (head + single streaming pass — the module's whole
    point), so a-traffic is one m×n pass for dense sources, or the
    stored-triplet payload d·mn·(itemsize + 8) for sparse (values plus
    the (row, col) int32 pair each nonzero ships with), plus the usual
    factor-sized passes."""
    s = _itemsize(cfg)
    d = _sparse_density
    if d < 1.0:
        a_bytes = d * m * n * (s + 8.0)
    else:
        a_bytes = m * n * s
    return a_bytes + factor_passes * (m * k + k * n) * s


def _tiled_mu_bytes(m, n, k, cfg=None, family="tiled"):
    return _tiled_bytes_common(m, n, k, cfg, 8.0)


def _tiled_hals_bytes(m, n, k, cfg=None, family="tiled"):
    # the k unrolled coordinate passes re-touch the updating factor,
    # as in the in-core hals model above
    return _tiled_bytes_common(m, n, k, cfg, 8.0 + 5.0 * k)


def _sketched_bytes(m, n, k, cfg=None, family="sketched"):
    """Per compressed iteration: the r-sized sketches L·A (r×n), A·R
    (m×r) and the projections L (r×m), R (n×r) are read once each —
    there is NO m×n traffic, which is the engine's entire point — plus
    the factor passes (the Nesterov extrapolation reads both the
    current and the previous accepted iterates, so ~10 factor-sized
    passes measured against cost_analysis)."""
    from nmfx.solvers.sketched import resolve_dim

    cfg = _resolve_cfg(cfg)
    r = resolve_dim(cfg, int(m), int(n), int(k))
    s = _itemsize(cfg)
    return (2.0 * r * (m + n) * s + 10.0 * (m * k + k * n) * s)


def _resolve_cfg(cfg):
    if cfg is not None:
        return cfg
    from nmfx.config import SolverConfig

    return SolverConfig()


#: THE coverage declaration NMFX009 cross-references: one literal entry
#: per registered (algorithm, engine-family) pair. Deliberately spelled
#: out rather than generated from the routing tables — a generated
#: table would vacuously "cover" any new engine, which is exactly the
#: silent drift the rule exists to catch.
_FLOPS = {
    ("mu", "vmap"): _mu_flops,
    ("mu", "packed"): _mu_flops,
    ("mu", "pallas"): _mu_flops,
    ("mu", "sketched"): _sketched_flops,
    ("mu", "tiled"): _tiled_flops,
    ("hals", "vmap"): _hals_flops,
    ("hals", "packed"): _hals_flops,
    # the packed kernel's permutation conjugations (Q·G·Qᵀ on (R·k)²
    # Grams) are O(R²k²·Rk) per LAUNCH, not per iteration — subleading
    # vs the per-iteration m×n Grams at modeled shapes, so the dense
    # hals FLOPs stand
    ("hals", "pallas"): _hals_flops,
    ("hals", "sketched"): _sketched_flops,
    ("hals", "tiled"): _tiled_flops,
    ("kl", "vmap"): _kl_flops,
    ("kl", "packed"): _kl_flops,
    ("als", "vmap"): _als_flops,
    ("als", "packed"): _als_flops,
    ("neals", "vmap"): _neals_flops,
    ("neals", "packed"): _neals_flops,
    ("snmf", "vmap"): _snmf_flops,
    ("snmf", "packed"): _snmf_flops,
}

_BYTES = {
    ("mu", "vmap"): _mu_bytes,
    ("mu", "packed"): _mu_bytes,
    ("mu", "pallas"): _pallas_mu_bytes,
    ("mu", "sketched"): _sketched_bytes,
    ("mu", "tiled"): _tiled_mu_bytes,
    ("hals", "vmap"): _hals_bytes,
    ("hals", "packed"): _hals_bytes,
    ("hals", "pallas"): _pallas_hals_bytes,
    ("hals", "sketched"): _sketched_bytes,
    ("hals", "tiled"): _tiled_hals_bytes,
    ("kl", "vmap"): _kl_bytes,
    ("kl", "packed"): _kl_bytes,
    ("als", "vmap"): _als_bytes,
    ("als", "packed"): _als_bytes,
    ("neals", "vmap"): _neals_bytes,
    ("neals", "packed"): _neals_bytes,
    ("snmf", "vmap"): _snmf_bytes,
    ("snmf", "packed"): _snmf_bytes,
}

assert set(_FLOPS) == set(_BYTES), \
    "every modeled engine needs BOTH a FLOPs and a bytes model"


def covered_engines() -> "frozenset[tuple[str, str]]":
    """The (algorithm, family) pairs the model table covers — the
    introspection hook NMFX009 reads (the FAULT_EVENTS/
    fault_event_categories pattern of NMFX008)."""
    return frozenset(_FLOPS)


def engine_universe() -> "frozenset[tuple[str, str]]":
    """Every (algorithm, engine-family) pair a SolverConfig can actually
    execute, derived from the AUTHORITATIVE routing declarations — the
    solver registry (``nmfx.solvers.SOLVERS``), the packed/sketched
    algorithm tuples (``nmfx.config``), and the slot-scheduler backend
    table (``sweep._GRID_EXEC_BACKENDS``, whose 'pallas' entries mark
    the kernel-capable algorithms) — minus :data:`COSTMODEL_EXEMPT`.
    A new algorithm or a new family routing expands this set while the
    literal model table stays behind, which is the NMFX009 finding."""
    from nmfx.config import (PACKED_ALGORITHMS, SKETCHED_ALGORITHMS,
                             TILED_ALGORITHMS)
    from nmfx.solvers import SOLVERS
    from nmfx.sweep import _GRID_EXEC_BACKENDS

    pairs = set()
    for algo in SOLVERS:
        if algo in COSTMODEL_EXEMPT:
            continue
        pairs.add((algo, "vmap"))
        if algo in PACKED_ALGORITHMS:
            pairs.add((algo, "packed"))
        if "pallas" in _GRID_EXEC_BACKENDS.get(algo, ()):
            pairs.add((algo, "pallas"))
        if algo in SKETCHED_ALGORITHMS:
            pairs.add((algo, "sketched"))
        if algo in TILED_ALGORITHMS:
            pairs.add((algo, "tiled"))
    return frozenset(pairs)


def check_costmodel_coverage(
    universe: "frozenset[tuple[str, str]]",
    covered: "frozenset[tuple[str, str]]",
    exempt: "tuple[str, ...]",
    algorithms: "frozenset[str]",
) -> "list[str]":
    """The pure NMFX009 contract check (tests inject mutated universes;
    the Rule wrapper passes the live declarations): registry engine
    families and costmodel coverage must match exactly, and the
    exemption list must stay honest."""
    problems: "list[str]" = []
    for algo, family in sorted(universe - covered):
        problems.append(
            f"engine ({algo!r}, {family!r}) is reachable from the "
            "routing tables but has no cost model in "
            "nmfx.obs.costmodel — its dispatches would report no "
            "FLOPs/bytes (mfu: None, no roofline verdict); add "
            "_FLOPS/_BYTES entries (or a COSTMODEL_EXEMPT rationale)")
    for algo, family in sorted(covered - universe):
        problems.append(
            f"nmfx.obs.costmodel models ({algo!r}, {family!r}), which "
            "no routing table can reach — stale entry; a renamed or "
            "removed engine would keep 'covered' status while its "
            "replacement ships unmodeled")
    for algo in sorted(set(exempt) & {a for a, _ in covered}):
        problems.append(
            f"algorithm {algo!r} is declared COSTMODEL_EXEMPT but has "
            "model entries — the exemption rationale no longer holds "
            "or the entries are wrong; keep exactly one of the two")
    for algo in sorted(set(exempt) - set(algorithms)):
        problems.append(
            f"COSTMODEL_EXEMPT names {algo!r}, which is not a "
            "registered solver algorithm — stale exemption")
    return problems


def iteration_flops(algorithm: str, family: str, m: int, n: int, k: int,
                    cfg=None) -> "float | None":
    """Model FLOPs of ONE iteration of ONE lane, or None for engines
    outside the model table (the exempt algorithms)."""
    fn = _FLOPS.get((algorithm, family))
    return None if fn is None else float(fn(m, n, k, cfg))


def iteration_bytes(algorithm: str, family: str, m: int, n: int, k: int,
                    cfg=None) -> "float | None":
    """Model HBM bytes moved by ONE iteration of ONE lane (see the byte
    model notes above), or None for unmodeled engines."""
    fn = _BYTES.get((algorithm, family))
    if fn is None:
        return None
    return float(fn(m, n, k, cfg, family))


def dispatch_cost(scfg, m: int, n: int, iters_by_k: dict,
                  mesh=None) -> "dict | None":
    """Total model FLOPs/bytes of one dispatch: Σ_k Σ_lane iterations ×
    per-iteration model, under the engine family ``scfg`` actually
    resolves to (``sweep.resolve_engine_family``). ``iters_by_k`` maps
    rank -> per-lane iteration counts (host ints/arrays). Returns
    ``{"flops", "bytes", "family", "arithmetic_intensity"}`` or None
    for unmodeled engines."""
    from nmfx.sweep import resolve_engine_family

    family = resolve_engine_family(scfg, mesh)
    flops = bytes_ = 0.0
    for k, iters in iters_by_k.items():
        fi = iteration_flops(scfg.algorithm, family, m, n, k, scfg)
        bi = iteration_bytes(scfg.algorithm, family, m, n, k, scfg)
        if fi is None or bi is None:
            return None
        total_iters = float(sum(int(i) for i in iters))
        flops += fi * total_iters
        bytes_ += bi * total_iters
    return {"flops": flops, "bytes": bytes_, "family": family,
            "arithmetic_intensity": (flops / bytes_ if bytes_ > 0
                                     else None)}


# --------------------------------------------------------------------------
# device peak table
# --------------------------------------------------------------------------

#: per-chip peaks by jax ``device_kind``: dense bf16 matmul FLOP/s (the
#: MFU denominator — bf16 is the bench default and what "default"
#: matmul precision runs on TPU; --precision highest burns multiple MXU
#: passes per matmul, so its lower MFU is real, not an accounting
#: artifact) and HBM bandwidth in bytes/s (the roofline's other axis).
#: Extend/override at runtime with :func:`set_device_peak` — e.g. for a
#: CPU container or a device kind newer than this table.
DEVICE_PEAKS = {
    "TPU v5 lite": {"flops": 197e12, "hbm_bytes_per_s": 819e9},  # v5e
    "TPU v4": {"flops": 275e12, "hbm_bytes_per_s": 1228e9},
    "TPU v5p": {"flops": 459e12, "hbm_bytes_per_s": 2765e9},
    "TPU v6 lite": {"flops": 918e12,  # v6e / Trillium
                    "hbm_bytes_per_s": 1640e9},
}

_peaks_lock = threading.Lock()


def set_device_peak(kind: str, flops: float,
                    hbm_bytes_per_s: float) -> None:
    """Override/extend the peak table for a device kind (the
    ``device-peak override`` knob in docs/observability.md)."""
    if flops <= 0 or hbm_bytes_per_s <= 0:
        raise ValueError("peaks must be positive")
    with _peaks_lock:
        DEVICE_PEAKS[kind] = {"flops": float(flops),
                              "hbm_bytes_per_s": float(hbm_bytes_per_s)}


def device_peak(kind: "str | None" = None) -> "dict | None":
    """Peak record for ``kind`` (default: the current jax default
    device's kind), or None when the kind is not in the table."""
    if kind is None:
        try:
            import jax

            kind = str(getattr(jax.devices()[0], "device_kind", "?"))
        except Exception:  # nmfx: ignore[NMFX006] -- returns None: no device, no peak
            return None
    with _peaks_lock:
        rec = DEVICE_PEAKS.get(kind)
    return None if rec is None else {**rec, "kind": kind}


# --------------------------------------------------------------------------
# per-dispatch attribution
# --------------------------------------------------------------------------

#: attribution histograms — the per-dispatch export surface
#: (docs/observability.md "Performance attribution"). Bucket choices:
#: MFU lives in [0, 1] (the 0.18 kernel target sits mid-scale);
#: achieved FLOP/s spans CPU containers (~1e9) through pod slices
#: (~1e15); arithmetic intensity spans bandwidth-bound small-k (~1)
#: through compressed-engine compute-dense (~1e3).
_mfu_hist = _metrics.histogram(
    "nmfx_perf_mfu",
    "model-FLOP utilization per dispatch vs the device-kind peak",
    labelnames=("kind",),
    buckets=(0.01, 0.02, 0.05, 0.08, 0.12, 0.16, 0.2, 0.25, 0.35, 0.5,
             0.75, 1.0))
_flops_hist = _metrics.histogram(
    "nmfx_perf_achieved_flops",
    "achieved model FLOP/s per dispatch (model FLOPs / solve wall)",
    labelnames=("kind",),
    buckets=(1e9, 1e10, 1e11, 5e11, 1e12, 5e12, 1e13, 5e13, 1e14,
             5e14, 1e15))
_ai_hist = _metrics.histogram(
    "nmfx_perf_arithmetic_intensity",
    "model arithmetic intensity (FLOPs / HBM bytes) per dispatch",
    labelnames=("kind",),
    buckets=(0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
             512.0, 1024.0))

_attrib_enabled = True
_agg_lock = threading.Lock()
#: per-dispatch-kind aggregates behind perf_report()/perf_summary()
_agg: "dict[str, dict]" = {}
#: bounded ring of recent attribution records (postmortem/report tail)
_recent: "deque[dict]" = deque(maxlen=256)


def enable_attribution() -> None:
    """Turn per-dispatch attribution on (the default). The cost while
    enabled is host-side model arithmetic on iteration counts that are
    already on host (or already being fetched) at every call site —
    the bench ``detail.obs`` stage gates it, together with span
    recording, under the < 3% warm-wall budget."""
    global _attrib_enabled
    _attrib_enabled = True


def disable_attribution() -> None:
    global _attrib_enabled
    _attrib_enabled = False


def attribution_enabled() -> bool:
    return _attrib_enabled


def reset_perf() -> None:
    """Drop the report aggregates (tests / bench arms). The registry
    histograms are monotonic and stay — windowed reads go through
    ``MetricsRegistry.delta``."""
    with _agg_lock:
        _agg.clear()
        _recent.clear()


def attribute_dispatch(kind: str, scfg, m: int, n: int,
                       iters_by_k: dict, solve_s: float,
                       mesh=None, devices: int = 1) -> "dict | None":
    """Attribute ONE dispatch: model FLOPs/bytes from the per-lane
    iteration counts, achieved FLOP/s over the measured ``solve_s``,
    MFU and bandwidth fraction against the device peak, and the
    roofline verdict. Records the ``nmfx_perf_*`` histograms (labeled
    by dispatch ``kind``) and feeds the report aggregates; returns the
    record (None when disabled, unmodeled, or unmeasurable).

    Call sites pass a wall that covers the device solve they measured
    (the profiled ``solve.*`` phase; the serve path passes the
    device-blocked fetch wall) and iteration counts that are already
    host-resident — attribution itself never forces a device sync."""
    if not _attrib_enabled or solve_s is None or solve_s <= 0.0:
        return None
    cost = dispatch_cost(scfg, m, n, iters_by_k, mesh)
    if cost is None:
        return None
    achieved = cost["flops"] / solve_s
    ai = cost["arithmetic_intensity"]
    peak = device_peak()
    mfu = bw_frac = ridge = None
    if peak is not None:
        mfu = achieved / (peak["flops"] * max(devices, 1))
        bw_frac = (cost["bytes"] / solve_s
                   / (peak["hbm_bytes_per_s"] * max(devices, 1)))
        ridge = peak["flops"] / peak["hbm_bytes_per_s"]
    rec = {
        "kind": kind,
        "algorithm": scfg.algorithm,
        "family": cost["family"],
        "shape": [int(m), int(n)],
        "model_flops": cost["flops"],
        "model_bytes": cost["bytes"],
        "solve_s": float(solve_s),
        "achieved_flops_per_s": achieved,
        "arithmetic_intensity": ai,
        "mfu": mfu,
        "hbm_bw_fraction": bw_frac,
        "verdict": _verdict(ai, ridge, mfu, bw_frac),
        "device_peak": peak,
    }
    _flops_hist.observe(achieved, kind=kind)
    if ai is not None:
        _ai_hist.observe(ai, kind=kind)
    if mfu is not None:
        _mfu_hist.observe(mfu, kind=kind)
    with _agg_lock:
        agg = _agg.setdefault(kind, {
            "dispatches": 0, "flops": 0.0, "bytes": 0.0, "seconds": 0.0,
            "device_seconds": 0.0,
            "algorithm": scfg.algorithm, "family": cost["family"]})
        agg["dispatches"] += 1
        agg["flops"] += cost["flops"]
        agg["bytes"] += cost["bytes"]
        agg["seconds"] += float(solve_s)
        # device-seconds weight the aggregate MFU/BW fractions: a
        # dispatch over N devices had N x peak available for its wall
        agg["device_seconds"] += float(solve_s) * max(devices, 1)
        _recent.append(rec)
    return rec


def _verdict(ai, ridge, mfu, bw_frac) -> str:
    """The roofline verdict string: which wall the dispatch sits under,
    and how far up it reaches."""
    if ai is None:
        return "no byte model"
    if ridge is None:
        return (f"unknown device peak (AI {ai:.1f} FLOP/B; "
                "set_device_peak() to get a verdict)")
    if ai >= ridge:
        return (f"compute-bound (AI {ai:.1f} >= ridge {ridge:.1f} "
                f"FLOP/B) at {mfu:.2f} MFU")
    return (f"bandwidth-bound (AI {ai:.1f} < ridge {ridge:.1f} "
            f"FLOP/B) at {bw_frac:.2f} of peak HBM BW")


def recent_attributions(limit: "int | None" = None) -> "list[dict]":
    """The most recent per-dispatch attribution records (bounded ring
    of 256, oldest first) — the per-dispatch drill-down behind
    :func:`perf_summary`'s aggregates: each record carries the shape,
    engine family, model FLOPs/bytes, measured wall, MFU/AI and the
    roofline verdict of ONE dispatch, so a low aggregate MFU can be
    attributed to the specific dispatches (e.g. the cold compile-wall
    outliers) that dragged it down."""
    with _agg_lock:
        recs = list(_recent)
    return recs if limit is None else recs[-limit:]


def perf_summary() -> dict:
    """Aggregated attribution per dispatch kind — the structured form
    behind ``NMFXServer.stats_snapshot()['perf']`` and the CLI
    ``--perf-report``."""
    peak = device_peak()
    ridge = (peak["flops"] / peak["hbm_bytes_per_s"]
             if peak is not None else None)
    out = {"device_peak": peak, "ridge_flops_per_byte": ridge,
           "kinds": {}}
    with _agg_lock:
        items = [(kind, dict(agg)) for kind, agg in _agg.items()]
    for kind, agg in items:
        secs = agg["seconds"]
        dev_secs = agg["device_seconds"]
        achieved = agg["flops"] / secs if secs > 0 else None
        ai = agg["flops"] / agg["bytes"] if agg["bytes"] > 0 else None
        # utilization fractions divide by DEVICE-seconds (each
        # dispatch's wall weighted by its device count) — the same
        # peak*devices denominator the per-dispatch records use
        mfu = (agg["flops"] / (peak["flops"] * dev_secs)
               if dev_secs > 0 and peak is not None else None)
        bw = (agg["bytes"] / dev_secs / peak["hbm_bytes_per_s"]
              if dev_secs > 0 and peak is not None else None)
        out["kinds"][kind] = {
            **agg,
            "achieved_flops_per_s": achieved,
            "arithmetic_intensity": ai,
            "mfu": mfu,
            "hbm_bw_fraction": bw,
            "verdict": _verdict(ai, ridge, mfu, bw),
        }
    return out


def perf_report() -> str:
    """Human-readable roofline table over every attributed dispatch
    kind — appended to ``Profiler.report()`` and printed by the CLI
    ``--perf-report``."""
    summary = perf_summary()
    if not summary["kinds"]:
        return ("perf attribution: no attributed dispatches "
                "(attribution disabled, or no modeled engine ran)")
    peak = summary["device_peak"]
    lines = []
    if peak is not None:
        lines.append(
            f"perf attribution — device {peak['kind']!r}: peak "
            f"{peak['flops'] / 1e12:.4g} TFLOP/s, "
            f"{peak['hbm_bytes_per_s'] / 1e9:.4g} GB/s HBM, ridge "
            f"{summary['ridge_flops_per_byte']:.4g} FLOP/B")
    else:
        lines.append(
            "perf attribution — device peak unknown "
            "(nmfx.obs.costmodel.set_device_peak() enables "
            "MFU/roofline verdicts)")
    lines.append(f"{'kind':<16}{'disp':>5}{'model GFLOP':>13}"
                 f"{'GB moved':>10}{'AI':>7}{'GFLOP/s':>9}{'MFU':>7}"
                 "  verdict")
    for kind in sorted(summary["kinds"]):
        rec = summary["kinds"][kind]
        mfu = "-" if rec["mfu"] is None else f"{rec['mfu']:.3f}"
        ai = ("-" if rec["arithmetic_intensity"] is None
              else f"{rec['arithmetic_intensity']:.1f}")
        ach = ("-" if rec["achieved_flops_per_s"] is None
               else f"{rec['achieved_flops_per_s'] / 1e9:.1f}")
        lines.append(
            f"{kind:<16}{rec['dispatches']:>5}"
            f"{rec['flops'] / 1e9:>13.2f}{rec['bytes'] / 1e9:>10.2f}"
            f"{ai:>7}{ach:>9}{mfu:>7}  {rec['verdict']}")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# XLA cross-check
# --------------------------------------------------------------------------

def xla_iteration_cost(algorithm: str, family: str, m: int, n: int,
                       k: int, cfg=None,
                       unrolls: "tuple[int, int]" = (2, 4)
                       ) -> "dict | None":
    """Per-iteration cost as XLA's own cost analysis sees it: compile
    the engine's update step unrolled ``unrolls[0]`` and ``unrolls[1]``
    times and difference ``compiled.cost_analysis()`` — fixed setup
    cost (init, the sketched engine's one-time L·A/A·R, constants)
    cancels, leaving the marginal per-iteration cost the analytic
    models claim to describe. Returns ``{"flops", "bytes"}`` per
    iteration, or None when the backend exposes no cost analysis or
    the family has no CPU-compilable form (pallas: Mosaic does not
    compile on CPU; its flop model is mu's — the same update math —
    and is cross-checked through the packed family).

    tests/test_costmodel.py gates the analytic table against this per
    engine with pinned tolerances on the smallest shapes."""
    from nmfx._compat import compiled_cost_analysis

    t1, t2 = unrolls
    if not (0 < t1 < t2):
        raise ValueError("unrolls must be increasing and positive")
    costs = []
    for t in (t1, t2):
        compiled = _compile_unrolled(algorithm, family, m, n, k, cfg, t)
        if compiled is None:
            return None
        ca = compiled_cost_analysis(compiled)
        if ca is None or "flops" not in ca:
            return None
        costs.append(ca)
    span = t2 - t1
    out = {"flops": (costs[1]["flops"] - costs[0]["flops"]) / span}
    b1, b2 = (c.get("bytes accessed") for c in costs)
    out["bytes"] = ((b2 - b1) / span
                    if b1 is not None and b2 is not None else None)
    return out


def _compile_unrolled(algorithm, family, m, n, k, cfg, t):
    """A compiled function running exactly ``t`` update iterations of
    the requested engine (no convergence checks — the models cover the
    update math; see the module docstring), Python-unrolled so XLA's
    while-body-counted-once ambiguity never enters the differencing."""
    import jax
    import jax.numpy as jnp

    cfg = _resolve_cfg(cfg)
    if family in ("pallas", "tiled"):
        # pallas: Mosaic does not compile on CPU. tiled: the streaming
        # loop is host-driven across many dispatches — no single
        # compiled step exists to difference; its update math is the
        # in-core mu/hals math, cross-checked through the vmap family.
        return None
    key = jax.random.key(0)
    kw, kh, ka = jax.random.split(key, 3)
    a = jax.random.uniform(ka, (m, n), jnp.float32, 0.1, 1.0)
    w0 = jax.random.uniform(kw, (m, k), jnp.float32, 0.1, 1.0)
    h0 = jax.random.uniform(kh, (k, n), jnp.float32, 0.1, 1.0)

    if family == "vmap":
        from nmfx.solvers import SOLVERS
        from nmfx.solvers import base as sbase

        mod = SOLVERS[algorithm]

        def run(a, w0, h0):
            state = sbase.init_state(a, w0, h0,
                                     mod.init_aux(a, w0, h0, cfg))
            for _ in range(t):
                state = mod.step(a, state, cfg, check=False)
            return state.w, state.h

        return jax.jit(run).lower(a, w0, h0).compile()

    if family == "sketched":
        from nmfx.solvers import base as sbase
        from nmfx.solvers import sketched

        def run(a, w0, h0):
            state = sbase.init_state(
                a, w0, h0, sketched.init_aux(a, w0, h0, cfg, key))
            for _ in range(t):
                state = sketched.step(a, state, cfg, check=False)
            return state.w, state.h

        return jax.jit(run).lower(a, w0, h0).compile()

    if family == "packed":
        from nmfx.ops.grid_mu import make_block

        block = make_block(cfg, a)
        done = jnp.zeros((1,), bool)
        wb, hb = w0[None], h0[None]
        kwargs = ({"pad_live": jnp.ones((1, k), bool)}
                  if algorithm == "snmf" else {})

        def run(a, wp, hp):
            for _ in range(t):
                wp, hp = block(a, wp, hp, done, cfg, **kwargs)
            return wp, hp

        return jax.jit(run).lower(a, wb, hb).compile()

    raise ValueError(f"unknown engine family {family!r}")


# ---------------------------------------------------------------------------
# Communication model (ISSUE 19): bytes-over-interconnect + collective
# counts per iteration per (algorithm × mesh shape), cross-validated
# against the compiled HLO's collective ops the same way the FLOPs
# models are validated against cost_analysis().
#
# The schedule being modeled is the MPI-FAUN/HPC-NMF communication-
# optimal one the grid-sharded driver executes (arxiv 1609.09154,
# 1509.09313): A is 2-D block-distributed and never moves; per factor
# update each shard contracts Gram-first and allreduces only the k×k
# Gram (or the kl k-vector) plus the k×(dim/shard) factor slab — one
# allreduce pair per present grid axis per iteration, O(k² + k·dim/p)
# words, and the restart axis is COMMUNICATION-FREE per iteration (its
# only collectives are the consensus psum and best-restart selection in
# the epilogue). The table below is exact against compiled HLO on the
# forced-CPU meshes (tests/test_costmodel.py; bench `detail.mesh` gates
# it per round), with payload element counts read off the solver psums:
#
#   kl           per axis: k×dim_loc quotient slab + k vector      (2 ops)
#   neals/snmf   per axis: k×k Gram + k×dim_loc normal-eq slab     (2 ops)
#   hals         per axis: k×k Gram + k×dim_loc shared-GEMM slab   (2 ops)
#   mu (packed)  per axis: (r_loc·k)² pool Gram + r_loc·k×dim_loc
#                numerator slab + the r_loc-lane i32 nonfinite-guard
#                reduction                                         (3 ops)
#
# dim_loc is n_loc for the feature axis (H-side terms, m-contracted)
# and m_loc for the sample axis (W-side terms, n-contracted); all f32
# payloads scale ×r_loc because vmapped lanes batch into one collective.
# ---------------------------------------------------------------------------

#: per-(grid-driver algorithm) collective schedule: ops per present
#: grid axis per iteration, f32 payload elements as a function of
#: (k, dim_loc, r_loc), and the optional i32 guard-lane payload. A
#: LITERAL table like _FLOPS/_BYTES: adding a grid algorithm without a
#: comm entry fails comm_model loudly, and the HLO cross-check pins
#: each entry exactly.
_COMM = {
    "kl": dict(ops_per_axis=2,
               payload=lambda k, d, r: r * (k * d + k),
               guard=None),
    "neals": dict(ops_per_axis=2,
                  payload=lambda k, d, r: r * (k * d + k * k),
                  guard=None),
    "snmf": dict(ops_per_axis=2,
                 payload=lambda k, d, r: r * (k * d + k * k),
                 guard=None),
    "hals": dict(ops_per_axis=2,
                 payload=lambda k, d, r: r * (k * d + k * k),
                 guard=None),
    "mu": dict(ops_per_axis=3,
               payload=lambda k, d, r: r * k * d + (r * k) ** 2,
               guard=lambda r: r),
}


def comm_covered_algorithms() -> frozenset:
    """Algorithms with a communication model — exactly the set the
    grid-sharded driver accepts (mu via the packed pool path, plus
    ``sweep.GRID_SOLVERS``); everything else is restart-parallel only
    and moves zero per-iteration bytes."""
    return frozenset(_COMM)


def _ring_wire_bytes(payload_bytes: float, p: int) -> float:
    """Bytes a p-participant ring allreduce moves per participant over
    the interconnect: 2(p-1)/p × payload (reduce-scatter +
    all-gather) — the standard bandwidth-optimal convention, and the
    convention MPI-FAUN's word counts use."""
    if p <= 1:
        return 0.0
    return 2.0 * (p - 1) / p * payload_bytes


def comm_model(algorithm: str, m: int, n: int, k: int, *,
               restart_shards: int = 1, feature_shards: int = 1,
               sample_shards: int = 1, restarts: "int | None" = None,
               itemsize: int = 4) -> dict:
    """Per-iteration collective schedule of one meshed factorization.

    Returns a dict with ``collectives_per_iter`` (allreduce op count in
    the compiled update program — 0 on a restart-only mesh: the
    communication-avoiding property), ``payload_bytes_per_iter`` (sum
    of allreduce payload sizes), ``wire_bytes_per_iter`` (ring-
    allreduce bytes over the interconnect per participant), a
    ``per_axis`` breakdown, and the ``epilogue`` (the per-k consensus
    reduction over the restart axis: one n_pad×n_pad psum plus the
    fault-count scalar — amortized over the whole solve, not per
    iteration). Counts and payload bytes are exact against compiled
    HLO (:func:`xla_comm_cost`); wire bytes are the ring convention.

    ``restarts``/``restart_shards`` set the local lane count r_loc
    (payloads scale with it); shapes use the padded local dims the
    sharded program actually allocates."""
    if algorithm not in _COMM:
        raise ValueError(
            f"no communication model for algorithm {algorithm!r} — the "
            "grid-sharded driver accepts "
            f"{sorted(_COMM)} (everything else is restart-parallel "
            "only); add a _COMM entry with the new schedule")
    for name, v in (("restart_shards", restart_shards),
                    ("feature_shards", feature_shards),
                    ("sample_shards", sample_shards)):
        if v < 1:
            raise ValueError(f"{name} must be >= 1, got {v}")
    ent = _COMM[algorithm]
    r_total = restart_shards if restarts is None else restarts
    r_loc = -(-r_total // restart_shards)
    m_loc = -(-m // feature_shards)
    n_loc = -(-n // sample_shards)
    per_axis = {}
    total_ops = 0
    total_payload = 0.0
    total_wire = 0.0
    for axis, p, dim_loc in (("features", feature_shards, n_loc),
                             ("samples", sample_shards, m_loc)):
        if p <= 1:
            continue
        payload = ent["payload"](k, dim_loc, r_loc) * itemsize
        ops = ent["ops_per_axis"]
        if ent["guard"] is not None:
            payload += ent["guard"](r_loc) * 4  # i32 lane flags
        wire = _ring_wire_bytes(payload, p)
        per_axis[axis] = dict(collectives=ops, payload_bytes=payload,
                              wire_bytes=wire, participants=p)
        total_ops += ops
        total_payload += payload
        total_wire += wire
    n_pad = n_loc * sample_shards
    epi_payload = (float(n_pad) * n_pad + 1) * itemsize \
        if restart_shards > 1 else 0.0
    epilogue = dict(
        collectives=2 if restart_shards > 1 else 0,
        payload_bytes=epi_payload,
        wire_bytes=_ring_wire_bytes(epi_payload, restart_shards))
    return dict(algorithm=algorithm,
                mesh_shape=(restart_shards, feature_shards,
                            sample_shards),
                r_loc=r_loc,
                collectives_per_iter=total_ops,
                payload_bytes_per_iter=total_payload,
                wire_bytes_per_iter=total_wire,
                per_axis=per_axis,
                epilogue=epilogue)


#: HLO scalar dtype sizes for collective payload parsing
_HLO_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2,
                    "s16": 2, "u16": 2, "f32": 4, "s32": 4, "u32": 4,
                    "f64": 8, "s64": 8, "u64": 8}


def _hlo_collectives(hlo_text: str) -> "tuple[int, float]":
    """(op count, total payload bytes) of the all-reduce instructions
    in an HLO module dump. Tuple-shaped results (XLA's allreduce
    combiner) count as one op with the summed payload."""
    import re

    ops = 0
    payload = 0.0
    for mres in re.finditer(r"=\s+(\(?[a-z0-9\[\],{}/ ]+?\)?)\s+"
                            r"all-reduce(?:-start)?\(", hlo_text):
        ops += 1
        for dt, dims in re.findall(r"([a-z][a-z0-9]*)\[([0-9,]*)\]",
                                   mres.group(1)):
            size = _HLO_DTYPE_BYTES.get(dt)
            if size is None:
                continue
            elems = 1
            for d in dims.split(","):
                if d:
                    elems *= int(d)
            payload += elems * size
    return ops, payload


def xla_comm_cost(algorithm: str, m: int, n: int, k: int, mesh,
                  cfg=None, r_loc: int = 2,
                  unrolls: "tuple[int, int]" = (1, 3)) -> "dict | None":
    """Measure the per-iteration collective count and payload bytes of
    the grid-sharded update program by compiling it at two unroll
    depths over ``mesh`` and differencing the HLO's all-reduce ops —
    the collective-op analogue of :func:`xla_iteration_cost`'s FLOP
    differencing (fixed setup/epilogue collectives cancel).

    Compiles the same per-step programs the sharded sweep executes:
    ``SOLVERS[alg].step`` with a bound ``ShardInfo`` under vmap for the
    grid solvers, the packed-pool ``_step`` for mu — update math only
    (check=False), matching what :func:`comm_model` models. Returns
    ``{"collectives_per_iter", "payload_bytes_per_iter"}``, or None
    when the program can't compile here (missing backend support)."""
    try:
        counts = [
            _hlo_collectives(
                _compile_grid_unrolled(algorithm, m, n, k, cfg, mesh,
                                       t, r_loc).as_text())
            for t in unrolls]
    except Exception:  # nmfx: ignore[NMFX006] -- the documented "no
        return None    # measurement on this backend" contract: callers
    #                    (tests, the bench mesh stage) skip the gate
    #                    when compilation is unavailable here
    dt = unrolls[1] - unrolls[0]
    return dict(
        collectives_per_iter=(counts[1][0] - counts[0][0]) / dt,
        payload_bytes_per_iter=(counts[1][1] - counts[0][1]) / dt)


def _compile_grid_unrolled(algorithm: str, m: int, n: int, k: int,
                           cfg, mesh, t: int, r_loc: int):
    """Compile ``t`` unrolled grid-sharded update steps over ``mesh``
    (no while loop — a while body's collectives appear once in HLO
    regardless of trip count, which would defeat the differencing)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from nmfx._compat import shard_map
    from nmfx.config import SolverConfig
    from nmfx.sweep import FEATURE_AXIS, RESTART_AXIS, SAMPLE_AXIS

    if cfg is None:
        cfg = SolverConfig(algorithm=algorithm)
    f_sh = mesh.shape.get(FEATURE_AXIS, 1)
    s_sh = mesh.shape.get(SAMPLE_AXIS, 1)
    r_sh = mesh.shape.get(RESTART_AXIS, 1)
    f_ax = FEATURE_AXIS if f_sh > 1 else None
    s_ax = SAMPLE_AXIS if s_sh > 1 else None
    rs = RESTART_AXIS if r_sh > 1 else None
    R = r_sh * r_loc
    a = jnp.ones((m, n), jnp.float32)

    if algorithm == "mu":
        from nmfx.ops import packed_mu as pm

        def body(a_loc, wp, hp):
            bd = pm.block_diag_mask(r_loc, k, jnp.float32)
            st = pm.PackedState(
                wp=wp, hp=hp, wp_prev=wp, hp_prev=hp,
                iteration=jnp.zeros((), jnp.int32),
                classes=jnp.full((r_loc, hp.shape[1]), -1, jnp.int32),
                stable=jnp.zeros((r_loc,), jnp.int32),
                done=jnp.zeros((r_loc,), bool),
                done_iter=jnp.zeros((r_loc,), jnp.int32),
                stop_reason=jnp.zeros((r_loc,), jnp.int32),
                nonfinite=None)
            for _ in range(t):
                st = pm._step(a_loc, bd, st, cfg, r_loc, False,
                              feature_axis=f_ax, sample_axis=s_ax,
                              n_total=n)
            return st.wp, st.hp

        wp = jnp.ones((m, R * k), jnp.float32)
        hp = jnp.ones((R * k, n), jnp.float32)
        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(f_ax, s_ax), P(f_ax, rs),
                                 P(rs, s_ax)),
                       out_specs=(P(f_ax, rs), P(rs, s_ax)),
                       check_vma=False)
        return jax.jit(fn).lower(a, wp, hp).compile()

    from nmfx.solvers import SOLVERS, base
    from nmfx.sweep import GRID_SOLVERS

    if algorithm not in GRID_SOLVERS:
        raise ValueError(
            f"algorithm {algorithm!r} has no grid-sharded form")
    grid_mod = SOLVERS[algorithm]
    shard_info = base.ShardInfo(f_ax, s_ax, m, n)
    step_fn = functools.partial(grid_mod.step, shard=shard_info)

    def body(a_loc, w0s, h0s):
        def lane(w0, h0):
            st = base.init_state(
                a_loc, w0, h0,
                grid_mod.init_aux(a_loc, w0, h0, cfg,
                                  shard=shard_info))
            for _ in range(t):
                st = st._replace(w_prev=st.w, h_prev=st.h,
                                 iteration=st.iteration + 1)
                st = step_fn(a_loc, st, cfg, False)
            return st.w, st.h

        return jax.vmap(lane)(w0s, h0s)

    w0s = jnp.ones((R, m, k), jnp.float32)
    h0s = jnp.ones((R, k, n), jnp.float32)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(f_ax, s_ax), P(rs, f_ax, None),
                             P(rs, None, s_ax)),
                   out_specs=(P(rs, f_ax, None), P(rs, None, s_ax)),
                   check_vma=False)
    return jax.jit(fn).lower(a, w0s, h0s).compile()
