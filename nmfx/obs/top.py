"""``nmfx-top`` — the live fleet dashboard over a telemetry_dir.

The human tail of the fleet observatory (ISSUE 14): point it at the
``telemetry_dir`` the instances publish into (``ServeConfig
.telemetry_dir``, ``ElasticShardRunner``, bench children) and it
renders, per refresh, the per-instance liveness table (role, pid,
device kind, heartbeat age, queue depth, inflight), the fleet-merged
serving stats (outcome counts, goodput, p50/p99 from the merged
histograms — union-of-observations exact, ``metrics
.bucket_quantile``), the mean MFU per dispatch kind, and the SLO
burn-rate status (``nmfx.obs.slo`` over the fleet snapshot, so the
alert states are the fleet's, not one replica's).

Forms follow the data's job (no charts for chart's sake): identity +
liveness is a table, headline load numbers are stat rows, SLO state is
a status line whose state is NEVER color-alone — each state carries a
symbol + word (``ok`` / ``FAST BURN`` / ``SLOW BURN``), so the
terminal, the ``--html`` static render, a monochrome pipe, and a
screen reader all agree.

Modes: the default loops at ``--interval`` (goodput is the
completed-count delta over the refresh interval); ``--once`` renders a
single frame (rates read ``n/a`` — one frame has no window);
``--html PATH`` writes a static HTML render of the frame and exits.
Stdlib-only, like the rest of ``nmfx.obs``.
"""

from __future__ import annotations

import argparse
import html
import sys
import time

from nmfx.obs import metrics as _metrics
from nmfx.obs import slo as _slo
from nmfx.obs.aggregate import FleetCollector

__all__ = ["gather", "main", "render_html", "render_text"]

#: alert-state presentation: symbol + word, state never color-alone
_STATE_MARK = {"ok": "· ok", "fast_burn": "!! FAST BURN",
               "slow_burn": "! SLOW BURN"}


def _combined_hist(rec: "dict | None") -> "dict | None":
    """Sum one histogram metric's state across ALL its labeled series
    (e.g. every ``outcome``) — the shared ``metrics
    .merge_bucket_state`` arithmetic, so quantiles over the combined
    state stay union-exact."""
    if rec is None or rec.get("type") != "histogram" \
            or not rec["series"]:
        return None
    out = None
    for st in rec["series"].values():
        if out is None:
            out = {"count": st["count"], "sum": st["sum"],
                   "min": st["min"], "max": st["max"],
                   "bucket_counts": list(st["bucket_counts"])}
        else:
            _metrics.merge_bucket_state(out, st)
    return out


def gather(collector: FleetCollector, engine: "_slo.SLOEngine",
           prev: "tuple[float, dict] | None" = None,
           now: "float | None" = None) -> dict:
    """One dashboard frame: instance rows, fleet stats, SLO status.
    ``prev`` is the previous frame's ``(t, fleet_snapshot)`` — rates
    (goodput) are computed over that window; None on the first frame
    (rates render n/a). The instance table and the merged stats derive
    from ONE ledger read, so the frame is a consistent cut (the SLO
    engine's windowed view reads through its own ``snapshot_fn``)."""
    now = time.time() if now is None else now
    payloads = collector.collect()
    snap = collector.fleet_snapshot(now, payloads=payloads)
    rows = collector.instances(now, payloads=payloads)
    gauge_by_instance: "dict[str, dict]" = {}
    for metric, field in (("nmfx_serve_queue_depth", "queue_depth"),
                          ("nmfx_serve_inflight", "inflight")):
        rec = snap.get(metric)
        if rec is None:
            continue
        for key, val in rec["series"].items():
            gauge_by_instance.setdefault(key[0], {})[field] = val
    for row in rows:
        # payload-embedded status (per-instance levels, ISSUE 15) wins
        # over the process-wide gauges, which N in-process replicas
        # overwrite each other on
        for field, val in gauge_by_instance.get(row["instance"],
                                                {}).items():
            row.setdefault(field, val)
    e2e = snap.get("nmfx_serve_e2e_seconds")
    outcomes: "dict[str, int]" = {}
    if e2e is not None and "outcome" in e2e["labels"]:
        idx = e2e["labels"].index("outcome")
        for key, st in e2e["series"].items():
            outcomes[key[idx]] = outcomes.get(key[idx], 0) + st["count"]
    combined = _combined_hist(e2e)
    p50 = p99 = None
    if combined is not None and e2e is not None:
        p50 = _metrics.bucket_quantile(e2e["buckets"], combined, 0.5)
        p99 = _metrics.bucket_quantile(e2e["buckets"], combined, 0.99)
    goodput = None
    if prev is not None:
        prev_t, prev_snap = prev
        delta = _metrics.snapshot_delta(snap, prev_snap)
        drec = _combined_hist(delta.get("nmfx_serve_e2e_seconds"))
        if drec is not None and now > prev_t:
            goodput = drec["count"] / (now - prev_t)
    mfu = {}
    mrec = snap.get("nmfx_perf_mfu")
    if mrec is not None:
        for key, st in mrec["series"].items():
            if st["count"]:
                mfu[",".join(key) or "all"] = st["sum"] / st["count"]
    # request economics (ISSUE 16): the result-cache/coalescing/extend
    # counters, fleet-summed across layers (server + router series)
    def _counter_sum(name):
        rec = snap.get(name)
        if rec is None or rec.get("type") != "counter":
            return None
        return sum(rec["series"].values())

    economics = None
    hits = _counter_sum("nmfx_result_cache_hits_total")
    misses = _counter_sum("nmfx_result_cache_misses_total")
    coalesced = _counter_sum("nmfx_result_cache_coalesced_total")
    extended = _counter_sum("nmfx_result_cache_extended_total")
    if any(v is not None
           for v in (hits, misses, coalesced, extended)):
        h, m, c = hits or 0.0, misses or 0.0, coalesced or 0.0
        served = sum(outcomes.values())
        economics = {
            "hits": int(h), "misses": int(m), "coalesced": int(c),
            "extended": int(extended or 0),
            "hit_rate": (h / (h + m)) if (h + m) else None,
            "coalesce_rate": (c / served) if served else None,
        }
    slo_status = engine.evaluate(now)
    return {"t": now, "instances": rows, "outcomes": outcomes,
            "p50_s": p50, "p99_s": p99, "goodput_req_per_s": goodput,
            "mfu": mfu, "economics": economics, "slo": slo_status,
            "snapshot": snap}


def _fmt(v, suffix="", digits=3) -> str:
    if v is None:
        return "n/a"
    return f"{v:.{digits}f}{suffix}"


def _role_summary(rows: "list[dict]") -> str:
    """One line summarizing the fleet BY ROLE (ISSUE 15): a service
    tier reads as "router 1 live · replica 2 live / 1 stale", so an
    operator sees the front door and its pool distinctly without
    scanning the instance table."""
    by_role: "dict[str, list[bool]]" = {}
    for row in rows:
        by_role.setdefault(str(row.get("role")), []).append(
            bool(row["stale"]))
    parts = []
    for role in sorted(by_role):
        stales = by_role[role]
        live = len(stales) - sum(stales)
        part = f"{role} {live} live"
        if sum(stales):
            part += f" / {sum(stales)} stale"
        parts.append(part)
    return " · ".join(parts)


def render_text(frame: dict, telemetry_dir: str) -> str:
    """The terminal frame — plain text, fixed-width columns."""
    lines = [f"nmfx-top — fleet telemetry from {telemetry_dir}"]
    rows = frame["instances"]
    if not rows:
        lines.append("  (no telemetry instances found — is anything "
                     "publishing here?)")
        return "\n".join(lines) + "\n"
    lines.append("roles: " + _role_summary(rows))
    lines.append(f"{'instance':<34}{'role':<9}{'pid':>7} "
                 f"{'device':<14}{'hb age':>8} {'state':<6}"
                 f"{'queue':>6}{'infl':>6}")
    for row in sorted(rows, key=lambda r: r["instance"]):
        state = "stale" if row["stale"] else "live"
        lines.append(
            f"{row['instance']:<34}{str(row['role']):<9}"
            f"{str(row['pid']):>7} {str(row['device_kind'])[:13]:<14}"
            f"{row['heartbeat_age_s']:>7.1f}s {state:<6}"
            f"{str(row.get('queue_depth', '-')):>6}"
            f"{str(row.get('inflight', '-')):>6}")
    out = frame["outcomes"]
    lines.append("")
    lines.append(
        "serve: "
        + " ".join(f"{k}={int(v)}" for k, v in sorted(out.items()))
        if out else "serve: no requests observed")
    goodput = _fmt(frame["goodput_req_per_s"], " req/s", 2)
    lines.append(f"latency: p50={_fmt(frame['p50_s'], 's')} "
                 f"p99={_fmt(frame['p99_s'], 's')}   "
                 f"goodput={goodput}")
    if frame["mfu"]:
        lines.append("mfu: " + " ".join(
            f"{kind}={val:.3f}"
            for kind, val in sorted(frame["mfu"].items())))
    econ = frame.get("economics")
    if econ is not None:
        lines.append(
            f"economics: hit_rate={_fmt(econ['hit_rate'], '', 2)} "
            f"(hits={econ['hits']} misses={econ['misses']}) "
            f"coalesce_rate={_fmt(econ['coalesce_rate'], '', 2)} "
            f"(coalesced={econ['coalesced']}) "
            f"extended={econ['extended']}")
    slo = frame["slo"]
    for name, obj in sorted(slo["objectives"].items()):
        burns = " ".join(f"{w}={_fmt(b, '', 2)}"
                         for w, b in obj["burn"].items())
        mark = _STATE_MARK.get(obj["state"], obj["state"])
        lines.append(f"slo {name:<14} {mark:<14} burn: {burns}")
    return "\n".join(lines) + "\n"


def render_html(frame: dict, telemetry_dir: str) -> str:
    """A static HTML render of one frame: the same tables and stat
    rows as the terminal (neutral ink, system fonts; SLO states carry
    symbol + word, never color alone)."""
    esc = html.escape

    def chip(state: str) -> str:
        return (f'<span class="chip {esc(state)}">'
                f"{esc(_STATE_MARK.get(state, state))}</span>")

    inst_rows = "".join(
        "<tr><td>{i}</td><td>{r}</td><td class='num'>{p}</td>"
        "<td>{d}</td><td class='num'>{a:.1f}s</td><td>{s}</td>"
        "<td class='num'>{q}</td><td class='num'>{f}</td></tr>".format(
            i=esc(str(row["instance"])), r=esc(str(row["role"])),
            p=esc(str(row["pid"])), d=esc(str(row["device_kind"])),
            a=row["heartbeat_age_s"],
            s="stale" if row["stale"] else "live",
            q=esc(str(row.get("queue_depth", "–"))),
            f=esc(str(row.get("inflight", "–"))))
        for row in sorted(frame["instances"],
                          key=lambda r: r["instance"]))
    outcome_row = " · ".join(
        f"{esc(k)}&nbsp;{int(v)}"
        for k, v in sorted(frame["outcomes"].items())) or "none yet"
    slo_rows = "".join(
        "<tr><td>{n}</td><td>{c}</td><td class='num'>{b}</td></tr>"
        .format(n=esc(name), c=chip(obj["state"]),
                b=esc(" ".join(f"{w}={_fmt(v, '', 2)}"
                               for w, v in obj["burn"].items())))
        for name, obj in sorted(frame["slo"]["objectives"].items()))
    stats = [
        ("p50 latency", _fmt(frame["p50_s"], " s")),
        ("p99 latency", _fmt(frame["p99_s"], " s")),
        ("goodput", _fmt(frame["goodput_req_per_s"], " req/s", 2)),
    ] + [(f"mfu {k}", f"{v:.3f}")
         for k, v in sorted(frame["mfu"].items())]
    if frame.get("economics") is not None:
        econ = frame["economics"]
        stats += [
            ("cache hit rate", _fmt(econ["hit_rate"], "", 2)),
            ("coalesce rate", _fmt(econ["coalesce_rate"], "", 2)),
            ("extended sweeps", str(econ["extended"])),
        ]
    stat_tiles = "".join(
        f'<div class="tile"><div class="label">{esc(label)}</div>'
        f'<div class="value">{esc(value)}</div></div>'
        for label, value in stats)
    stamp = time.strftime("%Y-%m-%d %H:%M:%S",
                          time.localtime(frame["t"]))
    return f"""<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<title>nmfx-top — fleet dashboard</title>
<style>
  :root {{ color-scheme: light dark; }}
  body {{ font: 14px/1.5 system-ui, sans-serif; margin: 24px;
         color: #1f2430; background: #fcfcfd; }}
  @media (prefers-color-scheme: dark) {{
    body {{ color: #e4e6ee; background: #16181f; }}
    table td, table th {{ border-color: #33363f; }}
    .tile {{ border-color: #33363f; }} }}
  h1 {{ font-size: 18px; margin: 0 0 4px; }}
  .sub {{ opacity: .65; margin-bottom: 16px; }}
  table {{ border-collapse: collapse; margin: 8px 0 20px; }}
  th, td {{ border-bottom: 1px solid #e3e5ea; padding: 4px 12px;
            text-align: left; }}
  td.num {{ text-align: right; font-variant-numeric: tabular-nums; }}
  .tiles {{ display: flex; gap: 12px; flex-wrap: wrap;
            margin: 8px 0 20px; }}
  .tile {{ border: 1px solid #e3e5ea; border-radius: 6px;
           padding: 8px 14px; }}
  .tile .label {{ font-size: 12px; opacity: .65; }}
  .tile .value {{ font-size: 18px;
                  font-variant-numeric: tabular-nums; }}
  .chip {{ font-weight: 600; }}
</style></head><body>
<h1>nmfx fleet dashboard</h1>
<div class="sub">telemetry: {esc(telemetry_dir)} · rendered {stamp}
</div>
<h2>Instances</h2>
<div class="sub">roles: {esc(_role_summary(frame["instances"]))}</div>
<table><tr><th>instance</th><th>role</th><th>pid</th><th>device</th>
<th>hb age</th><th>state</th><th>queue</th><th>inflight</th></tr>
{inst_rows or '<tr><td colspan="8">no instances</td></tr>'}</table>
<h2>Serving</h2>
<div class="sub">outcomes: {outcome_row}</div>
<div class="tiles">{stat_tiles}</div>
<h2>SLO burn status</h2>
<table><tr><th>objective</th><th>state</th><th>burn per window</th>
</tr>{slo_rows}</table>
</body></html>
"""


def main(argv: "list[str] | None" = None) -> int:
    p = argparse.ArgumentParser(
        prog="nmfx-top",
        description="Live terminal fleet dashboard over a shared "
                    "telemetry_dir (docs/observability.md 'Fleet "
                    "telemetry'): per-instance liveness/load, merged "
                    "latency quantiles and goodput, MFU, and SLO "
                    "burn-rate status.")
    p.add_argument("telemetry_dir",
                   help="the directory instances publish telemetry "
                        "snapshots into (ServeConfig.telemetry_dir)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh seconds (default 2)")
    p.add_argument("--stale-after", type=float, default=10.0,
                   help="heartbeat age beyond which an instance is "
                        "classified stale (default 10s)")
    p.add_argument("--once", action="store_true",
                   help="render a single frame and exit (rates that "
                        "need a window read n/a)")
    p.add_argument("--html", default=None, metavar="PATH",
                   help="write a static HTML render of one frame to "
                        "PATH and exit")
    args = p.parse_args(argv)
    if args.interval <= 0:
        p.error("--interval must be positive")
    collector = FleetCollector(args.telemetry_dir,
                               stale_after_s=args.stale_after)
    engine = _slo.SLOEngine(snapshot_fn=collector.fleet_snapshot)
    prev = None
    if args.html is not None or args.once:
        frame = gather(collector, engine, prev)
        if args.html is not None:
            with open(args.html, "w") as f:
                f.write(render_html(frame, args.telemetry_dir))
            print(f"nmfx-top: dashboard written to {args.html}",
                  file=sys.stderr)
        if args.once:
            print(render_text(frame, args.telemetry_dir), end="")
        return 0
    try:
        while True:
            frame = gather(collector, engine, prev)
            prev = (frame["t"], frame["snapshot"])
            sys.stdout.write("\x1b[2J\x1b[H"
                             + render_text(frame, args.telemetry_dir))
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
