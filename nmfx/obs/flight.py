"""Crash flight recorder: a bounded ring of recent structured events,
dumped as a redacted JSON postmortem when something dies.

The serve stack's failure paths are loud but EPHEMERAL: "the watchdog
resolved 14 stranded Futures" is a warn-once line, the armed fault
site that killed the scheduler is a log banner, the retries and
degradations that preceded a crash scrolled away minutes earlier.
This module keeps the last ``max_events`` structured events in memory
— dispatches, retries, degradations, fault-site fires, cache
evictions, checkpoint commits, watchdog actions — and on a crash
writes them as one inspectable JSON artifact, the aviation-recorder
shape: cheap enough to run always, read only when something went
wrong.

Event sources (all built in — no call-site opt-in):

* ``nmfx.faults.fire`` records every armed fault FIRE under the
  site's category from :data:`FAULT_EVENTS` (lint rule NMFX008 keeps
  that mapping covering every registered site);
* ``nmfx.faults.warn_once`` records every degradation category the
  moment it first (and, unlike the warning, EVERY time it) fires;
* the serve scheduler/watchdog, both caches' evictions, and the
  checkpoint ledger's commits record their own categories.

Dump triggers: the serve watchdog on a scheduler crash
(``ServerCrashed``), the conftest hang guard just before it kills a
stuck test, and SIGTERM via :func:`install_signal_dump` (explicit
installation only — the fault-registry discipline: nothing in the
environment alone changes behavior). ``dump()`` always builds and
retains the artifact (:func:`last_dump`); it writes to disk only when
a directory was :func:`configure`'d (CLI ``--flight-dir``) or an
explicit path is passed — library code never litters the cwd.

Redaction: payload values are stringified with a length cap and
payloads a key-count cap before they enter the ring — a recorded
event can reference a matrix or exception but never embed one, so a
postmortem is shareable without shipping tenant data.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import deque

from nmfx.guards import guarded_by

__all__ = ["FAULT_EVENTS", "FlightRecorder", "configure",
           "default_recorder", "dump", "fault_event_categories",
           "install_signal_dump", "last_dump", "record"]

#: fault site → flight-recorder event category emitted when the site
#: FIRES (``nmfx.faults.fire`` routes every fire through this mapping).
#: AUTHORITATIVE coverage declaration: lint rule NMFX008
#: cross-references it against ``nmfx.faults.SITES``, so a newly
#: registered fault site that never reaches the flight recorder — a
#: chaos rehearsal whose postmortem would be silent about its own
#: injected failure — fails lint instead of shipping.
FAULT_EVENTS = {
    "h2d.transfer": "fault.h2d.transfer",
    "compile.build": "fault.compile.build",
    "persist.deserialize": "fault.persist.deserialize",
    "harvest.worker": "fault.harvest.worker",
    "serve.scheduler": "fault.serve.scheduler",
    "solve.nonfinite": "fault.solve.nonfinite",
    "sched.stale_reload": "fault.sched.stale_reload",
    "ckpt.write": "fault.ckpt.write",
    "ckpt.load": "fault.ckpt.load",
    "proc.preempt": "fault.proc.preempt",
    "router.forward": "fault.router.forward",
    "replica.spawn": "fault.replica.spawn",
    "replica.heartbeat": "fault.replica.heartbeat",
}


def fault_event_categories() -> frozenset:
    """The fault sites the flight recorder emits fire events for — the
    introspection hook lint rule NMFX008 cross-references (the
    ``data_key_fields``/``manifest_key_fields`` discipline)."""
    return frozenset(FAULT_EVENTS)


#: redaction bounds: a payload VALUE is stringified and truncated, a
#: payload itself capped in keys — events describe, never embed
_MAX_VALUE_CHARS = 240
_MAX_PAYLOAD_KEYS = 16
_DEFAULT_MAX_EVENTS = 4096


def _redact_value(v):
    if v is None or isinstance(v, (bool, int, float)):
        return v
    if isinstance(v, (list, tuple)) and len(v) <= 32 and all(
            isinstance(x, (bool, int, float, str)) for x in v):
        return [_redact_value(x) for x in v]
    s = str(v)
    if len(s) > _MAX_VALUE_CHARS:
        s = s[:_MAX_VALUE_CHARS] + f"…[{len(s)} chars]"
    return s


@guarded_by("_lock", "_events", "_recorded", "_dir", "_last_dump")
class FlightRecorder:
    """Thread-safe bounded event ring + postmortem dump."""

    def __init__(self, max_events: int = _DEFAULT_MAX_EVENTS):
        if max_events < 1:
            raise ValueError("max_events must be >= 1")
        # REENTRANT on purpose: the SIGTERM dump handler runs ON the
        # main thread, possibly while that same thread is inside
        # record() holding this lock — a plain Lock would self-deadlock
        # the process instead of dumping and exiting
        self._lock = threading.RLock()
        self._events: "deque[dict]" = deque(maxlen=max_events)
        self._recorded = 0
        self._dir: "str | None" = None
        self._last_dump: "dict | None" = None
        self._t0 = time.monotonic()

    # -- recording ---------------------------------------------------------
    def record(self, category: str, /, **payload) -> None:
        """Append one structured event. Cheap (one dict + one lock) and
        bounded; payload values are redacted at RECORD time, so nothing
        unbounded is ever retained. ``category`` is positional-only —
        payload keys that would shadow the envelope fields
        (category/thread/timestamps) are prefixed ``payload_``."""
        reserved = {"category", "thread", "t_mono_s", "t_epoch_s"}
        if reserved & payload.keys():
            payload = {(f"payload_{k}" if k in reserved else k): v
                       for k, v in payload.items()}
        items = list(payload.items())
        if len(items) > _MAX_PAYLOAD_KEYS:
            items = items[:_MAX_PAYLOAD_KEYS] + [
                ("redacted_keys", len(payload) - _MAX_PAYLOAD_KEYS)]
        ev = {"t_mono_s": round(time.monotonic() - self._t0, 6),
              "t_epoch_s": round(time.time(), 3),
              "thread": threading.current_thread().name,
              "category": category,
              **{k: _redact_value(v) for k, v in items}}
        with self._lock:
            self._events.append(ev)
            self._recorded += 1

    def events(self, category: "str | None" = None) -> "list[dict]":
        """Snapshot of retained events, oldest first; optionally
        filtered by exact category."""
        with self._lock:
            evs = [dict(e) for e in self._events]
        if category is not None:
            evs = [e for e in evs if e["category"] == category]
        return evs

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._recorded - len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._recorded = 0

    # -- dumping -----------------------------------------------------------
    def configure(self, directory: "str | None") -> None:
        """Set (or with None, unset) the dump directory. Dumps are
        written only when a directory is configured or an explicit
        path is passed — never implicitly to the cwd."""
        with self._lock:
            self._dir = directory

    def dump(self, reason: str, path: "str | None" = None,
             extra: "dict | None" = None) -> "str | None":
        """Build the postmortem artifact (always retained —
        :meth:`last_dump`) and write it when a destination exists.
        Returns the written path, or None when nothing was written.
        Best-effort by design: a failing disk must not mask the crash
        being reported (write failures degrade to the in-memory
        artifact, warn-once)."""
        from nmfx import faults as _faults

        artifact = {
            "reason": reason,
            "t_epoch_s": round(time.time(), 3),
            "pid": os.getpid(),
            "armed_fault_sites": {
                site: str(_faults.armed(site))
                for site in _faults.SITES
                if _faults.armed(site) is not None},
            "dropped_events": self.dropped,
            "events": self.events(),
        }
        # perf/SLO context (ISSUE 14): a crash artifact used to carry
        # fault events but nothing about what the process was DOING —
        # the last per-dispatch roofline attributions (the ISSUE 13
        # drill-down ring) and the latest SLO status ride along, each
        # best-effort (a broken sibling module must not mask the crash
        # being reported)
        try:
            from nmfx.obs import costmodel as _costmodel

            artifact["perf_recent"] = [
                {k: _redact_value(v) for k, v in rec.items()}
                for rec in _costmodel.recent_attributions(limit=32)]
        except Exception:  # nmfx: ignore[NMFX006] -- best-effort
            artifact["perf_recent"] = []  # context only
        try:
            from nmfx.obs import slo as _slo

            artifact["slo"] = _slo.last_status()
        except Exception:  # nmfx: ignore[NMFX006] -- best-effort
            artifact["slo"] = None        # context only
        if extra:
            artifact["extra"] = {k: _redact_value(v)
                                 for k, v in extra.items()}
        with self._lock:
            self._last_dump = artifact
            directory = self._dir
        if path is None and directory is not None:
            safe = "".join(c if c.isalnum() or c in "-._" else "-"
                           for c in reason)
            path = os.path.join(
                directory, f"flight_{os.getpid()}_{safe}.json")
        if path is None:
            return None
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(artifact, f, indent=1)
            os.replace(tmp, path)
        except OSError as e:
            _faults.warn_once(
                "flight-dump-failed",
                f"could not write flight-recorder dump to {path!r} "
                f"({e}); the postmortem stays available in-process via "
                "nmfx.obs.flight.last_dump()")
            return None
        return path

    def last_dump(self) -> "dict | None":
        """The most recently built postmortem artifact (written to
        disk or not)."""
        with self._lock:
            return self._last_dump


_recorder = FlightRecorder()


def default_recorder() -> FlightRecorder:
    """The process-wide recorder every nmfx subsystem records into."""
    return _recorder


def record(category: str, /, **payload) -> None:
    """Record one event on the process-wide recorder."""
    _recorder.record(category, **payload)


def configure(directory: "str | None") -> None:
    """Point crash dumps at ``directory`` (CLI ``--flight-dir``)."""
    _recorder.configure(directory)


def dump(reason: str, path: "str | None" = None,
         extra: "dict | None" = None) -> "str | None":
    """Dump the process-wide recorder (see :meth:`FlightRecorder.dump`)."""
    return _recorder.dump(reason, path=path, extra=extra)


def last_dump() -> "dict | None":
    return _recorder.last_dump()


def install_signal_dump():
    """Hook SIGTERM so an external kill leaves a postmortem: the
    handler dumps the flight recorder, then defers to the previous
    disposition (the ``checkpoint.install_signal_flush`` contract —
    a previously-installed handler still runs, the default disposition
    still terminates). Explicit installation only (the CLI installs it
    alongside ``--flight-dir``); returns a zero-argument restore
    callable, a no-op off the main thread."""
    installed: dict = {}

    def _handler(signum, frame):
        _recorder.dump(f"signal-{signal.Signals(signum).name}")
        prev = installed.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev is signal.SIG_IGN:
            return
        else:
            raise SystemExit(128 + signum)

    try:
        installed[signal.SIGTERM] = signal.signal(signal.SIGTERM,
                                                  _handler)
    except ValueError:
        # not the main interpreter thread: nothing was installed
        return lambda: None

    def restore():
        for sig, prev in installed.items():
            signal.signal(sig, prev)
    return restore
