"""Fleet aggregation: merge N instance telemetry snapshots into one view.

The collector half of the fleet observatory (ISSUE 14): read every
``telemetry_*.json`` an instance published into a shared
``telemetry_dir`` (``nmfx.obs.export``) and merge them into ONE
registry-snapshot-shaped fleet view, mirroring the single-process API —
:meth:`FleetCollector.fleet_snapshot` / :meth:`FleetCollector
.fleet_delta` are the cross-process ``MetricsRegistry.snapshot`` /
``delta``, and :meth:`FleetCollector.prometheus_text` renders through
the identical formatter (``metrics.render_prometheus``).

Merge semantics (docs/observability.md "Fleet telemetry"):

* **Counters sum** across instances — a fleet total is the sum of
  per-process totals, exactly (pinned by tests/test_fleet.py against
  subprocess publishers).
* **Gauges key by instance**: a gauge is a per-process LEVEL (queue
  depth, inflight), so summing would fabricate a meaningless number;
  each series gains a leading ``instance`` label instead, and the
  fleet view keeps every replica's level addressable.
* **Histograms merge bucket-wise**: counts, sums, and per-bucket
  counts add; min/max combine. Because the state is a pure bucket sum,
  a quantile over the merged state (``metrics.bucket_quantile``)
  EQUALS the quantile of one histogram that observed the union of all
  instances' observations — the merged-quantile exactness contract.
* **Staleness drops gauges, keeps counters.** An instance whose
  heartbeat (the snapshot's embedded ``time``) is older than
  ``stale_after_s`` is dead-until-proven-alive: its gauges describe a
  level that no longer exists and drop from the fleet view, while its
  counters/histograms are monotone history that still happened and
  stay in the fleet totals.
* **Torn tolerance.** Unreadable / foreign-format / non-dict files are
  skipped warn-once (the checkpoint ledger's torn-record discipline) —
  one crashed writer can never take the fleet view down. Cross-
  instance schema conflicts (same metric name, different type, labels,
  or buckets) are resolved deterministically: the FIRST instance (by
  sorted instance name) to declare a metric fixes its schema, and
  every conflicting later instance's series for that metric is skipped
  warn-once rather than merged apples-into-oranges — a conflict is a
  deployment bug (mixed incompatible versions) the warn-once surfaces;
  the merge just refuses to hide it behind a corrupted sum.

Stdlib-only, like the rest of ``nmfx.obs``.
"""

from __future__ import annotations

import json
import os
import time

from nmfx.obs import metrics as _metrics
from nmfx.obs.export import FILE_PREFIX, FORMAT_VERSION

__all__ = ["FleetCollector", "merge_payloads"]


def _load_payloads(telemetry_dir: str) -> "dict[str, dict]":
    """Read every telemetry snapshot in the directory; torn/foreign
    files are skipped warn-once."""
    from nmfx.faults import warn_once

    out: "dict[str, dict]" = {}
    try:
        names = sorted(os.listdir(telemetry_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(FILE_PREFIX)
                and name.endswith(".json")):
            continue
        path = os.path.join(telemetry_dir, name)
        try:
            with open(path) as f:
                payload = json.load(f)
            if not isinstance(payload, dict) \
                    or payload.get("format") != FORMAT_VERSION \
                    or not isinstance(payload.get("metrics"), dict):
                raise ValueError("not a telemetry snapshot "
                                 f"(format {payload.get('format')!r})"
                                 if isinstance(payload, dict)
                                 else "not a JSON object")
        except (OSError, ValueError) as e:
            warn_once(
                "fleet-snapshot-torn",
                f"telemetry snapshot {path!r} is torn/corrupt/foreign "
                f"({e}); skipping it — the writing instance reads as "
                "stale until it publishes a good snapshot")
            continue
        instance = str(payload.get("instance") or name)
        out[instance] = payload
    return out


def merge_payloads(payloads: "dict[str, dict]",
                   stale: "frozenset[str] | set[str]" = frozenset()
                   ) -> dict:
    """Pure merge of instance payloads (``{instance: payload}``) into
    one registry-snapshot-shaped dict (series keyed by label-value
    TUPLES, like ``MetricsRegistry.snapshot``), applying the module
    docstring's semantics. ``stale`` names the instances whose gauges
    drop. Factored pure so tests can merge handcrafted universes."""
    from nmfx.faults import warn_once

    merged: dict = {}
    for instance in sorted(payloads):
        payload = payloads[instance]
        is_stale = instance in stale
        for name, entry in payload["metrics"].items():
            kind = entry.get("type")
            labels = tuple(entry.get("labels", ()))
            buckets = tuple(entry.get("buckets", ()) or ())
            if kind == "gauge" and is_stale:
                continue
            out_labels = (("instance",) + labels if kind == "gauge"
                          else labels)
            rec = merged.get(name)
            if rec is None:
                rec = merged[name] = {
                    "type": kind, "labels": out_labels,
                    "help": entry.get("help", ""), "series": {}}
                if kind == "histogram":
                    rec["buckets"] = buckets
            elif (rec["type"] != kind or rec["labels"] != out_labels
                  or (kind == "histogram"
                      and rec["buckets"] != buckets)):
                warn_once(
                    "fleet-metric-conflict",
                    f"instance {instance!r} publishes metric {name!r} "
                    f"as {kind} labels={out_labels} "
                    f"buckets={buckets or None}, conflicting with the "
                    "schema fixed by the first (sorted) instance that "
                    "declared it; skipping this instance's series for "
                    "this metric — mixed incompatible versions in one "
                    "fleet is a deployment bug, and a merge across two "
                    "schemas would hide it behind a corrupted sum")
                continue
            for srec in entry.get("series", ()):
                key = tuple(str(v) for v in srec["key"])
                val = srec["value"]
                if kind == "counter":
                    rec["series"][key] = rec["series"].get(key, 0.0) \
                        + float(val)
                elif kind == "gauge":
                    rec["series"][(instance,) + key] = float(val)
                elif kind == "histogram":
                    cur = rec["series"].get(key)
                    if cur is None:
                        rec["series"][key] = {
                            "count": int(val["count"]),
                            "sum": float(val["sum"]),
                            "min": val["min"], "max": val["max"],
                            "bucket_counts":
                                list(val["bucket_counts"])}
                    else:
                        _metrics.merge_bucket_state(
                            cur, {"count": int(val["count"]),
                                  "sum": float(val["sum"]),
                                  "min": val["min"],
                                  "max": val["max"],
                                  "bucket_counts":
                                      val["bucket_counts"]})
                else:
                    rec["series"][(instance,) + key] = val
    return merged


class FleetCollector:
    """Merge a ``telemetry_dir``'s instance snapshots into one fleet
    view (see the module docstring for the semantics)."""

    def __init__(self, telemetry_dir: str, *,
                 stale_after_s: float = 10.0):
        if stale_after_s <= 0:
            raise ValueError("stale_after_s must be positive")
        self.telemetry_dir = telemetry_dir
        self.stale_after_s = stale_after_s

    # -- raw collection ----------------------------------------------------
    def collect(self) -> "dict[str, dict]":
        """``{instance: payload}`` of every readable snapshot."""
        return _load_payloads(self.telemetry_dir)

    def instances(self, now: "float | None" = None,
                  payloads: "dict[str, dict] | None" = None
                  ) -> "list[dict]":
        """Per-instance identity + liveness rows (the ``nmfx-top``
        instance table): instance, pid, host, role, device kind,
        heartbeat age, and the stale classification. Pass ``payloads``
        (an earlier :meth:`collect`) to derive the rows from the same
        ledger read as a sibling :meth:`fleet_snapshot` — one frame,
        one consistent cut."""
        now = time.time() if now is None else now
        if payloads is None:
            payloads = self.collect()
        rows = []
        for instance, payload in payloads.items():
            age = now - float(payload.get("time", 0.0))
            row = {
                "instance": instance,
                "pid": payload.get("pid"),
                "host": payload.get("host"),
                "role": payload.get("role"),
                "device_kind": payload.get("device_kind"),
                "seq": payload.get("seq"),
                "heartbeat_age_s": round(age, 3),
                "stale": age > self.stale_after_s,
            }
            # per-instance status levels embedded in the payload
            # (ISSUE 15): the honest queue/inflight signal when N
            # replicas share one process registry — see
            # export.build_snapshot
            status = payload.get("status")
            if isinstance(status, dict):
                row.update({k: v for k, v in status.items()
                            if k not in row})
            rows.append(row)
        return rows

    def _stale_set(self, payloads: dict,
                   now: "float | None") -> "set[str]":
        now = time.time() if now is None else now
        return {instance for instance, payload in payloads.items()
                if now - float(payload.get("time", 0.0))
                > self.stale_after_s}

    # -- the registry-API mirror -------------------------------------------
    def fleet_snapshot(self, now: "float | None" = None,
                       payloads: "dict[str, dict] | None" = None
                       ) -> dict:
        """The merged fleet view, shaped exactly like
        ``MetricsRegistry.snapshot()`` (plus ``help``/``buckets``
        enrichment) — every consumer of a process snapshot (the SLO
        engine, ``snapshot_delta``, the Prometheus renderer) consumes
        this unchanged. ``payloads`` reuses an earlier
        :meth:`collect` read instead of re-scanning the ledger."""
        if payloads is None:
            payloads = self.collect()
        return merge_payloads(payloads,
                              self._stale_set(payloads, now))

    def fleet_delta(self, prev: dict,
                    now: "float | None" = None) -> dict:
        """What changed fleet-wide since ``prev`` (an earlier
        :meth:`fleet_snapshot`) — ``metrics.snapshot_delta``, the same
        arithmetic as the single-process ``MetricsRegistry.delta``."""
        return _metrics.snapshot_delta(self.fleet_snapshot(now), prev)

    def prometheus_text(self, now: "float | None" = None) -> str:
        """Merged Prometheus exposition — the fleet's ``/metrics``."""
        return _metrics.render_prometheus(self.fleet_snapshot(now))

    def quantile(self, metric: str, q: float,
                 snapshot: "dict | None" = None,
                 **labels) -> "float | None":
        """Bucket-interpolated quantile of one merged histogram series
        (``metrics.bucket_quantile`` over the merged state — equals
        the union-of-observations quantile)."""
        snap = snapshot if snapshot is not None else \
            self.fleet_snapshot()
        rec = snap.get(metric)
        if rec is None or rec["type"] != "histogram":
            return None
        key = tuple(str(labels[name]) for name in rec["labels"]
                    if name in labels)
        if len(key) != len(rec["labels"]):
            raise ValueError(
                f"expected labels {rec['labels']}, got {tuple(labels)}")
        st = rec["series"].get(key)
        if st is None:
            return None
        return _metrics.bucket_quantile(rec["buckets"], st, q)
