"""Per-process telemetry export: snapshot publishing + a /metrics port.

Every telemetry surface built so far is process-local — one
``NMFXServer.metrics_text()``, one Chrome trace, one postmortem — while
the deployments the ROADMAP targets are multi-process (replicated
servers, ``ElasticShardRunner`` shards over the heartbeat ledger,
bench subprocess children). This module is the per-process HALF of the
fleet observatory (ISSUE 14): each process periodically writes an
atomic JSON snapshot of its metrics registry plus its instance
identity into a shared ``telemetry_dir`` — the ``SweepCheckpoint
.heartbeat`` ledger idiom (``shard_<i>.json``), generalized from shard
progress to the full registry — and the collector
(``nmfx.obs.aggregate``) merges N such snapshots into one fleet view.

Design rules:

* **Atomic tmp+rename, torn-tolerant.** A snapshot file is written via
  ``telemetry_<instance>.json.tmp.<pid>`` + ``os.replace`` (the
  checkpoint ledger's write discipline), so a reader can never observe
  a half-written file; the collector still tolerates torn files
  (warn-once skip) because a crashed writer may leave a stale one.
* **Heartbeat = the snapshot's ``time``.** Liveness is the file's
  embedded wall-clock timestamp, not mtime (NFS/container clock skew
  on mtime is real; the embedded time is what the process asserted).
* **Stdlib-only, jax-optional.** Like the rest of ``nmfx.obs`` this
  module never imports jax; ``device_kind`` is read from jax ONLY when
  the process already imported it (``sys.modules``) — publishing from
  a jax-free collector/CLI process reports ``"unknown"`` rather than
  dragging a backend up.
* **Optional pull endpoint.** :func:`serve_metrics` exposes the same
  registry as a stdlib ``http.server`` Prometheus endpoint for
  scraper-based deployments; the snapshot ledger stays the primary
  path because it needs no port coordination and survives the process
  (a dead replica's last snapshot is still mergeable — counters
  retained, gauges dropped by staleness; ``nmfx.obs.aggregate``).

See docs/observability.md "Fleet telemetry" for the ledger layout.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time

from nmfx.obs import metrics as _metrics

__all__ = ["HeartbeatLedger", "TelemetryPublisher", "build_snapshot",
           "serve_metrics", "snapshot_path"]

#: snapshot format version — the collector skips (warn-once) files
#: written by a future incompatible format instead of misreading them
FORMAT_VERSION = 1

#: telemetry snapshot filenames in a telemetry_dir; distinct from the
#: checkpoint ledger's shard_<i>.json heartbeats and flight_*.json
#: postmortems so every ledger can share one directory
FILE_PREFIX = "telemetry_"

_publishes_total = _metrics.counter(
    "nmfx_telemetry_publishes_total",
    "telemetry snapshots published to the shared telemetry_dir")


def _device_kind() -> str:
    """Best-effort device kind WITHOUT initializing a backend: read
    jax only when the process already imported it."""
    jax = sys.modules.get("jax")
    if jax is None:
        return "unknown"
    try:
        return str(jax.devices()[0].device_kind)
    except Exception:  # nmfx: ignore[NMFX006] -- identity is advisory;
        return "unknown"  # a backend error must not break publishing


def _safe_instance(instance: str) -> str:
    return "".join(c if c.isalnum() or c in "-._" else "-"
                   for c in instance)


def snapshot_path(telemetry_dir: str, instance: str) -> str:
    """The ledger filename one instance publishes to."""
    return os.path.join(telemetry_dir,
                        f"{FILE_PREFIX}{_safe_instance(instance)}.json")


# --------------------------------------------------------------------------
class HeartbeatLedger:
    """Atomic per-instance JSON heartbeats in a shared directory — the
    ``shard_<i>.json`` idiom of the durable sweep ledger
    (``SweepCheckpoint.heartbeat``/``shard_status``), factored out
    (ISSUE 15) so every liveness consumer shares ONE write/read
    discipline: elastic shards, replica pools behind a router, and
    anything else that needs cheap cross-process "I am alive and here
    is my level" signaling without serializing a full registry
    snapshot.

    Semantics (the telemetry ledger's, scaled down):

    * one file per instance, ``<prefix><instance>.json``, written via
      tmp+rename — a reader can never observe a torn file from a live
      writer, and a torn file from a crashed writer reads as staleness;
    * liveness is the payload's embedded wall-clock ``time`` (what the
      process asserted), never mtime;
    * writes are best-effort: a heartbeat is a side channel, and an
      unwritable ledger must never take the heartbeating path down.
    """

    def __init__(self, directory: str, *, prefix: str = "hb_"):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.prefix = prefix

    def path(self, instance: str) -> str:
        return os.path.join(
            self.directory,
            f"{self.prefix}{_safe_instance(str(instance))}.json")

    def beat(self, instance: str, **info) -> "str | None":
        """Write one heartbeat (payload = ``info`` + pid + time);
        returns the path, or None when the write failed (best-effort
        by design — completion records / telemetry snapshots stay the
        ground truth)."""
        path = self.path(instance)
        payload = dict(info, instance=str(instance), pid=os.getpid(),
                       time=time.time())
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wt") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:  # nmfx: ignore[NMFX006] -- liveness side-channel
            return None  # only; see the class docstring
        return path

    def read(self, instance: str) -> "dict | None":
        try:
            with open(self.path(instance)) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            # nmfx: ignore[NMFX006] -- a torn heartbeat IS staleness
            return None
        return payload if isinstance(payload, dict) else None

    def status(self, stale_after_s: "float | None" = None) -> dict:
        """``{instance: payload}`` for every readable heartbeat; with
        ``stale_after_s`` each payload gains ``stale`` and ``age_s``
        from its embedded write time."""
        out: dict = {}
        now = time.time()
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return out
        for name in names:
            if not (name.startswith(self.prefix)
                    and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    payload = json.load(f)
            except (OSError, json.JSONDecodeError):
                # nmfx: ignore[NMFX006] -- a torn heartbeat IS staleness
                continue
            if not isinstance(payload, dict):
                continue
            age = now - float(payload.get("time", 0.0))
            if stale_after_s is not None:
                payload["age_s"] = round(age, 3)
                payload["stale"] = age > stale_after_s
            key = payload.get("instance",
                              name[len(self.prefix):-len(".json")])
            out[key] = payload
        return out


def build_snapshot(registry: "_metrics.MetricsRegistry | None" = None,
                   *, instance: str = "", role: str = "process",
                   seq: int = 0, status: "dict | None" = None) -> dict:
    """One publishable snapshot: instance identity (instance name, pid,
    host, role, device kind), the heartbeat timestamp, and the full
    registry snapshot enriched with each metric's help text and (for
    histograms) bucket bounds — everything the collector needs to
    merge and re-export without importing the publishing process's
    modules. Series label-tuples serialize as lists (JSON has no
    tuples); the collector converts them back. ``status`` is an
    optional small dict of per-INSTANCE levels (queue depth, inflight)
    riding the payload itself — the honest load signal when several
    instances share one process registry (N in-process replicas would
    overwrite each other's process-wide gauges), surfaced on the
    collector's instance rows and the ``nmfx-top`` table."""
    reg = registry if registry is not None else _metrics.registry()
    snap = reg.snapshot()
    payload_metrics: dict = {}
    for name, rec in snap.items():
        m = reg.get(name)
        entry = {
            "type": rec["type"],
            "labels": list(rec["labels"]),
            "help": m.help if m is not None else "",
            "series": [{"key": list(key), "value": val}
                       for key, val in rec["series"].items()],
        }
        if rec["type"] == "histogram" and m is not None:
            entry["buckets"] = list(m.buckets)
        payload_metrics[name] = entry
    payload = {
        "format": FORMAT_VERSION,
        "instance": instance,
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "role": role,
        "device_kind": _device_kind(),
        "time": time.time(),
        "seq": seq,
        "metrics": payload_metrics,
    }
    if status:
        payload["status"] = dict(status)
    return payload


class TelemetryPublisher:
    """Daemon-thread publisher: writes this process's registry snapshot
    into ``telemetry_dir`` every ``interval_s`` (atomic tmp+rename).
    ``publish_once()`` is the deterministic single-shot form tests and
    the bench rung drive directly; :meth:`close` publishes one final
    snapshot (so shutdown-time counters land) and stops the thread.
    Write failures degrade warn-once — telemetry is a side channel and
    must never take the serving path down with it."""

    def __init__(self, telemetry_dir: str, *,
                 instance: "str | None" = None, role: str = "server",
                 interval_s: float = 2.0,
                 registry: "_metrics.MetricsRegistry | None" = None,
                 status_fn=None):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        os.makedirs(telemetry_dir, exist_ok=True)
        self.telemetry_dir = telemetry_dir
        self.role = role
        self.instance = instance if instance is not None else \
            f"{role}-{socket.gethostname()}-{os.getpid()}"
        self.path = snapshot_path(telemetry_dir, self.instance)
        self.interval_s = interval_s
        self._registry = registry
        #: optional callable returning the per-instance ``status`` dict
        #: embedded in each snapshot (see build_snapshot) — a failing
        #: status_fn degrades to no status, never a missed heartbeat
        self._status_fn = status_fn
        self._seq = 0
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def __enter__(self) -> "TelemetryPublisher":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def publish_once(self) -> "str | None":
        """Build + atomically write one snapshot; returns the path, or
        None when the write failed (warn-once)."""
        from nmfx.faults import warn_once

        status = None
        if self._status_fn is not None:
            try:
                status = self._status_fn()
            except Exception as e:  # nmfx: ignore[NMFX006] -- degrades
                # to a status-less (still live) heartbeat, warn-once'd
                warn_once("telemetry-status-fn-failed",
                          f"telemetry status_fn failed ({e!r}); "
                          "publishing without per-instance status")
                status = None
        payload = build_snapshot(self._registry, instance=self.instance,
                                 role=self.role, seq=self._seq,
                                 status=status)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
        except OSError as e:
            try:
                os.unlink(tmp)
            except OSError:  # nmfx: ignore[NMFX006] -- tmp never
                pass         # created / already gone
            warn_once(
                "telemetry-publish-failed",
                f"could not publish telemetry snapshot to "
                f"{self.path!r} ({e}); this instance goes stale in the "
                "fleet view until a write succeeds")
            return None
        self._seq += 1
        _publishes_total.inc()
        return self.path

    def _run(self) -> None:
        while not self._stop.is_set():
            self.publish_once()
            self._stop.wait(self.interval_s)

    def start(self) -> "TelemetryPublisher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"nmfx-telemetry-{self.instance}")
            self._thread.start()
        return self

    def close(self) -> None:
        """Stop the thread and publish one final snapshot — shutdown-
        time counter totals must reach the ledger (the collector keeps
        a dead instance's counters; only its gauges drop)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        self.publish_once()


def serve_metrics(port: int = 0, *,
                  registry: "_metrics.MetricsRegistry | None" = None,
                  host: str = "127.0.0.1"):
    """Serve the registry's Prometheus text exposition over a stdlib
    ``http.server`` endpoint on a daemon thread (every path returns the
    payload — scrapers conventionally hit ``/metrics``). ``port=0``
    binds an ephemeral port; read the bound one from the returned
    server's ``.port``. Call ``.shutdown()`` to stop (the serve layer
    does, on ``NMFXServer.close`` — ``ServeConfig.metrics_port``)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else _metrics.registry()

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server's casing
            body = reg.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass  # a scrape per interval must not spam stderr

    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    server.port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name=f"nmfx-metrics-http-{server.port}")
    thread.start()
    return server
