"""Structured fault injection + the typed failure taxonomy of the
serve stack.

A server that survives failures must be able to REHEARSE them: every
recovery path in this repo (solo retry after a failed packed dispatch,
direct h2d after a data-cache placement failure, sequential harvest
after a worker death, the scheduler watchdog, the in-kernel numeric
quarantine) is exercised by arming a named fault site from this
registry and asserting the recovery contract — bit-identical results
where recovery is exact, a typed error otherwise, bounded wall time
always (tests/test_faults.py; bench.py's ``detail.serve.chaos`` rung).
The distributed-NMF literature treats per-worker failure/recovery as
first-class (MPI-FAUN, arxiv 1609.09154; out-of-memory tile streaming,
arxiv 2202.09518, is only viable if a lost tile is recoverable); this
module is the single-device analogue.

Design rules, learned from the retired ``NMFX_FAULT_INJECT_STALE_RELOAD``
env hook (ADVICE.md round 5; lint rule NMFX002):

* **Explicit arming only.** A site fires only after an in-process
  :func:`arm` call (or :func:`scoped`). Environment variables alone are
  inert — an inherited var can never corrupt a run.
* **Deterministic and seeded.** Hit-counted sites fire on an exact
  schedule (``every``-th hit, at most ``max_fires`` times); lane-rate
  sites select lanes by a splitmix of ``(seed, k, restart)``
  (:func:`poison_restarts`), never by wall clock or host RNG.
* **Trace-honest.** The two sites that alter TRACED code
  (``solve.nonfinite``, ``sched.stale_reload``) are keyed into every
  builder/executable cache through :func:`trace_token`, a
  content-addressed tuple of the armed specs themselves: an armed
  process can never silently serve a clean (or differently-armed,
  even from another process via the persistent disk cache) executable
  — the staleness class the old env hook suffered from — and an
  UNARMED process's cache keys are byte-identical to before this
  module existed.
* **Loud.** Arming any site logs a warning banner: results from an
  armed process are suspect by construction.

Sites (see docs/serving.md "Failure model" for the recovery matrix):

==================== ====================================================
``h2d.transfer``      the data cache's host→device input transfer
``compile.build``     the exec cache's AOT trace+compile
``persist.deserialize``  reading a persisted executable back from disk
``harvest.worker``    a harvest worker thread (serve + pipeline)
``serve.scheduler``   the serving scheduler loop (thread death)
``solve.nonfinite``   a restart lane's factors go non-finite in-kernel
``sched.stale_reload``  the slot scheduler's reload factor write (the
                      round-3 signature; ``bench.py --verify`` gate)
``ckpt.write``        a durable-ledger record/spill write
                      (``nmfx/checkpoint.py``; degrades warn-once)
``ckpt.load``         reading a completion record back from the ledger
                      (torn-record tolerance: skip + warn + re-run)
``proc.preempt``      process preemption between a chunk's solve and
                      its commit (raises ``checkpoint.Preempted`` —
                      BaseException, unswallowable; kill-and-resume
                      chaos for tests, bench ``detail.durability``, and
                      the elastic shard runner)
``router.forward``    the router's forward-to-replica step
                      (``nmfx/router.py``; recovery = backoff retry on
                      ANOTHER replica, at-most-once dispatch preserved)
``replica.spawn``     replica-pool scale-up (``nmfx/replica.py``; a
                      failed spawn degrades warn-once — the fleet keeps
                      serving at its current size)
``replica.heartbeat`` a replica's heartbeat/telemetry publication (the
                      frozen-publisher rehearsal: the replica keeps
                      serving but reads as stale, and the router drains
                      it — queued requests land on survivors)
==================== ====================================================
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
import warnings

from nmfx.obs import flight as _flight

__all__ = ["SITES", "FaultConfig", "FaultInjected", "InsufficientRestarts",
           "arm", "disarm", "armed", "fire", "fires", "hits", "inject",
           "poison_restarts", "scoped", "trace_token", "warn_once"]

#: every registered fault site (arming an unknown site is an error, so a
#: typo'd chaos test fails loudly instead of silently testing nothing)
SITES = ("h2d.transfer", "compile.build", "persist.deserialize",
         "harvest.worker", "serve.scheduler", "solve.nonfinite",
         "sched.stale_reload", "ckpt.write", "ckpt.load",
         "proc.preempt", "router.forward", "replica.spawn",
         "replica.heartbeat")

#: sites whose armed state changes TRACED code and therefore must key
#: the builder/executable caches (see trace_token)
_TRACE_SITES = ("solve.nonfinite", "sched.stale_reload")

#: sites configured by a per-lane/per-reload ``rate`` (or explicit
#: ``lanes``) instead of the hit counter
_RATE_SITES = ("solve.nonfinite", "sched.stale_reload")

_log = logging.getLogger("nmfx")


class FaultInjected(RuntimeError):
    """Raised at an armed hit-counted fault site. Carries the site name
    so recovery tests can assert WHICH failure they survived."""

    def __init__(self, site: str, hit: int):
        super().__init__(
            f"injected fault at site {site!r} (hit #{hit}) — this "
            "process has fault injection armed; results are part of a "
            "chaos rehearsal, not production output")
        self.site = site
        self.hit = hit


class InsufficientRestarts(RuntimeError):
    """A rank's surviving (non-quarantined) restarts fell below the
    configured floor (``ConsensusConfig.min_restarts`` /
    ``nmfconsensus(min_restarts=...)``): too many lanes stopped with
    ``StopReason.NUMERIC_FAULT`` for the consensus to be trustworthy.
    The quarantine masks a diverged lane exactly like a pad lane, so a
    FEW faulted restarts degrade gracefully; this error is the loud
    floor under that degradation."""


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """One armed site's firing policy (see :func:`arm`)."""

    site: str
    #: hit-counted sites: fire on every ``every``-th hit of the site
    every: int = 1
    #: stop firing (stay armed, inert) after this many fires; None =
    #: unlimited
    max_fires: "int | None" = None
    #: lane-rate sites: fraction of lanes/reloads faulted, selected
    #: deterministically from ``seed`` (``solve.nonfinite``,
    #: ``sched.stale_reload``)
    rate: "float | None" = None
    #: seed of the deterministic lane selection
    seed: int = 0
    #: explicit ``((k, restart), ...)`` lanes for ``solve.nonfinite`` —
    #: overrides ``rate`` (the exactness tests poison one named lane)
    lanes: "tuple | None" = None

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; registered sites: "
                f"{SITES}")
        if self.every < 1:
            raise ValueError("every must be >= 1")
        if self.max_fires is not None and self.max_fires < 1:
            raise ValueError("max_fires must be >= 1 or None")
        if self.rate is not None and not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")
        if self.lanes is not None:
            lanes = tuple((int(k), int(r)) for k, r in self.lanes)
            object.__setattr__(self, "lanes", lanes)
        if self.site in _RATE_SITES and self.rate is None \
                and self.lanes is None:
            raise ValueError(
                f"site {self.site!r} is lane-rate-armed: pass rate= "
                "(a fraction) or, for solve.nonfinite, explicit lanes=")


_lock = threading.Lock()
_specs: "dict[str, FaultConfig]" = {}
_hits: "dict[str, int]" = {}
_fires: "dict[str, int]" = {}


def arm(site: str, **kw) -> FaultConfig:
    """Arm ``site`` with a :class:`FaultConfig` built from ``kw``.
    Re-arming replaces the previous policy and resets the site's hit
    and fire counters. Logs a loud banner: an armed process's results
    are rehearsal output."""
    spec = FaultConfig(site=site, **kw)
    with _lock:
        _specs[site] = spec
        _hits[site] = 0
        _fires[site] = 0
    _log.warning(
        "fault site %r ARMED (%s): failures are being injected "
        "deliberately — results from this process are a chaos "
        "rehearsal", site, spec)
    _flight.record("fault.armed", site=site, spec=spec)
    return spec


def disarm(site: "str | None" = None) -> None:
    """Disarm one site (or every site, with ``None``). Counters are
    kept readable until the next :func:`arm`."""
    with _lock:
        if site is None:
            _specs.clear()
        else:
            _specs.pop(site, None)


def armed(site: str) -> "FaultConfig | None":
    """The site's armed policy, or None."""
    with _lock:
        return _specs.get(site)


def hits(site: str) -> int:
    """How many times the site was REACHED since it was last armed."""
    with _lock:
        return _hits.get(site, 0)


def fires(site: str) -> int:
    """How many times the site actually FIRED since it was last armed."""
    with _lock:
        return _fires.get(site, 0)


@contextlib.contextmanager
def scoped(site: str, **kw):
    """Arm ``site`` for the duration of a ``with`` block, restoring the
    previous (usually unarmed) policy on exit — the chaos suite's
    bread-and-butter shape."""
    with _lock:
        prev = _specs.get(site)
    spec = arm(site, **kw)
    try:
        yield spec
    finally:
        if prev is None:
            disarm(site)
        else:
            arm(prev.site, **{f.name: getattr(prev, f.name)
                              for f in dataclasses.fields(prev)
                              if f.name != "site"})


def fire(site: str) -> bool:
    """Count one hit of ``site``; True when this hit should fault.
    Unarmed sites cost one dict lookup under a lock and return False —
    cheap enough for the host-side hot paths they sit on."""
    with _lock:
        spec = _specs.get(site)
        if spec is None:
            return False
        _hits[site] = _hits.get(site, 0) + 1
        if spec.max_fires is not None and _fires[site] >= spec.max_fires:
            return False
        if _hits[site] % spec.every != 0:
            return False
        _fires[site] += 1
        hit = _hits[site]
    # flight-recorder event per FIRE (outside the lock; the recorder
    # has its own): the postmortem of a chaos run must show which
    # injected failures actually landed, not just what was armed —
    # lint rule NMFX008 keeps FAULT_EVENTS covering every site
    _flight.record(_flight.FAULT_EVENTS.get(site, f"fault.{site}"),
                   site=site, hit=hit)
    return True


def inject(site: str) -> None:
    """Raise :class:`FaultInjected` when this hit of ``site`` fires
    (the one-liner the instrumented host paths call)."""
    if fire(site):
        raise FaultInjected(site, hits(site))


# -- trace-affecting sites ------------------------------------------------
def trace_token() -> "tuple | None":
    """Hashable token the sweep builders / exec-cache keys include so
    TRACED fault state can never go stale in a cached executable: None
    while no trace-affecting site is armed (cache keys unchanged vs a
    fault-free build), else a tuple of the armed trace-affecting
    specs themselves. CONTENT-addressed, not generation-stamped: the
    token (and hence every in-memory AND persistent-disk executable
    key) differs exactly when the armed fault plan differs — two
    processes arming different lane sets can never collide on one
    persisted executable, re-arming the identical spec correctly
    reuses the already-built poisoned executable, and a ``scoped``
    block restores the surrounding build's keys on exit instead of
    forcing a spurious recompile."""
    with _lock:
        armed_specs = tuple((s, _specs[s]) for s in _TRACE_SITES
                            if s in _specs)
    if not armed_specs:
        return None
    return ("nmfx-faults", armed_specs)


def _mix01(*vals: int) -> float:
    """Deterministic uniform [0, 1) from integers — splitmix64-style,
    stable across processes (never Python ``hash``)."""
    x = 0
    for v in vals:
        x = (x + int(v) + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFFFFFFFFFFFFFF
        x = (x ^ (x >> 27)) * 0x94D049BB133111EB & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 31
    return (x >> 32) / float(1 << 32)


def poison_restarts(k: int, restarts: int) -> tuple[int, ...]:
    """The restart indices of rank ``k`` the armed ``solve.nonfinite``
    site poisons (empty when unarmed). Read by the sweep builders at
    TRACE time — the armed spec is static there (``trace_token`` keys
    the builder caches), so the poison set compiles in as constant
    indices: deterministic, seeded, identical for a lane whether it
    solves solo, whole-grid, bucketed, or packed with dispatch-mates
    (the quarantine-exactness tests depend on that invariance)."""
    spec = armed("solve.nonfinite")
    if spec is None:
        return ()
    if spec.lanes is not None:
        return tuple(r for kk, r in spec.lanes
                     if kk == int(k) and 0 <= r < restarts)
    return tuple(r for r in range(restarts)
                 if _mix01(spec.seed, int(k), r) < spec.rate)


def stale_reload_fraction() -> float:
    """The armed ``sched.stale_reload`` rate (0.0 = off) — read at
    trace time by ``nmfx.ops.sched_mu`` (the builder caches are
    trace_token-keyed, so arming after a trace can no longer silently
    serve the clean executable)."""
    spec = armed("sched.stale_reload")
    return float(spec.rate) if spec is not None else 0.0


# -- the shared degradation warn-once helper ------------------------------
_warned_lock = threading.Lock()
_warned: "set[str]" = set()


def warn_once(category: str, msg: str) -> None:
    """One warning per degradation category per process — the shared
    helper every graceful-fallback ``except`` handler routes through
    (lint rule NMFX006 enforces that broad handlers either re-raise,
    resolve a Future, or call this): the FIRST fallback of a kind is
    loud, steady-state degradation doesn't flood the logs, and nothing
    is ever silently swallowed. EVERY call (not just the first of a
    category) also lands a structured ``degradation`` event in the
    flight recorder — the warning dedups for log hygiene, but a crash
    postmortem needs the full degradation sequence."""
    _flight.record("degradation", degradation=category, msg=msg)
    with _warned_lock:
        if category in _warned:
            return
        _warned.add(category)
    warnings.warn(f"nmfx [{category}]: {msg}", RuntimeWarning,
                  stacklevel=3)
    _log.warning("[%s] %s", category, msg)


def _reset_warned() -> None:
    """Test hook: forget which degradation categories already warned."""
    with _warned_lock:
        _warned.clear()
