"""Executable-reuse serving layer: shape-bucketed AOT sweep cache, a
persistent on-disk executable store, a pipelined compile pool, and a
double-buffered host↔device pipeline.

The reference amortizes nothing — every run re-spawns its R workers and
re-loads ``libnmf.so`` from scratch (``nmf.r:53-119``). The TPU port
inherited an analogous cold-start tax at a worse exchange rate: XLA keys
compiled executables by EXACT input shape, so a service sweeping datasets
of nearby-but-different shapes pays the full trace+compile — measured
22.3 s against a 1.85 s warm solve at the north star (BENCH_r05) — on
*every* new shape, and every FRESH PROCESS pays it again even for shapes
it has served before. Both MPI-FAUN (arxiv 1609.09154) and the
distributed out-of-memory NMF line (arxiv 2202.09518) treat setup
amortization across many factorizations as a first-class cost at scale;
this module attacks shape churn, process churn, and compile serialism:

* **Shape buckets** (``ExecCacheConfig``): incoming ``(m, n)`` rounds up
  to a coarse lattice (quantum-aligned steps that double as the
  dimension grows, so relative padding overhead stays bounded while the
  bucket count stays logarithmic). One executable serves every dataset
  in its bucket: A is zero-padded, the initial factors are drawn at the
  TRUE shape outside the executable (``sweep.bucketed_lane_init_fn``) and
  zero-padded in, and the executable masks pad columns out of
  labels/consensus and renormalizes dnorms from dynamic true dims
  (``sweep._build_bucketed_sweep_fn``) — the same exact-zero padding
  invariant the feature/sample sharding already relies on.
* **AOT compilation**: executables are built with
  ``jax.jit(...).lower(...).compile()``, so warmup is explicit (CLI
  ``--warm-shapes``), batchable at startup, and measurable
  (``compile.cache_miss`` phase; hits mark ``compile.cache_hit``).
  Entries are LRU-bounded (``max_entries``) — each live executable pins
  device memory for its program.
* **Disk persistence** (``ExecCacheConfig.cache_dir``): compiled
  executables are serialized (``nmfx._compat.serialize_compiled``) into
  a cache directory keyed by the bucket key extended with the device
  kind and jax/jaxlib/platform versions, with atomic tmp+rename writes
  (concurrent writers race safely — readers never observe a partial
  file) and a byte-capped mtime-LRU eviction INDEPENDENT of the
  in-memory LRU (a memory eviction never deletes the disk entry;
  re-admission from disk is a hit). A fresh process's cold start
  becomes deserialize-and-dispatch instead of trace-and-compile
  (``compile.persist_hit``/``compile.deserialize`` phases); corrupt or
  environment-mismatched entries fall back to a clean recompile with
  ONE warning, never a crash.
* **Pipelined compilation**: :meth:`ExecCache.warm` compiles multiple
  pending executables concurrently in a thread pool (XLA compilation
  releases the GIL) and, with ``background=True``, off the caller's
  thread entirely — a request that arrives mid-warm WAITS on the
  in-flight compile instead of duplicating it (the in-flight future
  registry). Under ``ExecCacheConfig.pipeline_ranks`` a cold
  :meth:`run_sweep` builds per-rank executables the same way and
  dispatches lowest-rank-first, so the k=2 solve runs on device while
  higher ranks are still compiling (per-rank ``compile.k=<k>`` spans).
* **Transfer overlap**: :meth:`ExecCache.prefetch` starts the next
  request's host→device transfer while the current sweep runs (the
  transfer also overlaps the request's own lane-init compute, which for
  random init never touches A); :func:`start_host_fetch` begins
  non-blocking device→host copies of finished results so they stream
  back during subsequent compute instead of paying one end barrier. The
  lane-init buffers are donated to the executable where the backend
  honors donation (``donate_inits``) — they are rebuilt per request, so
  aliasing them away is free (cf. the proven-safe donation note in
  ``pallas_mu.fused_block_iterations``).

Cache keys cover everything that changes the compiled program: bucket
shape, the rank set, restart count, the full SolverConfig (its dataclass
hash — the solver-config fingerprint, which since round 6 includes the
``check_block`` cadence field and the nested ``experimental`` knobs, so
the bucket key versions on the new cadence/experimental fields
automatically — two configs differing only in cadence compile and cache
separately), label rule, keep_factors, the scheduler knobs, the mesh,
and the jax version + backend platform. The DISK key additionally
covers the device kind and the jaxlib/PJRT platform versions (a cache
directory shared across an upgrade simply misses cleanly and re-fills).
InitConfig is deliberately NOT in the key: initialization runs outside
the executable, which is what makes one bucket executable serve every
init scheme and true shape.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import os
import pickle
import queue
import tempfile
import threading
import time
import warnings
from collections import OrderedDict
from concurrent.futures import Future
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from nmfx.config import (ConsensusConfig, ExecCacheConfig, InitConfig,
                         SolverConfig)
from nmfx.guards import guarded_by
from nmfx.obs import flight as _flight
from nmfx.obs import metrics as _metrics
from nmfx.sweep import (KSweepOutput, _attribute_dispatch, _noop_rank,
                        _pad_count, _build_bucketed_sweep_fn,
                        bucketed_lane_init_fn, grid_axes_active,
                        grid_exec_ok)

__all__ = ["ExecCache", "PlacedMatrix", "WarmTask", "start_host_fetch",
           "bucket_dim", "solver_key_fields", "persist_key_fields",
           "compile_count"]

#: on-disk record format version; bumped on any layout OR compiled-
#: numerics change so old entries fail the format check (one warning,
#: clean recompile) instead of deserializing garbage. v2: ISSUE 13 —
#: the bucketed builder's pool geometry became composition-independent
#: (padded to the full slot width, tail cascade pinned off; the
#: packed==solo bit-identity fix in sweep._pad_pool_lanes), so a v1
#: executable deserialized next to freshly-compiled v2 ones would
#: re-introduce exactly the cross-geometry drift the fix removes —
#: and make warm- and cold-cache processes disagree bitwise.
_DISK_FORMAT = 2
#: suffix of persisted executable entries (the eviction scan and the
#: tests key on it; atomic-write temp files use a different suffix so a
#: crashed writer's leftovers are never mistaken for entries)
_DISK_SUFFIX = ".nmfxexec"
#: age after which an orphaned atomic-write temp file (a writer killed
#: between mkstemp and the rename) is swept by the eviction scan — far
#: beyond any real compile+serialize, so a live writer is never raced
_PART_MAX_AGE_S = 3600.0

# registry counter of actual .lower().compile() calls — the honesty
# counter behind the zero-compile cold-start contract: a fresh process
# serving from a warm disk cache must leave it at ZERO
# (tests/test_exec_cache.py, bench.py cold_persist stage).
# compile_count() below is the back-compat read shim (ISSUE 10)
_compile_total = _metrics.counter(
    "nmfx_exec_compile_total",
    "executables actually compiled through the serving layer "
    "(.lower().compile() calls; deserialized disk hits do not count)")
_exec_evictions_total = _metrics.counter(
    "nmfx_exec_cache_evictions_total",
    "in-memory executable-cache entries evicted (LRU bound; the disk "
    "record, if any, survives)")


def compile_count() -> int:
    """How many executables this process has ACTUALLY compiled through
    the serving layer (``.lower().compile()`` calls; deserialized disk
    hits do not count). Reads the registry counter
    ``nmfx_exec_compile_total`` (back-compat shim)."""
    return int(_compile_total.total())


def _note_compile() -> None:
    _compile_total.inc()


def solver_key_fields() -> frozenset:
    """The SolverConfig fields the bucket key covers — the introspection
    hook NMFX001 reads instead of parsing ``ExecCache._key``.

    The key embeds the SolverConfig dataclass VALUE itself (frozen
    dataclass ``__eq__``/``__hash__``, which compare every field
    including the nested ExperimentalConfig), so coverage is total by
    construction — as long as every field participates in comparison.
    Reading ``field.compare`` keeps this hook honest: a field added with
    ``compare=False`` would silently alias two different-numerics
    configs onto one cached executable, and shows up here (and in
    NMFX001) as uncovered."""
    return frozenset(f.name for f in dataclasses.fields(SolverConfig)
                     if f.compare)


def persist_key_fields() -> frozenset:
    """The SolverConfig fields the PERSISTENT disk key covers — the
    second NMFX001 introspection hook.

    The disk key is the ``repr`` of the in-memory key (plus the
    device/jax environment), and dataclass ``__repr__`` renders exactly
    the fields declared with ``repr=True`` — so this hook reads
    ``field.repr``. The honesty argument mirrors
    :func:`solver_key_fields`: a field added with ``repr=False`` would
    be present in the in-memory key (hash/eq) but INVISIBLE in the disk
    key, so two configs differing only in it would map to one disk
    entry and a fresh process would deserialize the wrong executable —
    that gap shows up here (and fails lint) instead of shipping."""
    return frozenset(f.name for f in dataclasses.fields(SolverConfig)
                     if f.repr)


@functools.lru_cache(maxsize=1)
def _env_fingerprint() -> tuple:
    """Everything about the runtime that can invalidate a serialized
    executable beyond the bucket key itself: jax/jaxlib versions, the
    backend platform, the device kind, and the PJRT platform version
    (XLA build). Part of the hashed disk-entry name AND stored inside
    each entry, so a mismatched entry is detected even on a hash
    collision or a hand-moved file. Constant for the process lifetime
    (the backend cannot change once initialized) — cached."""
    import jaxlib

    dev = jax.devices()[0]
    client = getattr(dev, "client", None)
    return (jax.__version__, jaxlib.__version__, jax.default_backend(),
            str(getattr(dev, "device_kind", "?")),
            str(getattr(client, "platform_version", "?")))


def bucket_dim(x: int, quantum: int, growth_steps: int = 8) -> int:
    """Round ``x`` up to the shape lattice: multiples of a step that
    starts at ``quantum`` and doubles whenever the dimension exceeds
    ``growth_steps`` steps — relative padding overhead stays below
    2/growth_steps (the last doubling can land the step at up to
    2x/growth_steps), bucket count logarithmic in the dimension.
    (Defaults land the north-star 5000×500 on 5120×512, the
    hardware-probed VMEM boundary shape.)"""
    if x < 1:
        raise ValueError(f"dimension must be >= 1, got {x}")
    step = quantum
    while step * growth_steps < x:
        step *= 2
    return -(-x // step) * step


def start_host_fetch(tree) -> None:
    """Begin non-blocking device→host copies for every array leaf.

    The copies enqueue behind whatever compute produces the arrays and
    populate each array's host-side cache, so a later ``device_get`` /
    ``np.asarray`` finds the data already resident instead of paying a
    blocking round trip per batch — results stream back WHILE the next
    rank/request computes. Safe on any backend; arrays without an async
    copy path are skipped (the later device_get then behaves as before).
    """
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                leaf.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass  # no async path: the eventual device_get still works


class PlacedMatrix(NamedTuple):
    """A dataset already padded to its bucket and placed (possibly still
    in flight — ``device_put`` is asynchronous) on device."""

    a_pad: jax.Array  # (m_pad, n_pad), zero-padded
    true_shape: tuple[int, int]
    bucket: tuple[int, int]


class _Entry(NamedTuple):
    #: the jitted builder output (traceable); None for entries
    #: deserialized from disk, which never re-trace
    fn: object | None
    compiled: "jax.stages.Compiled"  # the AOT executable actually called
    bucket: tuple[int, int]
    #: seconds this entry's compile took — for disk-loaded entries, the
    #: ORIGINAL compile cost recorded by whichever process paid it
    compile_s: float
    #: seconds spent deserializing (0.0 for freshly-compiled entries)
    deserialize_s: float = 0.0
    #: where this entry came from: "compile" or "disk"
    source: str = "compile"
    #: this entry's persisted file (None when not on disk) — memory hits
    #: touch its mtime so the disk mtime-LRU sees hot buckets as hot
    #: even when they are served from memory for days
    path: "str | None" = None


class WarmTask:
    """Handle to a background :meth:`ExecCache.warm` — ``done()`` polls,
    ``result()`` joins and returns (or raises) the warm report. The
    worker is a daemon thread: a process that exits mid-warm abandons
    the remaining compiles (persisted entries written so far survive)."""

    def __init__(self, thread: threading.Thread, box: dict):
        self._thread = thread
        self._box = box

    def done(self) -> bool:
        return not self._thread.is_alive()

    def result(self, timeout: "float | None" = None) -> "list[dict]":
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("background warmup still compiling")
        err = self._box.get("error")
        if err is not None:
            raise err
        return self._box["report"]


@guarded_by("_lock", "_entries", "_entries_cap", "_inflight", "_warned",
            "_warm_failures", "hits", "misses", "evictions",
            "persist_hits", "persist_misses", "disk_evictions")
class ExecCache:
    """LRU of AOT-compiled, shape-bucketed sweep executables, optionally
    backed by a persistent on-disk store (``ExecCacheConfig.cache_dir``).

    One instance is meant to live for a serving process's lifetime and be
    passed to ``nmfconsensus(exec_cache=...)`` / ``sweep(exec_cache=...)``
    on every request; repeat requests whose shapes fall in a warm bucket
    skip compilation entirely, and with a cache directory a FRESH process
    deserializes instead of recompiling. Request serving is meant to stay
    single-threaded (like jit's own caches), but compilation is
    internally thread-safe: background/parallel warms and a foreground
    request de-duplicate through an in-flight future registry, so no
    executable is ever built twice concurrently.
    """

    def __init__(self, cfg: ExecCacheConfig = ExecCacheConfig()):
        self.cfg = cfg
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        #: effective in-memory LRU bound — cfg.max_entries, raised by the
        #: per-rank mode to the largest request's rank count so that one
        #: sweep's per-rank executables never thrash the LRU (ks=2..10 is
        #: 9 entries against the default cap of 8)
        self._entries_cap = cfg.max_entries
        self._inflight: "dict[tuple, Future]" = {}
        self._lock = threading.RLock()
        self._warned: set[str] = set()
        #: background-warm failures by cache key: a compile that died on
        #: a warm worker thread is recorded here and surfaced (one
        #: warning + a clean recompile) on the NEXT executable()/
        #: run_sweep touching that bucket — a corrupt warm must never
        #: silently strand or silently vanish (tests/test_exec_cache.py)
        self._warm_failures: "dict[tuple, BaseException]" = {}
        # Concurrency audit (the serve front-end hits one instance from
        # request + warm + scheduler threads — tests/test_exec_cache.py
        # ::test_concurrent_executable_access): every mutation of
        # _entries / _inflight / _entries_cap / _warned / the counters
        # below goes through _lock; _Entry values are immutable
        # NamedTuples; the only check-then-act across a lock release is
        # the in-flight future registry, which is exactly the dedup
        # that makes concurrent same-key compiles single-flight.
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.persist_hits = 0
        self.persist_misses = 0
        self.disk_evictions = 0

    # -- policy ------------------------------------------------------------
    def bucket_shape(self, m: int, n: int) -> tuple[int, int]:
        return (bucket_dim(m, self.cfg.m_quantum, self.cfg.growth_steps),
                bucket_dim(n, self.cfg.n_quantum, self.cfg.growth_steps))

    def cacheable(self, ccfg: ConsensusConfig, scfg: SolverConfig,
                  mesh=None) -> bool:
        """Whether this (config, mesh) can serve through the bucketed
        executables: the whole-grid slot-scheduled engine must be able to
        run it (``grid_exec_ok`` — excludes feature/sample-sharded
        meshes, whose builders do their own shape padding) under a
        grid-capable ``grid_exec`` mode, in a single-process job
        (multi-host sweeps coordinate registry broadcasts the cache does
        not replicate)."""
        return (grid_exec_ok(scfg, mesh)
                and ccfg.grid_exec in ("auto", "grid")
                and not grid_axes_active(mesh)
                and jax.process_count() == 1)

    def _key(self, bucket: tuple[int, int], ccfg: ConsensusConfig,
             scfg: SolverConfig, icfg: InitConfig, mesh) -> tuple:
        tail = ccfg.grid_tail_slots
        if isinstance(tail, list):
            tail = tuple(tail)
        # random init is baked INTO the executable (the zero-compile hit
        # path), so its config keys the entry; NNDSVD lane batches are
        # built outside per true shape and leave the executable
        # init-agnostic
        init_key = icfg if icfg.method == "random" else "external"
        key = (bucket, tuple(sorted(ccfg.ks, reverse=True)),
               ccfg.restarts, scfg, init_key, ccfg.label_rule,
               ccfg.keep_factors, ccfg.grid_slots, tail, mesh,
               jax.__version__, jax.default_backend())
        # trace-affecting fault state (nmfx.faults — solve.nonfinite /
        # sched.stale_reload) keys the executable: an armed process can
        # never serve a clean cached/persisted executable and vice
        # versa. None (nothing armed, the production state) leaves the
        # key — and hence every existing disk entry — untouched.
        from nmfx import faults

        tok = faults.trace_token()
        return key if tok is None else key + (tok,)

    def _donate(self) -> bool:
        # donation is a no-op-with-warning on backends that ignore it;
        # keep the logs clean there
        return (self.cfg.donate_inits
                and jax.default_backend() in ("tpu", "gpu"))

    def _workers(self, pending: int) -> int:
        if self.cfg.compile_workers > 0:
            return self.cfg.compile_workers
        return max(1, min(pending, os.cpu_count() or 2))

    def _compile_concurrently(self, keys, run_one) -> "dict[object, Future]":
        """Run ``run_one(key)`` for every key on DAEMON worker threads —
        not a ThreadPoolExecutor, whose non-daemon workers are joined at
        interpreter exit: a process quitting mid-background-warm must
        abandon in-flight compiles (as :class:`WarmTask` documents)
        instead of hanging until XLA finishes work whose results are
        discarded. Returns one Future per key; workers drain the keys in
        the given order. Shared by :meth:`warm` and the per-rank
        pipeline so the two call sites cannot drift apart."""
        keys = list(keys)
        futs = {k: Future() for k in keys}
        pending: "queue.SimpleQueue" = queue.SimpleQueue()
        for k in keys:
            pending.put(k)

        def drain():
            while True:
                try:
                    k = pending.get_nowait()
                except queue.Empty:
                    return
                try:
                    futs[k].set_result(run_one(k))
                except BaseException as e:
                    futs[k].set_exception(e)

        for _ in range(self._workers(len(keys))):
            threading.Thread(target=drain, daemon=True,
                             name="nmfx-exec-compile").start()
        return futs

    # -- the persistent store ----------------------------------------------
    def _persist_repr(self, key: tuple) -> str:
        """The canonical disk-key string: the in-memory key's repr (every
        SolverConfig field with ``repr=True`` renders into it — the
        coverage :func:`persist_key_fields` declares) extended with the
        device/jax environment. Deterministic across processes: dataclass
        reprs are field-ordered and Mesh reprs are device-ordered."""
        return repr((key, _env_fingerprint()))

    def _disk_path(self, key: tuple) -> str:
        digest = hashlib.sha256(
            self._persist_repr(key).encode()).hexdigest()[:40]
        return os.path.join(self.cfg.cache_dir, digest + _DISK_SUFFIX)

    def _warn_once(self, category: str, msg: str) -> None:
        """One warning per failure category per cache instance — a
        serving process logs the first corrupt/mismatched/unwritable
        event and then degrades silently (the fallback is always a
        clean recompile, never a crash)."""
        with self._lock:
            if category in self._warned:
                return
            self._warned.add(category)
        warnings.warn(f"nmfx exec cache: {msg}", RuntimeWarning,
                      stacklevel=4)

    def _disk_load(self, path: str, key: tuple,
                   bucket: tuple[int, int], prof) -> "_Entry | None":
        from nmfx._compat import deserialize_compiled

        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            # a TRANSIENT read problem (fd pressure, a network
            # filesystem hiccup) — recompile here, but leave the entry
            # alone: it may be perfectly valid for the other processes
            # sharing this cache directory
            self._warn_once(
                "disk-read",
                f"could not read cache entry ({e}); recompiling")
            return None
        try:
            # chaos site: deserializing a persisted executable — the
            # recovery is THIS handler's existing fallback (drop the
            # entry, warn once, recompile), which is exact: a recompiled
            # executable produces bit-identical results
            from nmfx import faults

            faults.inject("persist.deserialize")
            rec = pickle.loads(data)
            if not (isinstance(rec, dict)
                    and rec.get("format") == _DISK_FORMAT):
                raise ValueError(f"unrecognized record format in {path}")
            if rec.get("key") != self._persist_repr(key):
                raise ValueError(
                    f"stored key mismatch in {path} (written under a "
                    "different jax/jaxlib/device environment or config)")
            t0 = time.perf_counter()
            with prof.phase("compile.deserialize"):
                compiled = deserialize_compiled(rec["blob"])
            dt = time.perf_counter() - t0
            try:
                os.utime(path)  # mtime-LRU: a hit refreshes the entry
            except OSError:
                pass
            return _Entry(None, compiled, bucket,
                          float(rec.get("compile_s", 0.0)), dt, "disk",
                          path)
        except Exception as e:
            # a CONTENT failure — truncated pickle, stale environment,
            # a PJRT that can't deserialize this blob: the entry itself
            # is unusable, so drop it, warn once, recompile
            self._warn_once(
                "disk-read",
                f"discarding unusable cache entry and recompiling ({e})")
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    def _disk_store(self, path: str, key: tuple, entry: _Entry) -> bool:
        from nmfx._compat import serialize_compiled

        try:
            blob = serialize_compiled(entry.compiled)
            rec = pickle.dumps(
                {"format": _DISK_FORMAT, "key": self._persist_repr(key),
                 "blob": blob, "compile_s": entry.compile_s},
                protocol=pickle.HIGHEST_PROTOCOL)
            d = os.path.dirname(path) or "."
            os.makedirs(d, exist_ok=True)
            # atomic publish: concurrent writers (two serving processes
            # cold-starting the same bucket) each rename a complete temp
            # file onto the entry path — last wins, readers never see a
            # partial file (tests/test_multiprocess.py)
            fd, tmp = tempfile.mkstemp(dir=d, prefix="write-",
                                       suffix=".part")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(rec)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            self._evict_disk(keep=path)
            return True
        except Exception as e:
            self._warn_once(
                "disk-write",
                f"could not persist executable ({e}); this process "
                "serves from memory only")
            return False

    def _evict_disk(self, keep: "str | None" = None) -> None:
        """Byte-capped mtime-LRU over the cache directory: evict
        oldest-touched entries until the directory fits
        ``max_disk_bytes``. The just-written entry (``keep``) survives
        even when it alone exceeds the cap. Independent of the
        in-memory LRU by design — memory evictions never call this."""
        d = self.cfg.cache_dir
        try:
            stats = []
            now = time.time()
            for name in os.listdir(d):
                p = os.path.join(d, name)
                if name.endswith(".part"):
                    # a writer killed between mkstemp and the rename
                    # leaves an entry-sized orphan the byte cap can't
                    # see; sweep any old enough that no live writer can
                    # still own it
                    try:
                        if now - os.stat(p).st_mtime > _PART_MAX_AGE_S:
                            os.remove(p)
                    except OSError:
                        pass
                    continue
                if not name.endswith(_DISK_SUFFIX):
                    continue
                try:
                    st = os.stat(p)
                except OSError:
                    continue  # concurrently evicted by another process
                stats.append((st.st_mtime, st.st_size, p))
            total = sum(size for _, size, _ in stats)
            keep_abs = os.path.abspath(keep) if keep is not None else None
            for _, size, p in sorted(stats):
                if total <= self.cfg.max_disk_bytes:
                    break
                if os.path.abspath(p) == keep_abs:
                    continue
                try:
                    os.remove(p)
                except OSError:
                    continue
                total -= size
                with self._lock:
                    self.disk_evictions += 1
        except OSError as e:
            self._warn_once("disk-evict",
                            f"disk eviction scan failed ({e})")

    # -- compilation -------------------------------------------------------
    def executable(self, shape: tuple[int, int], ccfg: ConsensusConfig,
                   scfg: SolverConfig = SolverConfig(),
                   icfg: InitConfig = InitConfig(), mesh=None,
                   profiler=None) -> tuple[_Entry, bool]:
        """The (entry, was_hit) for a request shape — served from memory,
        the in-flight compile registry, or the disk store, compiling AOT
        only when all three miss. ``shape`` is the TRUE (m, n); the entry
        is keyed by its bucket, so any same-bucket shape returns the same
        executable. ``was_hit`` means "no compile was paid for this
        call" (memory hit, a wait on another thread's in-flight compile,
        or a disk deserialize)."""
        prof = profiler if profiler is not None else _null()
        bucket = self.bucket_shape(*shape)
        key = self._key(bucket, ccfg, scfg, icfg, mesh)
        with self._lock:
            stale = self._warm_failures.pop(key, None)
        if stale is not None:
            # a background warm died building THIS bucket's executable
            # after its waiters (if any) were already failed — surface
            # it on the next request instead of swallowing it until
            # WarmTask.result(), then recompile cleanly below
            self._warn_once(
                "warm-failed",
                f"background warmup failed for this bucket ({stale!r}); "
                "recompiling in the foreground")
        wait = None
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                wait = self._inflight.get(key)
                if wait is None:
                    fut: Future = Future()
                    self._inflight[key] = fut
        if entry is not None:
            if entry.path is not None:
                # keep the disk mtime-LRU honest: a bucket served from
                # memory for days is the HOTTEST entry, not the coldest
                try:
                    os.utime(entry.path)
                except OSError:
                    pass
            prof.mark("compile.cache_hit")
            return entry, True
        if wait is not None:
            # another thread (a background warm, a parallel compile) is
            # already building this exact executable — wait for it
            # instead of compiling twice
            with prof.phase("compile.inflight_wait"):
                entry = wait.result()
            with self._lock:
                self.hits += 1
            prof.mark("compile.cache_hit")
            return entry, True
        try:
            entry, served = self._load_or_compile(bucket, key, ccfg, scfg,
                                                  icfg, mesh, prof)
            with self._lock:
                self._entries[key] = entry
                # in-memory LRU only: an evicted entry's DISK record (if
                # any) stays — a later request re-admits it as a persist
                # hit instead of recompiling
                while len(self._entries) > self._entries_cap:
                    evicted_key, _ = self._entries.popitem(last=False)
                    self.evictions += 1
                    _exec_evictions_total.inc()
                    _flight.record("cache.evict", cache="exec",
                                   bucket=str(evicted_key[0]))
                self._inflight.pop(key, None)
            fut.set_result(entry)
            return entry, served
        except BaseException as e:
            with self._lock:
                self._inflight.pop(key, None)
            fut.set_exception(e)
            raise

    def _load_or_compile(self, bucket, key, ccfg, scfg, icfg, mesh,
                         prof) -> tuple[_Entry, bool]:
        path = (self._disk_path(key) if self.cfg.cache_dir is not None
                else None)
        if path is not None:
            entry = self._disk_load(path, key, bucket, prof)
            if entry is not None:
                with self._lock:
                    self.persist_hits += 1
                prof.mark("compile.persist_hit")
                return entry, True
            with self._lock:
                self.persist_misses += 1
            prof.mark("compile.persist_miss")
        entry = self._compile(bucket, ccfg, scfg, icfg, mesh, prof)
        if path is not None and self._disk_store(path, key, entry):
            entry = entry._replace(path=path)
        return entry, False

    def _compile(self, bucket, ccfg, scfg, icfg, mesh, prof) -> _Entry:
        from nmfx import faults

        # chaos site: the AOT trace+compile. Fired BEFORE any counter
        # moves, so an injected build failure never books a phantom
        # miss/compile; recovery lives in the callers (the serve layer
        # retries solo with backoff, warm() records per-bucket failures)
        faults.inject("compile.build")
        with self._lock:
            self.misses += 1
        _note_compile()
        ks = tuple(sorted(ccfg.ks))
        span = (f"compile.k={ks[0]}" if len(ks) == 1
                else f"compile.ks={ks[0]}-{ks[-1]}")
        with prof.phase("compile.cache_miss"), prof.phase(span):
            t0 = time.perf_counter()
            tail = (tuple(ccfg.grid_tail_slots)
                    if isinstance(ccfg.grid_tail_slots, list)
                    else ccfg.grid_tail_slots)
            inside_init = icfg.method == "random"
            fn = _build_bucketed_sweep_fn(
                tuple(ccfg.ks), ccfg.restarts, scfg, ccfg.label_rule,
                mesh, ccfg.keep_factors, ccfg.grid_slots, tail, bucket,
                donate_inits=self._donate(),
                init_cfg=icfg if inside_init else None,
                fault_token=faults.trace_token())
            m_pad, n_pad = bucket
            dtype = jnp.dtype(scfg.dtype)
            padded = _pad_count(ccfg.restarts, mesh)
            k_max = max(ccfg.ks)
            b = len(ccfg.ks) * padded  # ConsensusConfig dedupes ks
            sharding = (NamedSharding(mesh, P()) if mesh is not None
                        else None)

            def struct(shape_, dt):
                if sharding is None:
                    return jax.ShapeDtypeStruct(shape_, dt)
                return jax.ShapeDtypeStruct(shape_, dt, sharding=sharding)

            i32 = (struct((), jnp.int32), struct((), jnp.int32),
                   struct((), jnp.int32))
            if inside_init:
                # fn(a_pad, root_key, m_true, n_true, flip_floor)
                compiled = fn.lower(
                    struct((m_pad, n_pad), dtype),
                    struct((), jax.random.key(0).dtype), *i32).compile()
            else:
                # fn(a_pad, w0, h0, m_true, n_true, flip_floor)
                compiled = fn.lower(
                    struct((m_pad, n_pad), dtype),
                    struct((b, m_pad, k_max), dtype),
                    struct((b, k_max, n_pad), dtype), *i32).compile()
            compile_s = time.perf_counter() - t0
        return _Entry(fn, compiled, bucket, compile_s)

    def warm(self, shapes: Sequence[tuple[int, int]],
             ccfg: ConsensusConfig, scfg: SolverConfig = SolverConfig(),
             icfg: InitConfig = InitConfig(), mesh=None,
             profiler=None, parallel: bool = True,
             background: bool = False, _record_failures: bool = False):
        """Batch-compile the executables for each shape's bucket (the
        CLI's ``--warm-shapes``) — CONCURRENTLY in a thread pool when
        more than one is pending (XLA compilation releases the GIL), and
        per rank when ``pipeline_ranks`` is on. With ``background=True``
        the warm runs on a daemon thread and a :class:`WarmTask` handle
        returns immediately (the CLI's ``--warm-cache``): a request
        arriving mid-warm waits on the matching in-flight compile
        instead of duplicating it. Returns one record per executable:
        its shape, bucket, rank set, whether it was already warm
        (``cache_hit`` — no compile paid now), the compile seconds, and
        the entry's origin (``source``: "compile"/"disk")."""
        if background:
            box: dict = {}

            def work():
                try:
                    box["report"] = self.warm(
                        shapes, ccfg, scfg, icfg, mesh, profiler=None,
                        parallel=parallel, background=False,
                        _record_failures=True)
                except BaseException as e:  # nmfx: ignore[NMFX006] -- WarmTask re-raises
                    box["error"] = e

            thread = threading.Thread(target=work, daemon=True,
                                      name="nmfx-exec-warm")
            thread.start()
            return WarmTask(thread, box)
        prof = profiler if profiler is not None else _null()
        specs: list[tuple[tuple[int, int], ConsensusConfig]] = []
        for m, n in shapes:
            if self.cfg.pipeline_ranks and len(ccfg.ks) > 1:
                specs.extend(((m, n), dataclasses.replace(ccfg, ks=(k,)))
                             for k in sorted(ccfg.ks))
            else:
                specs.append(((m, n), ccfg))
        if self.cfg.pipeline_ranks:
            # one request needs its per-rank entries co-resident, so the
            # effective LRU bound rises to the RANK count — never to
            # shapes×ranks, which would silently void the max_entries
            # device-memory bound. Warming more shapes than max_entries
            # keeps only the most recent in memory; pair with cache_dir
            # so the rest stay disk-warm (deserialize, not recompile).
            with self._lock:
                self._entries_cap = max(self._entries_cap, len(ccfg.ks))
        def note_failure(spec, exc) -> None:
            # remember which BUCKET the dead compile belonged to, so
            # the next foreground request touching it warns-and-
            # recompiles instead of the failure staying invisible
            # until (a possibly never-called) WarmTask.result().
            # Background warms only: a foreground warm raises straight
            # to its caller, and recording it too would double-report
            # (and mislabel) an already-surfaced failure on the next
            # request touching the bucket
            if not _record_failures:
                return
            shape, c = spec
            key = self._key(self.bucket_shape(*shape), c, scfg, icfg,
                            mesh)
            with self._lock:
                self._warm_failures[key] = exc

        pooled = parallel and len(specs) > 1
        if pooled:
            # workers get a NullProfiler (Profiler phase bookkeeping is
            # single-threaded); compile walls land in the report and are
            # credited to the profiler below. The first failed spec's
            # exception re-raises (the WarmTask.result contract) AFTER
            # every spec is drained and every failure recorded.
            futs = self._compile_concurrently(
                range(len(specs)),
                lambda i: self.executable(specs[i][0], specs[i][1],
                                          scfg, icfg, mesh))
            results, first_err = [], None
            for i in range(len(specs)):
                try:
                    results.append(futs[i].result())
                except BaseException as e:  # nmfx: ignore[NMFX006] -- re-raised below
                    note_failure(specs[i], e)
                    if first_err is None:
                        first_err = e
            if first_err is not None:
                raise first_err
        else:
            # sequential: executable() records its own compile spans on
            # the caller's profiler directly
            results = []
            for s, c in specs:
                try:
                    results.append(self.executable(s, c, scfg, icfg,
                                                   mesh, prof))
                except BaseException as e:
                    note_failure((s, c), e)
                    raise
        report = []
        for (shape, c), (entry, hit) in zip(specs, results):
            if pooled and not hit and entry.source == "compile":
                prof.add_seconds(
                    f"compile.k={c.ks[0]}" if len(c.ks) == 1
                    else f"compile.ks={min(c.ks)}-{max(c.ks)}",
                    entry.compile_s)
            report.append({"shape": tuple(shape), "bucket": entry.bucket,
                           "ks": tuple(c.ks), "cache_hit": hit,
                           "source": entry.source,
                           "compile_s": round(entry.compile_s, 3),
                           "deserialize_s": round(entry.deserialize_s, 3)})
        return report

    @property
    def stats(self) -> dict:
        with self._lock:  # a consistent snapshot under concurrent serving
            return {"entries": len(self._entries), "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions,
                    "persist_hits": self.persist_hits,
                    "persist_misses": self.persist_misses,
                    "disk_evictions": self.disk_evictions,
                    "warm_failures": len(self._warm_failures),
                    "max_entries": self._entries_cap,
                    "cache_dir": self.cfg.cache_dir}

    # -- the host<->device pipeline ---------------------------------------
    def prefetch(self, a, scfg: SolverConfig = SolverConfig(),
                 mesh=None, profiler=None) -> PlacedMatrix:
        """Pad ``a`` to its bucket and START its host→device transfer.

        ``device_put`` is asynchronous: this returns immediately, so
        calling it for request i+1 right after dispatching request i's
        solve overlaps the transfer with compute — the double-buffering
        half of the pipeline. Passing the returned handle to
        :meth:`run_sweep` skips the placement wait entirely.
        """
        prof = profiler if profiler is not None else _null()
        m, n = a.shape
        bucket = self.bucket_shape(m, n)
        # through the device-resident input cache: a repeat request over
        # the same matrix (the serving steady state) re-uses the padded
        # device buffer outright — zero bytes transferred, gated by
        # data_cache.transfer_count()/h2d_bytes(); a first touch
        # dispatches a chunked async copy that overlaps the bucket's
        # compile/dispatch
        from nmfx.data_cache import default_cache

        # NOT wrapped in a phase here: place() books its own elapsed
        # time (xfer.h2d_overlap on a miss, an xfer.h2d_cache_hit mark
        # on a hit) — an outer span would double-count the same seconds
        # in the audit's overlap ledger. place_resilient: a cache-layer
        # placement failure degrades to a direct uncached transfer of
        # the same padded bytes (bit-identical results, warn-once)
        from nmfx.data_cache import place_resilient

        a_pad = place_resilient(a, scfg, mesh, pad_shape=bucket,
                                profiler=prof)
        return PlacedMatrix(a_pad, (m, n), bucket)

    def _solve_args(self, placed: PlacedMatrix, ccfg: ConsensusConfig,
                    scfg: SolverConfig, icfg: InitConfig, mesh,
                    prof) -> tuple:
        """The executable's runtime arguments for one request: the padded
        matrix, the init route's inputs, and the dynamic true-dimension
        scalars (shared by the whole-grid and per-rank dispatch paths)."""
        m_true, n_true = placed.true_shape
        # host-side (the executable's static n is the bucket width, so
        # it cannot compute floor(tol·n_true) itself), via the SAME
        # helper batch_convergence uses — decision parity by sharing
        from nmfx.ops.packed_mu import flip_budget

        flip = flip_budget(scfg.class_flip_tol, n_true)
        dev_args = (jnp.asarray(m_true, jnp.int32),
                    jnp.asarray(n_true, jnp.int32),
                    jnp.asarray(flip, jnp.int32))
        rep = NamedSharding(mesh, P()) if mesh is not None else None
        if rep is not None:
            dev_args = tuple(jax.device_put(x, rep) for x in dev_args)
        if icfg.method == "random":
            # init happens INSIDE the executable with dynamic true dims
            # (sweep._dyn_lane_init): a new shape in a warm bucket costs
            # zero compilation
            root = jax.random.key(ccfg.seed)
            if rep is not None:
                root = jax.device_put(root, rep)
            return (placed.a_pad, root, *dev_args)
        with prof.phase("exec_cache.init") as sync:
            # NNDSVD factors the true matrix: its lane batch is a
            # small per-true-shape jit outside the executable
            init_fn = bucketed_lane_init_fn(
                placed.true_shape, tuple(ccfg.ks),
                _pad_count(ccfg.restarts, mesh), icfg, scfg.dtype,
                placed.bucket)
            a_true = placed.a_pad[:m_true, :n_true]
            w0, h0 = sync(init_fn(a_true, jax.random.key(ccfg.seed)))
        if rep is not None:
            w0 = jax.device_put(w0, rep)
            h0 = jax.device_put(h0, rep)
        return (placed.a_pad, w0, h0, *dev_args)

    def run_sweep(self, a, ccfg: ConsensusConfig,
                  scfg: SolverConfig = SolverConfig(),
                  icfg: InitConfig = InitConfig(), mesh=None, *,
                  profiler=None, on_rank=None) -> dict[int, KSweepOutput]:
        """One full (k × restart) sweep through the bucketed executable —
        the drop-in serving counterpart of ``sweep.sweep`` (same result
        contract: true-shape per-k ``KSweepOutput``).

        ``a`` may be a raw matrix or a :class:`PlacedMatrix` from
        :meth:`prefetch`. Under a NullProfiler nothing here blocks: the
        solve dispatches asynchronously and the results' host copies are
        started non-blocking, so callers that pipeline requests get full
        transfer/compute overlap; a real profiler deliberately blocks
        per phase for honest attribution (its documented contract).

        ``on_rank(k, KSweepOutput)``: the streaming hook of
        ``sweep.sweep`` — invoked per rank the moment its (async)
        output exists, so a harvest pipeline can pull and post-process
        rank k while later ranks still solve; under ``pipeline_ranks``
        this fires as each rank's executable is dispatched, which is
        the fully-streamed serving shape.
        """
        prof = profiler if profiler is not None else _null()
        if on_rank is None:
            on_rank = _noop_rank
        if not self.cacheable(ccfg, scfg, mesh):
            raise ValueError(
                "configuration is not cacheable (see ExecCache.cacheable)"
                " — route it through nmfx.sweep.sweep instead")
        placed = (a if isinstance(a, PlacedMatrix)
                  else self.prefetch(a, scfg, mesh, profiler=prof))
        if self.cfg.pipeline_ranks and len(ccfg.ks) > 1:
            return self._run_sweep_ranks(placed, ccfg, scfg, icfg, mesh,
                                         prof, on_rank)
        m_true, n_true = placed.true_shape
        entry, _ = self.executable(placed.true_shape, ccfg, scfg, icfg,
                                   mesh, prof)
        solve_args = self._solve_args(placed, ccfg, scfg, icfg, mesh, prof)
        t0 = time.perf_counter()
        with prof.phase("solve.grid") as sync:
            raw = sync(entry.compiled(*solve_args))
        solve_wall = time.perf_counter() - t0
        out = {k: _unpad(v, m_true, n_true) for k, v in raw.items()}
        with prof.phase("xfer.overlap"):
            start_host_fetch(out)
        for k in out:
            on_rank(k, out[k])
        # per-dispatch roofline attribution (profiled runs only — the
        # wall is the compile-free executable call, so exec.* kinds are
        # the cleanest MFU surface; see sweep._attribute_dispatch)
        _attribute_dispatch("exec.grid", scfg, placed.true_shape, out,
                            solve_wall, mesh, prof)
        return out

    def _run_sweep_ranks(self, placed: PlacedMatrix, ccfg: ConsensusConfig,
                         scfg: SolverConfig, icfg: InitConfig, mesh,
                         prof, on_rank) -> dict[int, KSweepOutput]:
        """Pipelined per-rank serving (``ExecCacheConfig.pipeline_ranks``):
        one bucketed executable per rank, compiled concurrently on cold
        start, dispatched ascending-k as each compile lands — the lowest
        rank is already solving on device while higher ranks still
        compile, and under a NullProfiler each rank's async dispatch
        overlaps the next rank's compile wait. Each rank's results are
        exactly a single-rank grid sweep's (``ks=(k,)``); they differ
        from the whole-grid default only by float-tolerance GEMM-batching
        drift, which is why the mode is an opt-in."""
        ks = tuple(sorted(ccfg.ks))
        m_true, n_true = placed.true_shape
        rank_cfgs = {k: dataclasses.replace(ccfg, ks=(k,)) for k in ks}
        # one request needs all its per-rank entries co-resident: raise
        # the effective LRU bound so the flagship ks=2..10 (9 entries vs
        # the default cap of 8) cannot thrash itself into a perpetual
        # one-recompile-per-request tax
        with self._lock:
            self._entries_cap = max(self._entries_cap, len(ks))
            # the hot path stays thread-free: only ranks actually
            # missing from memory get compile workers (a fully-warm
            # request spawns no threads at all)
            missing = [k for k in ks
                       if self._key(placed.bucket, rank_cfgs[k], scfg,
                                    icfg, mesh) not in self._entries]
        futs: "dict[object, Future]" = {}
        if missing:
            # the coordinator consumes ranks ascending while later
            # compiles continue in flight on the daemon workers
            futs = self._compile_concurrently(
                missing,
                lambda k: self.executable(placed.true_shape,
                                          rank_cfgs[k], scfg, icfg,
                                          mesh))
        out: dict[int, KSweepOutput] = {}
        for k in ks:
            ck = rank_cfgs[k]
            if k in futs:
                with prof.phase("compile.pipeline_wait"):
                    entry, hit = futs[k].result()
                if hit:
                    prof.mark("compile.cache_hit")
                elif entry.source == "compile":
                    # the per-rank compile span, measured in the worker
                    # thread, credited here on the coordinating thread
                    prof.add_seconds(f"compile.k={k}", entry.compile_s)
            else:
                entry, _ = self.executable(placed.true_shape, ck, scfg,
                                           icfg, mesh, prof)
            solve_args = self._solve_args(placed, ck, scfg, icfg, mesh,
                                          prof)
            t0 = time.perf_counter()
            with prof.phase(f"solve.k={k}") as sync:
                raw = sync(entry.compiled(*solve_args))
            solve_wall = time.perf_counter() - t0
            out[k] = _unpad(raw[k], m_true, n_true)
            with prof.phase("xfer.overlap"):
                start_host_fetch(out[k])
            # stream rank k to its consumer while ranks k+1... are
            # still compiling/solving — the moment the ISSUE-5 warm
            # path converges on: harvest overlaps the device pipeline
            on_rank(k, out[k])
            _attribute_dispatch("exec.k", scfg, placed.true_shape,
                                {k: out[k]}, solve_wall, mesh, prof)
        return {k: out[k] for k in ccfg.ks}


def _unpad(out_k: KSweepOutput, m: int, n: int) -> KSweepOutput:
    """Slice one rank's padded outputs back to the request's true shape
    (lazy device-side views; per-restart stats are already exact)."""
    return out_k._replace(
        consensus=out_k.consensus[:n, :n],
        labels=out_k.labels[:, :n],
        best_w=out_k.best_w[:m, :],
        best_h=out_k.best_h[:, :n],
        all_w=None if out_k.all_w is None else out_k.all_w[:, :m, :],
        all_h=None if out_k.all_h is None else out_k.all_h[:, :, :n])


def _null():
    from nmfx.profiling import NullProfiler

    return NullProfiler()
