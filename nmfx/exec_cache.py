"""Executable-reuse serving layer: shape-bucketed AOT sweep cache plus a
double-buffered host↔device pipeline.

The reference amortizes nothing — every run re-spawns its R workers and
re-loads ``libnmf.so`` from scratch (``nmf.r:53-119``). The TPU port
inherited an analogous cold-start tax at a worse exchange rate: XLA keys
compiled executables by EXACT input shape, so a service sweeping datasets
of nearby-but-different shapes pays the full trace+compile — measured
22.3 s against a 1.85 s warm solve at the north star (BENCH_r05) — on
*every* new shape. Both MPI-FAUN (arxiv 1609.09154) and the distributed
out-of-memory NMF line (arxiv 2202.09518) identify data movement, not
FLOPs, as the binding constraint for alternating-update NMF at scale;
this module attacks both ends:

* **Shape buckets** (``ExecCacheConfig``): incoming ``(m, n)`` rounds up
  to a coarse lattice (quantum-aligned steps that double as the
  dimension grows, so relative padding overhead stays bounded while the
  bucket count stays logarithmic). One executable serves every dataset
  in its bucket: A is zero-padded, the initial factors are drawn at the
  TRUE shape outside the executable (``sweep.bucketed_lane_init_fn``) and
  zero-padded in, and the executable masks pad columns out of
  labels/consensus and renormalizes dnorms from dynamic true dims
  (``sweep._build_bucketed_sweep_fn``) — the same exact-zero padding
  invariant the feature/sample sharding already relies on.
* **AOT compilation**: executables are built with
  ``jax.jit(...).lower(...).compile()``, so warmup is explicit (CLI
  ``--warm-shapes``), batchable at startup, and measurable
  (``compile.cache_miss`` phase; hits mark ``compile.cache_hit``).
  Entries are LRU-bounded (``max_entries``) — each live executable pins
  device memory for its program.
* **Transfer overlap**: :meth:`ExecCache.prefetch` starts the next
  request's host→device transfer while the current sweep runs (the
  transfer also overlaps the request's own lane-init compute, which for
  random init never touches A); :func:`start_host_fetch` begins
  non-blocking device→host copies of finished results so they stream
  back during subsequent compute instead of paying one end barrier. The
  lane-init buffers are donated to the executable where the backend
  honors donation (``donate_inits``) — they are rebuilt per request, so
  aliasing them away is free (cf. the proven-safe donation note in
  ``pallas_mu.fused_block_iterations``).

Cache keys cover everything that changes the compiled program: bucket
shape, the rank set, restart count, the full SolverConfig (its dataclass
hash — the solver-config fingerprint, which since round 6 includes the
``check_block`` cadence field and the nested ``experimental`` knobs, so
the bucket key versions on the new cadence/experimental fields
automatically — two configs differing only in cadence compile and cache
separately), label rule, keep_factors, the scheduler knobs, the mesh,
and the jax version + backend platform.
InitConfig is deliberately NOT in the key: initialization runs outside
the executable, which is what makes one bucket executable serve every
init scheme and true shape.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from nmfx.config import (ConsensusConfig, ExecCacheConfig, InitConfig,
                         SolverConfig)
from nmfx.sweep import (KSweepOutput, _pad_count,
                        _build_bucketed_sweep_fn, bucketed_lane_init_fn,
                        grid_axes_active, grid_exec_ok)

__all__ = ["ExecCache", "PlacedMatrix", "start_host_fetch", "bucket_dim",
           "solver_key_fields"]


def solver_key_fields() -> frozenset:
    """The SolverConfig fields the bucket key covers — the introspection
    hook NMFX001 reads instead of parsing ``ExecCache._key``.

    The key embeds the SolverConfig dataclass VALUE itself (frozen
    dataclass ``__eq__``/``__hash__``, which compare every field
    including the nested ExperimentalConfig), so coverage is total by
    construction — as long as every field participates in comparison.
    Reading ``field.compare`` keeps this hook honest: a field added with
    ``compare=False`` would silently alias two different-numerics
    configs onto one cached executable, and shows up here (and in
    NMFX001) as uncovered."""
    import dataclasses

    return frozenset(f.name for f in dataclasses.fields(SolverConfig)
                     if f.compare)


def bucket_dim(x: int, quantum: int, growth_steps: int = 8) -> int:
    """Round ``x`` up to the shape lattice: multiples of a step that
    starts at ``quantum`` and doubles whenever the dimension exceeds
    ``growth_steps`` steps — relative padding overhead stays below
    2/growth_steps (the last doubling can land the step at up to
    2x/growth_steps), bucket count logarithmic in the dimension.
    (Defaults land the north-star 5000×500 on 5120×512, the
    hardware-probed VMEM boundary shape.)"""
    if x < 1:
        raise ValueError(f"dimension must be >= 1, got {x}")
    step = quantum
    while step * growth_steps < x:
        step *= 2
    return -(-x // step) * step


def start_host_fetch(tree) -> None:
    """Begin non-blocking device→host copies for every array leaf.

    The copies enqueue behind whatever compute produces the arrays and
    populate each array's host-side cache, so a later ``device_get`` /
    ``np.asarray`` finds the data already resident instead of paying a
    blocking round trip per batch — results stream back WHILE the next
    rank/request computes. Safe on any backend; arrays without an async
    copy path are skipped (the later device_get then behaves as before).
    """
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                leaf.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass  # no async path: the eventual device_get still works


class PlacedMatrix(NamedTuple):
    """A dataset already padded to its bucket and placed (possibly still
    in flight — ``device_put`` is asynchronous) on device."""

    a_pad: jax.Array  # (m_pad, n_pad), zero-padded
    true_shape: tuple[int, int]
    bucket: tuple[int, int]


class _Entry(NamedTuple):
    fn: "jax.stages.Wrapped"  # the jitted builder output (traceable)
    compiled: "jax.stages.Compiled"  # the AOT executable actually called
    bucket: tuple[int, int]
    compile_s: float


class ExecCache:
    """LRU of AOT-compiled, shape-bucketed sweep executables.

    One instance is meant to live for a serving process's lifetime and be
    passed to ``nmfconsensus(exec_cache=...)`` / ``sweep(exec_cache=...)``
    on every request; repeat requests whose shapes fall in a warm bucket
    skip compilation entirely. Thread-hostile by design (like jit's own
    caches): serialize requests or shard caches per worker.
    """

    def __init__(self, cfg: ExecCacheConfig = ExecCacheConfig()):
        self.cfg = cfg
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- policy ------------------------------------------------------------
    def bucket_shape(self, m: int, n: int) -> tuple[int, int]:
        return (bucket_dim(m, self.cfg.m_quantum, self.cfg.growth_steps),
                bucket_dim(n, self.cfg.n_quantum, self.cfg.growth_steps))

    def cacheable(self, ccfg: ConsensusConfig, scfg: SolverConfig,
                  mesh=None) -> bool:
        """Whether this (config, mesh) can serve through the bucketed
        executables: the whole-grid slot-scheduled engine must be able to
        run it (``grid_exec_ok`` — excludes feature/sample-sharded
        meshes, whose builders do their own shape padding) under a
        grid-capable ``grid_exec`` mode, in a single-process job
        (multi-host sweeps coordinate registry broadcasts the cache does
        not replicate)."""
        return (grid_exec_ok(scfg, mesh)
                and ccfg.grid_exec in ("auto", "grid")
                and not grid_axes_active(mesh)
                and jax.process_count() == 1)

    def _key(self, bucket: tuple[int, int], ccfg: ConsensusConfig,
             scfg: SolverConfig, icfg: InitConfig, mesh) -> tuple:
        tail = ccfg.grid_tail_slots
        if isinstance(tail, list):
            tail = tuple(tail)
        # random init is baked INTO the executable (the zero-compile hit
        # path), so its config keys the entry; NNDSVD lane batches are
        # built outside per true shape and leave the executable
        # init-agnostic
        init_key = icfg if icfg.method == "random" else "external"
        return (bucket, tuple(sorted(ccfg.ks, reverse=True)),
                ccfg.restarts, scfg, init_key, ccfg.label_rule,
                ccfg.keep_factors, ccfg.grid_slots, tail, mesh,
                jax.__version__, jax.default_backend())

    def _donate(self) -> bool:
        # donation is a no-op-with-warning on backends that ignore it;
        # keep the logs clean there
        return (self.cfg.donate_inits
                and jax.default_backend() in ("tpu", "gpu"))

    # -- compilation -------------------------------------------------------
    def executable(self, shape: tuple[int, int], ccfg: ConsensusConfig,
                   scfg: SolverConfig = SolverConfig(),
                   icfg: InitConfig = InitConfig(), mesh=None,
                   profiler=None) -> tuple[_Entry, bool]:
        """The (entry, was_hit) for a request shape — compiling AOT on
        miss, LRU-touching on hit. ``shape`` is the TRUE (m, n); the
        entry is keyed by its bucket, so any same-bucket shape returns
        the same executable."""
        prof = profiler if profiler is not None else _null()
        bucket = self.bucket_shape(*shape)
        inside_init = icfg.method == "random"
        key = self._key(bucket, ccfg, scfg, icfg, mesh)
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
            self.hits += 1
            prof.mark("compile.cache_hit")
            return entry, True
        self.misses += 1
        with prof.phase("compile.cache_miss"):
            t0 = time.perf_counter()
            tail = (tuple(ccfg.grid_tail_slots)
                    if isinstance(ccfg.grid_tail_slots, list)
                    else ccfg.grid_tail_slots)
            fn = _build_bucketed_sweep_fn(
                tuple(ccfg.ks), ccfg.restarts, scfg, ccfg.label_rule,
                mesh, ccfg.keep_factors, ccfg.grid_slots, tail, bucket,
                donate_inits=self._donate(),
                init_cfg=icfg if inside_init else None)
            m_pad, n_pad = bucket
            dtype = jnp.dtype(scfg.dtype)
            padded = _pad_count(ccfg.restarts, mesh)
            k_max = max(ccfg.ks)
            b = len(ccfg.ks) * padded  # ConsensusConfig dedupes ks
            sharding = (NamedSharding(mesh, P()) if mesh is not None
                        else None)

            def struct(shape_, dt):
                if sharding is None:
                    return jax.ShapeDtypeStruct(shape_, dt)
                return jax.ShapeDtypeStruct(shape_, dt, sharding=sharding)

            i32 = (struct((), jnp.int32), struct((), jnp.int32),
                   struct((), jnp.int32))
            if inside_init:
                # fn(a_pad, root_key, m_true, n_true, flip_floor)
                compiled = fn.lower(
                    struct((m_pad, n_pad), dtype),
                    struct((), jax.random.key(0).dtype), *i32).compile()
            else:
                # fn(a_pad, w0, h0, m_true, n_true, flip_floor)
                compiled = fn.lower(
                    struct((m_pad, n_pad), dtype),
                    struct((b, m_pad, k_max), dtype),
                    struct((b, k_max, n_pad), dtype), *i32).compile()
            compile_s = time.perf_counter() - t0
        entry = _Entry(fn, compiled, bucket, compile_s)
        self._entries[key] = entry
        while len(self._entries) > self.cfg.max_entries:
            # the compiled program's memory is held by entry.compiled;
            # dropping the dict reference releases it (entry.fn is the
            # lru_cached builder, whose own jit cache was never
            # populated — this layer only calls .lower().compile())
            self._entries.popitem(last=False)
            self.evictions += 1
        return entry, False

    def warm(self, shapes: Sequence[tuple[int, int]],
             ccfg: ConsensusConfig, scfg: SolverConfig = SolverConfig(),
             icfg: InitConfig = InitConfig(), mesh=None,
             profiler=None) -> list[dict]:
        """Batch-compile the executables for each shape's bucket at
        startup (the CLI's ``--warm-shapes``). Returns one record per
        shape: its bucket, whether it was already warm, and the compile
        seconds paid."""
        report = []
        for m, n in shapes:
            entry, hit = self.executable((m, n), ccfg, scfg, icfg, mesh,
                                         profiler)
            report.append({"shape": (m, n), "bucket": entry.bucket,
                           "cache_hit": hit,
                           "compile_s": round(entry.compile_s, 3)})
        return report

    @property
    def stats(self) -> dict:
        return {"entries": len(self._entries), "hits": self.hits,
                "misses": self.misses, "evictions": self.evictions,
                "max_entries": self.cfg.max_entries}

    # -- the host<->device pipeline ---------------------------------------
    def prefetch(self, a, scfg: SolverConfig = SolverConfig(),
                 mesh=None, profiler=None) -> PlacedMatrix:
        """Pad ``a`` to its bucket and START its host→device transfer.

        ``device_put`` is asynchronous: this returns immediately, so
        calling it for request i+1 right after dispatching request i's
        solve overlaps the transfer with compute — the double-buffering
        half of the pipeline. Passing the returned handle to
        :meth:`run_sweep` skips the placement wait entirely.
        """
        prof = profiler if profiler is not None else _null()
        dtype = jnp.dtype(scfg.dtype)
        m, n = a.shape
        bucket = self.bucket_shape(m, n)
        m_pad, n_pad = bucket
        with prof.phase("xfer.overlap"):
            if isinstance(a, jax.Array):
                a_pad = jnp.pad(jnp.asarray(a, dtype),
                                ((0, m_pad - m), (0, n_pad - n)))
            else:
                ah = np.zeros(bucket, dtype)
                ah[:m, :n] = np.asarray(a, dtype)
                a_pad = ah
            if mesh is not None:
                a_pad = jax.device_put(a_pad, NamedSharding(mesh, P()))
            else:
                a_pad = jax.device_put(a_pad)
        return PlacedMatrix(a_pad, (m, n), bucket)

    def run_sweep(self, a, ccfg: ConsensusConfig,
                  scfg: SolverConfig = SolverConfig(),
                  icfg: InitConfig = InitConfig(), mesh=None, *,
                  profiler=None) -> dict[int, KSweepOutput]:
        """One full (k × restart) sweep through the bucketed executable —
        the drop-in serving counterpart of ``sweep.sweep`` (same result
        contract: true-shape per-k ``KSweepOutput``).

        ``a`` may be a raw matrix or a :class:`PlacedMatrix` from
        :meth:`prefetch`. Under a NullProfiler nothing here blocks: the
        solve dispatches asynchronously and the results' host copies are
        started non-blocking, so callers that pipeline requests get full
        transfer/compute overlap; a real profiler deliberately blocks
        per phase for honest attribution (its documented contract).
        """
        prof = profiler if profiler is not None else _null()
        if not self.cacheable(ccfg, scfg, mesh):
            raise ValueError(
                "configuration is not cacheable (see ExecCache.cacheable)"
                " — route it through nmfx.sweep.sweep instead")
        placed = (a if isinstance(a, PlacedMatrix)
                  else self.prefetch(a, scfg, mesh, profiler=prof))
        m_true, n_true = placed.true_shape
        entry, _ = self.executable(placed.true_shape, ccfg, scfg, icfg,
                                   mesh, prof)
        # host-side (the executable's static n is the bucket width, so
        # it cannot compute floor(tol·n_true) itself), via the SAME
        # helper batch_convergence uses — decision parity by sharing
        from nmfx.ops.packed_mu import flip_budget

        flip = flip_budget(scfg.class_flip_tol, n_true)
        dev_args = (jnp.asarray(m_true, jnp.int32),
                    jnp.asarray(n_true, jnp.int32),
                    jnp.asarray(flip, jnp.int32))
        rep = NamedSharding(mesh, P()) if mesh is not None else None
        if rep is not None:
            dev_args = tuple(jax.device_put(x, rep) for x in dev_args)
        if icfg.method == "random":
            # init happens INSIDE the executable with dynamic true dims
            # (sweep._dyn_lane_init): a new shape in a warm bucket costs
            # zero compilation
            root = jax.random.key(ccfg.seed)
            if rep is not None:
                root = jax.device_put(root, rep)
            solve_args = (placed.a_pad, root, *dev_args)
        else:
            with prof.phase("exec_cache.init") as sync:
                # NNDSVD factors the true matrix: its lane batch is a
                # small per-true-shape jit outside the executable
                init_fn = bucketed_lane_init_fn(
                    placed.true_shape, tuple(ccfg.ks),
                    _pad_count(ccfg.restarts, mesh), icfg, scfg.dtype,
                    placed.bucket)
                a_true = placed.a_pad[:m_true, :n_true]
                w0, h0 = sync(init_fn(a_true, jax.random.key(ccfg.seed)))
            if rep is not None:
                w0 = jax.device_put(w0, rep)
                h0 = jax.device_put(h0, rep)
            solve_args = (placed.a_pad, w0, h0, *dev_args)
        with prof.phase("solve.grid") as sync:
            raw = sync(entry.compiled(*solve_args))
        out = {k: _unpad(v, m_true, n_true) for k, v in raw.items()}
        with prof.phase("xfer.overlap"):
            start_host_fetch(out)
        return out


def _unpad(out_k: KSweepOutput, m: int, n: int) -> KSweepOutput:
    """Slice one rank's padded outputs back to the request's true shape
    (lazy device-side views; per-restart stats are already exact)."""
    return out_k._replace(
        consensus=out_k.consensus[:n, :n],
        labels=out_k.labels[:, :n],
        best_w=out_k.best_w[:m, :],
        best_h=out_k.best_h[:, :n],
        all_w=None if out_k.all_w is None else out_k.all_w[:, :m, :],
        all_h=None if out_k.all_h is None else out_k.all_h[:, :, :n])


def _null():
    from nmfx.profiling import NullProfiler

    return NullProfiler()
