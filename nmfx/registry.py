"""Sweep checkpoint/resume registry.

The reference gets crash restartability for free from its BatchJobs
filesystem registry — every job's (W, H, iter) result is persisted as a
serialized file under ``file.dir`` (reference ``nmf.r:63``, SURVEY.md §2c) —
but never exploits it: ``runNMFinJobs`` is fire-and-wait (reference
``nmf.r:112-113``). Here the same durability exists at the natural TPU
granularity, the per-rank reduced result (SURVEY.md §5: "per-(k,seed-block)
result checkpointing gives the same restartability"): after each rank k
finishes, its ``KSweepOutput`` is written as one ``.npz``; a re-run of the
same sweep loads finished ranks instead of recomputing them.

A fingerprint of everything that determines the numbers — data, solver and
init configs, restart count, seed, label rule — guards the cache: a registry
written under one configuration refuses to serve another (the reference's
registry has no such guard; a stale ``file.dir`` silently mixes runs).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

import numpy as np

_META_NAME = "registry.json"
#: v3: fingerprint hashes ALL config field values (not just non-default
#: ones), so changing a field's default invalidates pre-change registries.
#: v4: keep_factors joins the payload — a registry written without
#: per-restart factors must not silently serve a keep_factors sweep.
#: v5: SolverConfig gained kl_bf16_quotient (round 5) — by the v3 rule
#: any new field invalidates pre-change registries (loud error with
#: remediation, never stale numbers); the bump records the cause.
#: v6: round 6 — SolverConfig gained check_block (a cadence field whose
#: pallas drift class is real numerics) and the experimental knobs
#: (incl. kl_bf16_quotient, moved) regrouped under
#: SolverConfig.experimental, changing the hashed field map
#: v7: ISSUE 7 — SolverConfig gained nonfinite_guard (the numeric
#: quarantine changes stop reasons and reduction masks whenever a lane
#: diverges, so checkpoints must not cross the setting; fault-free runs
#: are bit-identical either way, but the v3 rule — any new field
#: invalidates — applies)
#: v8: ISSUE 12 — SolverConfig gained the sketched-engine surface
#: (sketch: SketchConfig, screen, screen_keep) and backend grew the
#: "sketched" family; every one of them changes the numbers a sweep
#: records (a screened registry masks lanes an unscreened one solves),
#: so the v3 rule applies
#: v9: ISSUE 20 — ExperimentalConfig gained the kernel-schedule knobs
#: (autotune, block_m, fused_updates) and backend='pallas' now routes
#: algorithm='hals' through the slot scheduler. fused/phased mu is
#: bit-exact either way, but block_m changes Mosaic tile-order
#: accumulation and hals-pallas is a different engine family than the
#: XLA hals it replaces under that backend — the v3 rule (any hashed
#: field-map change invalidates) applies regardless
_FORMAT_VERSION = 9

#: AUTHORITATIVE list of SolverConfig fields excluded from the
#: fingerprint payload. Every entry must be declared execution-strategy
#: -only in ``SolverConfig.NON_NUMERICS_FIELDS`` — the static analyzer
#: (``nmfx.analysis`` rule NMFX001) cross-references the two lists, so a
#: numerics-affecting field can no longer be dropped from the
#: fingerprint silently (the silent-stale-resume class this module's
#: guard exists for). ``restart_chunk``: chunked and unchunked sweeps
#: are bit-identical by construction (prefix-stable PRNG keys; see
#: tests/test_solvers.py).
FINGERPRINT_SOLVER_EXCLUDED = ("restart_chunk",)

#: SolverConfig fields hashed by a RESOLVED value instead of their raw
#: one (still covered — two configs differing here hash differently
#: whenever the numbers can differ): ``backend`` hashes as its resolved
#: engine family, so "auto" and the explicit equivalent choice share
#: checkpoints while different engine families never do.
FINGERPRINT_SOLVER_RESOLVED = ("backend",)


def fingerprint_solver_fields() -> frozenset:
    """The SolverConfig fields the fingerprint covers (raw or resolved)
    — the introspection hook NMFX001 reads instead of parsing
    ``_fingerprint``'s body."""
    import dataclasses as _dc

    from nmfx.config import SolverConfig

    return (frozenset(f.name for f in _dc.fields(SolverConfig))
            - set(FINGERPRINT_SOLVER_EXCLUDED))


def _all_fields(cfg) -> dict:
    """Every config field by value — including default-valued ones.

    An earlier scheme hashed only non-default fields for forward
    compatibility (old registries survive new fields), but that lets a
    release that *changes a default value* silently match registries
    computed under the old default and resume stale numbers. Hashing all
    values is the conservative choice: a default change (or a new field)
    invalidates old registries, which then recompute — correctness over
    cache retention."""
    return dataclasses.asdict(cfg)


def _fingerprint(a: np.ndarray, solver_cfg, init_cfg, restarts: int,
                 seed: int, label_rule: str,
                 keep_factors: bool = False, mesh=None) -> str:
    """Hash of every input that affects sweep numerics.

    The execution-strategy knob ``backend`` is hashed by its *resolved
    engine family* ("auto" picks a concrete engine per algorithm: the
    packed/scheduled GEMM family for mu and hals, the vmapped generic
    driver otherwise), since different engines group matmul reductions
    differently and are not bit-identical — but "auto" vs an explicit
    equivalent choice is. The ``mesh`` participates ONLY in that
    resolution (mirroring ``sweep._build_sweep_fn``'s routing): on a
    feature/sample-sharded mesh hals executes the grid-sharded generic
    driver, not the packed family, so its family resolves to "vmap"
    there — the mesh shape itself stays out of the hash (see below).
    ``restart_chunk`` is excluded entirely: chunked
    and unchunked sweeps are bit-identical by construction (prefix-stable
    PRNG keys; see tests/test_solvers.py).
    ``ConsensusConfig.grid_exec``/``grid_slots`` and the mesh shape are
    likewise excluded: within one engine family, whole-grid vs per-k
    execution (and different device meshes) reorder GEMM reductions but
    solve the same factorizations from the same keys — equivalent within
    float tolerance, like resuming on different hardware.
    """
    from nmfx.sweep import resolve_engine_family

    h = hashlib.sha256()
    arr = np.ascontiguousarray(np.asarray(a))
    h.update(str(arr.shape).encode())
    h.update(str(arr.dtype).encode())
    h.update(arr.tobytes())
    solver = _all_fields(solver_cfg)
    for name in FINGERPRINT_SOLVER_EXCLUDED:
        solver.pop(name, None)
    # every field declared resolved MUST have a resolver here — a
    # KeyError on a stale declaration is the loud failure NMFX001's
    # cross-reference expects, never a silently-raw hash
    resolvers = {"backend": lambda: resolve_engine_family(solver_cfg,
                                                          mesh)}
    for name in FINGERPRINT_SOLVER_RESOLVED:
        solver[name] = resolvers[name]()
    payload = {
        "solver": solver,
        "init": _all_fields(init_cfg),
        "restarts": restarts,
        "seed": seed,
        "label_rule": label_rule,
        "keep_factors": keep_factors,
        "format": _FORMAT_VERSION,
    }
    h.update(json.dumps(payload, sort_keys=True).encode())
    return h.hexdigest()


class SweepRegistry:
    """Directory of per-rank sweep results, keyed by a config fingerprint."""

    def __init__(self, directory: str, fingerprint: str):
        self.directory = directory
        self.fingerprint = fingerprint
        os.makedirs(directory, exist_ok=True)
        meta_path = os.path.join(directory, _META_NAME)
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
            except (json.JSONDecodeError, OSError) as e:
                raise ValueError(
                    f"registry metadata at {meta_path!r} is unreadable "
                    f"({e}) — the directory is corrupt; delete it (or point "
                    "checkpoint_dir at a fresh directory) to start over") \
                    from e
            if meta.get("fingerprint") != fingerprint:
                raise ValueError(
                    f"registry at {directory!r} was written for a different "
                    "(data, config, seed) combination — or by an older nmfx "
                    "whose fingerprint scheme differs. Refusing to mix "
                    "results; point checkpoint_dir at a fresh directory")
        else:
            tmp = meta_path + ".tmp"
            with open(tmp, "wt") as f:
                json.dump({"fingerprint": fingerprint,
                           "format": _FORMAT_VERSION}, f)
            os.replace(tmp, meta_path)

    @classmethod
    def open(cls, directory: str, a, solver_cfg, init_cfg,
             restarts: int, seed: int, label_rule: str,
             keep_factors: bool = False, mesh=None) -> "SweepRegistry":
        return cls(directory, _fingerprint(a, solver_cfg, init_cfg,
                                           restarts, seed, label_rule,
                                           keep_factors, mesh))

    def _path(self, k: int) -> str:
        return os.path.join(self.directory, f"k{k}.npz")

    def completed_ks(self) -> list[int]:
        ks = []
        for name in os.listdir(self.directory):
            if name.startswith("k") and name.endswith(".npz"):
                try:
                    ks.append(int(name[1:-4]))
                except ValueError:
                    continue
        return sorted(ks)

    def has(self, k: int) -> bool:
        return os.path.exists(self._path(k))

    def save(self, k: int, out) -> None:
        """Persist one rank's KSweepOutput atomically (write + rename, so a
        crash mid-write never leaves a half-result that resume would trust)."""
        import jax

        path = self._path(k)
        tmp = path + ".tmp"
        # one batched device→host transfer for the whole pytree: per-field
        # np.asarray paid one tunnel round trip each (~1 s/rank on a
        # remote-attached TPU vs ~0.15 s batched)
        host = jax.device_get(tuple(out))
        with open(tmp, "wb") as f:  # file handle: savez won't append ".npz"
            np.savez(f, **{n: np.asarray(v)
                           for n, v in zip(out._fields, host)
                           if v is not None})
        os.replace(tmp, path)

    def load(self, k: int):
        """Load one rank's result as a KSweepOutput of host numpy arrays;
        only the optional factor fields (all_w/all_h of a sweep without
        keep_factors) may be absent — any other missing field is a
        version/corruption problem and raises (which try_load's self-heal
        then turns into a recompute)."""
        from nmfx.sweep import KSweepOutput

        optional = ("all_w", "all_h")
        with np.load(self._path(k)) as z:
            return KSweepOutput(**{
                f: None if f in optional and f not in z.files else z[f]
                for f in KSweepOutput._fields})

    def try_load(self, k: int):
        """``load`` that returns None for a missing OR unreadable rank file
        (truncated by a crash predating the atomic-write scheme, external
        corruption, a field-set mismatch from an older nmfx). The sweep
        treats None as not-checkpointed: it recomputes and overwrites —
        self-healing resume instead of an opaque zipfile traceback."""
        if not self.has(k):
            return None
        try:
            return self.load(k)
        except Exception as e:  # nmfx: ignore[NMFX006] -- logged; heals by recompute
            import logging

            logging.getLogger("nmfx").warning(
                "checkpoint for k=%d at %s is unreadable (%s); recomputing",
                k, self._path(k), e)
            return None
