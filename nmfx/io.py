"""GCT / RES expression-matrix I/O (pure numpy, no pandas).

Covers the reference's R readers/writer: ``read.dataset``/``read.gct``/
``read.res``/``write.gct`` (reference ``nmf.r:261-408``).

Divergence from observed reference behavior, on purpose: the reference's
``write.gct`` emits a malformed header line containing BOTH the column indices
``1..ncol`` and the column names (reference ``nmf.r:384-392``); we write a
well-formed GCT v1.2 header (``Name<TAB>Description<TAB><col names...>``) that
its own ``read.gct`` — and ours — parses correctly.
"""

from __future__ import annotations

import math
import os
from typing import NamedTuple, Sequence

import numpy as np


class Dataset(NamedTuple):
    """An expression matrix with row/column labels."""

    values: np.ndarray  # (n_rows, n_cols) float64
    row_names: list[str]
    col_names: list[str]

    @property
    def shape(self):
        return self.values.shape


def read_dataset(path: str):
    """Dispatch on file extension (reference ``read.dataset``,
    nmf.r:261-269, extended): dense GCT/RES load as a :class:`Dataset`;
    the sparse formats (MatrixMarket ``.mtx``, the ``.csr.npz`` CSR
    bundle) load as a :class:`nmfx.sparse.SparseMatrix` — the form the
    out-of-core tile pipeline streams without densifying."""
    lower = path.lower()
    if lower.endswith(".gct"):
        return read_gct(path)
    if lower.endswith(".res"):
        return read_res(path)
    if lower.endswith(".mtx"):
        return read_mtx(path)
    if lower.endswith(".csr.npz"):
        return read_csr_npz(path)
    raise ValueError(f"Input is not a res/gct/mtx/csr.npz file: {path}")


#: rows per streamed parse batch (read_gct) — big enough that parser
#: dispatch amortizes, small enough that the transient text of one
#: batch is noise next to the values array itself
_GCT_CHUNK_ROWS = 2048


def read_gct(path: str, chunk_rows: int = _GCT_CHUNK_ROWS) -> Dataset:
    """Read a GCT v1.2 file (reference ``read.gct``, nmf.r:371-377).

    Layout: line 1 version tag ``#1.2``; line 2 ``<rows>TAB<cols>``; line 3
    header ``Name TAB Description TAB <sample names...>``; then one row per
    gene: name, description, values. The Description column is dropped, as the
    reference does (``ds <- ds[-1]``, nmf.r:376).

    STREAMED (ISSUE 17): the header fixes the output shape, so the
    values array is allocated once up front and data rows are parsed in
    ``chunk_rows`` batches directly into it — peak host RAM is the
    values array plus one batch of text, never the whole file's bytes
    on top of the array (the atlas-scale requirement pinned by
    tests/test_io.py). Batches stay binary end to end: only the header
    lines and the row names are str-decoded.
    """
    if chunk_rows < 1:
        raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
    with open(path, "rb") as f:
        version = f.readline().decode().strip()
        if not version.startswith("#"):
            raise ValueError(f"{path}: missing GCT version line, got {version!r}")
        dims = f.readline().decode().split()
        if len(dims) < 2:
            raise ValueError(f"{path}: malformed GCT dimension line")
        n_rows, n_cols = int(dims[0]), int(dims[1])
        header = f.readline().decode().rstrip("\r\n").split("\t")
        col_names = [c for c in header[2:] if c != ""]
        values = np.empty((n_rows, n_cols), np.float64)
        row_names: list[str] = []
        chunk: list[bytes] = []
        seen = 0  # data rows encountered (counted past n_rows for the error)

        def _flush() -> None:
            # bulk-parse one batch: native C++ from_chars when the host
            # library is built (nmfx/native/gct_io.cpp), else numpy's
            # tokenizer — the per-value Python float() loop both replace
            # was ~6x slower at 20000x1000 (the data loader must not
            # dwarf the few-second on-TPU solve)
            from nmfx import native

            r0 = seen - len(chunk)
            if native.available():
                try:
                    block, _ = native.parse_gct_rows(
                        b"\n".join(chunk) + b"\n", len(chunk), n_cols)
                except ValueError as e:
                    raise ValueError(
                        f"{path}: {e}; expected name<TAB>description<TAB>"
                        f"{n_cols} numeric values per row") from e
            else:
                try:
                    block = np.loadtxt(
                        [line.decode() for line in chunk],
                        delimiter="\t", dtype=np.float64, comments=None,
                        usecols=range(2, 2 + n_cols), ndmin=2)
                except ValueError as e:
                    raise ValueError(
                        f"{path}: malformed GCT data row ({e}); expected "
                        f"name<TAB>description<TAB>{n_cols} numeric values "
                        "per row") from e
            values[r0:seen] = block
            chunk.clear()

        for raw in f:
            line = raw.rstrip(b"\r\n")
            if not line:  # skip blank lines
                continue
            seen += 1
            if seen > n_rows:
                continue  # keep counting for the row-count error below
            tab = line.find(b"\t")
            row_names.append(
                line[:tab if tab != -1 else len(line)].decode())
            chunk.append(line)
            if len(chunk) >= chunk_rows:
                _flush()
        if seen == n_rows and chunk:
            _flush()
        if seen != n_rows:
            raise ValueError(
                f"{path}: found {seen} data rows, header said {n_rows}")
    if len(col_names) != n_cols:
        # tolerate headers with trailing junk; fall back to numbered columns
        col_names = (col_names + [str(i + 1) for i in range(n_cols)])[:n_cols]
    return Dataset(values, row_names, col_names)


def read_res(path: str) -> Dataset:
    """Read a RES file (reference ``read.res``, nmf.r:351-369).

    RES interleaves a value column and a call column per sample; sample names
    sit at every 2nd header field starting at the 3rd (reference extracts
    ``temp[seq(3, colst, 2)]``, nmf.r:358). Row names come from the Accession
    (2nd) column; line 3 holds the row count.
    """
    with open(path, "rt") as f:
        header = f.readline().rstrip("\n").split("\t")
        col_names = [c for c in header[2::2] if c != ""]
        f.readline()  # per-sample description line, unused
        n_rows = int(f.readline().split()[0])
        row_names: list[str] = []
        numeric: list[str] = []
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            fields = line.split("\t")
            row_names.append(fields[1])
            numeric.append("\t".join(fields[2::2]))
    values = (np.loadtxt(numeric, delimiter="\t", dtype=np.float64,
                         comments=None, ndmin=2)
              if numeric else np.empty((0, len(col_names))))
    if values.shape[0] != n_rows:
        raise ValueError(
            f"{path}: found {values.shape[0]} data rows, header said {n_rows}"
        )
    if values.shape[1] != len(col_names):
        raise ValueError(
            f"{path}: {values.shape[1]} value columns vs {len(col_names)} names"
        )
    return Dataset(values, row_names, col_names)


def read_mtx(path: str):
    """Read a MatrixMarket coordinate file as a
    :class:`nmfx.sparse.SparseMatrix` (pure numpy — no scipy in the
    container). Supports the ``matrix coordinate real|integer
    general`` header; ``pattern`` entries load as 1.0. MatrixMarket is
    1-indexed and may carry duplicate entries, which sum (the
    ``from_coo`` canonicalization)."""
    from nmfx.sparse import SparseMatrix

    with open(path, "rb") as f:
        banner = f.readline().decode().strip().lower().split()
        if (len(banner) < 4 or banner[0] != "%%matrixmarket"
                or banner[1] != "matrix" or banner[2] != "coordinate"):
            raise ValueError(
                f"{path}: expected a MatrixMarket 'matrix coordinate' "
                f"banner, got {' '.join(banner)!r}")
        field = banner[3]
        if field not in ("real", "integer", "pattern"):
            raise ValueError(
                f"{path}: unsupported MatrixMarket field {field!r} "
                "(real/integer/pattern)")
        if len(banner) > 4 and banner[4] != "general":
            raise ValueError(
                f"{path}: only 'general' symmetry is supported, got "
                f"{banner[4]!r}")
        line = f.readline()
        while line.startswith(b"%") or not line.strip():
            line = f.readline()
        dims = line.split()
        if len(dims) != 3:
            raise ValueError(f"{path}: malformed MatrixMarket size line")
        m, n, nnz = int(dims[0]), int(dims[1]), int(dims[2])
        ncols = 2 if field == "pattern" else 3
        try:
            trip = np.loadtxt(f, dtype=np.float64, comments="%",
                              usecols=range(ncols), ndmin=2)
        except ValueError as e:
            raise ValueError(
                f"{path}: malformed MatrixMarket entry ({e})") from e
        if trip.shape[0] != nnz:
            raise ValueError(
                f"{path}: found {trip.shape[0]} entries, size line said "
                f"{nnz}")
    rows = trip[:, 0].astype(np.int64) - 1  # 1-indexed on disk
    cols = trip[:, 1].astype(np.int64) - 1
    vals = (np.ones(nnz, np.float64) if field == "pattern"
            else trip[:, 2])
    return SparseMatrix.from_coo(rows, cols, vals, (m, n))


def read_csr_npz(path: str):
    """Read the simple CSR bundle ``write_csr_npz`` emits (an ``npz``
    with ``indptr``/``indices``/``data``/``shape`` — the loader pays
    exactly the stored-triplet bytes, no text parse, no densify)."""
    from nmfx.sparse import SparseMatrix

    with np.load(path, allow_pickle=False) as z:
        try:
            return SparseMatrix(indptr=z["indptr"], indices=z["indices"],
                                data=z["data"],
                                shape=tuple(int(x) for x in z["shape"]))
        except (KeyError, ValueError) as e:
            raise ValueError(
                f"{path}: not a valid CSR bundle "
                f"(indptr/indices/data/shape): {e}") from e


def write_csr_npz(sp, path: str) -> None:
    """Persist a :class:`nmfx.sparse.SparseMatrix` as the ``.csr.npz``
    bundle :func:`read_csr_npz` loads."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    np.savez(path, indptr=sp.indptr, indices=sp.indices, data=sp.data,
             shape=np.asarray(sp.shape, np.int64))


def _to_chars_double(v: float) -> str:
    """Byte-exact Python equivalent of ``std::to_chars(double)`` (the native
    writer's formatter, nmfx/native/gct_io.cpp): shortest-roundtrip digits,
    presented in fixed or scientific notation — whichever is SHORTER, fixed
    on ties (C++17 [charconv.to.chars]). Python's ``repr`` produces the same
    shortest digits but chooses notation by a fixed magnitude window
    (1e-4 ≤ |x| < 1e16), so e.g. 1e10 reprs as ``10000000000`` where
    to_chars emits ``1e+10`` — using repr directly would leave written GCTs
    dependent on whether the C++ library is built. Byte-parity with the
    real native output is property-tested in tests/test_io.py."""
    if v != v:
        # to_chars preserves the NaN sign bit ("-nan"); so must we
        return "-nan" if math.copysign(1.0, v) < 0 else "nan"
    if v in (float("inf"), float("-inf")):
        return "-inf" if v < 0 else "inf"
    if v == 0.0:
        return "-0" if str(v)[0] == "-" else "0"
    from decimal import Decimal

    sign, digits, exp = Decimal(repr(float(v))).as_tuple()
    ds = "".join(map(str, digits)).rstrip("0") or "0"
    exp += len(digits) - len(ds)  # fold stripped trailing zeros into exp
    # value = ds × 10^exp; scientific exponent E places the point after ds[0]
    e = exp + len(ds) - 1
    sci = (ds[0] + ("." + ds[1:] if len(ds) > 1 else "")
           + f"e{'+' if e >= 0 else '-'}{abs(e):02d}")
    if exp >= 0:
        # integral value whose shortest digits don't cover the magnitude:
        # in fixed notation to_chars re-derives the digits, and among the
        # equal-length candidates (exact integer vs shortest-digits padded
        # with zeros — same magnitude, same length) proximity breaks the
        # tie, so the EXACT integer wins (e.g. 70414783084508816.0 prints
        # exactly, not ...820)
        fixed = str(abs(int(v)))
    elif -exp < len(ds):
        fixed = ds[:exp] + "." + ds[exp:]
    else:
        fixed = "0." + "0" * (-exp - len(ds)) + ds
    body = fixed if len(fixed) <= len(sci) else sci
    return "-" + body if sign else body


def write_gct(
    values: np.ndarray,
    path: str,
    row_names: Sequence[str] | None = None,
    col_names: Sequence[str] | None = None,
    descriptions: Sequence[str] | None = None,
) -> None:
    """Write a well-formed GCT v1.2 file (cf. reference ``write.gct``,
    nmf.r:379-408, which duplicates row names into Name and Description —
    we keep that default but emit a spec-conformant header).
    """
    values = np.atleast_2d(np.asarray(values))
    n_rows, n_cols = values.shape
    if row_names is None:
        row_names = [str(i + 1) for i in range(n_rows)]
    if col_names is None:
        col_names = [str(i + 1) for i in range(n_cols)]
    if descriptions is None:
        descriptions = row_names
    if len(row_names) != n_rows or len(col_names) != n_cols:
        raise ValueError("row/col name lengths do not match matrix shape")
    if len(descriptions) != n_rows:
        raise ValueError("descriptions length does not match matrix rows")
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    from nmfx import native

    vals = np.ascontiguousarray(values, dtype=np.float64)
    header = ("#1.2\n" + f"{n_rows}\t{n_cols}\n"
              + "Name\tDescription\t" + "\t".join(map(str, col_names))
              + "\n")
    if native.available():
        # shortest exact float64 repr via C++ to_chars (bit-roundtrip,
        # compact): C interleaves the name/description prefixes and the
        # formatted values into one buffer, written in binary — the data
        # block never round-trips through Python str
        prefs = [f"{name}\t{desc}\t".encode()
                 for name, desc in zip(row_names, descriptions)]
        ends = np.cumsum([len(p) for p in prefs], dtype=np.int64)
        body = native.format_gct_body(vals, b"".join(prefs), ends)
        with open(path, "wb") as f:
            f.write(header.encode())
            f.write(body)
    else:
        with open(path, "wt") as f:
            f.write(header)
            # per-cell std::to_chars-equivalent formatting (_to_chars_double)
            # so the file bytes do not depend on whether the native library
            # is built (an earlier %.17g scheme printed 0.10000000000000001
            # where the native path wrote 0.1). Orders of magnitude slower
            # per value than the C codec — large writes want the native
            # library (auto-built on import when a toolchain is present)
            for name, desc, row in zip(row_names, descriptions, vals):
                cells = "\t".join(_to_chars_double(v) for v in row)
                f.write(f"{name}\t{desc}\t{cells}\n")
