"""GCT / RES expression-matrix I/O (pure numpy, no pandas).

Covers the reference's R readers/writer: ``read.dataset``/``read.gct``/
``read.res``/``write.gct`` (reference ``nmf.r:261-408``).

Divergence from observed reference behavior, on purpose: the reference's
``write.gct`` emits a malformed header line containing BOTH the column indices
``1..ncol`` and the column names (reference ``nmf.r:384-392``); we write a
well-formed GCT v1.2 header (``Name<TAB>Description<TAB><col names...>``) that
its own ``read.gct`` — and ours — parses correctly.
"""

from __future__ import annotations

import math
import os
from typing import NamedTuple, Sequence

import numpy as np


class Dataset(NamedTuple):
    """An expression matrix with row/column labels."""

    values: np.ndarray  # (n_rows, n_cols) float64
    row_names: list[str]
    col_names: list[str]

    @property
    def shape(self):
        return self.values.shape


def read_dataset(path: str) -> Dataset:
    """Dispatch on file extension (reference ``read.dataset``, nmf.r:261-269)."""
    lower = path.lower()
    if lower.endswith(".gct"):
        return read_gct(path)
    if lower.endswith(".res"):
        return read_res(path)
    raise ValueError(f"Input is not a res or gct file: {path}")


def read_gct(path: str) -> Dataset:
    """Read a GCT v1.2 file (reference ``read.gct``, nmf.r:371-377).

    Layout: line 1 version tag ``#1.2``; line 2 ``<rows>TAB<cols>``; line 3
    header ``Name TAB Description TAB <sample names...>``; then one row per
    gene: name, description, values. The Description column is dropped, as the
    reference does (``ds <- ds[-1]``, nmf.r:376).
    """
    # binary end to end: the multi-hundred-MB data block of a large GCT is
    # never str-decoded — only the three header lines and the row names are
    with open(path, "rb") as f:
        version = f.readline().decode().strip()
        if not version.startswith("#"):
            raise ValueError(f"{path}: missing GCT version line, got {version!r}")
        dims = f.readline().decode().split()
        if len(dims) < 2:
            raise ValueError(f"{path}: malformed GCT dimension line")
        n_rows, n_cols = int(dims[0]), int(dims[1])
        header = f.readline().decode().rstrip("\r\n").split("\t")
        col_names = [c for c in header[2:] if c != ""]
        # bulk-parse the numeric block: native C++ from_chars when the host
        # library is built (nmfx/native/gct_io.cpp), else numpy's tokenizer
        # — the per-value Python float() loop both replace was ~6x slower
        # at 20000x1000 (the data loader must not dwarf the few-second
        # on-TPU solve)
        tail = f.read()
        # single scan for line bounds and names — no full copy of the
        # multi-hundred-MB block (only the short name slices are decoded)
        spans: list[tuple[int, int]] = []
        row_names = []
        pos, total = 0, len(tail)
        while pos < total:
            nl = tail.find(b"\n", pos)
            if nl == -1:
                nl = total
            end = nl - 1 if nl > pos and tail[nl - 1:nl] == b"\r" else nl
            if end > pos:  # skip blank lines
                spans.append((pos, end))
                tab = tail.find(b"\t", pos, end)
                row_names.append(
                    tail[pos:tab if tab != -1 else end].decode())
            pos = nl + 1
        if len(spans) != n_rows:
            raise ValueError(
                f"{path}: found {len(spans)} data rows, header said {n_rows}")
        from nmfx import native

        if native.available():
            try:
                values, _ = native.parse_gct_rows(tail, n_rows, n_cols)
            except ValueError as e:
                raise ValueError(
                    f"{path}: {e}; expected name<TAB>description<TAB>"
                    f"{n_cols} numeric values per row") from e
        else:
            try:
                values = np.loadtxt(
                    [tail[s:e].decode() for s, e in spans],
                    delimiter="\t", dtype=np.float64, comments=None,
                    usecols=range(2, 2 + n_cols), ndmin=2)
            except ValueError as e:
                raise ValueError(
                    f"{path}: malformed GCT data row ({e}); expected "
                    f"name<TAB>description<TAB>{n_cols} numeric values per "
                    "row") from e
    if len(col_names) != n_cols:
        # tolerate headers with trailing junk; fall back to numbered columns
        col_names = (col_names + [str(i + 1) for i in range(n_cols)])[:n_cols]
    return Dataset(values, row_names, col_names)


def read_res(path: str) -> Dataset:
    """Read a RES file (reference ``read.res``, nmf.r:351-369).

    RES interleaves a value column and a call column per sample; sample names
    sit at every 2nd header field starting at the 3rd (reference extracts
    ``temp[seq(3, colst, 2)]``, nmf.r:358). Row names come from the Accession
    (2nd) column; line 3 holds the row count.
    """
    with open(path, "rt") as f:
        header = f.readline().rstrip("\n").split("\t")
        col_names = [c for c in header[2::2] if c != ""]
        f.readline()  # per-sample description line, unused
        n_rows = int(f.readline().split()[0])
        row_names: list[str] = []
        numeric: list[str] = []
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            fields = line.split("\t")
            row_names.append(fields[1])
            numeric.append("\t".join(fields[2::2]))
    values = (np.loadtxt(numeric, delimiter="\t", dtype=np.float64,
                         comments=None, ndmin=2)
              if numeric else np.empty((0, len(col_names))))
    if values.shape[0] != n_rows:
        raise ValueError(
            f"{path}: found {values.shape[0]} data rows, header said {n_rows}"
        )
    if values.shape[1] != len(col_names):
        raise ValueError(
            f"{path}: {values.shape[1]} value columns vs {len(col_names)} names"
        )
    return Dataset(values, row_names, col_names)


def _to_chars_double(v: float) -> str:
    """Byte-exact Python equivalent of ``std::to_chars(double)`` (the native
    writer's formatter, nmfx/native/gct_io.cpp): shortest-roundtrip digits,
    presented in fixed or scientific notation — whichever is SHORTER, fixed
    on ties (C++17 [charconv.to.chars]). Python's ``repr`` produces the same
    shortest digits but chooses notation by a fixed magnitude window
    (1e-4 ≤ |x| < 1e16), so e.g. 1e10 reprs as ``10000000000`` where
    to_chars emits ``1e+10`` — using repr directly would leave written GCTs
    dependent on whether the C++ library is built. Byte-parity with the
    real native output is property-tested in tests/test_io.py."""
    if v != v:
        # to_chars preserves the NaN sign bit ("-nan"); so must we
        return "-nan" if math.copysign(1.0, v) < 0 else "nan"
    if v in (float("inf"), float("-inf")):
        return "-inf" if v < 0 else "inf"
    if v == 0.0:
        return "-0" if str(v)[0] == "-" else "0"
    from decimal import Decimal

    sign, digits, exp = Decimal(repr(float(v))).as_tuple()
    ds = "".join(map(str, digits)).rstrip("0") or "0"
    exp += len(digits) - len(ds)  # fold stripped trailing zeros into exp
    # value = ds × 10^exp; scientific exponent E places the point after ds[0]
    e = exp + len(ds) - 1
    sci = (ds[0] + ("." + ds[1:] if len(ds) > 1 else "")
           + f"e{'+' if e >= 0 else '-'}{abs(e):02d}")
    if exp >= 0:
        # integral value whose shortest digits don't cover the magnitude:
        # in fixed notation to_chars re-derives the digits, and among the
        # equal-length candidates (exact integer vs shortest-digits padded
        # with zeros — same magnitude, same length) proximity breaks the
        # tie, so the EXACT integer wins (e.g. 70414783084508816.0 prints
        # exactly, not ...820)
        fixed = str(abs(int(v)))
    elif -exp < len(ds):
        fixed = ds[:exp] + "." + ds[exp:]
    else:
        fixed = "0." + "0" * (-exp - len(ds)) + ds
    body = fixed if len(fixed) <= len(sci) else sci
    return "-" + body if sign else body


def write_gct(
    values: np.ndarray,
    path: str,
    row_names: Sequence[str] | None = None,
    col_names: Sequence[str] | None = None,
    descriptions: Sequence[str] | None = None,
) -> None:
    """Write a well-formed GCT v1.2 file (cf. reference ``write.gct``,
    nmf.r:379-408, which duplicates row names into Name and Description —
    we keep that default but emit a spec-conformant header).
    """
    values = np.atleast_2d(np.asarray(values))
    n_rows, n_cols = values.shape
    if row_names is None:
        row_names = [str(i + 1) for i in range(n_rows)]
    if col_names is None:
        col_names = [str(i + 1) for i in range(n_cols)]
    if descriptions is None:
        descriptions = row_names
    if len(row_names) != n_rows or len(col_names) != n_cols:
        raise ValueError("row/col name lengths do not match matrix shape")
    if len(descriptions) != n_rows:
        raise ValueError("descriptions length does not match matrix rows")
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    from nmfx import native

    vals = np.ascontiguousarray(values, dtype=np.float64)
    header = ("#1.2\n" + f"{n_rows}\t{n_cols}\n"
              + "Name\tDescription\t" + "\t".join(map(str, col_names))
              + "\n")
    if native.available():
        # shortest exact float64 repr via C++ to_chars (bit-roundtrip,
        # compact): C interleaves the name/description prefixes and the
        # formatted values into one buffer, written in binary — the data
        # block never round-trips through Python str
        prefs = [f"{name}\t{desc}\t".encode()
                 for name, desc in zip(row_names, descriptions)]
        ends = np.cumsum([len(p) for p in prefs], dtype=np.int64)
        body = native.format_gct_body(vals, b"".join(prefs), ends)
        with open(path, "wb") as f:
            f.write(header.encode())
            f.write(body)
    else:
        with open(path, "wt") as f:
            f.write(header)
            # per-cell std::to_chars-equivalent formatting (_to_chars_double)
            # so the file bytes do not depend on whether the native library
            # is built (an earlier %.17g scheme printed 0.10000000000000001
            # where the native path wrote 0.1). Orders of magnitude slower
            # per value than the C codec — large writes want the native
            # library (auto-built on import when a toolchain is present)
            for name, desc, row in zip(row_names, descriptions, vals):
                cells = "\t".join(_to_chars_double(v) for v in row)
                f.write(f"{name}\t{desc}\t{cells}\n")
